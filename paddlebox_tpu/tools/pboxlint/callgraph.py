"""Whole-package call graph over the AST (foundation for PB6xx).

Indexes every module-level function, every method (decorated defs are
still plain ``FunctionDef`` nodes), and every nested def/closure into a
``PackageGraph`` of qualified names (``ps.service.PSClient.pull_sparse``,
``ps.host_table.ShardedHostTable.bulk_pull.pull_shard``), then resolves
call sites:

  * ``self.m()`` / ``cls.m()`` through the class hierarchy — the defining
    class, its package bases, and any package subclass override (CHA-style
    virtual dispatch).
  * plain names through local nested defs, module scope, and imports.
  * ``obj.m()`` through light local type inference: ``x = ClassName(...)``,
    ``x = self.attr`` / ``for x in self.attr`` where the attr (or its
    container elements) got a class type in ``__init__``-style assignments.
  * ``WorkPool``/executor hand-offs — ``pool.submit(f, ...)``,
    ``pool.map(f, ...)``, ``threading.Thread(target=f)`` — become *spawn*
    edges to ``f``: the target runs on another thread, so callers' held
    lock sets must NOT flow into it, but the target is still analyzed as
    a root of its own.
  * anything else ``x.m()`` falls back to CHA widening: edges to every
    package function/method named ``m``.  Unknown targets widen the
    analysis — they never drop it (lockgraph keeps the caller's held-set
    across the call either way).

Stdlib-only (`ast`), same contract as the rest of pboxlint.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from paddlebox_tpu.tools.pboxlint.core import Module, dotted_name

# receiver factories whose .submit/.map targets run on a bounded WorkPool;
# the value is the pool kind used by PB603
_POOL_FACTORIES = {"table_pool": "table", "pack_pool": "pack"}
_SPAWN_KEYWORDS = {"target"}          # Thread(target=...), Timer(function=...)

# CHA widening never applies to method names that are overwhelmingly
# builtin-collection/str/file calls on untyped receivers — widening
# `d.get(...)` to every package `get` method floods the lock analysis
# with phantom paths.  Typed receivers still resolve these precisely.
_WIDEN_SKIP = {
    "get", "clear", "pop", "append", "add", "update", "items", "keys",
    "values", "copy", "extend", "remove", "discard", "sort", "reverse",
    "setdefault", "popitem", "popleft", "count", "index", "join",
    "split", "strip", "close", "read", "write", "flush", "seek", "tell",
    "encode", "decode", "format", "startswith", "endswith", "lower",
    "upper", "replace", "record", "put", "send", "recv", "tolist",
    "astype", "reshape", "item", "sum", "max", "min", "mean", "fill",
    # threading/executor primitive names: `evt.wait()`, `t.join()`,
    # `jax.tree.map(...)`, `httpd.shutdown()` — widening these to
    # package methods floods the lock analysis; typed receivers (and
    # the pool factories) still resolve them precisely
    "map", "submit", "shutdown", "wait", "notify", "notify_all",
    "set", "is_set", "acquire", "release", "start", "run",
}


def module_name(path: str) -> str:
    """File path → package-relative dotted module name.

    ``.../paddlebox_tpu/ps/service.py`` → ``ps.service``; paths outside
    the package (test snippets) use their basename stem.
    """
    parts = os.path.normpath(path).split(os.sep)
    if "paddlebox_tpu" in parts:
        parts = parts[len(parts) - parts[::-1].index("paddlebox_tpu"):]
    stem = [p[:-3] if p.endswith(".py") else p for p in parts]
    stem = [p for p in stem if p] or [os.path.basename(path)]
    if stem[-1] == "__init__":
        stem = stem[:-1] or ["__init__"]
    return ".".join(stem)


@dataclasses.dataclass
class CallSite:
    line: int
    name: str                    # terminal call name, for messages
    targets: Tuple[str, ...]     # resolved function qnames
    kind: str                    # "call" | "spawn"
    widened: bool = False        # dynamic-call CHA fallback used
    pool: Optional[str] = None   # pool kind for WorkPool spawns
    node: Optional[ast.Call] = dataclasses.field(
        default=None, repr=False, compare=False)   # the ast call site


class FuncInfo:
    def __init__(self, qname: str, mod: Module, node: ast.AST,
                 cls: Optional["ClassInfo"], self_name: Optional[str]):
        self.qname = qname
        self.mod = mod
        self.node = node
        self.cls = cls              # enclosing class (closures keep it)
        self.self_name = self_name  # receiver arg name, None for functions
        self.calls: List[CallSite] = []    # filled by PackageGraph.resolve

    def __repr__(self) -> str:      # pragma: no cover - debugging aid
        return f"<Func {self.qname}>"


class ClassInfo:
    def __init__(self, qname: str, node: ast.ClassDef, mod: Module):
        self.qname = qname
        self.name = node.name
        self.node = node
        self.mod = mod
        self.methods: Dict[str, FuncInfo] = {}
        self.base_names: List[str] = [dotted_name(b) for b in node.bases]
        self.bases: List[str] = []        # package base qnames, resolved
        self.subclasses: Set[str] = set()
        self.attr_types: Dict[str, str] = {}   # self.X = Cls() → X: qname
        self.elem_types: Dict[str, str] = {}   # self.X = [Cls()...] / .append


class PackageGraph:
    """Index + resolver over a set of parsed modules."""

    def __init__(self, modules: Sequence[Module]):
        self.modules = list(modules)
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.by_method_name: Dict[str, List[str]] = {}
        self.class_by_name: Dict[str, List[str]] = {}
        # per-module: local name → qname it refers to (imports + defs)
        self._scope: Dict[str, Dict[str, str]] = {}
        for mod in self.modules:
            self._index_module(mod)
        self._link_classes()
        for cls in self.classes.values():
            self._infer_attr_types(cls)
        # module-global var → class qname (`_POOL = WorkPool(...)` under a
        # `global _POOL` decl, or a module-level ctor assignment)
        self.global_types: Dict[str, Dict[str, str]] = {}
        for mod in self.modules:
            self.global_types[mod.path] = self._infer_global_types(mod)
        for fn in list(self.functions.values()):
            fn.calls = list(self._resolve_calls(fn))

    # ------------------------------------------------------------- indexing
    def _index_module(self, mod: Module) -> None:
        modname = module_name(mod.path)
        scope = self._scope.setdefault(mod.path, {})
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    scope[alias.asname or alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(stmt, ast.ImportFrom) and stmt.module:
                for alias in stmt.names:
                    scope[alias.asname or alias.name] = \
                        f"{stmt.module}.{alias.name}"
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_function(mod, stmt, f"{modname}.{stmt.name}",
                                     None, None)
                scope[stmt.name] = f"{modname}.{stmt.name}"
            elif isinstance(stmt, ast.ClassDef):
                qname = f"{modname}.{stmt.name}"
                cls = ClassInfo(qname, stmt, mod)
                self.classes[qname] = cls
                self.class_by_name.setdefault(stmt.name, []).append(qname)
                scope[stmt.name] = qname
                for m in stmt.body:
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self_name = (m.args.args[0].arg
                                     if m.args.args else None)
                        fi = self._index_function(
                            mod, m, f"{qname}.{m.name}", cls, self_name)
                        cls.methods[m.name] = fi
                        self.by_method_name.setdefault(
                            m.name, []).append(fi.qname)

    def _index_function(self, mod: Module, node, qname: str,
                        cls: Optional[ClassInfo],
                        self_name: Optional[str]) -> FuncInfo:
        fi = FuncInfo(qname, mod, node, cls, self_name)
        self.functions[qname] = fi
        # index direct nested defs (each recursion handles its own nesting)
        stack: List[ast.AST] = list(node.body)
        while stack:
            child = stack.pop()
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_function(mod, child, f"{qname}.{child.name}",
                                     cls, self_name)
                continue
            if isinstance(child, ast.Lambda):
                continue
            stack.extend(ast.iter_child_nodes(child))
        return fi

    def _link_classes(self) -> None:
        for cls in self.classes.values():
            scope = self._scope.get(cls.mod.path, {})
            for base in cls.base_names:
                head = base.split(".", 1)[0]
                resolved = None
                if base in self.classes:
                    resolved = base
                elif scope.get(base) in self.classes:
                    resolved = scope[base]
                elif head in scope:
                    # module alias: `hb.Base` with `import x as hb`
                    tail = base.split(".", 1)[1] if "." in base else ""
                    for cand in self.class_by_name.get(
                            tail.rsplit(".", 1)[-1], []):
                        resolved = cand
                        break
                elif base.rsplit(".", 1)[-1] in self.class_by_name:
                    cands = self.class_by_name[base.rsplit(".", 1)[-1]]
                    if len(cands) == 1:
                        resolved = cands[0]
                if resolved:
                    cls.bases.append(resolved)
                    self.classes[resolved].subclasses.add(cls.qname)

    # ------------------------------------------------- attribute type model
    def _class_from_ctor(self, mod: Module, call: ast.AST) -> Optional[str]:
        if not isinstance(call, ast.Call):
            return None
        name = dotted_name(call.func)
        if not name:
            return None
        scope = self._scope.get(mod.path, {})
        if name in self.classes:
            return name
        if scope.get(name) in self.classes:
            return scope[name]
        tail = name.rsplit(".", 1)[-1]
        cands = self.class_by_name.get(tail, [])
        if len(cands) == 1 and (tail[:1].isupper() or "." in name):
            return cands[0]
        return None

    def _infer_attr_types(self, cls: ClassInfo) -> None:
        for fi in cls.methods.values():
            self_name = fi.self_name or "self"
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == self_name):
                        ctor = self._class_from_ctor(cls.mod, node.value)
                        if ctor:
                            cls.attr_types[t.attr] = ctor
                            continue
                        elem = self._container_elem(cls.mod, node.value)
                        if elem:
                            cls.elem_types[t.attr] = elem
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("append", "add") \
                        and node.args:
                    recv = node.func.value
                    if (isinstance(recv, ast.Attribute)
                            and isinstance(recv.value, ast.Name)
                            and recv.value.id == self_name):
                        ctor = self._class_from_ctor(cls.mod, node.args[0])
                        if ctor:
                            cls.elem_types.setdefault(recv.attr, ctor)

    @staticmethod
    def _assign_pairs(node: ast.Assign):
        """(target, value) pairs, unpacking `a, b = x, y` pairwise."""
        for t in node.targets:
            if isinstance(t, ast.Tuple) and isinstance(node.value,
                                                       ast.Tuple) \
                    and len(t.elts) == len(node.value.elts):
                for tt, vv in zip(t.elts, node.value.elts):
                    yield tt, vv
            else:
                yield t, node.value

    def _infer_global_types(self, mod: Module) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign):
                for t, v in self._assign_pairs(stmt):
                    if isinstance(t, ast.Name):
                        ctor = self._class_from_ctor(mod, v)
                        if ctor:
                            out[t.id] = ctor
        for node in mod.walk():
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            gnames: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Global):
                    gnames.update(sub.names)
            if not gnames:
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for t, v in self._assign_pairs(sub):
                        if isinstance(t, ast.Name) and t.id in gnames:
                            ctor = self._class_from_ctor(mod, v)
                            if ctor:
                                out.setdefault(t.id, ctor)
        return out

    def _container_elem(self, mod: Module, node: ast.AST) -> Optional[str]:
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            for elt in node.elts:
                ctor = self._class_from_ctor(mod, elt)
                if ctor:
                    return ctor
        elif isinstance(node, (ast.ListComp, ast.SetComp)):
            return self._class_from_ctor(mod, node.elt)
        elif isinstance(node, ast.DictComp):
            return self._class_from_ctor(mod, node.value)
        elif isinstance(node, ast.Dict):
            for v in node.values:
                ctor = self._class_from_ctor(mod, v)
                if ctor:
                    return ctor
        return None

    # ----------------------------------------------------- call resolution
    def _method_targets(self, cls_q: str, meth: str,
                        virtual: bool = True) -> List[str]:
        """Resolve `meth` on class `cls_q`: defining class or nearest base,
        plus subclass overrides (virtual dispatch)."""
        out: List[str] = []
        seen: Set[str] = set()
        stack = [cls_q]
        while stack:                     # walk up the bases for the def
            q = stack.pop(0)
            if q in seen or q not in self.classes:
                continue
            seen.add(q)
            cls = self.classes[q]
            if meth in cls.methods:
                out.append(cls.methods[meth].qname)
                break
            stack.extend(cls.bases)
        if virtual:                      # and down for overrides
            stack = list(self.classes.get(cls_q).subclasses
                         if cls_q in self.classes else [])
            while stack:
                q = stack.pop()
                if q in seen or q not in self.classes:
                    continue
                seen.add(q)
                cls = self.classes[q]
                if meth in cls.methods:
                    out.append(cls.methods[meth].qname)
                stack.extend(cls.subclasses)
        return out

    def _local_types(self, fn: FuncInfo) -> Dict[str, str]:
        """var name → class qname, from ctor assignments and typed-attr
        aliases/iteration within this one function body."""
        out: Dict[str, str] = dict(
            self.global_types.get(fn.mod.path, {}))
        cls = fn.cls
        self_name = fn.self_name

        def attr_type(node: ast.AST) -> Optional[str]:
            while isinstance(node, ast.Subscript):
                node = node.value
            if (cls is not None and isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == self_name):
                return cls.attr_types.get(node.attr)
            return None

        def elem_type(node: ast.AST) -> Optional[str]:
            base = node
            if isinstance(base, ast.Call):     # e.g. list(self._shards)
                base = base.args[0] if base.args else base
            while isinstance(base, ast.Subscript):
                base = base.value
            if (cls is not None and isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == self_name):
                return cls.elem_types.get(base.attr)
            if isinstance(base, ast.Name) and base.id in out:
                return None
            return None

        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign):
                for tgt, val in self._assign_pairs(node):
                    if not isinstance(tgt, ast.Name):
                        continue
                    var = tgt.id
                    ctor = self._class_from_ctor(fn.mod, val)
                    if ctor:
                        out[var] = ctor
                        continue
                    at = attr_type(val)
                    if at:
                        out[var] = at
                        continue
                    if isinstance(val, ast.Name) and val.id in out:
                        out[var] = out[val.id]      # alias copy
                        continue
                    # x = self._shards[i] → element type
                    if isinstance(val, ast.Subscript):
                        et = elem_type(val.value)
                        if et:
                            out[var] = et
            elif isinstance(node, (ast.For, ast.comprehension)):
                tgt = node.target
                it = node.iter
                if isinstance(tgt, ast.Name):
                    et = elem_type(it)
                    if et:
                        out[tgt.id] = et
        return out

    def _value_targets(self, fn: FuncInfo, node: ast.AST,
                       local_types: Dict[str, str]) -> List[str]:
        """Resolve a *value reference* (callback arg) to function qnames."""
        if isinstance(node, ast.Name):
            nested = f"{fn.qname}.{node.id}"
            if nested in self.functions:
                return [nested]
            scope = self._scope.get(fn.mod.path, {})
            q = scope.get(node.id)
            if q in self.functions:
                return [q]
            if q in self.classes or node.id in self.classes:
                cq = q if q in self.classes else node.id
                return self._method_targets(cq, "__init__", virtual=False)
        elif isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name):
                if base.id == fn.self_name and fn.cls is not None:
                    return self._method_targets(fn.cls.qname, node.attr)
                bq = local_types.get(base.id)
                if bq:
                    return self._method_targets(bq, node.attr)
            # CHA fallback for bound-method references
            if node.attr in _WIDEN_SKIP:
                return []
            return [q for q in self.by_method_name.get(node.attr, [])]
        elif isinstance(node, ast.Lambda):
            return []          # lambda bodies are walked inline by callers
        return []

    def _resolve_calls(self, fn: FuncInfo):
        local_types = self._local_types(fn)
        scope = self._scope.get(fn.mod.path, {})
        modname = module_name(fn.mod.path)

        own_body: List[ast.AST] = []
        for stmt in fn.node.body:
            own_body.append(stmt)

        def walk_own(nodes):
            """Yield nodes of this function body, not nested defs."""
            stack = list(nodes)
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                yield node
                stack.extend(ast.iter_child_nodes(node))

        for node in walk_own(own_body):
            if not isinstance(node, ast.Call):
                continue
            site = self._resolve_one(fn, node, local_types, scope, modname)
            if site is not None:
                site.node = node
                yield site

    def _ctor_pool_kind(self, call: ast.Call) -> Optional[str]:
        """`table_pool()` / `pack_pool()` / `WorkPool(n, kind=...)` →
        the pool kind, None for any other call."""
        tail = dotted_name(call.func).rsplit(".", 1)[-1]
        if tail in _POOL_FACTORIES:
            return _POOL_FACTORIES[tail]
        if tail == "WorkPool":
            for kw in call.keywords:
                if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                    return str(kw.value.value)
            if len(call.args) > 1 and isinstance(call.args[1], ast.Constant):
                return str(call.args[1].value)
            return "table"              # WorkPool's default kind
        return None

    def _fn_pool_kinds(self, fn: FuncInfo) -> Dict[str, str]:
        """var name → pool kind, for locals assigned from a pool factory
        or WorkPool ctor anywhere in this function (`pool = pack_pool()`
        then `pool.submit(...)` must still be a spawn edge)."""
        cached = getattr(fn, "_pool_kinds", None)
        if cached is not None:
            return cached
        out: Dict[str, str] = {}
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign):
                for tgt, val in self._assign_pairs(node):
                    if isinstance(tgt, ast.Name) and isinstance(val,
                                                                ast.Call):
                        kind = self._ctor_pool_kind(val)
                        if kind is not None:
                            out[tgt.id] = kind
        fn._pool_kinds = out
        return out

    def _pool_kind(self, fn: FuncInfo, recv: ast.AST,
                   local_types: Dict[str, str]) -> Optional[str]:
        """Is `recv` a WorkPool?  → pool kind ("table"/"pack"/"?")."""
        if isinstance(recv, ast.Call):
            return self._ctor_pool_kind(recv)
        if isinstance(recv, ast.Name):
            kind = self._fn_pool_kinds(fn).get(recv.id)
            if kind is not None:
                return kind
            t = local_types.get(recv.id)
            if t and t.rsplit(".", 1)[-1] == "WorkPool":
                return "?"
        if isinstance(recv, ast.Attribute):
            base = recv.value
            if isinstance(base, ast.Name) and base.id == fn.self_name \
                    and fn.cls is not None:
                t = fn.cls.attr_types.get(recv.attr)
                if t and t.rsplit(".", 1)[-1] == "WorkPool":
                    return "?"
        return None

    def _resolve_one(self, fn: FuncInfo, node: ast.Call,
                     local_types: Dict[str, str], scope: Dict[str, str],
                     modname: str) -> Optional[CallSite]:
        func = node.func
        # -- spawn edges: Thread(target=f) / pool.submit(f) / pool.map(f)
        ctor_name = dotted_name(func).rsplit(".", 1)[-1]
        if ctor_name in ("Thread", "Timer"):
            for kw in node.keywords:
                if kw.arg in _SPAWN_KEYWORDS:
                    targets = self._value_targets(fn, kw.value, local_types)
                    if targets:
                        return CallSite(node.lineno, "Thread",
                                        tuple(sorted(targets)), "spawn")
            return None
        if isinstance(func, ast.Attribute) and func.attr in ("submit",
                                                             "map"):
            pool = self._pool_kind(fn, func.value, local_types)
            if pool is not None and node.args:
                targets = self._value_targets(fn, node.args[0], local_types)
                return CallSite(node.lineno, func.attr,
                                tuple(sorted(targets)), "spawn", pool=pool)

        # -- synchronous calls
        if isinstance(func, ast.Name):
            name = func.id
            nested = f"{fn.qname}.{name}"
            if nested in self.functions:
                return CallSite(node.lineno, name, (nested,), "call")
            q = scope.get(name)
            if q is None and f"{modname}.{name}" in self.functions:
                q = f"{modname}.{name}"
            if q in self.functions:
                return CallSite(node.lineno, name, (q,), "call")
            if q in self.classes:
                ctor = self._method_targets(q, "__init__", virtual=False)
                if ctor:
                    return CallSite(node.lineno, name, tuple(ctor), "call")
            return None

        if isinstance(func, ast.Attribute):
            meth = func.attr
            base = func.value
            # self.m() / cls.m()
            if isinstance(base, ast.Name) and fn.cls is not None \
                    and base.id == fn.self_name:
                targets = self._method_targets(fn.cls.qname, meth)
                if targets:
                    return CallSite(node.lineno, meth,
                                    tuple(sorted(targets)), "call")
                return None
            # module.f() via imports
            dn = dotted_name(func)
            if dn:
                head = dn.split(".", 1)[0]
                if head in scope:
                    q = scope[head] + dn[len(head):]
                    if q in self.functions:
                        return CallSite(node.lineno, meth, (q,), "call")
                    if q in self.classes:
                        ctor = self._method_targets(q, "__init__",
                                                    virtual=False)
                        if ctor:
                            return CallSite(node.lineno, meth,
                                            tuple(ctor), "call")
            # typed receiver: x.m() / self.attr.m()
            recv_cls: Optional[str] = None
            if isinstance(base, ast.Name):
                recv_cls = local_types.get(base.id)
            elif isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == fn.self_name and fn.cls is not None:
                recv_cls = fn.cls.attr_types.get(base.attr)
            elif isinstance(base, ast.Subscript):
                inner = base.value
                if isinstance(inner, ast.Attribute) \
                        and isinstance(inner.value, ast.Name) \
                        and inner.value.id == fn.self_name \
                        and fn.cls is not None:
                    recv_cls = fn.cls.elem_types.get(inner.attr)
            if recv_cls:
                targets = self._method_targets(recv_cls, meth)
                if targets:
                    return CallSite(node.lineno, meth,
                                    tuple(sorted(targets)), "call")
            # CHA widening: any package method with this name
            cands = (self.by_method_name.get(meth, [])
                     if meth not in _WIDEN_SKIP else [])
            if cands:
                return CallSite(node.lineno, meth, tuple(sorted(cands)),
                                "call", widened=True)
        return None
