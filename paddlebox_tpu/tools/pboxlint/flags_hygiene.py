"""PB2xx — flag hygiene (the gflags-registry discipline, flags.py).

  PB201  ``get_flags("name")`` / ``set_flags({"name": ...})`` references a
         flag never registered via ``define_flag`` anywhere in the linted
         set — a typo'd name raises KeyError at runtime, possibly deep in
         a pass loop.
  PB202  a ``define_flag`` default cannot round-trip through ``_coerce``
         (the ``FLAGS_<name>`` env-override parser): non-scalar defaults
         or values whose str() form parses back differently would make
         env overrides silently diverge from programmatic sets.
  PB203  raw ``os.environ["FLAGS_..."]`` / ``os.getenv("FLAGS_...")``
         access outside flags.py — bypasses the registry (no defaults, no
         coercion, no set_flags visibility).
  PB205  a flag is registered via ``define_flag`` but never read by a
         literal ``get_flags("name")`` (or set by a literal ``set_flags``
         key) anywhere in the linted set — a dead knob: env overrides and
         launcher exports of it silently change nothing.  Skipped when
         any ``get_flags`` call uses a non-literal name (the reads are
         then out of static reach).
"""

from __future__ import annotations

import ast
from typing import Any, List, Optional

from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext, dotted_name)


def _coerce_roundtrips(default: Any) -> bool:
    """Mirror flags._coerce: env text is parsed by the *default's* type."""
    try:
        if isinstance(default, bool):
            return (str(default).lower() in ("1", "true", "yes", "on")) \
                == default
        if isinstance(default, int):
            return int(str(default)) == default
        if isinstance(default, float):
            return float(str(default)) == default
        return isinstance(default, str)
    except (TypeError, ValueError):
        return False


def _literal(node: ast.AST) -> Optional[Any]:
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    findings: List[Finding] = []
    is_flags_module = mod.basename == "flags.py"
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        tail = name.rsplit(".", 1)[-1]

        if tail == "get_flags" and node.args:
            arg = node.args[0]
            if (isinstance(arg, ast.Constant) and isinstance(arg.value, str)
                    and arg.value not in ctx.defined_flags
                    and not ctx.dynamic_flag_defs):
                findings.append(Finding(
                    mod.path, node.lineno, "PB201",
                    f"get_flags({arg.value!r}) but no define_flag registers "
                    f"that name anywhere in the linted set — KeyError at "
                    f"runtime"))

        elif tail == "set_flags" and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Dict) and not ctx.dynamic_flag_defs:
                for k in arg.keys:
                    if (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)
                            and k.value not in ctx.defined_flags):
                        findings.append(Finding(
                            mod.path, k.lineno, "PB201",
                            f"set_flags key {k.value!r} is not a registered "
                            f"flag — KeyError at runtime"))

        elif tail == "define_flag" and len(node.args) >= 2:
            name_node = node.args[0]
            if (isinstance(name_node, ast.Constant)
                    and isinstance(name_node.value, str)
                    and name_node.value not in ctx.read_flags
                    and not ctx.dynamic_flag_reads):
                findings.append(Finding(
                    mod.path, node.lineno, "PB205",
                    f"flag {name_node.value!r} is defined but never read "
                    f"via get_flags anywhere in the linted set — dead "
                    f"knob (env/launcher overrides of it do nothing)"))
            default_node = node.args[1]
            default = _literal(default_node)
            if default is None and not (
                    isinstance(default_node, ast.Constant)
                    and default_node.value is None):
                continue        # non-literal default: out of static reach
            if not _coerce_roundtrips(default):
                fname = (node.args[0].value
                         if isinstance(node.args[0], ast.Constant) else "?")
                findings.append(Finding(
                    mod.path, node.lineno, "PB202",
                    f"define_flag({fname!r}) default {default!r} "
                    f"({type(default).__name__}) does not round-trip "
                    f"through _coerce — a FLAGS_ env override would "
                    f"diverge from the programmatic value"))

        elif not is_flags_module:
            key_node: Optional[ast.AST] = None
            if name == "os.getenv" and node.args:
                key_node = node.args[0]
            elif (name == "os.environ.get" and node.args):
                key_node = node.args[0]
            if (isinstance(key_node, ast.Constant)
                    and isinstance(key_node.value, str)
                    and key_node.value.startswith("FLAGS_")):
                findings.append(Finding(
                    mod.path, node.lineno, "PB203",
                    f"raw environment read of {key_node.value!r} outside "
                    f"flags.py — use get_flags() so defaults/coercion/"
                    f"set_flags apply"))

    if not is_flags_module:
        for node in mod.walk():
            if (isinstance(node, ast.Subscript)
                    and dotted_name(node.value) == "os.environ"
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and node.slice.value.startswith("FLAGS_")):
                findings.append(Finding(
                    mod.path, node.lineno, "PB203",
                    f"raw environment read of {node.slice.value!r} outside "
                    f"flags.py — use get_flags() so defaults/coercion/"
                    f"set_flags apply"))
    return findings
