"""pboxlint runner: module model, suppressions, checker registry, CLI core.

Stdlib-only (`ast` + `re`) so the linter can run in any environment the
package imports in — including the tier-1 gate — with no extra deps.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

# `# pboxlint: disable=PB101,PB102 -- why` (same line) or
# `# pboxlint: disable-next=PB101 -- why` (line above the finding).
_SUPPRESS_RE = re.compile(
    r"#\s*pboxlint:\s*disable(?P<next>-next)?"
    r"(?:\s*=\s*(?P<codes>[A-Za-z0-9_,\s]+))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    code: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


class Module:
    """One parsed source file + its suppression table."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        # line -> set of suppressed codes ("ALL" suppresses everything)
        self.suppressions: Dict[int, Set[str]] = {}
        for lineno, text in enumerate(source.splitlines(), 1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            codes = m.group("codes")
            target = lineno + 1 if m.group("next") else lineno
            parsed = ({c.strip().upper()
                       for c in re.split(r"[,\s]+", codes) if c.strip()}
                      if codes else {"ALL"})
            self.suppressions.setdefault(target, set()).update(parsed)

    @property
    def basename(self) -> str:
        return os.path.basename(self.path)

    def suppressed(self, finding: Finding) -> bool:
        codes = self.suppressions.get(finding.line)
        return bool(codes) and ("ALL" in codes or finding.code in codes)


class PackageContext:
    """Cross-module state shared by every checker (e.g. the flag registry
    built from all `define_flag` call sites in the linted set)."""

    def __init__(self, modules: Sequence[Module]):
        self.modules = list(modules)
        self.defined_flags: Set[str] = set()
        self.dynamic_flag_defs = False    # define_flag with non-literal name
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Call)
                        and _call_name(node).endswith("define_flag")
                        and node.args):
                    arg = node.args[0]
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str):
                        self.defined_flags.add(arg.value)
                    else:
                        self.dynamic_flag_defs = True


def _call_name(node: ast.Call) -> str:
    """Dotted name of a call target ('' when not a plain name chain)."""
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    """`a.b.c` → "a.b.c"; anything non-name-chain contributes ""."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def ALL_CHECKERS():
    # local import: checker modules import core for helpers
    from paddlebox_tpu.tools.pboxlint import (atomic_io, device_cache,
                                              flags_hygiene, flight_events,
                                              lifecycle, locks, metric_names,
                                              purity, retries)
    return (locks.check, flags_hygiene.check, metric_names.check,
            flight_events.check, purity.check, lifecycle.check,
            retries.check, atomic_io.check, device_cache.check)


def lint_modules(modules: Sequence[Module]) -> List[Finding]:
    ctx = PackageContext(modules)
    findings: List[Finding] = []
    for mod in modules:
        for check in ALL_CHECKERS():
            findings.extend(f for f in check(mod, ctx)
                            if not mod.suppressed(f))
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


def lint_paths(paths: Sequence[str]
               ) -> Tuple[List[Finding], List[Tuple[str, str]]]:
    """→ (findings, [(path, parse-error)])."""
    modules: List[Module] = []
    errors: List[Tuple[str, str]] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            modules.append(Module(path, src))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append((path, repr(e)))
    return lint_modules(modules), errors


def lint_source(source: str, path: str = "<snippet>",
                extra: Optional[Sequence[Module]] = None) -> List[Finding]:
    """Lint one source string (unit-test surface for checker snippets)."""
    mods = [Module(path, source)] + list(extra or [])
    return [f for f in lint_modules(mods) if f.path == path]


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args or "-h" in args or "--help" in args:
        print(__doc__)
        print("usage: python -m paddlebox_tpu.tools.pboxlint "
              "<file-or-dir> [...]")
        return 0 if args else 2
    findings, errors = lint_paths(args)
    for path, err in errors:
        print(f"{path}:0: PB000 parse failure: {err}")
    for f in findings:
        print(f.render())
    if errors:
        return 2
    if findings:
        print(f"pboxlint: {len(findings)} finding(s)")
        return 1
    return 0
