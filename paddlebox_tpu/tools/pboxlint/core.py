"""pboxlint runner: module model, suppressions, checker registry, CLI core.

Stdlib-only (`ast` + `re`) so the linter can run in any environment the
package imports in — including the tier-1 gate — with no extra deps.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

# `# pboxlint: disable=PB101,PB102 -- why` (same line) or
# `# pboxlint: disable-next=PB101 -- why` (line above the finding).
_SUPPRESS_RE = re.compile(
    r"#\s*pboxlint:\s*disable(?P<next>-next)?"
    r"(?:\s*=\s*(?P<codes>[A-Za-z0-9_,\s]+))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    code: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


class Module:
    """One parsed source file + its suppression table."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self._nodes: Optional[Tuple[ast.AST, ...]] = None
        self._by_type: Dict[type, Tuple[ast.AST, ...]] = {}
        # line -> set of suppressed codes ("ALL" suppresses everything)
        self.suppressions: Dict[int, Set[str]] = {}
        for lineno, text in enumerate(source.splitlines(), 1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            codes = m.group("codes")
            target = lineno + 1 if m.group("next") else lineno
            parsed = ({c.strip().upper()
                       for c in re.split(r"[,\s]+", codes) if c.strip()}
                      if codes else {"ALL"})
            self.suppressions.setdefault(target, set()).update(parsed)

    @property
    def basename(self) -> str:
        return os.path.basename(self.path)

    def walk(self) -> Tuple[ast.AST, ...]:
        """Whole-tree node list, materialized ONCE per module — the
        shared-AST pass.  14 checkers each doing ``ast.walk(mod.tree)``
        (several more than once) re-traverse the same tree ~40×; they
        iterate this cache instead.  Order matches ``ast.walk`` (BFS),
        so existing checker logic is unaffected."""
        nodes = self._nodes
        if nodes is None:
            nodes = self._nodes = tuple(ast.walk(self.tree))
        return nodes

    def nodes_of(self, node_type: type) -> Tuple[ast.AST, ...]:
        """``walk()`` filtered to one node type (isinstance), cached —
        the common shape ``for n in ast.walk(tree): if isinstance(n, T)``
        collapses to a pre-bucketed tuple."""
        got = self._by_type.get(node_type)
        if got is None:
            got = self._by_type[node_type] = tuple(
                n for n in self.walk() if isinstance(n, node_type))
        return got

    def suppressed(self, finding: Finding) -> bool:
        codes = self.suppressions.get(finding.line)
        return bool(codes) and ("ALL" in codes or finding.code in codes)


class PackageContext:
    """Cross-module state shared by every checker (e.g. the flag registry
    built from all `define_flag` call sites in the linted set)."""

    def __init__(self, modules: Sequence[Module]):
        self.modules = list(modules)
        self.defined_flags: Set[str] = set()
        # flag name → (path, line) of its define_flag site
        self.flag_def_sites: Dict[str, Tuple[str, int]] = {}
        self.read_flags: Set[str] = set()   # get_flags/set_flags literals
        self.dynamic_flag_defs = False    # define_flag with non-literal name
        self.dynamic_flag_reads = False   # get_flags with non-literal name
        for mod in self.modules:
            for node in mod.walk():
                if not isinstance(node, ast.Call):
                    continue
                tail = _call_name(node).rsplit(".", 1)[-1]
                if tail == "define_flag" and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str):
                        self.defined_flags.add(arg.value)
                        self.flag_def_sites.setdefault(
                            arg.value, (mod.path, node.lineno))
                    else:
                        self.dynamic_flag_defs = True
                elif tail == "get_flags" and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str):
                        self.read_flags.add(arg.value)
                    else:
                        self.dynamic_flag_reads = True
                elif tail == "set_flags" and node.args \
                        and isinstance(node.args[0], ast.Dict):
                    for k in node.args[0].keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            self.read_flags.add(k.value)


def _call_name(node: ast.Call) -> str:
    """Dotted name of a call target ('' when not a plain name chain)."""
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    """`a.b.c` → "a.b.c"; anything non-name-chain contributes ""."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def ALL_CHECKERS():
    # local import: checker modules import core for helpers
    from paddlebox_tpu.tools.pboxlint import (atomic_io, cluster_commit,
                                              device_cache, flags_hygiene,
                                              flight_events, heat_names,
                                              lifecycle, lockgraph, locks,
                                              metric_names, purity, raceguard,
                                              retries, serving_path, slo_rules,
                                              step_path)
    return (locks.check, flags_hygiene.check, metric_names.check,
            flight_events.check, purity.check, lifecycle.check,
            retries.check, atomic_io.check, device_cache.check,
            lockgraph.check, raceguard.check, slo_rules.check,
            serving_path.check, cluster_commit.check, step_path.check,
            heat_names.check)


def select_matches(code: str, select: Optional[Sequence[str]]) -> bool:
    """``--select`` semantics: ``PB901`` matches exactly; a family token
    ending in ``xx`` (``PB9xx``, ``PB6XX``) is a prefix match.  ``None``
    or empty selects everything."""
    if not select:
        return True
    for tok in select:
        tok = tok.strip().upper()
        if not tok:
            continue
        if tok.endswith("XX"):
            if code.upper().startswith(tok[:-2]):
                return True
        elif code.upper() == tok:
            return True
    return False


def lint_modules(modules: Sequence[Module],
                 select: Optional[Sequence[str]] = None,
                 stats: Optional[Dict[str, float]] = None) -> List[Finding]:
    """Single shared pass: every module is parsed ONCE (in Module) and
    every cross-module analysis (flag registry, callgraph, lockgraph,
    raceguard) is built ONCE on the shared PackageContext — checkers
    cache on ``ctx``.  ``stats`` (if given) accumulates per-checker
    seconds; shared-analysis build cost lands on whichever checker runs
    first (lockgraph pays the fixpoint, raceguard rides the cache)."""
    import time

    ctx = PackageContext(modules)
    findings: List[Finding] = []
    for mod in modules:
        for check in ALL_CHECKERS():
            t0 = time.perf_counter() if stats is not None else 0.0
            found = check(mod, ctx)
            if stats is not None:
                key = check.__module__.rsplit(".", 1)[-1]
                stats[key] = stats.get(key, 0.0) \
                    + (time.perf_counter() - t0)
            findings.extend(f for f in found
                            if not mod.suppressed(f)
                            and select_matches(f.code, select))
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


def lint_paths(paths: Sequence[str],
               select: Optional[Sequence[str]] = None,
               stats: Optional[Dict[str, float]] = None
               ) -> Tuple[List[Finding], List[Tuple[str, str]]]:
    """→ (findings, [(path, parse-error)])."""
    modules: List[Module] = []
    errors: List[Tuple[str, str]] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            modules.append(Module(path, src))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append((path, repr(e)))
    return lint_modules(modules, select=select, stats=stats), errors


def lint_source(source: str, path: str = "<snippet>",
                extra: Optional[Sequence[Module]] = None,
                select: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one source string (unit-test surface for checker snippets)."""
    mods = [Module(path, source)] + list(extra or [])
    return [f for f in lint_modules(mods, select=select) if f.path == path]


def baseline_counts(findings: Sequence[Finding]) -> Dict[str, int]:
    """Findings → {"path:code": count} — the baseline-diff key.  Line
    numbers and messages churn on every edit, so the diff is keyed on
    per-file per-code counts: a PR that *adds* a finding of some code to
    a file fails; moving or rewording existing ones does not."""
    out: Dict[str, int] = {}
    for f in findings:
        key = f"{f.path}:{f.code}"
        out[key] = out.get(key, 0) + 1
    return out


_USAGE = """\
usage: python -m paddlebox_tpu.tools.pboxlint [options] <file-or-dir> [...]

options:
  --format=text|json   output format (json: {findings, errors, counts})
  --select=CODES       only report the given codes/families, e.g.
                       --select=PB901,PB6xx (a token ending in "xx" is a
                       family prefix; composes with --baseline and both
                       formats — counts/baselines see the filtered set)
  --baseline FILE      compare against a saved baseline (json produced by
                       --format=json, or just its "counts" object); exit 1
                       only on findings NEW relative to the baseline
  --write-baseline FILE
                       write the current per-file/per-code counts to FILE
                       (and exit by the normal rules)
  --stats              report per-checker wall time (text: to stderr;
                       json: a "stats" object of seconds)

exit codes:
  0  clean (or, with --baseline, no new findings)
  1  findings (with --baseline: at least one new finding bucket)
  2  parse/usage errors (a file that does not parse is never clean)
"""


def main(argv: Optional[Sequence[str]] = None) -> int:
    import json

    args = list(sys.argv[1:] if argv is None else argv)
    if not args or "-h" in args or "--help" in args:
        print(__doc__)
        print(_USAGE)
        return 0 if args else 2
    fmt = "text"
    baseline_path: Optional[str] = None
    write_baseline: Optional[str] = None
    select: Optional[List[str]] = None
    want_stats = False
    paths: List[str] = []
    i = 0
    while i < len(args):
        a = args[i]
        if a.startswith("--format="):
            fmt = a.split("=", 1)[1]
            if fmt not in ("text", "json"):
                print(f"pboxlint: unknown format {fmt!r}", file=sys.stderr)
                return 2
        elif a.startswith("--select=") or (a == "--select"
                                           and i + 1 < len(args)):
            if a == "--select":
                i += 1
                raw = args[i]
            else:
                raw = a.split("=", 1)[1]
            select = [t for t in re.split(r"[,\s]+", raw) if t]
            if not select:
                print("pboxlint: --select needs at least one code",
                      file=sys.stderr)
                return 2
        elif a == "--stats":
            want_stats = True
        elif a == "--baseline" and i + 1 < len(args):
            i += 1
            baseline_path = args[i]
        elif a == "--write-baseline" and i + 1 < len(args):
            i += 1
            write_baseline = args[i]
        elif a.startswith("--"):
            print(_USAGE, file=sys.stderr)
            return 2
        else:
            paths.append(a)
        i += 1
    if not paths:
        print(_USAGE, file=sys.stderr)
        return 2

    stats: Optional[Dict[str, float]] = {} if want_stats else None
    findings, errors = lint_paths(paths, select=select, stats=stats)
    counts = baseline_counts(findings)

    new_keys: List[str] = []
    if baseline_path is not None:
        try:
            with open(baseline_path, encoding="utf-8") as f:
                base = json.load(f)
        except (OSError, ValueError) as e:
            print(f"pboxlint: cannot read baseline {baseline_path}: {e!r}",
                  file=sys.stderr)
            return 2
        base_counts = base.get("counts", base)
        if not isinstance(base_counts, dict):
            print("pboxlint: baseline has no counts object",
                  file=sys.stderr)
            return 2
        new_keys = sorted(k for k, n in counts.items()
                          if n > int(base_counts.get(k, 0)))

    if fmt == "json":
        out = {
            "findings": [dataclasses.asdict(f) for f in findings],
            "errors": [{"path": p, "error": e} for p, e in errors],
            "counts": counts,
            "new": new_keys,
        }
        if stats is not None:
            out["stats"] = {k: round(v, 4) for k, v in stats.items()}
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        for path, err in errors:
            print(f"{path}:0: PB000 parse failure: {err}")
        for f in findings:
            print(f.render())
        if stats is not None:
            total = sum(stats.values())
            for k in sorted(stats, key=stats.get, reverse=True):
                print(f"pboxlint: stats: {k:<14} {stats[k]:7.3f}s",
                      file=sys.stderr)
            print(f"pboxlint: stats: {'TOTAL':<14} {total:7.3f}s",
                  file=sys.stderr)

    if write_baseline is not None:
        with open(write_baseline, "w", encoding="utf-8") as f:
            json.dump({"counts": counts}, f, indent=2, sort_keys=True)

    if errors:
        return 2
    if baseline_path is not None:
        if new_keys:
            if fmt != "json":
                for k in new_keys:
                    print(f"pboxlint: NEW vs baseline: {k}")
                print(f"pboxlint: {len(new_keys)} new finding bucket(s)")
            return 1
        return 0
    if findings:
        if fmt != "json":
            print(f"pboxlint: {len(findings)} finding(s)")
        return 1
    return 0
