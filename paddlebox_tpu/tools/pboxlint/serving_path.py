"""PB701 — serving read-path purity (the lock-free serving contract).

The serving tier's whole guarantee (ps/serving.py) is that answering a
query can never mutate a table, contend on a shard lock, or run
optimizer math: tables are frozen at load, swaps are a reference flip,
and the read path is pure gathers.  That property is structural — one
"harmless" helper call away from silently regressing (e.g. a fallback
that upserts a missing row, or a stats helper that reuses a locked
training path) — so this rule proves it over the whole-package call
graph instead of trusting review:

  PB701  a table-mutating verb, a ``ps.host_table._Shard.lock``
         acquisition, or a ``ps.optimizer.*`` call is TRANSITIVELY
         reachable from the serving read path.

Roots are the read-path entry points: ``*_serve_read`` (the replica's
verb body) and ``lookup_rows`` (the frozen table's gather) in any
``serving`` module.  Reachability reuses the PB6xx interprocedural
machinery (``lockgraph.LockAnalysis`` over ``callgraph.PackageGraph``)
including its widening cap, so PB701's view of "reachable" is exactly
the lock analysis's.  Mutators are recognized two ways:

  * by NAME for the package's distinctive mutating verbs
    (``bulk_write`` / ``upsert`` / ``end_day`` / ``shrink`` /
    ``filter_keep`` / ``push_sparse`` / ``push_sparse_delta`` /
    ``push_dense`` / ``load_xbox``) — catches unresolved dynamic calls;
    deliberately NOT generic names (``load``/``save``/``replace`` —
    ``json.load`` and ``str.replace`` would drown the rule), those are
    matched by full qname only.
  * by resolved QNAME for the generic-named ones
    (``ShardedHostTable.save/load``, ``io.checkpoint.save_xbox``) and
    by prefix for the optimizer package.

Findings anchor in the serving module: at the offending line when the
offense is in serving code itself, else at the serving-side call site
whose chain reaches the offense (the chain is spelled out in the
message — the fix is almost always "don't call that from the read
path").

PB702 — frozen-plane immutability (the delta-patch contract).

The streamed-freshness design (FrozenHostTable.patched) only stays
zero-failed-requests because a published plane set is NEVER written:
readers enter a generation lock-free precisely because its ``_keys`` /
``_soa`` arrays cannot change under them, and a delta patch builds a NEW
object copy-on-write before the one-reference flip.  An in-place "quick
patch" (``tab._soa[f][pos] = rows`` — the obvious shortcut) would be a
data race against every in-flight reader and break bit-identity between
a patched replica and a from-scratch chain load, so:

  PB702  any assignment (plain, augmented, or through subscripts) whose
         target resolves to a ``._keys`` / ``._soa`` attribute outside
         ``__init__`` in a serving module is a finding — the
         copy-on-write patch builder (``FrozenHostTable.patched`` /
         ``restrict``) is the sanctioned mutation path; construction
         (``__init__``) is the only place the planes may be assigned.

Purely syntactic (no call graph): the planes are named consistently and
only serving modules hold FrozenHostTables, so an attribute-name match
scoped to serving files has no false-positive surface worth the
interprocedural cost.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from paddlebox_tpu.tools.pboxlint import callgraph, lockgraph
from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext)

_ROOT_NAMES = {"_serve_read", "lookup_rows"}
_SHARD_LOCK = "ps.host_table._Shard.lock"
_OPT_PREFIX = "ps.optimizer."
# distinctive mutating verb names — safe to match on the bare call name
_MUTATOR_NAMES = frozenset({
    "bulk_write", "upsert", "end_day", "shrink", "filter_keep",
    "push_sparse", "push_sparse_delta", "push_dense", "load_xbox",
})
# generic-named mutators: full resolved qname only
_MUTATOR_QNAMES = frozenset({
    "ps.host_table.ShardedHostTable.save",
    "ps.host_table.ShardedHostTable.load",
    "ps.host_table._Shard.replace",
    "io.checkpoint.save_xbox",
    "io.checkpoint.load_xbox",
})


def _is_serving_module(fn: "callgraph.FuncInfo") -> bool:
    mod = callgraph.module_name(fn.mod.path)
    return mod.rsplit(".", 1)[-1] == "serving"


def _own_body_calls(fn_node) -> List[ast.Call]:
    """Every ast.Call in the function's OWN body (nested defs excluded —
    they are their own summaries and only matter if actually called)."""
    out: List[ast.Call] = []
    stack = list(fn_node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Call):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _offenses(summary) -> List[Tuple[int, str]]:
    """(line, description) of every forbidden act in ONE function body."""
    out: List[Tuple[int, str]] = []
    for fp, line, _held in summary.acquires:
        if fp == _SHARD_LOCK:
            out.append((line, f"acquires shard lock {_SHARD_LOCK}"))
    # name-based mutator match straight off the AST: an UNRESOLVED call
    # (untyped receiver, nothing to widen to) never becomes a CallSite,
    # but `x.bulk_write(...)` is damning whatever x turns out to be
    for node in _own_body_calls(summary.fn.node):
        func = node.func
        tail = (func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else "")
        if tail in _MUTATOR_NAMES:
            out.append((node.lineno,
                        f"calls table-mutating verb {tail}()"))
    # qname match for the generic-named mutators (resolved targets only;
    # widened CHA guesses would fire on every same-named method)
    for cs in summary.fn.calls:
        if cs.kind != "call" or cs.widened \
                or cs.name in _MUTATOR_NAMES:
            continue
        for t in cs.targets:
            if t in _MUTATOR_QNAMES:
                out.append((cs.line, f"calls table-mutating {t}"))
                break
            if t.startswith(_OPT_PREFIX):
                out.append((cs.line, f"calls optimizer {t}"))
                break
    return out


def _analyze(lg: "lockgraph.LockAnalysis") -> List[Finding]:
    roots = sorted(
        q for q, s in lg.summaries.items()
        if _is_serving_module(s.fn)
        and q.rsplit(".", 1)[-1] in _ROOT_NAMES)
    if not roots:
        return []
    # BFS with parent edges (caller qname, serving-side call line) so a
    # deep offense can be anchored at the serving call site it hangs off
    parent: Dict[str, Tuple[str, int]] = {}
    seen: Set[str] = set(roots)
    stack = list(roots)
    while stack:
        q = stack.pop()
        for cs in lg.summaries[q].fn.calls:
            for t in lg._call_targets(cs):
                if t in lg.summaries and t not in seen:
                    seen.add(t)
                    parent[t] = (q, cs.line)
                    stack.append(t)

    def anchor(q: str, line: int) -> Optional[Tuple[str, int, str]]:
        """(serving qname, serving line, chain text) for offense in q."""
        chain: List[str] = []
        cur, cur_line = q, line
        while not _is_serving_module(lg.summaries[cur].fn):
            chain.append(cur)
            if cur not in parent:
                return None        # unreachable from a serving anchor
            cur, cur_line = parent[cur]
        chain.append(cur)
        return cur, cur_line, " → ".join(reversed(chain))

    findings: List[Finding] = []
    emitted: Set[Tuple[str, int, str]] = set()
    for q in sorted(seen):
        for line, desc in _offenses(lg.summaries[q]):
            anch = anchor(q, line)
            if anch is None:
                continue
            aq, aline, chain = anch
            key = (aq, aline, desc)
            if key in emitted:
                continue
            emitted.add(key)
            where = ("" if q == aq
                     else f" via {chain} ({lg.summaries[q].fn.mod.path}:"
                          f"{line})")
            findings.append(Finding(
                lg.summaries[aq].fn.mod.path, aline, "PB701",
                f"serving read path {aq} {desc}{where} — the read tier "
                f"is frozen-table + lock-free by contract; mutation, "
                f"shard locking and optimizer math belong to the "
                f"training tier (swap in a new generation instead)"))
    return findings


# -- PB702: frozen-plane immutability (syntactic) ---------------------------
_PLANES = frozenset({"_soa", "_keys"})


def _plane_write_attrs(stmt) -> List[ast.Attribute]:
    """Attribute nodes among ``stmt``'s assignment targets that resolve
    (through any number of subscript layers) to a frozen plane."""
    if isinstance(stmt, ast.Assign):
        tgts = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        tgts = [stmt.target]
    else:
        return []
    out: List[ast.Attribute] = []
    for t in tgts:
        elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
        for el in elts:
            cur = el
            while isinstance(cur, ast.Subscript):
                cur = cur.value
            if isinstance(cur, ast.Attribute) and cur.attr in _PLANES:
                out.append(cur)
    return out


def _pb702(mod: Module) -> List[Finding]:
    if mod.basename != "serving.py":
        return []
    findings: List[Finding] = []

    def walk(node, in_init: bool) -> None:
        for child in ast.iter_child_nodes(node):
            inner = in_init
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                inner = child.name == "__init__"
            if not inner:
                for att in _plane_write_attrs(child):
                    findings.append(Finding(
                        mod.path, child.lineno, "PB702",
                        f"write to frozen plane .{att.attr} outside "
                        f"__init__ — published FrozenHostTable planes "
                        f"are immutable (lock-free readers + patched-"
                        f"vs-reload bit-identity depend on it); build "
                        f"a new object via the copy-on-write patch "
                        f"builder (FrozenHostTable.patched/restrict) "
                        f"and publish it with the generation flip"))
            walk(child, inner)

    walk(mod.tree, False)
    return findings


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    cache = getattr(ctx, "_pb701", None)
    if cache is None:
        lg = getattr(ctx, "_lockgraph", None)
        if lg is None:
            lg = lockgraph.analyze(ctx.modules)
            ctx._lockgraph = lg
        cache = _analyze(lg)
        ctx._pb701 = cache
    return [f for f in cache if f.path == mod.path] + _pb702(mod)
