"""PB2xx (cont.) — key-space hygiene for observability (ps/heat.py +
utils/sketch.py discipline).

  PB208  a RAW FEATURE KEY flows into observability state:

         * package-wide — a metric/span name sink (the PB204 vocabulary:
           ``stat_*`` / ``span`` / ``start_span``) or a flight-event
           kind (``flight.record``) is built from a part whose terminal
           component is key-like (``key`` / ``keys`` / ``feasign`` /
           ``fid`` / ``slot_key`` / ``hot_key``) — a 10^11-cardinality
           key space minted into names/kinds grows the registry (or
           shreds the event taxonomy) without bound, one entry per hot
           key, or
         * in obs modules — a dict grows per key: a subscript
           store/augassign or ``setdefault`` whose index terminal is
           key-like.  Exact per-key state in the obs layer is an
           unbounded-memory bug by construction.

Key-derived observability routes through the streaming sketch types in
``utils/sketch.py`` (bounded, mergeable, decayable — count-min /
SpaceSaving / HyperLogLog via ``ps/heat.py``); sketch.py itself is the
sanctioned sink and is exempt from the dict rule.  PB204/PB206 already
flag these name sites generically as "not a bounded field"; PB208 names
the specific disease and its cure.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext, dotted_name)
from paddlebox_tpu.tools.pboxlint.metric_names import (_NAME_SINKS,
                                                       _binop_leaves,
                                                       _terminal_field)
from paddlebox_tpu.tools.pboxlint.flight_events import _record_sinks

# terminal components that denote a raw feature key (the wire/table
# vocabulary: feasign is the reference's name for a sparse feature id)
_KEY_LIKE = frozenset({"key", "keys", "feasign", "fid", "slot_key",
                       "hot_key"})

# the obs layer, where per-key dict growth is policed (basenames —
# checker snippets lint under bare filenames); sketch.py is the
# sanctioned bounded sink and deliberately absent
_OBS_BASENAMES = frozenset({"monitor.py", "trace.py", "flight.py",
                            "timeline.py", "obs_server.py", "doctor.py",
                            "intervals.py", "heat.py"})


def _key_part(node: ast.AST) -> Optional[str]:
    """The key-like terminal of a value expression, or None."""
    field = _terminal_field(node)
    return field if field in _KEY_LIKE else None


def _name_findings(mod: Module, call: ast.Call, arg: ast.AST,
                   what: str) -> List[Finding]:
    out: List[Finding] = []

    def flag(part: str) -> None:
        out.append(Finding(
            mod.path, call.lineno, "PB208",
            f"{dotted_name(call.func) or '<call>'}(...) {what} is built "
            f"from raw feature key {part!r} — a 10^11-cardinality key "
            f"space must never be minted into observability names; "
            f"route key-derived observability through the streaming "
            f"sketches (utils/sketch.py via ps/heat.py)"))

    if isinstance(arg, ast.JoinedStr):
        for part in arg.values:
            if isinstance(part, ast.FormattedValue):
                kp = _key_part(part.value)
                if kp is not None:
                    flag(kp)
        return out
    leaves = _binop_leaves(arg)
    if isinstance(arg, ast.BinOp) and leaves is not None:
        for leaf in leaves:
            if not isinstance(leaf, ast.Constant):
                kp = _key_part(leaf)
                if kp is not None:
                    flag(kp)
    return out


def _dict_findings(mod: Module) -> List[Finding]:
    """Obs-module-only: per-key dict growth (subscript store/augassign,
    ``setdefault``)."""
    out: List[Finding] = []

    def flag(lineno: int, form: str, part: str) -> None:
        out.append(Finding(
            mod.path, lineno, "PB208",
            f"{form} keyed by raw feature key {part!r} in obs code — "
            f"exact per-key state is unbounded memory by construction; "
            f"route key-derived observability through the bounded "
            f"sketch types (utils/sketch.py)"))

    for node in mod.walk():
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Subscript):
                    kp = _key_part(t.slice)
                    if kp is not None:
                        flag(node.lineno, "dict store", kp)
        elif isinstance(node, ast.Call) and node.args:
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "setdefault"):
                kp = _key_part(node.args[0])
                if kp is not None:
                    flag(node.lineno, "dict setdefault", kp)
    return out


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    findings: List[Finding] = []
    flight_sinks = _record_sinks(mod)
    for node in mod.walk():
        if not (isinstance(node, ast.Call) and node.args):
            continue
        called = dotted_name(node.func)
        if called.rsplit(".", 1)[-1] in _NAME_SINKS:
            findings.extend(_name_findings(mod, node, node.args[0],
                                           "metric/span name"))
        elif called in flight_sinks:
            findings.extend(_name_findings(mod, node, node.args[0],
                                           "flight event kind"))
    if os.path.basename(mod.path) in _OBS_BASENAMES:
        findings.extend(_dict_findings(mod))
    return findings
