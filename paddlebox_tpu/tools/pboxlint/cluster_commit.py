"""PB8xx — PS-cluster commit discipline (the 2-phase lifecycle rule).

With a sharded fleet (ps/cluster.py ServerMap), a lifecycle verb sent to
ONE shard is a cluster-consistency bug: `end_day` decays show/click on
that shard only (the table silently forks across shards), and a per-shard
`save`/`load` outside the cluster fan-out bypasses the single-MANIFEST
commit point that lets crash recovery roll every shard back together.
All such verbs must route through the ps/cluster.py helpers
(``two_phase_lifecycle`` / ``cluster_save`` / ``cluster_load``), which
degrade to the plain single-server send when n == 1 — so there is never
a reason for caller code to hand-build these frames.

  PB801  a raw wire frame carrying a cluster lifecycle verb — a
         ``_call``/``_call_attempts`` send whose request dict literal has
         ``"cmd"`` ∈ {end_day, lifecycle_prepare, lifecycle_commit,
         lifecycle_abort, save, load} — built outside ps/cluster.py.
         The 2-phase helper owns these rids (``<group>.p<k>`` /
         ``<group>.c<k>``): a hand-rolled send invents rids outside the
         pinned txn group, so a retry after partial failure stops
         deduplicating and exactly-once dies.  (``shrink``/``size`` and
         the row verbs are NOT in the set — they are shard-local by
         construction.)

  PB802  a lifecycle verb (``end_day`` / ``save`` / ``load``) invoked on
         one member of a subscripted fleet collection
         (``clients[0].end_day()``, ``servers[k].save(...)``) — the
         syntactic shape of "I picked one shard of a fleet by hand".
         Route through a single sharded client (whose methods fan out
         cluster-wide) instead.

  PB803  hand-built fleet membership: a direct ``ServerMap(...)``
         construction, or an assignment to a ``.addrs`` / ``.epoch``
         attribute, outside the sanctioned modules.  With elastic
         membership the epoch IS the routing fence — a map invented (or
         mutated) outside ps/cluster.py's ``make_server_map`` /
         ``map_from_desc`` and ps/reshard.py's cutover can carry a
         stale or colliding epoch, and every server it reaches will
         either reject the traffic (wrong_epoch) or, worse, accept
         writes addressed by a partition no one else agrees on.

  PB806  a rid-group LITERAL handed to a lifecycle/push verb from the
         trainer-fleet modules (``trainer/``, ``fleet.py``,
         ``parallel/collective.py``) whose pre-colon dedup token carries
         no ``.t<rank>`` trainer namespace.  The fleet's exactly-once
         story is per-trainer rid namespacing: rank r's replayed chunks
         may only dedup against rank r's own landed chunks, so every
         group token must be either rank-suffixed or minted by the
         sanctioned ``parallel.collective.namespaced_group()`` helper
         (whose ``rank=None`` form is the leader-failover namespace —
         the ONE sanctioned un-suffixed shape, for verbs that must stay
         exactly-once across a leader change).  A bare literal that
         spells neither is a replay-collision bug waiting for the first
         trainer restart.

``ps/cluster.py`` and ``ps/reshard.py`` (the implementations) and test
files are exempt.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext)

_SEND_NAMES = ("_call", "_call_attempts")
_CLUSTER_VERBS = ("end_day", "lifecycle_prepare", "lifecycle_commit",
                  "lifecycle_abort", "save", "load")
_MEMBER_VERBS = ("end_day", "save", "load")
_EXEMPT_PATHS = ("/ps/cluster.py", "/ps/reshard.py")
_MAP_ATTRS = ("addrs", "epoch")

# PB806 scope: the trainer-fleet modules whose rid groups MUST be
# per-trainer namespaced (or minted by namespaced_group)
_FLEET_PATHS = ("/fleet.py", "/parallel/collective.py")
_FLEET_DIRS = ("/trainer/",)
_GROUP_KWARGS = ("group", "rid_group", "rid")
_GROUP_POS_VERBS = {"pin_group": 1}    # verb -> positional index of group


def _send_name(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _frame_verb(node: ast.Call) -> Optional[str]:
    """The ``"cmd"`` value of the send's request-dict literal (first
    positional arg), when both are compile-time constants."""
    if not node.args or not isinstance(node.args[0], ast.Dict):
        return None
    for k, v in zip(node.args[0].keys, node.args[0].values):
        if isinstance(k, ast.Constant) and k.value == "cmd" \
                and isinstance(v, ast.Constant) \
                and isinstance(v.value, str):
            return v.value
    return None


def _in_fleet_scope(path: str) -> bool:
    return any(path.endswith(p) for p in _FLEET_PATHS) \
        or any(d in path for d in _FLEET_DIRS)


def _group_token_unnamespaced(node: ast.AST) -> bool:
    """True when ``node`` is a compile-time group string whose dedup
    token (text before the first ``:``) visibly lacks the ``.t<rank>``
    trainer namespace.  Names/calls (``namespaced_group(...)`` results)
    are not literals and never flag; an f-string passes as soon as a
    constant fragment shows ``.t`` before the colon."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        token = node.value.split(":", 1)[0]
        return ".t" not in token
    if isinstance(node, ast.JoinedStr):
        for part in node.values:
            if isinstance(part, ast.Constant) \
                    and isinstance(part.value, str):
                head, colon, _ = part.value.partition(":")
                if ".t" in head:
                    return False
                if colon:
                    return True          # token closed without namespace
        return True                      # no visible namespace anywhere
    return False


def _receiver_subscripted(func: ast.Attribute) -> bool:
    """True when the receiver chain picks a collection member:
    ``clients[0].end_day`` / ``fleet.servers[k].save``."""
    node = func.value
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Subscript):
            return True
        node = node.value
    return False


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    path = mod.path.replace("\\", "/")
    if any(path.endswith(p) for p in _EXEMPT_PATHS) or "/tests/" in path \
            or mod.basename.startswith("test_"):
        return []
    findings: List[Finding] = []
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        if _send_name(node.func) in _SEND_NAMES:
            verb = _frame_verb(node)
            if verb in _CLUSTER_VERBS:
                findings.append(Finding(
                    mod.path, node.lineno, "PB801",
                    f"hand-built cluster lifecycle frame (cmd={verb!r}): "
                    "route through the ps/cluster.py helpers "
                    "(two_phase_lifecycle / cluster_save / cluster_load) "
                    "— a raw single-shard send invents rids outside the "
                    "pinned txn group, so a retry after partial failure "
                    "stops deduplicating and the shards fork"))
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MEMBER_VERBS \
                and _receiver_subscripted(node.func):
            findings.append(Finding(
                mod.path, node.lineno, "PB802",
                f"lifecycle verb {node.func.attr!r} on one member of a "
                "fleet collection: with a ServerMap in scope a "
                "single-shard lifecycle send forks the cluster — call it "
                "on the sharded client (which fans out 2-phase / through "
                "the cluster MANIFEST) instead"))
        if _in_fleet_scope(path):
            group_vals = [kw.value for kw in node.keywords
                          if kw.arg in _GROUP_KWARGS]
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _GROUP_POS_VERBS:
                idx = _GROUP_POS_VERBS[node.func.attr]
                if len(node.args) > idx:
                    group_vals.append(node.args[idx])
            for gv in group_vals:
                if _group_token_unnamespaced(gv):
                    findings.append(Finding(
                        mod.path, node.lineno, "PB806",
                        "rid-group literal without a trainer namespace: "
                        "the dedup token (text before ':') must carry "
                        ".t<rank> so a restarted trainer's replay can "
                        "only dedup against its OWN landed chunks — "
                        "mint groups via parallel.collective."
                        "namespaced_group() (rank=None is the sanctioned "
                        "leader-failover namespace)"))
        if _send_name(node.func) == "ServerMap":
            findings.append(Finding(
                mod.path, node.lineno, "PB803",
                "hand-built ServerMap: construct fleet membership via "
                "ps/cluster.py make_server_map / map_from_desc (or let "
                "ps/reshard.py's cutover mint the next epoch) — a map "
                "invented here can carry a stale or colliding epoch and "
                "break the routing fence"))
    for node in mod.walk():
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Attribute) and t.attr in _MAP_ATTRS:
                findings.append(Finding(
                    mod.path, t.lineno, "PB803",
                    f"mutating membership field '.{t.attr}' in place: "
                    "ServerMaps are immutable once published — route "
                    "changes through ps/reshard.py's epoch-bumped "
                    "cutover (or make_server_map for a fresh fleet) so "
                    "every client and server agrees on the fence"))
    return findings
