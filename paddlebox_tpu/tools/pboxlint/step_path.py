"""PB301 — no full-working-set elementwise math in per-step functions.

The sparse step's cost model (ISSUE/ROADMAP item 1, BENCH step_ms split)
is that per-step math scales with the BATCH (the [P] valid occurrences /
[U] unique rows it actually touches), not with the WORKING SET ([N] pass
rows, 2M at bench geometry).  A single innocuous-looking
``jnp.where(touched, ws["show"] + g, ws["show"])`` inside a jitted step
is a full-[N] sweep per step — exactly the regression class
ps/ragged_path.py exists to eliminate, and one that creeps back silently
because the op is *correct*, just O(N) instead of O(U).

  PB301  a step-path function uses the full working-set array ``ws[...]``
         as an elementwise operand (math, comparison, non-gather call
         argument, or a non-structural attribute like ``.T``/``.astype``)
         instead of gathering rows first.

Scope is deliberately narrow — the three step-lowering modules
(``fast_path.py``, ``mxu_path.py``, ``ragged_path.py``), functions that
take the working set as a ``ws`` parameter — so the rule never fires on
host-side table code, which legitimately sweeps [N].

A ``ws[...]`` use is ALLOWED (not a finding) when it is:

  * gathered: ``ws[f][rows]`` — the ws subscript is itself indexed, so
    downstream math runs on the gathered rows, not the full array;
  * structural: ``.at`` (scatter builder), ``.shape``/``.dtype``/
    ``.ndim``/``.size`` metadata;
  * a bare argument to a gather/scatter METHOD call —
    ``tab.at[...].set(ws["show"])``, ``jnp.take(ws["w"], rows)`` — a
    relayout copy, not per-element math (func attr in ``set``/``add``/
    ``max``/``min``/``mul``/``take``);
  * a bare reference: RHS of a plain assign, a return value, a dict /
    tuple / list element (aliasing, e.g. ``out[extra] = ws[extra]``).

Everything else — BinOp / UnaryOp / Compare operands, arguments to any
other call, other attributes — is a finding, anchored at the enclosing
statement's first line (one finding per statement).  The fast/mxu paths'
documented-cheap [N] scalar sweeps carry inline
``# pboxlint: disable-next=PB301 -- why`` suppressions; anything new
must either gather first or argue its own suppression in review.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext)

_STEP_MODULES = frozenset({"fast_path.py", "mxu_path.py", "ragged_path.py"})
# metadata / scatter-builder attributes on ws[...] that touch no elements
_STRUCTURAL_ATTRS = frozenset({"at", "shape", "dtype", "ndim", "size"})
# gather/scatter method calls a bare ws[...] may feed (relayout, not math)
_MOVE_METHODS = frozenset({"set", "add", "max", "min", "mul", "take"})


def _parents(fn: ast.AST) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    stack = [fn]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            out[child] = node
            stack.append(child)
    return out


def _is_ws_subscript(node: ast.AST) -> bool:
    return (isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and node.value.id == "ws")


def _allowed(node: ast.Subscript, parent: ast.AST) -> bool:
    """True when this ws[...] use is structurally safe (see docstring)."""
    if isinstance(parent, ast.Subscript) and parent.value is node:
        return True                     # gathered: ws[f][rows]
    if isinstance(parent, ast.Attribute) and parent.value is node:
        return parent.attr in _STRUCTURAL_ATTRS
    if isinstance(parent, ast.Call) and node in parent.args:
        func = parent.func
        tail = (func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else "")
        return tail in _MOVE_METHODS    # .at[..].set(ws[..]) / take(ws[..])
    if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.Return,
                           ast.Dict, ast.Tuple, ast.List, ast.Starred)):
        return True                     # bare alias / collection element
    return False


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    if mod.basename not in _STEP_MODULES:
        return []
    findings: List[Finding] = []
    for fn in mod.nodes_of(ast.FunctionDef):
        args = fn.args
        names = {a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)}
        if "ws" not in names:
            continue
        parents = _parents(fn)
        seen_lines: set = set()
        for node in ast.walk(fn):
            if not _is_ws_subscript(node) or node not in parents:
                continue
            if _allowed(node, parents[node]):
                continue
            # anchor at the enclosing statement's first line so multiline
            # expressions dedupe and disable-next comments land
            stmt = node
            while stmt in parents and not isinstance(stmt, ast.stmt):
                stmt = parents[stmt]
            line = stmt.lineno if isinstance(stmt, ast.stmt) else node.lineno
            if line in seen_lines:
                continue
            seen_lines.add(line)
            key = (node.slice.value
                   if isinstance(node.slice, ast.Constant) else "...")
            findings.append(Finding(
                mod.path, line, "PB301",
                f"per-step function {fn.name}() uses full working-set "
                f"array ws[{key!r}] as an elementwise operand — a per-step "
                f"O(N) sweep over the whole pass working set; gather the "
                f"touched rows first and do the math in the [U]/[P] domain "
                f"(ps/ragged_path.py), or document the cost with a "
                f"disable-next suppression"))
    return findings
