"""PB9xx — guarded-by inference + whole-program data-race detection.

The Eraser recipe (Savage et al.), run statically over the package: for
every class attribute, collect every load/store site together with the
set of locks held at that site, and **infer the guarding lock as the
intersection of the locksets at post-construction mutation sites**.  A
field whose locked mutation sites agree on a lock is *guarded*; accesses
that break the discipline are the race classes:

  PB901  write with an empty/inconsistent lockset on a field that is
         guarded elsewhere — the classic lost-update/torn-invariant
         write.  An explicit ``# pboxlint: guarded-by=pkg.Cls._lock``
         annotation (on the field's assignment line, or on a class-body
         declaration) overrides inference and makes EVERY unguarded
         write a finding.
  PB902  read of a multi-word invariant outside its lock: two fields
         co-mutated inside one critical section form an invariant; a
         function reading both with the lock not held can observe the
         torn intermediate state.
  PB903  escape of a guarded container/array reference out of its
         critical section — ``return self._rows`` hands the caller a
         live alias that outlives the lock; return a copy or a frozen
         view instead.
  PB904  thread-spawned callable (``Thread(target=)``, ``pool.submit``,
         ``pool.map``) that reaches a write or container access of a
         guarded field with no lock held on any path from the spawn —
         the caller's locks never flow into a spawned task.

Locksets are interprocedural: a function's *entry-held* set is the
intersection (meet) over every in-package call site of the caller's
lockset there, so a private helper only ever called under the table
lock analyzes as holding it.  Spawn edges contribute the empty set
(a new thread starts with nothing), and dynamic calls WIDEN (CHA over
same-named methods, capped like lockgraph) — the caller's held-set is
never dropped through a call the resolver cannot pin down.

Soundness model — benign publication idioms that must NOT be findings:

  * constructor-only writes: ``__init__``/``__new__`` and private
    helpers reachable only from them run before the instance is shared;
    their writes neither infer guards nor violate them.
  * immutable-after-publish (freeze points): a field never mutated
    after construction has no mutation sites, hence no guard and no
    findings — ``FrozenHostTable``-style objects are clean by
    construction.
  * atomic-flag idioms: a bare store of a literal ``True``/``False``/
    ``None`` is a single-word publish (atomic under the GIL) and is not
    a PB901 unless the field carries an explicit guarded-by annotation.
  * single-word bare reads are snapshots (GIL-atomic reference loads)
    — only multi-word reads (PB902) and container traffic race.
  * ``threading.local()`` fields are per-thread by definition.

The inferred map doubles as the **runtime contract**: ``guard_map()``
exports ``{"ps.service.PSServer._staged": ["ps.service.PSServer.
_staged_lock"], ...}`` in the same class-fingerprint namespace the
``utils/lockdep.py`` guards witness reports, so tier-1 can assert every
runtime-observed (site, held-locks) pair is contained in the static map
— the cross-validation contract that made PB6xx trustworthy.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from paddlebox_tpu.tools.pboxlint import callgraph, lockgraph
from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext, dotted_name)

_GUARDED_BY_RE = re.compile(
    r"#\s*pboxlint:\s*guarded-by\s*=\s*(?P<fp>[A-Za-z0-9_.]+)")

# container constructors whose product is a mutable shared structure —
# the PB903 escape classes (numpy arrays included: views alias storage)
_CONTAINER_CTORS = {"list", "dict", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter", "bytearray",
                    "zeros", "empty", "ones", "full", "array", "arange"}
# calls that produce a fresh object — returning these is NOT an escape
_COPY_CALLS = {"list", "dict", "set", "tuple", "sorted", "frozenset",
               "bytes", "copy", "deepcopy", "min", "max", "sum", "len"}
_MUTATOR_METHODS = {"append", "extend", "insert", "add", "update",
                    "setdefault", "pop", "popleft", "popitem", "remove",
                    "discard", "clear", "sort", "reverse", "fill"}

_WIDEN_FANOUT_CAP = lockgraph._WIDEN_FANOUT_CAP


@dataclasses.dataclass
class _Access:
    """One load/store of ``<recv>.<attr>`` where recv's class is known."""
    cq: str                    # receiver class qname ("ps.host_table._Shard")
    attr: str
    line: int
    kind: str                  # "read" | "write"
    held: Tuple[str, ...]      # locks held LOCALLY at the site (fixpoint
    #                            adds the function's entry-held set)
    const_store: bool = False  # write of a literal True/False/None
    container_op: bool = False  # subscript store / mutator-method / iteration


@dataclasses.dataclass
class _Escape:
    """``return self.X`` / ``yield self.X`` of the bare reference."""
    cq: str
    attr: str
    line: int


class _FnAccesses:
    def __init__(self) -> None:
        self.accesses: List[_Access] = []
        self.escapes: List[_Escape] = []


class _AccessWalker(ast.NodeVisitor):
    """lockgraph's W-visitor shape, tracking held locks through ``with``
    blocks, but recording attribute loads/stores instead of call sites.
    Nested defs are their own summaries and are skipped."""

    def __init__(self, analysis: "RaceAnalysis", fn: "callgraph.FuncInfo"):
        self.an = analysis
        self.fn = fn
        self.local_types = analysis.la.graph._local_types(fn)
        self.out = _FnAccesses()
        self.held: List[str] = []
        # escape-analysis lite: a local assigned a fresh package-class
        # ctor IN THIS BODY is unshared — accesses through it cannot
        # race and must not pollute guard inference
        self.fresh: Set[str] = set()
        classes = analysis.la.graph.class_by_name
        for node in ast.walk(fn.node):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                tail = dotted_name(node.value.func).rsplit(".", 1)[-1]
                if tail in classes:
                    self.fresh.add(node.targets[0].id)

    # -- receiver resolution -----------------------------------------------
    def _recv(self, node: ast.AST) -> Optional[Tuple[str, str]]:
        """``<name>.<attr>`` → (class qname, attr) when the receiver's
        class is known (self, or a ctor/attr-typed local)."""
        if not (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)):
            return None
        base = node.value.id
        if base in self.fresh:
            return None             # unshared fresh object: cannot race
        if self.fn.cls is not None and base == self.fn.self_name:
            return self.fn.cls.qname, node.attr
        t = self.local_types.get(base)
        if t is not None:
            return t, node.attr
        return None

    def _record(self, node: ast.AST, kind: str, *, const_store: bool = False,
                container_op: bool = False) -> None:
        rv = self._recv(node)
        if rv is None:
            return
        cq, attr = rv
        self.out.accesses.append(_Access(
            cq, attr, node.lineno, kind, tuple(self.held),
            const_store=const_store, container_op=container_op))

    # -- lock context --------------------------------------------------------
    def _ld(self, expr: ast.AST) -> Optional[lockgraph.LockDef]:
        return self.an.la._lock_expr(self.fn, expr, self.local_types)

    def visit_With(self, node: ast.With) -> None:
        n = 0
        for item in node.items:
            ld = self._ld(item.context_expr)
            if ld is None:
                self.visit(item.context_expr)
            else:
                self.held.append(ld.fp)
                n += 1
        for stmt in node.body:
            self.visit(stmt)
        if n:
            del self.held[len(self.held) - n:]

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node) -> None:
        pass                        # nested defs get their own walk

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    # -- stores --------------------------------------------------------------
    def _store_target(self, target: ast.AST, value: ast.AST) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._store_target(elt, value)
            return
        if isinstance(target, ast.Starred):
            self._store_target(target.value, value)
            return
        if isinstance(target, ast.Subscript):
            # self.X[...] = v mutates the container X in place
            self._record(target.value, "write", container_op=True)
            self.visit(target.slice)
            return
        const = isinstance(value, ast.Constant) \
            and (value.value is None or isinstance(value.value, bool))
        self._record(target, "write", const_store=const)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._store_target(t, node.value)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._store_target(node.target, node.value)
            self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.target, ast.Subscript):
            self._record(node.target.value, "read")
            self._record(node.target.value, "write", container_op=True)
            self.visit(node.target.slice)
        else:
            self._record(node.target, "read")
            self._record(node.target, "write")
        self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self._record(t.value, "write", container_op=True)

    # -- loads / calls / escapes --------------------------------------------
    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load):
            self._record(node, "read")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATOR_METHODS:
            self._record(node.func.value, "write", container_op=True)
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        # iterating a container while another thread mutates it is the
        # dict-changed-size crash class — record as a container read
        self._record(node.iter, "read", container_op=True)
        self.generic_visit(node)

    def _escape_value(self, value: Optional[ast.AST]) -> None:
        rv = self._recv(value) if value is not None else None
        if rv is not None:
            self.out.escapes.append(_Escape(rv[0], rv[1], value.lineno))

    def visit_Return(self, node: ast.Return) -> None:
        self._escape_value(node.value)
        if node.value is not None:
            self.visit(node.value)

    def visit_Yield(self, node: ast.Yield) -> None:
        self._escape_value(node.value)
        if node.value is not None:
            self.visit(node.value)


@dataclasses.dataclass
class FieldInfo:
    """Everything known about one (owner-class, attr) field."""
    cq: str
    attr: str
    guard: FrozenSet[str] = frozenset()
    annotated: bool = False
    inconsistent: bool = False     # locked sites disagree on the lock
    container: bool = False
    thread_local: bool = False
    writes: List[Tuple[str, "_Access", FrozenSet[str]]] = \
        dataclasses.field(default_factory=list)   # (fn q, acc, full lockset)
    reads: List[Tuple[str, "_Access", FrozenSet[str]]] = \
        dataclasses.field(default_factory=list)

    @property
    def site(self) -> str:
        return f"{self.cq}.{self.attr}"


class RaceAnalysis:
    """Whole-package PB9xx result on top of a shared LockAnalysis."""

    def __init__(self, la: lockgraph.LockAnalysis):
        self.la = la
        self.graph = la.graph
        self.fn_acc: Dict[str, _FnAccesses] = {}
        self.entry: Dict[str, FrozenSet[str]] = {}
        self.fields: Dict[Tuple[str, str], FieldInfo] = {}
        self.findings: List[Finding] = []
        self._annotations: Dict[Tuple[str, str], Set[str]] = {}
        self._containers: Dict[str, Set[str]] = {}
        self._locals_cls: Dict[str, Set[str]] = {}   # threading.local attrs
        self._init_only: Dict[str, Set[str]] = {}
        self._init_ctx_cache: Dict[str, bool] = {}
        self._owner_key: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self._scan_classes()
        for q, fn in self.graph.functions.items():
            w = _AccessWalker(self, fn)
            for stmt in fn.node.body:
                w.visit(stmt)
            self.fn_acc[q] = w.out
        self._entry_fixpoint()
        self._build_fields()
        self._infer_guards()
        self._pb901_sites: Set[Tuple[str, int, str]] = set()
        self._check_pb901()
        self._check_pb902()
        self._check_pb903()
        self._check_pb904()
        self.findings.sort(key=lambda f: (f.path, f.line, f.code))

    # ------------------------------------------------------ class scanning
    def _scan_classes(self) -> None:
        for cq, cls in self.graph.classes.items():
            containers: Set[str] = set()
            tlocals: Set[str] = set()
            for fi in cls.methods.values():
                self_name = fi.self_name or "self"
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        if not (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == self_name):
                            continue
                        tail = ""
                        if isinstance(node.value, ast.Call):
                            tail = dotted_name(
                                node.value.func).rsplit(".", 1)[-1]
                        if isinstance(node.value, (ast.List, ast.Dict,
                                                   ast.Set, ast.ListComp,
                                                   ast.DictComp,
                                                   ast.SetComp)) \
                                or tail in _CONTAINER_CTORS:
                            containers.add(t.attr)
                        if tail == "local" and isinstance(node.value,
                                                          ast.Call) \
                                and dotted_name(node.value.func) in (
                                    "threading.local", "local"):
                            tlocals.add(t.attr)
            self._containers[cq] = containers
            self._locals_cls[cq] = tlocals
            self._init_only[cq] = self._init_only_methods(cls)
            self._scan_annotations(cls)

    @staticmethod
    def _init_only_methods(cls: "callgraph.ClassInfo") -> Set[str]:
        """__init__/__new__ plus private helpers called only from the
        init set (pre-publication builders) — same rule as PB1xx."""
        calls: Dict[str, Set[str]] = {}
        for name, fi in cls.methods.items():
            callees: Set[str] = set()
            self_name = fi.self_name or "self"
            for node in ast.walk(fi.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == self_name):
                    callees.add(node.func.attr)
            calls[name] = callees
        out = {"__init__", "__new__"}
        callers: Dict[str, Set[str]] = {n: set() for n in cls.methods}
        for name, callees in calls.items():
            for c in callees:
                if c in callers:
                    callers[c].add(name)
        changed = True
        while changed:
            changed = False
            for name, who in callers.items():
                if (name not in out and name.startswith("_")
                        and not name.startswith("__")
                        and who and who <= out):
                    out.add(name)
                    changed = True
        return out

    def _scan_annotations(self, cls: "callgraph.ClassInfo") -> None:
        """``# pboxlint: guarded-by=<fp>`` on a line that assigns (or
        declares, class-body AnnAssign) ``self.<attr>`` / ``attr``."""
        mod = cls.mod
        annotated_lines: Dict[int, str] = {}
        for lineno, text in enumerate(mod.source.splitlines(), 1):
            m = _GUARDED_BY_RE.search(text)
            if m:
                annotated_lines[lineno] = m.group("fp")
        if not annotated_lines:
            return
        for stmt in cls.node.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)) \
                    and stmt.lineno in annotated_lines:
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        self._annotations.setdefault(
                            (cls.qname, t.id), set()).add(
                                annotated_lines[stmt.lineno])
        for fi in cls.methods.values():
            self_name = fi.self_name or "self"
            for node in ast.walk(fi.node):
                if not (isinstance(node, (ast.Assign, ast.AnnAssign))
                        and node.lineno in annotated_lines):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == self_name):
                        self._annotations.setdefault(
                            (cls.qname, t.attr), set()).add(
                                annotated_lines[node.lineno])

    # ------------------------------------------------------ entry fixpoint
    def _prop_targets(self, cs: "callgraph.CallSite") -> Tuple[str, ...]:
        """Call targets the caller's lockset flows into.  Spawn targets
        run on a fresh thread — they contribute ∅ to the meet instead.
        Widened calls propagate (the held-set is never dropped) unless
        the CHA fan-out exceeds the cap."""
        if cs.kind != "call":
            return ()
        if cs.widened and len(cs.targets) > _WIDEN_FANOUT_CAP:
            return ()
        return cs.targets

    def _entry_fixpoint(self) -> None:
        incoming: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}
        spawn_roots: Set[str] = set()
        for q, s in self.la.summaries.items():
            for cs in s.fn.calls:
                held = s.call_held.get(id(cs.node), ())
                if cs.kind == "spawn":
                    spawn_roots.update(cs.targets)
                    continue
                if self._init_ctx(q):
                    # pre-publication call: the constructing thread owns
                    # the object exclusively, so the call site's (lack
                    # of) locks says nothing about the steady state
                    continue
                for t in self._prop_targets(cs):
                    incoming.setdefault(t, []).append((q, held))
        # descending meet over call edges, ⊤ as a distinct sentinel (NOT
        # the set of all locks — in a one-lock module a legitimate meet
        # can equal that set and must survive)
        top = object()
        entry: Dict[str, object] = {}
        for q in self.la.summaries:
            if q in incoming and q not in spawn_roots:
                entry[q] = top
            else:
                entry[q] = frozenset()
        changed = True
        while changed:
            changed = False
            for q, edges in incoming.items():
                if q in spawn_roots:
                    continue
                met: object = top
                for caller, held in edges:
                    ce = entry.get(caller, frozenset())
                    if ce is top:
                        continue          # ⊤ caller: identity for the meet
                    have = ce | frozenset(held)
                    met = have if met is top else (met & have)
                if met is not top and met != entry[q]:
                    entry[q] = met
                    changed = True
        # a call cycle with no root caller never shrinks from ⊤ — treat
        # its sites as lockset-unknown (∅) rather than held-everything
        self.entry = {q: (frozenset() if e is top else e)
                      for q, e in entry.items()}

    # ------------------------------------------------------------ field db
    def _owner(self, cq: str, attr: str,
               touched: Set[Tuple[str, str]]) -> str:
        """Topmost package ancestor that also touches ``attr`` — a
        subclass writing an inherited field shares the base's identity."""
        best = cq
        stack = list(self.graph.classes.get(cq, _NO_CLS).bases)
        seen = {cq}
        while stack:
            b = stack.pop()
            if b in seen or b not in self.graph.classes:
                continue
            seen.add(b)
            if (b, attr) in touched or attr in self._annotations_cls(b):
                best = b
            stack.extend(self.graph.classes[b].bases)
        return best

    def _annotations_cls(self, cq: str) -> Set[str]:
        return {a for (c, a) in self._annotations if c == cq}

    def _is_method(self, cq: str, attr: str) -> bool:
        seen: Set[str] = set()
        stack = [cq]
        while stack:
            q = stack.pop()
            if q in seen or q not in self.graph.classes:
                continue
            seen.add(q)
            if attr in self.graph.classes[q].methods:
                return True
            stack.extend(self.graph.classes[q].bases)
        return False

    def _is_lock_attr(self, cq: str, attr: str) -> bool:
        return self.la._class_lock(cq, attr) is not None

    def _init_ctx(self, q: str) -> bool:
        """Does function ``q`` run pre-publication — an ``__init__``/
        ``__new__``, a private helper reachable only from one, or a
        closure nested inside either?"""
        cached = self._init_ctx_cache.get(q)
        if cached is not None:
            return cached
        out = False
        tail = q.rsplit(".", 1)[-1]
        if tail in ("__init__", "__new__"):
            out = True
        else:
            for owner_cq, init_set in self._init_only.items():
                for name in init_set:
                    mq = f"{owner_cq}.{name}"
                    if q == mq or q.startswith(mq + "."):
                        out = True
                        break
                if out:
                    break
        self._init_ctx_cache[q] = out
        return out

    def _build_fields(self) -> None:
        touched: Set[Tuple[str, str]] = set()
        for out in self.fn_acc.values():
            for acc in out.accesses:
                touched.add((acc.cq, acc.attr))
        for q, out in self.fn_acc.items():
            ent = self.entry.get(q, frozenset())
            for acc in out.accesses:
                if self._is_lock_attr(acc.cq, acc.attr) \
                        or self._is_method(acc.cq, acc.attr) \
                        or acc.attr.startswith("__"):
                    continue
                owner = self._owner(acc.cq, acc.attr, touched)
                key = (owner, acc.attr)
                self._owner_key[(acc.cq, acc.attr)] = key
                fi = self.fields.get(key)
                if fi is None:
                    fi = self.fields[key] = FieldInfo(owner, acc.attr)
                    fi.container = acc.attr in self._containers.get(
                        owner, ()) or acc.attr in self._containers.get(
                            acc.cq, ())
                    fi.thread_local = acc.attr in self._locals_cls.get(
                        owner, ()) or acc.attr in self._locals_cls.get(
                            acc.cq, ())
                full = frozenset(acc.held) | ent
                if acc.kind == "write":
                    fi.writes.append((q, acc, full))
                else:
                    fi.reads.append((q, acc, full))

    def _post_ctor_writes(self, fi: FieldInfo):
        return [(q, acc, full) for q, acc, full in fi.writes
                if not self._init_ctx(q)]

    def _infer_guards(self) -> None:
        for key, fi in self.fields.items():
            ann = self._annotations.get(key)
            if ann:
                fi.guard = frozenset(ann)
                fi.annotated = True
                continue
            if fi.thread_local:
                continue
            post = self._post_ctor_writes(fi)
            locked = [full for _q, _a, full in post if full]
            # the discipline must be the RULE, not the exception: a
            # guard is inferred only when locked mutation sites are the
            # strict majority — one incidental locked path (e.g. a
            # wrapper serializing an otherwise main-thread object under
            # ITS lock) does not define a discipline for the field
            if not locked or len(locked) * 2 <= len(post):
                continue
            meet = frozenset.intersection(*locked)
            if meet:
                fi.guard = meet
            else:
                # locked sites disagree — pick the lock covering the
                # most mutation sites as the candidate guard and call
                # the discipline inconsistent
                count: Dict[str, int] = {}
                for full in locked:
                    for fp in full:
                        count[fp] = count.get(fp, 0) + 1
                best = max(sorted(count), key=lambda fp: count[fp])
                fi.guard = frozenset([best])
                fi.inconsistent = True

    # ------------------------------------------------------------ checkers
    def _path_line(self, q: str, acc: _Access) -> Tuple[str, int]:
        return self.la.summaries[q].fn.mod.path, acc.line

    def _check_pb901(self) -> None:
        for key in sorted(self.fields):
            fi = self.fields[key]
            if not fi.guard or fi.thread_local:
                continue
            guarded_at = next(
                (self._path_line(q, a)
                 for q, a, full in self._post_ctor_writes(fi)
                 if fi.guard <= full), None)
            for q, acc, full in self._post_ctor_writes(fi):
                if fi.guard <= full:
                    continue
                if acc.const_store and not fi.annotated:
                    continue        # atomic-flag publish
                path, line = self._path_line(q, acc)
                why = ("declared guarded-by " if fi.annotated else
                       "inconsistently locked — candidate guard "
                       if fi.inconsistent else "mutated under ")
                wit = (f" (e.g. {guarded_at[0]}:{guarded_at[1]})"
                       if guarded_at else "")
                self._pb901_sites.add((path, line, fi.attr))
                self.findings.append(Finding(
                    path, line, "PB901",
                    f"{fi.site} written here holding "
                    f"{{{', '.join(sorted(full)) or 'nothing'}}} but "
                    f"{why}{'+'.join(sorted(fi.guard))} elsewhere{wit} — "
                    f"a concurrent writer tears the field; take the "
                    f"guard or annotate/redesign the publication"))

    def _invariant_groups(self) -> Dict[Tuple[str, str, str], str]:
        """{(owner cq, attrA, attrB) → lock}: pairs of fields of one
        class co-mutated inside one function while sharing a guard lock
        that IS both fields' inferred guard."""
        groups: Dict[Tuple[str, str, str], str] = {}
        for q, out in self.fn_acc.items():
            by_cls: Dict[str, List[_Access]] = {}
            for acc in out.accesses:
                if acc.kind == "write" and acc.held:
                    by_cls.setdefault(acc.cq, []).append(acc)
            for cq, accs in by_cls.items():
                attrs = sorted({a.attr for a in accs})
                for i, a1 in enumerate(attrs):
                    for a2 in attrs[i + 1:]:
                        f1 = self._field_of(cq, a1)
                        f2 = self._field_of(cq, a2)
                        if f1 is None or f2 is None:
                            continue
                        common = (f1.guard & f2.guard
                                  & frozenset(h for a in accs if a.attr == a1
                                              for h in a.held)
                                  & frozenset(h for a in accs if a.attr == a2
                                              for h in a.held))
                        if common and not (f1.inconsistent
                                           or f2.inconsistent):
                            groups[(f1.cq, min(a1, a2), max(a1, a2))] = \
                                sorted(common)[0]
        return groups

    def _field_of(self, cq: str, attr: str) -> Optional[FieldInfo]:
        key = self._owner_key.get((cq, attr))
        return self.fields.get(key) if key is not None else None

    def _check_pb902(self) -> None:
        groups = self._invariant_groups()
        reported: Set[Tuple[str, int]] = set()
        for (cq, a1, a2), lock in sorted(groups.items()):
            for q, out in sorted(self.fn_acc.items()):
                if self._init_ctx(q):
                    continue
                ent = self.entry.get(q, frozenset())
                bare: Dict[str, _Access] = {}
                for acc in out.accesses:
                    fi = self._field_of(acc.cq, acc.attr)
                    if fi is None or fi.cq != cq \
                            or acc.attr not in (a1, a2):
                        continue
                    full = frozenset(acc.held) | ent
                    if acc.kind == "read" and lock not in full:
                        bare.setdefault(acc.attr, acc)
                    elif lock in full:
                        bare.clear()    # this fn does lock; mixed —
                        break           # trust the locked region
                if len(bare) == 2:
                    acc = max(bare.values(), key=lambda a: a.line)
                    path, line = self._path_line(q, acc)
                    if (path, line) in reported:
                        continue
                    reported.add((path, line))
                    self.findings.append(Finding(
                        path, line, "PB902",
                        f"{cq}.{a1}/{a2} form a multi-word invariant "
                        f"(co-mutated under {lock}) but are read here "
                        f"with it not held — a concurrent mutation is "
                        f"observable mid-update; read both under the "
                        f"lock or snapshot them together"))

    def _check_pb903(self) -> None:
        for q, out in sorted(self.fn_acc.items()):
            for esc in out.escapes:
                fi = self._field_of(esc.cq, esc.attr)
                if fi is None or not fi.guard or not fi.container \
                        or fi.thread_local:
                    continue
                if self._init_ctx(q):
                    continue
                path = self.la.summaries[q].fn.mod.path
                self.findings.append(Finding(
                    path, esc.line, "PB903",
                    f"{fi.site} is a container guarded by "
                    f"{'+'.join(sorted(fi.guard))} but its bare "
                    f"reference escapes here — the caller aliases live "
                    f"mutable state outside the critical section; "
                    f"return a copy (list()/dict()/.copy()) or a "
                    f"frozen view"))

    def _check_pb904(self) -> None:
        spawn_sites: List[Tuple[str, "callgraph.CallSite"]] = []
        for q, s in self.la.summaries.items():
            for cs in s.fn.calls:
                if cs.kind == "spawn":
                    spawn_sites.append((q, cs))
        reported: Set[Tuple[str, int, str]] = set()
        for q, cs in sorted(spawn_sites, key=lambda t: (t[0], t[1].line)):
            for t in cs.targets:
                self._walk_spawn(t, frozenset(), set(), reported)

    def _walk_spawn(self, q: str, held: FrozenSet[str],
                    seen: Set[Tuple[str, FrozenSet[str]]],
                    reported: Set[Tuple[str, int, str]]) -> None:
        key = (q, held)
        if key in seen or q not in self.fn_acc:
            return
        seen.add(key)
        out = self.fn_acc[q]
        # constructing a fresh object ON the spawned thread is still
        # pre-publication — skip init-context accesses, walk their calls
        accesses = () if self._init_ctx(q) else out.accesses
        for acc in accesses:
            fi = self._field_of(acc.cq, acc.attr)
            if fi is None or not fi.guard or fi.thread_local \
                    or fi.inconsistent:
                continue
            full = held | frozenset(acc.held)
            if fi.guard & full:
                continue
            # single-word bare reads are GIL-atomic snapshots; what
            # races on a spawn path is a write or container traffic
            if acc.kind != "write" and not acc.container_op:
                continue
            if acc.const_store and not fi.annotated:
                continue
            path, line = self._path_line(q, acc)
            if (path, line, acc.attr) in reported \
                    or (path, line, acc.attr) in self._pb901_sites:
                continue
            reported.add((path, line, acc.attr))
            self.findings.append(Finding(
                path, line, "PB904",
                f"thread-spawned path reaches this "
                f"{'write to' if acc.kind == 'write' else 'traversal of'}"
                f" {fi.site} with no lock held (guard "
                f"{'+'.join(sorted(fi.guard))}) — the spawner's locks "
                f"never flow into a new thread; take the guard inside "
                f"the task"))
        s = self.la.summaries.get(q)
        if s is None:
            return
        for cs in s.fn.calls:
            site_held = held | frozenset(
                s.call_held.get(id(cs.node), ()))
            for t in self._prop_targets(cs):
                self._walk_spawn(t, site_held, seen, reported)

    # ------------------------------------------------------------- exports
    def guard_map(self) -> Dict[str, List[str]]:
        """{field site → sorted guard fingerprints} — the static half of
        the lockdep.guards() runtime containment contract."""
        return {fi.site: sorted(fi.guard)
                for fi in self.fields.values()
                if fi.guard and not fi.inconsistent}


class _NoCls:
    bases: List[str] = []


_NO_CLS = _NoCls()


def analyze(modules: Sequence[Module]) -> RaceAnalysis:
    return RaceAnalysis(lockgraph.analyze(modules))


def analyze_paths(paths: Sequence[str]) -> RaceAnalysis:
    """Convenience for tests & the runtime cross-validation soak."""
    from paddlebox_tpu.tools.pboxlint.core import iter_py_files
    mods = []
    for p in iter_py_files(paths):
        with open(p, encoding="utf-8") as f:
            mods.append(Module(p, f.read()))
    return analyze(mods)


def guard_map_paths(paths: Sequence[str]) -> Dict[str, List[str]]:
    return analyze_paths(paths).guard_map()


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    la = getattr(ctx, "_lockgraph", None)
    if la is None:
        la = lockgraph.analyze(ctx.modules)
        ctx._lockgraph = la             # shared with lockgraph.check
    cache = getattr(ctx, "_raceguard", None)
    if cache is None:
        cache = RaceAnalysis(la)
        ctx._raceguard = cache
    return [f for f in cache.findings if f.path == mod.path]
