"""rank_attention op (≙ operators/rank_attention_op.{cc,cu} +
rank_attention.cu.h kernels expand_input_by_rank_kernel :28 and
expand_rank_attention_param_kernel :67).

Semantics: each instance carries its own rank (1-based; 0 = absent) and up to
``max_rank`` peer entries (rank, input-row-index) in ``rank_offset``
[B, 1 + 2*max_rank].  The op selects, per (own_rank, peer_rank) pair, a
parameter block [in_col, out_col] from rank_param (laid out
[max_rank*max_rank*in_col, out_col], block id = own*max_rank + peer — the
``start = lower*max_rank + faster`` addressing at rank_attention.cu.h:90),
gathers the peer input rows, and contracts:
    out[b] = Σ_k  x[index_bk] @ P[own_b, peer_bk]
TPU-first: instead of materializing the expanded [B, max_rank*in_col] input
and parameter copies (InputHelp/ParamHelp workspaces), one batched einsum —
gathers feed the MXU directly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rank_attention(x: jnp.ndarray, rank_offset: jnp.ndarray,
                   rank_param: jnp.ndarray, max_rank: int = 3):
    """x [B, in_col]; rank_offset [B, 1+2*max_rank] int32;
    rank_param [max_rank*max_rank*in_col, out_col].
    → (out [B, out_col], ins_rank [B])."""
    B, in_col = x.shape
    out_col = rank_param.shape[-1]
    param = rank_param.reshape(max_rank * max_rank, in_col, out_col)

    own = rank_offset[:, 0] - 1                       # [B]
    peer = rank_offset[:, 1::2] - 1                   # [B, K]
    index = rank_offset[:, 2::2]                      # [B, K]
    valid = (own[:, None] >= 0) & (peer >= 0)         # [B, K]

    xin = x[jnp.clip(index, 0, B - 1)]                # [B, K, in_col]
    block_id = jnp.clip(own[:, None], 0, max_rank - 1) * max_rank \
        + jnp.clip(peer, 0, max_rank - 1)
    blocks = param[block_id]                          # [B, K, in_col, out_col]
    w = valid.astype(x.dtype)[..., None]
    out = jnp.einsum("bki,bkio->bo", xin * w, blocks)
    ins_rank = rank_offset[:, 0].astype(x.dtype)
    return out, ins_rank


def batch_fc(x: jnp.ndarray, w: jnp.ndarray, bias: jnp.ndarray) -> jnp.ndarray:
    """≙ operators/batch_fc_op.cu: per-slot batched FC.
    x [S, B, in], w [S, in, out], bias [S, out] → [S, B, out]."""
    return jnp.einsum("sbi,sio->sbo", x, w) + bias[:, None, :]
