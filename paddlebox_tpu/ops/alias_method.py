"""Alias-method discrete sampling (≙ operators/alias_method_op.{cc,cu,h}:
Walker's alias method for O(1) draws from a discrete distribution — used by
PaddleBox models for negative sampling).

TPU-first split: the alias table build is host-side numpy (once per
distribution change); sampling is a jit-able two-gather + select, so it runs
inside the train step at full vector width.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp


def build_alias_table(probs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """probs [K] (unnormalized ok) → (accept [K] f32, alias [K] i32)."""
    p = np.asarray(probs, np.float64)
    p = p / p.sum()
    K = len(p)
    accept = np.zeros(K, np.float32)
    alias = np.zeros(K, np.int32)
    scaled = p * K
    small = [i for i in range(K) if scaled[i] < 1.0]
    large = [i for i in range(K) if scaled[i] >= 1.0]
    while small and large:
        s = small.pop()
        l = large.pop()
        accept[s] = scaled[s]
        alias[s] = l
        scaled[l] = scaled[l] - (1.0 - scaled[s])
        (small if scaled[l] < 1.0 else large).append(l)
    for i in large + small:
        accept[i] = 1.0
        alias[i] = i
    return accept, alias


def alias_sample(key, accept: jnp.ndarray, alias: jnp.ndarray,
                 shape: Tuple[int, ...]) -> jnp.ndarray:
    """Draw samples ~ the distribution encoded by (accept, alias)."""
    K = accept.shape[0]
    k1, k2 = jax.random.split(key)
    col = jax.random.randint(k1, shape, 0, K)
    u = jax.random.uniform(k2, shape)
    return jnp.where(u < accept[col], col, alias[col]).astype(jnp.int32)
