"""fused_seqpool_cvm op-family variants: tradew / with_conv / with_credit /
with_diff_thres / with_pcoc.

≙ operators/fused/fused_seqpool_cvm_{tradew,with_conv,with_credit,
with_diff_thres,with_pcoc}_op.{cc,cu} in the reference.  Same shape contract
as ops/seqpool_cvm.py: ``emb [S, B, L, H]`` batch-pack layout with
per-(slot, instance) ``lengths`` — masked sums the XLA fuser turns into a
single pass over the gathered embeddings.

Backward passes mirror the reference CUDA grad kernels exactly (they are NOT
the analytic VJPs): the leading "CVM" gradient columns are overwritten with
per-instance statistics (show/click/... counts, or q_values for pcoc) so the
push path accumulates lifecycle counters, and the embedx columns broadcast
the pooled output grad over the valid keys.

Variant summaries (all column indices refer to the per-key value vector):

- tradew (fused_seqpool_cvm_tradew_op.cu:34-89,269-425): per-key layout
  ``[cvm(2) | trade_w(T) | embedx]``; with ``trade_id >= 0`` the embedx pool
  is weighted by the key's selected trade weight, and the backward produces
  a real product-rule gradient for the weight column (the one variant whose
  grad is analytic).
- with_conv (fused_seqpool_cvm_with_conv_op.cu): cvm_offset=3
  ``[show, click, conv]``; CVM stage show→log1p, click→log1p,
  conv→log1p(conv)-log1p(click); ``show_filter`` drops the show column;
  ``embedx_concate_size`` emits per-key (not pooled) slices.
- with_credit (fused_seqpool_cvm_with_credit_op.cu): cvm_offset=4
  ``[show, click, conv, credit]`` each log1p'd; ``show_filter`` drops show.
- with_diff_thres (fused_seqpool_cvm_with_diff_thres_op.cu:95-145): base op
  plus a per-slot threshold vector (``xbox_diff_thres_filter``) and
  ``clk_filter`` (output keeps show only).
- with_pcoc (fused_seqpool_cvm_with_pcoc_op.cu:120-310): leading columns
  ``[show, clk, show2, clk2, pclk*pclk_num]`` producing smoothed ctr + pcoc
  ratio features; grad uses an extra per-instance ``q_values`` input.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp


def _keymask(lengths, L):
    return jnp.arange(L)[None, None, :] < lengths[:, :, None]  # [S,B,L]


def _filter_mask(emb, keymask, show_coeff, clk_coeff, threshold):
    """Per-key show/click threshold filter (cols 0/1 of the value vector)."""
    show, click = emb[..., 0], emb[..., 1]
    keep = (show - click) * show_coeff + click * clk_coeff >= threshold
    return keymask & keep


def _masked_sum(vals, mask, pad_value):
    w = mask.astype(vals.dtype)[..., None]
    return pad_value + jnp.sum(vals * w, axis=2)  # [S, B, H]


def _slot_major(out):
    """[S, B, W] → [B, S*W] (per-slot output tensors, concatenated)."""
    S, B, W = out.shape
    return jnp.transpose(out, (1, 0, 2)).reshape(B, S * W)


def _unslot_major(dy, S):
    B = dy.shape[0]
    W = dy.shape[1] // S
    return dy.reshape(B, S, W).transpose(1, 0, 2)  # [S, B, W]


def _log1p(x):
    return jnp.log(x + 1.0)


# ---------------------------------------------------------------------------
# tradew
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def fused_seqpool_cvm_tradew(emb, lengths, ins_cvm, use_cvm=True,
                             pad_value=0.0, cvm_offset=2, trade_id=-1,
                             trade_num=0):
    """emb [S,B,L,E+trade_num] with per-key ``[cvm|trade_w|embedx]`` layout
    → [B, S*E] (use_cvm) or [B, S*(E-cvm_offset)]."""
    out, _ = _tradew_fwd_impl(emb, lengths, use_cvm, pad_value, cvm_offset,
                              trade_id, trade_num)
    return out


def _tradew_fwd_impl(emb, lengths, use_cvm, pad_value, cvm_offset, trade_id,
                     trade_num):
    S, B, L, H = emb.shape
    mask = _keymask(lengths, L)
    cvm_part = emb[..., :cvm_offset]
    embedx = emb[..., cvm_offset + trade_num:]
    if trade_id >= 0:
        tw = emb[..., cvm_offset + trade_id:cvm_offset + trade_id + 1]
        embedx = embedx * tw
    vals = jnp.concatenate([cvm_part, embedx], axis=-1)  # [S,B,L,E]
    pooled = _masked_sum(vals, mask, pad_value)  # [S, B, E]
    show = _log1p(pooled[..., 0:1])
    click = _log1p(pooled[..., 1:2]) - show
    if use_cvm:
        # cols 2..cvm_offset (if any) pass through raw, keeping fwd width E
        # consistent with the dy[..., cvm_offset:] slice in the backward
        out = jnp.concatenate([show, click, pooled[..., 2:]], -1)
    else:
        out = pooled[..., cvm_offset:]
    return _slot_major(out), mask


def _tradew_fwd(emb, lengths, ins_cvm, use_cvm, pad_value, cvm_offset,
                trade_id, trade_num):
    out, mask = _tradew_fwd_impl(emb, lengths, use_cvm, pad_value, cvm_offset,
                                 trade_id, trade_num)
    return out, (emb, mask, ins_cvm)


def _tradew_bwd(use_cvm, pad_value, cvm_offset, trade_id, trade_num, res, dy):
    emb, mask, ins_cvm = res
    S, B, L, H = emb.shape
    dy = _unslot_major(dy, S).astype(emb.dtype)  # [S, B, W]
    d_embedx_out = dy[..., cvm_offset:] if use_cvm else dy  # [S,B,Ex]
    w = mask.astype(emb.dtype)[..., None]  # [S,B,L,1]
    if trade_id >= 0:
        # FusedSeqpoolCVMTradeWGradKernel: cvm cols zeroed, selected trade
        # col gets per-key dot(dy_embedx, key embedx), embedx cols get
        # dy * key trade weight.
        d_cvm = jnp.zeros((S, B, L, cvm_offset), emb.dtype)
        embedx_in = emb[..., cvm_offset + trade_num:]
        dot = jnp.einsum("sble,sbe->sbl", embedx_in, d_embedx_out)
        d_trade = jnp.zeros((S, B, L, trade_num), emb.dtype)
        d_trade = d_trade.at[..., trade_id].set(dot)
        tw = emb[..., cvm_offset + trade_id:cvm_offset + trade_id + 1]
        d_ex = d_embedx_out[:, :, None, :] * tw
        d_emb = jnp.concatenate([d_cvm, d_trade, d_ex], -1) * w
    else:
        # NoTradeId: cvm cols ← instance cvm, trade cols ← 0, embedx ← dy.
        d_cvm = jnp.broadcast_to(ins_cvm[None, :, None, :].astype(emb.dtype),
                                 (S, B, L, 2))
        if cvm_offset > 2:
            d_cvm = jnp.concatenate(
                [d_cvm, jnp.zeros((S, B, L, cvm_offset - 2), emb.dtype)], -1)
        d_trade = jnp.zeros((S, B, L, trade_num), emb.dtype)
        d_ex = jnp.broadcast_to(d_embedx_out[:, :, None, :],
                                (S, B, L, d_embedx_out.shape[-1]))
        d_emb = jnp.concatenate([d_cvm, d_trade, d_ex], -1) * w
    d_lengths = np.zeros((S, B), dtype=jax.dtypes.float0)
    return d_emb, d_lengths, jnp.zeros_like(ins_cvm)


fused_seqpool_cvm_tradew.defvjp(_tradew_fwd, _tradew_bwd)


# ---------------------------------------------------------------------------
# with_conv
# ---------------------------------------------------------------------------

CONV_OFFSET = 3  # show, click, conv


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def fused_seqpool_cvm_with_conv(emb, lengths, ins_cvm, use_cvm=True,
                                pad_value=0.0, need_filter=False,
                                show_coeff=0.2, clk_coeff=1.0,
                                threshold=0.96, show_filter=False,
                                embedx_concate_size=1):
    """emb [S,B,L,E] with ``[show, click, conv, embedx]`` per-key layout →
    [B, S*C*W] where C=embedx_concate_size and W is E (use_cvm), E-1
    (show_filter) or E-3 (no cvm)."""
    out, _ = _conv_fwd_impl(emb, lengths, use_cvm, pad_value, need_filter,
                            show_coeff, clk_coeff, threshold, show_filter,
                            embedx_concate_size)
    return out


def _conv_pool(emb, lengths, pad_value, need_filter, show_coeff, clk_coeff,
               threshold, C):
    """→ pooled [S, B, C, E], keymask [S, B, L]."""
    S, B, L, E = emb.shape
    mask = _keymask(lengths, L)
    if need_filter:
        mask = _filter_mask(emb, mask, show_coeff, clk_coeff, threshold)
    if C == 1:
        pooled = _masked_sum(emb, mask, pad_value)[:, :, None, :]
    else:
        # position k pools exactly key k (when k < length), else pad_value
        # (FusedSeqpoolWithConvKernelNormalEmbedxConcate :96-124)
        take = jnp.minimum(jnp.arange(C), L - 1)
        vals = emb[:, :, take, :]  # [S,B,C,E]
        mk = mask[:, :, take] & (jnp.arange(C)[None, None, :] < L)
        pooled = pad_value + vals * mk.astype(emb.dtype)[..., None]
    return pooled, mask


def _conv_transform(pooled, use_cvm, show_filter):
    """CVM stage on pooled [S,B,C,E] → [S,B,C,W]."""
    show = _log1p(pooled[..., 0:1])
    click = _log1p(pooled[..., 1:2])
    conv = _log1p(pooled[..., 2:3]) - click
    if use_cvm:
        if show_filter:
            return jnp.concatenate([click, conv, pooled[..., 3:]], -1)
        return jnp.concatenate([show, click, conv, pooled[..., 3:]], -1)
    return pooled[..., CONV_OFFSET:]


def _conv_fwd_impl(emb, lengths, use_cvm, pad_value, need_filter, show_coeff,
                   clk_coeff, threshold, show_filter, C):
    S, B, L, E = emb.shape
    pooled, mask = _conv_pool(emb, lengths, pad_value, need_filter,
                              show_coeff, clk_coeff, threshold, C)
    out = _conv_transform(pooled, use_cvm, show_filter)  # [S,B,C,W]
    out = out.reshape(S, B, -1)
    return _slot_major(out), mask


def _conv_fwd(emb, lengths, ins_cvm, use_cvm, pad_value, need_filter,
              show_coeff, clk_coeff, threshold, show_filter, C):
    out, mask = _conv_fwd_impl(emb, lengths, use_cvm, pad_value, need_filter,
                               show_coeff, clk_coeff, threshold,
                               show_filter, C)
    return out, (mask, ins_cvm)


def _conv_bwd(use_cvm, pad_value, need_filter, show_coeff, clk_coeff,
              threshold, show_filter, C, res, dy):
    mask, ins_cvm = res
    S, B, L = mask.shape
    dt = dy.dtype
    dy = _unslot_major(dy, S).reshape(S, B, C, -1)  # [S,B,C,W]
    if use_cvm and show_filter:
        # WithShow grad (:537-563): all three cvm cols ← instance cvm,
        # embedx ← dy shifted by the dropped show column.
        d_pooled = jnp.concatenate(
            [jnp.broadcast_to(ins_cvm[None, :, None, :].astype(dt),
                              (S, B, C, CONV_OFFSET)),
             dy[..., CONV_OFFSET - 1:]], -1)
    elif use_cvm:
        d_pooled = jnp.concatenate(
            [jnp.broadcast_to(ins_cvm[None, :, None, :].astype(dt),
                              (S, B, C, CONV_OFFSET)),
             dy[..., CONV_OFFSET:]], -1)
    else:
        d_pooled = jnp.concatenate(
            [jnp.broadcast_to(ins_cvm[None, :, None, :].astype(dt),
                              (S, B, C, CONV_OFFSET)), dy], -1)
    w = mask.astype(dt)
    if C == 1:
        d_emb = d_pooled[:, :, 0, :][:, :, None, :] * w[..., None]
    else:
        # key k takes grad from concat position min(k, C-1)
        # (GradKernelWithCVMConcate :517-533: last position covers the tail)
        pos = jnp.minimum(jnp.arange(L), C - 1)
        d_emb = d_pooled[:, :, pos, :] * w[..., None]
    d_lengths = np.zeros((S, B), dtype=jax.dtypes.float0)
    return d_emb, d_lengths, jnp.zeros_like(ins_cvm)


fused_seqpool_cvm_with_conv.defvjp(_conv_fwd, _conv_bwd)


# ---------------------------------------------------------------------------
# with_credit
# ---------------------------------------------------------------------------

CREDIT_OFFSET = 4  # show, click, conv, credit


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_seqpool_cvm_with_credit(emb, lengths, ins_cvm, use_cvm=True,
                                  pad_value=0.0, show_filter=False):
    """emb [S,B,L,E] with ``[show, click, conv, credit, embedx]`` layout →
    [B, S*W]; the four lifecycle columns are each log1p'd
    (FusedCVMWithCreditKernelWithCVM :53-71)."""
    out, _ = _credit_fwd_impl(emb, lengths, use_cvm, pad_value, show_filter)
    return out


def _credit_fwd_impl(emb, lengths, use_cvm, pad_value, show_filter):
    S, B, L, E = emb.shape
    mask = _keymask(lengths, L)
    pooled = _masked_sum(emb, mask, pad_value)  # [S,B,E]
    if use_cvm:
        cvm_cols = _log1p(pooled[..., :CREDIT_OFFSET])
        if show_filter:
            out = jnp.concatenate([cvm_cols[..., 1:],
                                   pooled[..., CREDIT_OFFSET:]], -1)
        else:
            out = jnp.concatenate([cvm_cols, pooled[..., CREDIT_OFFSET:]], -1)
    else:
        out = pooled[..., CREDIT_OFFSET:]
    return _slot_major(out), mask


def _credit_fwd(emb, lengths, ins_cvm, use_cvm, pad_value, show_filter):
    out, mask = _credit_fwd_impl(emb, lengths, use_cvm, pad_value,
                                 show_filter)
    return out, (mask, ins_cvm)


def _credit_bwd(use_cvm, pad_value, show_filter, res, dy):
    mask, ins_cvm = res
    S, B, L = mask.shape
    dt = dy.dtype
    dy = _unslot_major(dy, S)
    if use_cvm:
        skip = CREDIT_OFFSET - 1 if show_filter else CREDIT_OFFSET
        d_embedx = dy[..., skip:]
    else:
        d_embedx = dy
    d_cvm = jnp.broadcast_to(ins_cvm[None, :, :].astype(dt),
                             (S, B, CREDIT_OFFSET))
    d_pooled = jnp.concatenate([d_cvm, d_embedx], -1)
    d_emb = d_pooled[:, :, None, :] * mask.astype(dt)[..., None]
    d_lengths = np.zeros((S, B), dtype=jax.dtypes.float0)
    return d_emb, d_lengths, jnp.zeros_like(ins_cvm)


fused_seqpool_cvm_with_credit.defvjp(_credit_fwd, _credit_bwd)


# ---------------------------------------------------------------------------
# with_diff_thres
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11, 12))
def fused_seqpool_cvm_with_diff_thres(emb, lengths, ins_cvm, use_cvm=True,
                                      pad_value=0.0, need_filter=False,
                                      show_coeff=0.2, clk_coeff=1.0,
                                      threshold=0.96, threshold_vec=(),
                                      quant_ratio=0, clk_filter=False,
                                      xbox_diff_thres_filter=False):
    """Base fused_seqpool_cvm plus per-slot thresholds
    (``threshold_vec[slot]`` when xbox_diff_thres_filter) and ``clk_filter``
    (output [log1p(show), embedx], the click column dropped)."""
    out, _ = _dt_fwd_impl(emb, lengths, use_cvm, pad_value, need_filter,
                          show_coeff, clk_coeff, threshold, threshold_vec,
                          quant_ratio, clk_filter, xbox_diff_thres_filter)
    return out


def _dt_fwd_impl(emb, lengths, use_cvm, pad_value, need_filter, show_coeff,
                 clk_coeff, threshold, threshold_vec, quant_ratio, clk_filter,
                 xbox_diff_thres_filter):
    S, B, L, E = emb.shape
    mask = _keymask(lengths, L)
    if need_filter:
        thr = (jnp.asarray(threshold_vec, emb.dtype)[:, None, None]
               if xbox_diff_thres_filter else threshold)
        mask = _filter_mask(emb, mask, show_coeff, clk_coeff, thr)
    if quant_ratio > 0:
        ex = jnp.floor(emb[..., 2:] * quant_ratio + 0.5) / quant_ratio
        vals = jnp.concatenate([emb[..., :2], ex], -1)
    else:
        vals = emb
    pooled = _masked_sum(vals, mask, pad_value)
    show = _log1p(pooled[..., 0:1])
    click = _log1p(pooled[..., 1:2]) - show
    if use_cvm:
        if clk_filter:
            out = jnp.concatenate([show, pooled[..., 2:]], -1)
        else:
            out = jnp.concatenate([show, click, pooled[..., 2:]], -1)
    else:
        out = pooled[..., 2:]
    return _slot_major(out), mask


def _dt_fwd(emb, lengths, ins_cvm, *nd):
    out, mask = _dt_fwd_impl(emb, lengths, *nd)
    return out, (mask, ins_cvm)


def _dt_bwd(use_cvm, pad_value, need_filter, show_coeff, clk_coeff, threshold,
            threshold_vec, quant_ratio, clk_filter, xbox_diff_thres_filter,
            res, dy):
    mask, ins_cvm = res
    S, B, L = mask.shape
    dt = dy.dtype
    dy = _unslot_major(dy, S)
    if use_cvm:
        d_embedx = dy[..., 1:] if clk_filter else dy[..., 2:]
    else:
        d_embedx = dy
    d_cvm = jnp.broadcast_to(ins_cvm[None, :, :].astype(dt), (S, B, 2))
    d_pooled = jnp.concatenate([d_cvm, d_embedx], -1)
    d_emb = d_pooled[:, :, None, :] * mask.astype(dt)[..., None]
    d_lengths = np.zeros((S, B), dtype=jax.dtypes.float0)
    return d_emb, d_lengths, jnp.zeros_like(ins_cvm)


fused_seqpool_cvm_with_diff_thres.defvjp(_dt_fwd, _dt_bwd)


# ---------------------------------------------------------------------------
# with_pcoc
# ---------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11, 12))
def fused_seqpool_cvm_with_pcoc(emb, lengths, ins_cvm, q_values, use_cvm=True,
                                pad_value=0.0, need_filter=False,
                                show_coeff=0.2, clk_coeff=1.0,
                                threshold=0.96, cvm_offset=7,
                                max_cvm_offset=7, quant_ratio=0):
    """emb [S,B,L,E] with leading ``[show, clk, show2, clk2,
    pclk*(cvm_offset-4)]`` columns; ins_cvm [B, cvm_offset]; q_values
    [B, cvm_offset-4].  Output columns (use_cvm): log1p(show),
    smoothed ctr, pclk_num pcoc-vs-show2 ratios, pclk_num pcoc-vs-clk2
    ratios, then embedx (FusedCVMWithPCOCKernelWithCVM :122-157)."""
    out, _ = _pcoc_fwd_impl(emb, lengths, use_cvm, pad_value, need_filter,
                            show_coeff, clk_coeff, threshold, cvm_offset,
                            max_cvm_offset, quant_ratio)
    return out


def _pcoc_fwd_impl(emb, lengths, use_cvm, pad_value, need_filter, show_coeff,
                   clk_coeff, threshold, cvm_offset, max_cvm_offset,
                   quant_ratio):
    S, B, L, E = emb.shape
    pclk_num = cvm_offset - 4
    mask = _keymask(lengths, L)
    if need_filter:
        mask = _filter_mask(emb, mask, show_coeff, clk_coeff, threshold)
    if quant_ratio > 0:
        ex = (jnp.floor(emb[..., max_cvm_offset:] * quant_ratio + 0.5)
              / quant_ratio)
        vals = jnp.concatenate([emb[..., :max_cvm_offset], ex], -1)
    else:
        vals = emb
    pooled = _masked_sum(vals, mask, pad_value)  # [S,B,E]
    if use_cvm:
        # log1p only the lifecycle columns — embedx sums can be < -1 and
        # would produce NaN lanes (sliced away, but they trip jax_debug_nans)
        lg = _log1p(pooled[..., :4 + pclk_num])
        show = lg[..., 0:1]
        ctr = lg[..., 1:2] - lg[..., 0:1]
        pcoc1 = lg[..., 4:4 + pclk_num] - lg[..., 2:3]
        pcoc2 = lg[..., 4:4 + pclk_num] - lg[..., 3:4]
        out = jnp.concatenate(
            [show, ctr, pcoc1, pcoc2, pooled[..., max_cvm_offset:]], -1)
    else:
        out = pooled[..., max_cvm_offset:]
    return _slot_major(out), mask


def _pcoc_fwd(emb, lengths, ins_cvm, q_values, *nd):
    out, mask = _pcoc_fwd_impl(emb, lengths, *nd)
    return out, (mask, ins_cvm, q_values)


def _pcoc_bwd(use_cvm, pad_value, need_filter, show_coeff, clk_coeff,
              threshold, cvm_offset, max_cvm_offset, quant_ratio, res, dy):
    mask, ins_cvm, q_values = res
    S, B, L = mask.shape
    dt = dy.dtype
    pclk_num = cvm_offset - 4
    embed_index_diff = max_cvm_offset - 2 - 2 * pclk_num
    dy = _unslot_major(dy, S)
    d_embedx = dy[..., max_cvm_offset - embed_index_diff:] if use_cvm else dy
    # cols 0..3 ← instance show/clk/show2/clk2; cols 4..cvm_offset ← q_values;
    # cols cvm_offset..max_cvm_offset ← 0 (GradKernelWithCVM :274-284)
    d_lead = jnp.concatenate(
        [jnp.broadcast_to(ins_cvm[None, :, :4].astype(dt), (S, B, 4)),
         jnp.broadcast_to(q_values[None, :, :].astype(dt), (S, B, pclk_num)),
         jnp.zeros((S, B, max_cvm_offset - cvm_offset), dt)], -1)
    d_pooled = jnp.concatenate([d_lead, d_embedx], -1)
    d_emb = d_pooled[:, :, None, :] * mask.astype(dt)[..., None]
    d_lengths = np.zeros((S, B), dtype=jax.dtypes.float0)
    return d_emb, d_lengths, jnp.zeros_like(ins_cvm), jnp.zeros_like(q_values)


fused_seqpool_cvm_with_pcoc.defvjp(_pcoc_fwd, _pcoc_bwd)
