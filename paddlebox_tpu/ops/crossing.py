"""Permutation crossings between sorted and canonical occurrence domains.

The mxu hot path (ps/mxu_path.py) moves per-occurrence values between
canonical [S, L, B] order and the plan's sorted order twice per step.
BENCH_r03's step profile measured these two crossings as the DOMINANT step
cost (~8.2 ms each at 1.27M x 12 f32 on v5e): XLA lowers `jnp.take` to a
serial per-row gather on TPU.  Two interchangeable lowerings:

* "take" — jnp.take rows by source index (current XLA gather).
* "sort" — applying a known permutation IS a key-value sort whose keys are
  the DESTINATION positions: `lax.sort((dest, v0, ..., vw))` lands value j
  at position dest[j], and XLA's TPU sort is a vectorized bitonic network,
  not a serial gather.  (The reference never faces this: CUDA scatters by
  thread id, box_wrapper.cu:75; the sort IS the TPU-native scatter.)

Which wins depends on backend and geometry, so `best_mode` measures both
once per geometry on the live backend and caches the answer
(FLAGS_mxu_crossing pins it to "take"/"sort" explicitly).
"""

from __future__ import annotations

import functools
import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu import flags

log = logging.getLogger(__name__)


def permute_by_dest(channels, dest: jnp.ndarray):
    """out[:, dest[j]] = values[:, j] for a permutation `dest` of 0..n-1.

    channels: sequence of [n] arrays (channel-major payload).  Returns the
    permuted channels stacked [w, n].  Lowered as ONE multi-operand sort.
    """
    ops = jax.lax.sort((dest,) + tuple(channels), num_keys=1)
    return jnp.stack(ops[1:], axis=0)


def _bench_once(fn, args, reps: int = 3) -> float:
    r = jax.jit(fn)
    out = r(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = r(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def best_mode(take_rows: int, sort_n: int, w: int, backend: str,
              dtype: str = "float32") -> str:
    """Measured winner for a crossing that a "take" lowering serves with
    `take_rows` output rows and a "sort" lowering serves with a `sort_n`-
    element w+1-operand sort.  Measurements cached per geometry (including
    the crossing dtype — bf16 halves the bytes and shifts the take/sort
    break-even); the flag is read OUTSIDE the cache so pinning works after
    a tuned pass too."""
    mode = flags.get_flags("mxu_crossing")
    if mode not in ("take", "sort", "auto"):
        raise ValueError(
            f"FLAGS_mxu_crossing={mode!r}: must be take | sort | auto")
    if mode != "auto":
        return mode
    if backend == "cpu":
        return "take"       # XLA CPU gathers are fine; sort is the slow one
    return _measure(take_rows, sort_n, w, backend, dtype)


@functools.lru_cache(maxsize=None)
def _measure(take_rows: int, sort_n: int, w: int, backend: str,
             dtype: str = "float32") -> str:
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.normal(0, 1, (sort_n, w)).astype(
        np.float32)).astype(dtype)
    idx = jnp.asarray(
        rng.integers(0, sort_n, take_rows).astype(np.int32))
    dest = jnp.asarray(rng.permutation(sort_n).astype(np.int32))
    t_take = _bench_once(lambda v, i: jnp.take(v, i, axis=0), (src, idx))
    try:
        t_sort = _bench_once(
            lambda v, d: permute_by_dest(tuple(v.T), d), (src, dest))
    except Exception as e:  # noqa: BLE001 — a lowering failure on an
        # unusual backend must degrade to the safe default, not kill the
        # step build (the sort mode is a pure optimization)
        log.warning("crossing auto-tune: sort lowering failed (%s: %s) — "
                    "using take", type(e).__name__, e)
        return "take"
    mode = "sort" if t_sort < t_take else "take"
    log.info("crossing auto-tune (take_rows=%d sort_n=%d w=%d %s): "
             "take=%.2fms sort=%.2fms -> %s", take_rows, sort_n, w, backend,
             t_take * 1e3, t_sort * 1e3, mode)
    return mode
