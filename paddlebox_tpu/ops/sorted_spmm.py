"""Sorted one-hot-matmul gather/scatter — the TPU-native sparse hot path.

Why this exists (measured on v5e): XLA lowers `table[idx]` gathers and
`.at[idx].add` scatters to a *serial* per-row loop on TPU — ~5-10ms per
426k-row gather and ~36ms per 426k-row scatter into a [2M, 8] table.  The
reference's CUDA kernels (PullCopy box_wrapper.cu:75, PushMergeCopyAtomic
box_wrapper.cu:476, HeterComm merge heter_comm_inl.h:69-103) rely on massive
scatter/gather parallelism + atomics that the TPU memory system does not
offer.  The TPU-native formulation: treat pull/push as a block-sparse matrix
product and feed the MXU —

  1. sort the batch's row ids once (`lax.sort`, bitonic, vectorized, ~0.5ms);
  2. walk the sorted occurrences in fixed 512-wide *chunks* against 2048-row
     table *tiles*; each (chunk, tile) work item builds a {0,1} one-hot in
     VMEM and runs one [W,TILE]x[TILE,C] (gather) or [W,C]x[C,TILE] (scatter)
     matmul on the MXU — duplicates merge for free in the contraction;
  3. a worklist enumerates the (chunk, tile) pairs actually touched.  Because
     rows are sorted, each chunk's tiles are a consecutive range and every
     tile's visits are adjacent in the worklist, so Pallas block revisiting
     accumulates partial products in VMEM without ever materializing the
     one-hot in HBM (a pure-XLA scan of the same schedule spends ~8us/item
     on HBM one-hot traffic; the Pallas kernel spends ~2us on the MXU).

Skew-robust with *static* shapes: a popular key spanning many chunks just
contributes to more work items; the worklist bound is exactly
  n_chunks + n_tiles   (each chunk >= 1 item; tile-boundary crossings and
gap fills add at most one item per tile), so jit shapes never depend on the
key distribution.

All offsets are chunk-aligned, so every DMA is a regular [W, C]/[W, TILE]
block copy (no per-row DMAs — TPU DMA wants 128-lane-aligned slices).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

CHUNK = 512     # occurrences per work-item (lane dim of payload blocks)
TILE = 2048     # table rows per tile (lane dim of table blocks)


def _round_up(n: int, a: int) -> int:
    return (n + a - 1) // a * a


@dataclasses.dataclass(frozen=True)
class SpmmDims:
    """Static geometry shared by the plan and both kernels."""
    p: int           # real occurrence count
    p_pad: int       # p rounded up to CHUNK
    n_chunks: int
    n_kernel: int    # table rows incl. the trailing sentinel tile
    n_tiles: int     # n_kernel // TILE
    n_work: int      # n_chunks + n_tiles (static worklist bound)
    chunk: int = CHUNK
    tile: int = TILE

    @property
    def sentinel(self) -> int:
        """Row id pad occurrences are parked at: first row of the last
        (sentinel) tile — gathers zeros, scatters into a discarded tile."""
        return self.n_kernel - self.tile


def spmm_dims(p: int, n_rows: int, chunk: int = CHUNK,
              tile: int = TILE) -> SpmmDims:
    """n_rows: logical table height (rows 0..n_rows-1 addressable)."""
    p_pad = _round_up(max(p, 1), chunk)
    n_kernel = _round_up(n_rows, tile) + tile  # + sentinel tile
    n_tiles = n_kernel // tile
    n_chunks = p_pad // chunk
    return SpmmDims(p=p, p_pad=p_pad, n_chunks=n_chunks, n_kernel=n_kernel,
                    n_tiles=n_tiles, n_work=n_chunks + n_tiles,
                    chunk=chunk, tile=tile)


def with_p_pad(dims: SpmmDims, p_pad: int) -> SpmmDims:
    """The same table geometry over a different (chunk-aligned) sorted-
    domain width — single source of the n_work = n_chunks + n_tiles
    worklist invariant for trimmed plans."""
    n_chunks = p_pad // dims.chunk
    return dataclasses.replace(dims, p=p_pad, p_pad=p_pad, n_chunks=n_chunks,
                               n_work=n_chunks + dims.n_tiles)


def trimmed_dims(dims: SpmmDims, max_real: int) -> SpmmDims:
    """Static geometry for a plan that drops leading padding occurrences.

    Padding/unseen occurrences carry row 0 and therefore sort to the FRONT
    of the sorted domain; keeping only the last `keep` sorted positions
    (chunk-aligned, `keep >= max_real + sentinel tail`) still covers every
    real occurrence.  At avg_len < capacity this shrinks the kernel
    worklist and the push crossing by the padding fraction (the reference
    never materializes padding at all — its pack is LoD-ragged,
    data_feed.cu:1210; this is the static-shape equivalent).

    The kept width is bucketed to 1/8ths of the full width so passes whose
    widest batch drifts between builds land on at most 8 distinct plan
    shapes — a new shape retraces the packed step jit, and an unbounded
    per-pass recompile would cost far more than the trim saves.
    """
    tail = dims.p_pad - dims.p          # sentinel-padded tail, always kept
    keep = _round_up(min(dims.p_pad, max(max_real + tail, 1)), dims.chunk)
    granule = _round_up(max(dims.p_pad // 8, dims.chunk), dims.chunk)
    keep = min(_round_up(keep, granule), dims.p_pad)
    return with_p_pad(dims, keep)


def build_plan(rows: jnp.ndarray, dims: SpmmDims, eff: SpmmDims = None):
    """Sort the occurrence row ids and enumerate (chunk, tile) work items.

    rows: [p] int32 in canonical (slot, lod, batch) order.
    Returns (rows2d [n_chunks, chunk] sorted+padded, perm [p], inv_perm [p],
    chunk_ids [n_work], tile_ids [n_work], first_gather [n_work],
    first_scatter [n_work], first_occ [p_pad]).  first_occ marks the first
    occurrence of each distinct row in sorted order — lets a scatter carry an
    exact "any one occurrence" column (e.g. the slot id) instead of a mean.
    Everything vectorized — no serial scatters.

    eff (from `trimmed_dims`): emit the trimmed plan instead — the sorted
    arrays keep only the last eff.p_pad positions (callers guarantee the
    dropped prefix is all row-0 occurrences, i.e. the number of nonzero
    rows is <= eff.p_pad - (dims.p_pad - dims.p)).  Shape changes:
    rows2d [eff.n_chunks, chunk] and the worklist shrink; perm stays the
    FULL [p] bijection (sorted position -> canonical source, position 0 =
    first DROPPED element — consumers derive the kept suffix with a static
    slice, see mxu_path); inv_perm [p] becomes the kept-domain position,
    NEGATIVE for dropped (row-0) occurrences — gather consumers mask those
    to zero, exactly the value row 0 holds.
    """
    p, c, t = dims.p, dims.chunk, dims.tile
    iota = jnp.arange(p, dtype=jnp.int32)
    sorted_rows, perm = jax.lax.sort((rows.astype(jnp.int32), iota),
                                     num_keys=1)
    inv_perm = jax.lax.sort((perm, iota), num_keys=1)[1]
    pad = jnp.full((dims.p_pad - p,), dims.sentinel, jnp.int32)
    rows_padded = jnp.concatenate([sorted_rows, pad])
    if eff is not None and eff.p_pad < dims.p_pad:
        p0 = dims.p_pad - eff.p_pad     # static, chunk-aligned
        rows_padded = rows_padded[p0:]
        inv_perm = inv_perm - p0
        dims = eff
    first_occ = jnp.concatenate(
        [jnp.ones((1,), jnp.float32),
         (rows_padded[1:] != rows_padded[:-1]).astype(jnp.float32)])
    rows2d = rows_padded.reshape(dims.n_chunks, 1, c)

    tile_of = rows2d[:, 0, :] // t                          # [n_chunks, c]
    lo, hi = tile_of[:, 0], tile_of[:, -1]
    # visit range per chunk: cover inter-chunk tile gaps (so every tile is
    # visited exactly once overall — scatter needs zero-filled deltas) and
    # share boundary tiles (consecutive visits => VMEM accumulation works)
    vlo = jnp.concatenate([jnp.zeros((1,), lo.dtype),
                           jnp.minimum(lo[1:], hi[:-1] + 1)])
    vhi = jnp.concatenate([hi[:-1], jnp.full((1,), dims.n_tiles - 1,
                                             hi.dtype)])
    slots = vhi - vlo + 1                                   # >= 1
    cum = jnp.cumsum(slots)
    work = jnp.arange(dims.n_work, dtype=jnp.int32)
    c_of = jnp.searchsorted(cum, work, side="right").astype(jnp.int32)
    c_of = jnp.minimum(c_of, dims.n_chunks - 1)
    base = jnp.where(c_of > 0, cum[jnp.maximum(c_of - 1, 0)], 0)
    tile_ids = jnp.clip(vlo[c_of] + work - base, 0, dims.n_tiles - 1)
    tile_ids = tile_ids.astype(jnp.int32)
    first_g = jnp.concatenate([jnp.ones((1,), jnp.int32),
                               (c_of[1:] != c_of[:-1]).astype(jnp.int32)])
    first_s = jnp.concatenate([jnp.ones((1,), jnp.int32),
                               (tile_ids[1:] != tile_ids[:-1]).astype(
                                   jnp.int32)])
    return rows2d, perm, inv_perm, c_of, tile_ids, first_g, first_s, first_occ


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _gather_kernel(ch_ref, tl_ref, fst_ref, rows_ref, table_ref, out_ref):
    i = pl.program_id(0)
    tile = tl_ref[i]
    t = table_ref.shape[1]
    c = rows_ref.shape[2]
    loc = rows_ref[0, 0, :] - tile * t                     # [c]
    oh = (jax.lax.broadcasted_iota(jnp.int32, (t, c), 0)
          == loc[None, :]).astype(jnp.bfloat16)            # [t, c] in VMEM
    # one-hot entries are exact in bf16, so a hi/lo split of the f32 table
    # gives f32-accurate sums in two cheap bf16 MXU passes (vs 6 for
    # Precision.HIGHEST)
    tab = table_ref[...]
    hi = tab.astype(jnp.bfloat16)
    lo = (tab - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    dn = (((1,), (0,)), ((), ()))
    contrib = (jax.lax.dot_general(hi, oh, dn,
                                   preferred_element_type=jnp.float32)
               + jax.lax.dot_general(lo, oh, dn,
                                     preferred_element_type=jnp.float32))

    @pl.when(fst_ref[i] == 1)
    def _():
        out_ref[...] = contrib

    @pl.when(fst_ref[i] == 0)
    def _():
        out_ref[...] += contrib


def _scatter_kernel(ch_ref, tl_ref, fst_ref, rows_ref, pay_ref, out_ref):
    i = pl.program_id(0)
    tile = tl_ref[i]
    t = out_ref.shape[1]
    c = rows_ref.shape[2]
    loc = rows_ref[0, 0, :] - tile * t                     # [c]
    oh = (loc[:, None] ==
          jax.lax.broadcasted_iota(jnp.int32, (c, t), 1)
          ).astype(jnp.bfloat16)                           # [c, t] in VMEM
    pay = pay_ref[...]
    hi = pay.astype(jnp.bfloat16)
    lo = (pay - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    dn = (((1,), (0,)), ((), ()))
    contrib = (jax.lax.dot_general(hi, oh, dn,
                                   preferred_element_type=jnp.float32)
               + jax.lax.dot_general(lo, oh, dn,
                                     preferred_element_type=jnp.float32))

    @pl.when(fst_ref[i] == 1)
    def _():
        out_ref[...] = contrib

    @pl.when(fst_ref[i] == 0)
    def _():
        out_ref[...] += contrib


def gather_sorted(table_fm: jnp.ndarray, rows2d: jnp.ndarray,
                  chunk_ids: jnp.ndarray, tile_ids: jnp.ndarray,
                  first_g: jnp.ndarray, dims: SpmmDims,
                  interpret: bool = False) -> jnp.ndarray:
    """table_fm [W, n_kernel] feature-major -> gathered [W, p_pad] in sorted
    occurrence order (pad columns come from the zero sentinel tile)."""
    w = table_fm.shape[0]
    c, t = dims.chunk, dims.tile
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(dims.n_work,),
        in_specs=[
            pl.BlockSpec((1, 1, c), lambda i, ch, tl, fs: (ch[i], 0, 0)),
            pl.BlockSpec((w, t), lambda i, ch, tl, fs: (0, tl[i])),
        ],
        out_specs=pl.BlockSpec((w, c), lambda i, ch, tl, fs: (0, ch[i])),
    )
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((w, dims.p_pad), jnp.float32),
        interpret=interpret,
    )(chunk_ids, tile_ids, first_g, rows2d, table_fm)


def scatter_add_sorted(payload_fm: jnp.ndarray, rows2d: jnp.ndarray,
                       chunk_ids: jnp.ndarray, tile_ids: jnp.ndarray,
                       first_s: jnp.ndarray, dims: SpmmDims,
                       interpret: bool = False) -> jnp.ndarray:
    """payload_fm [W, p_pad] in sorted order -> merged delta [W, n_kernel]
    (every table row = sum of its occurrences' payload columns; untouched
    rows exactly zero; sentinel tile holds pad garbage — slice it off)."""
    w = payload_fm.shape[0]
    c, t = dims.chunk, dims.tile
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(dims.n_work,),
        in_specs=[
            pl.BlockSpec((1, 1, c), lambda i, ch, tl, fs: (ch[i], 0, 0)),
            pl.BlockSpec((w, c), lambda i, ch, tl, fs: (0, ch[i])),
        ],
        out_specs=pl.BlockSpec((w, t), lambda i, ch, tl, fs: (0, tl[i])),
    )
    return pl.pallas_call(
        _scatter_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((w, dims.n_kernel), jnp.float32),
        interpret=interpret,
    )(chunk_ids, tile_ids, first_s, rows2d, payload_fm)


# ---------------------------------------------------------------------------
# XLA reference implementations (CPU tests / fallback)
# ---------------------------------------------------------------------------

def gather_sorted_xla(table_fm, rows2d, chunk_ids, tile_ids, first_g, dims,
                      interpret: bool = False):
    rows = rows2d.reshape(-1)
    return jnp.take(table_fm, rows, axis=1)


def scatter_add_sorted_xla(payload_fm, rows2d, chunk_ids, tile_ids, first_s,
                           dims, interpret: bool = False):
    rows = rows2d.reshape(-1)
    out = jnp.zeros((payload_fm.shape[0], dims.n_kernel), jnp.float32)
    return out.at[:, rows].add(payload_fm)
