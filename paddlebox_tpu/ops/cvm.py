"""Standalone CVM op (≙ operators/cvm_op.{h,cc,cu}).

Forward (cvm_op.h:35-36): for x = [show, click, embedx...]:
    y0 = log(show + 1) ; y1 = log(click + 1) - log(show + 1)
use_cvm=True keeps the transformed columns, False strips them.

Backward (CvmGradComputeKernel, cvm_op.h:44-56) is deliberately NOT the
analytic derivative: dx[2:] = dy[...], and dx[0:2] is set to the instance's
raw (show, click) so the pushed "gradient" carries impression counts to the
sparse optimizer (dy_mf_update_value, optimizer.cuh.h:84-97 reads them as
g_show/g_click).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def cvm(x: jnp.ndarray, ins_cvm: jnp.ndarray, use_cvm: bool = True):
    """x: [..., E] with E >= 2 (cols 0,1 = show, click); ins_cvm: [..., 2]."""
    return _cvm_fwd_impl(x, use_cvm)


def _cvm_fwd_impl(x, use_cvm):
    show = jnp.log(x[..., 0:1] + 1.0)
    click = jnp.log(x[..., 1:2] + 1.0) - show
    if use_cvm:
        return jnp.concatenate([show, click, x[..., 2:]], axis=-1)
    return x[..., 2:]


def _cvm_fwd(x, ins_cvm, use_cvm):
    return _cvm_fwd_impl(x, use_cvm), (ins_cvm, x.shape)


def _cvm_bwd(use_cvm, res, dy):
    ins_cvm, x_shape = res
    if use_cvm:
        d_embedx = dy[..., 2:]
    else:
        d_embedx = dy
    dx = jnp.concatenate([ins_cvm.astype(dy.dtype), d_embedx], axis=-1)
    return dx, jnp.zeros_like(ins_cvm)


cvm.defvjp(_cvm_fwd, _cvm_bwd)
