"""Pallas TPU kernel: fused embedding-row gather + sequence sum-pool.

The hot-path op the reference implements as PullCopy/FusedSeqpoolKernel CUDA
kernels (box_wrapper.cu:75, fused_seqpool_cvm_op.cu:35): for each (slot,
instance), fetch its feasign rows from the embedding table and sum-pool
them.  Here as one Pallas kernel: row ids are scalar-prefetched to SMEM so
the kernel can issue data-dependent HBM→VMEM DMAs (PrefetchScalarGridSpec),
rows stream in double-buffered, and the pooled block is written once —
the [R, L, D] gathered intermediate never exists in HBM.

Status: experimental alternative to the XLA take+einsum fast path
(ps/fast_path.py).  Correct under interpret mode on CPU (tests); benchmarked
against the XLA path on hardware before being switched on (the per-row DMA
granularity of tiny mf_dim tables may favor XLA's native gather).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_BLOCK = 128  # pooled rows produced per grid step


def _kernel(idx_ref, len_ref, table_ref, out_ref, row_buf, sem):
    """idx_ref [R, L] / len_ref [R] in SMEM (scalar prefetch);
    table_ref [N, D] in ANY/HBM; out_ref block [ROW_BLOCK, D] in VMEM;
    row_buf [2, L, D] VMEM scratch; sem [2, L] DMA semaphores."""
    blk = pl.program_id(0)
    L = idx_ref.shape[1]
    R = idx_ref.shape[0]

    def start_fetch(r, slot):
        """Issue DMAs for all L rows of pooled-row r into buffer `slot`."""
        def issue(l, _):
            dma = pltpu.make_async_copy(
                table_ref.at[idx_ref[r, l]],
                row_buf.at[slot, l],
                sem.at[slot, l])
            dma.start()
            return 0

        jax.lax.fori_loop(0, L, issue, 0)

    def wait_fetch(r, slot):
        def waitone(l, _):
            pltpu.make_async_copy(
                table_ref.at[idx_ref[r, l]],
                row_buf.at[slot, l],
                sem.at[slot, l]).wait()
            return 0

        jax.lax.fori_loop(0, L, waitone, 0)

    first = blk * ROW_BLOCK
    start_fetch(first, 0)

    def body(i, _):
        r = first + i
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < ROW_BLOCK)
        def _():
            start_fetch(r + 1, 1 - slot)

        wait_fetch(r, slot)
        length = len_ref[r]
        mask = (jax.lax.broadcasted_iota(jnp.int32, (L, 1), 0)
                < length).astype(row_buf.dtype)
        pooled = jnp.sum(row_buf[slot] * mask, axis=0)
        out_ref[i, :] = pooled
        return 0

    jax.lax.fori_loop(0, ROW_BLOCK, body, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def gather_pool(table: jnp.ndarray, idx: jnp.ndarray, lengths: jnp.ndarray,
                interpret: bool = False) -> jnp.ndarray:
    """table [N, D]; idx [R, L] row ids (0 = reserved zero row);
    lengths [R] → pooled [R, D] = sum of the first `lengths[r]` rows."""
    R, L = idx.shape
    N, D = table.shape
    assert R % ROW_BLOCK == 0, f"R must be a multiple of {ROW_BLOCK}"
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(R // ROW_BLOCK,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((ROW_BLOCK, D),
                               lambda blk, idx_ref, len_ref: (blk, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, L, D), table.dtype),
            pltpu.SemaphoreType.DMA((2, L)),
        ],
    )
    return pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, D), table.dtype),
        interpret=interpret,
    )(idx.astype(jnp.int32), lengths.astype(jnp.int32), table)
