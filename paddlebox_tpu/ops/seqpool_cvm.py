"""Fused per-slot sequence sum-pool + CVM transform.

≙ the fused_seqpool_cvm op family (fused/fused_seqpool_cvm_op.cu — seqpool
kernels :35-369, CVM stage FusedCVMKernelWithCVM :371, grad
FusedSeqpoolCVMGradKernelWithCVM :814; attr surface
fused_seqpool_cvm_op.cc:113-146).

TPU-first shape contract: instead of per-slot ragged LoD tensors, input is the
batch-pack layout ``emb [S, B, L, E]`` (slot, instance, key-capacity,
embedding) with per-(slot, instance) ``lengths`` — a masked sum over L that
XLA fuses with the upstream gather and downstream matmul; no scalar loops.

Supported attrs (parity with the CUDA variants):
- pad_value          : init value of each pooled output element
- use_cvm            : keep (log-transformed) show/click cols or strip them
- quant              : quant_ratio > 0 rounds embedx to the quant grid
                       (FusedSeqpoolKernelQuant :59)
- need_filter        : drop keys with show_coeff*(show-click)+clk_coeff*click
                       < threshold (FusedSeqpoolKernelQuantFilter :139)
- embed_threshold    : additionally drop keys whose embedx L2-ish score is
                       below embed_threshold (KernelEmbedQuantFilter :230)

Backward mirrors the reference exactly (NOT analytic AD): embedx grads are
the pooled-output grads broadcast over the valid keys; show/click grad
columns carry the *instance* show/click so pushes accumulate counts
(see ops/cvm.py docstring).
"""

from __future__ import annotations

from functools import partial
import numpy as np
import jax
import jax.numpy as jnp

CVM_OFFSET = 2  # show, click


def _quantize(x, quant_ratio):
    return jnp.floor(x * quant_ratio + 0.5) / quant_ratio


def _pool(emb, lengths, pad_value, quant_ratio, need_filter,
          show_coeff, clk_coeff, threshold, embed_threshold,
          embed_thres_size):
    S, B, L, E = emb.shape
    keymask = (jnp.arange(L)[None, None, :] < lengths[:, :, None])  # [S,B,L]
    if need_filter:
        show = emb[..., 0]
        click = emb[..., 1]
        keep = (show - click) * show_coeff + click * clk_coeff >= threshold
        if embed_threshold > 0:
            embedx = emb[..., CVM_OFFSET:CVM_OFFSET + embed_thres_size]
            score = (jnp.sqrt(jnp.sum(embedx[..., 1:] ** 2, axis=-1))
                     + jnp.abs(embedx[..., 0]))
            keep = keep & (score >= embed_threshold)
        keymask = keymask & keep
    w = keymask.astype(emb.dtype)[..., None]
    if quant_ratio > 0:
        embedx_q = _quantize(emb[..., CVM_OFFSET:], quant_ratio)
        vals = jnp.concatenate([emb[..., :CVM_OFFSET], embedx_q], axis=-1)
    else:
        vals = emb
    pooled = pad_value + jnp.sum(vals * w, axis=2)  # [S, B, E]
    return pooled, keymask


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def fused_seqpool_cvm(emb: jnp.ndarray, lengths: jnp.ndarray,
                      ins_cvm: jnp.ndarray,
                      use_cvm: bool = True, pad_value: float = 0.0,
                      quant_ratio: int = 0, need_filter: bool = False,
                      show_coeff: float = 0.2, clk_coeff: float = 1.0,
                      threshold: float = 0.96,
                      embed_threshold: float = 0.0,
                      embed_thres_size: int = 0) -> jnp.ndarray:
    """emb [S,B,L,E], lengths [S,B] int, ins_cvm [B,2] → [B, S*E] (use_cvm)
    or [B, S*(E-2)]."""
    out, _ = _fwd_impl(emb, lengths, use_cvm, pad_value, quant_ratio,
                       need_filter, show_coeff, clk_coeff, threshold,
                       embed_threshold, embed_thres_size)
    return out


def _fwd_impl(emb, lengths, use_cvm, pad_value, quant_ratio, need_filter,
              show_coeff, clk_coeff, threshold, embed_threshold,
              embed_thres_size):
    S, B, L, E = emb.shape
    pooled, keymask = _pool(emb, lengths, pad_value, quant_ratio,
                            need_filter, show_coeff, clk_coeff, threshold,
                            embed_threshold, embed_thres_size)
    show = jnp.log(pooled[..., 0:1] + 1.0)
    click = jnp.log(pooled[..., 1:2] + 1.0) - show
    if use_cvm:
        out = jnp.concatenate([show, click, pooled[..., CVM_OFFSET:]], axis=-1)
        width = E
    else:
        out = pooled[..., CVM_OFFSET:]
        width = E - CVM_OFFSET
    # [S, B, width] → [B, S*width] slot-major concat (≙ the per-slot output
    # tensors the reference's consumers concat)
    out = jnp.transpose(out, (1, 0, 2)).reshape(B, S * width)
    return out, keymask


def _fwd(emb, lengths, ins_cvm, use_cvm, pad_value, quant_ratio, need_filter,
         show_coeff, clk_coeff, threshold, embed_threshold, embed_thres_size):
    out, keymask = _fwd_impl(emb, lengths, use_cvm, pad_value, quant_ratio,
                             need_filter, show_coeff, clk_coeff, threshold,
                             embed_threshold, embed_thres_size)
    return out, (keymask, ins_cvm)


def _bwd(use_cvm, pad_value, quant_ratio, need_filter, show_coeff, clk_coeff,
         threshold, embed_threshold, embed_thres_size, res, dy):
    keymask, ins_cvm = res
    S, B, L = keymask.shape
    emb_dtype = dy.dtype
    width = dy.shape[1] // S
    dy = dy.reshape(B, S, width).transpose(1, 0, 2)  # [S, B, width]
    if use_cvm:
        d_embedx = dy[..., CVM_OFFSET:]
    else:
        d_embedx = dy
    # show/click grad columns carry instance counts
    # (FusedSeqpoolCVMGradKernelWithCVM :828-830 reads cvm_values)
    d_cvm = jnp.broadcast_to(ins_cvm[None, :, :].astype(emb_dtype),
                             (S, B, CVM_OFFSET))
    d_pooled = jnp.concatenate([d_cvm, d_embedx], axis=-1)  # [S, B, E]
    w = keymask.astype(emb_dtype)[..., None]
    d_emb = d_pooled[:, :, None, :] * w  # broadcast over valid keys
    d_lengths = np.zeros((S, B), dtype=jax.dtypes.float0)
    return d_emb, d_lengths, jnp.zeros_like(ins_cvm)


fused_seqpool_cvm.defvjp(_fwd, _bwd)
