from paddlebox_tpu.ops.seqpool_cvm import fused_seqpool_cvm  # noqa: F401
from paddlebox_tpu.ops.cvm import cvm  # noqa: F401
