from paddlebox_tpu.ops.seqpool_cvm import fused_seqpool_cvm  # noqa: F401
from paddlebox_tpu.ops.seqpool_cvm_variants import (  # noqa: F401
    fused_seqpool_cvm_tradew, fused_seqpool_cvm_with_conv,
    fused_seqpool_cvm_with_credit, fused_seqpool_cvm_with_diff_thres,
    fused_seqpool_cvm_with_pcoc)
from paddlebox_tpu.ops.cvm import cvm  # noqa: F401
