"""Multi-process launcher + distributed runtime init.

≙ `python -m paddle.distributed.launch` (launch/main.py + controllers/):
spawns one worker process per host rank with the rendezvous env, restarts
failed locals, and tears the job down on fatal errors.  The TPU analogue of
the rendezvous itself is ``jax.distributed.initialize`` (coordinator =
process 0), which stands in for MPICluster/gloo (SURVEY.md §5 backend map).

Usage:
    python -m paddlebox_tpu.launch --nproc_per_node 2 train.py --args...
Inside the worker, call ``init_distributed()`` before building topology.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> int:
    """≙ fleet.init collective rendezvous (MPICluster box_wrapper.h:446).
    Reads PBOX_* env set by the launcher when args are omitted.  Returns
    this process's rank.  No-op for single-process jobs."""
    import jax
    num = num_processes if num_processes is not None else \
        int(os.environ.get("PBOX_WORLD_SIZE", "1"))
    if num <= 1:
        return 0
    rank = process_id if process_id is not None else \
        int(os.environ.get("PBOX_RANK", "0"))
    coord = coordinator or os.environ.get("PBOX_COORDINATOR",
                                          "127.0.0.1:12355")
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=num, process_id=rank)
    return rank


def launch(script: str, script_args: List[str], nproc: int,
           coordinator: str = "127.0.0.1:12355",
           max_restarts: int = 0, log_dir: str = "") -> int:
    """Spawn nproc workers; restart failed ones up to max_restarts
    (≙ launch controllers' replica watch)."""
    procs: List[Optional[subprocess.Popen]] = [None] * nproc
    restarts = [0] * nproc

    def spawn(rank: int) -> subprocess.Popen:
        env = dict(os.environ)
        env.update({
            "PBOX_RANK": str(rank),
            "PBOX_WORLD_SIZE": str(nproc),
            "PBOX_COORDINATOR": coordinator,
        })
        stdout = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            stdout = open(os.path.join(log_dir, f"worker-{rank}.log"), "ab")
        return subprocess.Popen([sys.executable, script] + script_args,
                                env=env, stdout=stdout,
                                stderr=subprocess.STDOUT if stdout else None)

    for r in range(nproc):
        procs[r] = spawn(r)

    exit_code = 0
    try:
        while True:
            alive = 0
            for r, p in enumerate(procs):
                if p is None:
                    continue
                ret = p.poll()
                if ret is None:
                    alive += 1
                elif ret != 0 and restarts[r] < max_restarts:
                    restarts[r] += 1
                    procs[r] = spawn(r)
                    alive += 1
                elif ret != 0:
                    # fatal: kill the rest (≙ controller abort)
                    exit_code = ret
                    for q in procs:
                        if q is not None and q.poll() is None:
                            q.send_signal(signal.SIGTERM)
                    return exit_code
                else:
                    procs[r] = None
            if alive == 0:
                return exit_code
            time.sleep(0.2)
    except KeyboardInterrupt:
        for q in procs:
            if q is not None and q.poll() is None:
                q.send_signal(signal.SIGTERM)
        return 130


def main():
    ap = argparse.ArgumentParser(prog="paddlebox_tpu.launch")
    ap.add_argument("--nproc_per_node", type=int, default=1)
    ap.add_argument("--coordinator", default="127.0.0.1:12355")
    ap.add_argument("--max_restarts", type=int, default=0)
    ap.add_argument("--log_dir", default="")
    ap.add_argument("script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    sys.exit(launch(args.script, args.script_args, args.nproc_per_node,
                    args.coordinator, args.max_restarts, args.log_dir))


if __name__ == "__main__":
    main()
