"""Multi-process launcher + distributed runtime init.

≙ `python -m paddle.distributed.launch` (launch/main.py + controllers/):
spawns one worker process per host rank with the rendezvous env, restarts
failed locals, and tears the job down on fatal errors.  The TPU analogue of
the rendezvous itself is ``jax.distributed.initialize`` (coordinator =
process 0), which stands in for MPICluster/gloo (SURVEY.md §5 backend map).

Usage:
    python -m paddlebox_tpu.launch --nproc_per_node 2 train.py --args...
Inside the worker, call ``init_distributed()`` before building topology.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from paddlebox_tpu.utils import flight


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> int:
    """≙ fleet.init collective rendezvous (MPICluster box_wrapper.h:446).
    Reads PBOX_* env set by the launcher when args are omitted.  Returns
    this process's rank.  No-op for single-process jobs."""
    import jax
    from paddlebox_tpu.utils import doctor, obs_server
    # worker-side observability entry: FLAGS_obs_port (assigned base+rank
    # by the launcher) starts the /metrics exporter; FLAGS_obs_trace the
    # span tracer — both no-ops when unset.  The wedge doctor's SIGUSR1
    # handler makes every worker live-interrogable (kill -USR1 <pid>
    # writes a postmortem bundle under FLAGS_obs_postmortem_dir).
    obs_server.maybe_start_from_flags()
    doctor.install()
    num = num_processes if num_processes is not None else \
        int(os.environ.get("PBOX_WORLD_SIZE", "1"))
    if num <= 1:
        return 0
    rank = process_id if process_id is not None else \
        int(os.environ.get("PBOX_RANK", "0"))
    coord = coordinator or os.environ.get("PBOX_COORDINATOR",
                                          "127.0.0.1:12355")
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=num, process_id=rank)
    return rank


class ClusterScraper:
    """Supervisor-side cluster aggregation: a periodic thread pulling
    every worker's ``/statz?raw=1``, folding the live scrapes through
    the bucket-wise ``obs_server.merge_snapshots`` into a JOB-LEVEL
    timeline (utils/timeline.TimelineRing), served at ``/clusterz`` —
    the horizontal half of the telemetry timeline.

    Tolerant of dead/restarting workers by construction: a failed
    scrape just drops that worker from the interval's fold (and marks
    it dead in the ``workers`` map) — the merged series carries on with
    whoever answers.  ``stop()`` joins the thread (PB405)."""

    def __init__(self, ports: List[int], interval_s: float = 5.0,
                 cap: int = 512, host: str = "127.0.0.1",
                 prefix: str = ""):
        from paddlebox_tpu.utils import obs_server, timeline
        self._obs = obs_server
        self.ports = list(ports)
        self.interval_s = float(interval_s)
        self.host = host
        # narrow the per-interval pull to one dotted subtree (the
        # /statz?prefix= filter) — "" scrapes everything
        self.prefix = prefix
        self.ring = timeline.TimelineRing(cap)
        self._alive: Dict[int, bool] = {p: False for p in self.ports}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_ports(self, ports: List[int]) -> None:
        """Fold more workers into the scrape set — how the trainer
        fleet's /statz exporters join the same /clusterz timeline as the
        PS tier (launched later than the scraper, hence dynamic)."""
        with self._lock:
            for p in ports:
                if p not in self._alive:
                    self.ports.append(p)
                    self._alive[p] = False

    def scrape_once(self) -> int:
        """One scrape+merge round; returns how many workers answered
        (0 appends nothing — an all-dead interval is a gap, not a zero
        sample)."""
        path = "/statz?raw=1"
        if self.prefix:
            path += f"&prefix={self.prefix}"
        snaps = []
        with self._lock:
            ports = list(self.ports)   # snapshot: add_ports appends live
        for p in ports:
            snap = self._obs.scrape(p, path=path, host=self.host)
            with self._lock:
                self._alive[p] = snap is not None
            if snap:
                snaps.append(snap)
        if snaps:
            merged = self._obs.merge_snapshots(snaps)
            # pboxlint: disable-next=PB102 -- TimelineRing locks internally; single scrape-thread writer
            self.ring.append(merged)
        return len(snaps)

    def start(self) -> "ClusterScraper":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="pbox-clusterscrape", daemon=True)
            self._thread.start()
        self._obs.set_clusterz_provider(self.render)
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — scraping must never die
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._obs.set_clusterz_provider(None)

    def render(self, name: Optional[str] = None,
               n: Optional[int] = None) -> Dict:
        """The /clusterz payload: index + per-worker liveness, or one
        merged metric's series via ``?name=``."""
        if name:
            out = self.ring.series(name, n=n)
            out["enabled"] = True
            return out
        with self._lock:
            workers = {str(p): alive for p, alive in self._alive.items()}
        latest = self.ring.samples(1)
        return {"enabled": True, "interval_s": self.interval_s,
                "len": len(self.ring), "workers": workers,
                "names": self.ring.names(),
                "latest": latest[0]["stats"] if latest else {}}


def launch(script: str, script_args: List[str], nproc: int,
           coordinator: str = "127.0.0.1:12355",
           max_restarts: int = 0, log_dir: str = "",
           obs_port: int = 0) -> int:
    """Spawn nproc workers; restart failed ones up to max_restarts
    (≙ launch controllers' replica watch).

    obs_port > 0 assigns each worker rank its own exporter port
    (``FLAGS_obs_port = obs_port + rank``); the launcher then scrapes
    every worker's /statz periodically and prints ONE merged job-wide
    snapshot at teardown (the supervisor-side half of the observability
    layer — obs_server.merge_snapshots)."""
    procs: List[Optional[subprocess.Popen]] = [None] * nproc
    restarts = [0] * nproc
    obs_last: Dict[int, Dict] = {}      # rank -> last good /statz
    obs_t = [0.0]

    def spawn(rank: int) -> subprocess.Popen:
        env = dict(os.environ)
        env.update({
            "PBOX_RANK": str(rank),
            "PBOX_WORLD_SIZE": str(nproc),
            "PBOX_COORDINATOR": coordinator,
        })
        if obs_port:
            # pboxlint: disable-next=PB203 -- env export to spawned workers
            env["FLAGS_obs_port"] = str(obs_port + rank)
        stdout = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            stdout = open(os.path.join(log_dir, f"worker-{rank}.log"), "ab")
        return subprocess.Popen([sys.executable, script] + script_args,
                                env=env, stdout=stdout,
                                stderr=subprocess.STDOUT if stdout else None)

    def obs_scrape(final: bool = False) -> None:
        """Best-effort periodic pull of every live worker's /statz; the
        merged view prints once at job teardown (day end)."""
        if not obs_port:
            return
        now = time.time()
        if not final and now - obs_t[0] < 5.0:
            return
        obs_t[0] = now
        from paddlebox_tpu.utils import obs_server
        for r, p in enumerate(procs):
            if p is not None and p.poll() is None:
                # raw=1 ships each worker's histogram buckets so the
                # merged percentiles are recomputed bucket-wise instead
                # of max-of-percentiles (obs_server.merge_snapshots)
                snap = obs_server.scrape(obs_port + r,
                                         path="/statz?raw=1")
                if snap:
                    obs_last[r] = snap
        if final and obs_last:
            merged = obs_server.merge_snapshots(list(obs_last.values()))
            print("[obs] merged job snapshot "
                  f"({len(obs_last)} workers): "
                  + json.dumps(merged, sort_keys=True),
                  file=sys.stderr, flush=True)

    for r in range(nproc):
        procs[r] = spawn(r)

    scraper: Optional[ClusterScraper] = None
    if obs_port:
        # job-level merged timeline: the supervisor serves /clusterz on
        # the port just past the worker range (obs_port + nproc)
        from paddlebox_tpu.utils import obs_server
        scraper = ClusterScraper(
            [obs_port + r for r in range(nproc)]).start()
        obs_server.start(port=obs_port + nproc)

    exit_code = 0
    try:
        while True:
            alive = 0
            for r, p in enumerate(procs):
                if p is None:
                    continue
                ret = p.poll()
                if ret is None:
                    alive += 1
                elif ret != 0 and restarts[r] < max_restarts:
                    restarts[r] += 1
                    flight.record("worker_restart", rank=r, code=ret,
                                  restarts=restarts[r])
                    procs[r] = spawn(r)
                    alive += 1
                elif ret != 0:
                    # fatal: kill the rest (≙ controller abort)
                    exit_code = ret
                    for q in procs:
                        if q is not None and q.poll() is None:
                            q.send_signal(signal.SIGTERM)
                    return exit_code
                else:
                    procs[r] = None
            if alive == 0:
                return exit_code
            obs_scrape()
            time.sleep(0.2)
    except KeyboardInterrupt:
        for q in procs:
            if q is not None and q.poll() is None:
                q.send_signal(signal.SIGTERM)
        return 130
    finally:
        obs_scrape(final=True)
        if scraper is not None:
            scraper.stop()


def launch_elastic(script: str, script_args: List[str], nproc: int,
                   elastic_dir: str,
                   coordinator_host: str = "127.0.0.1",
                   coordinator_base_port: int = 12400,
                   min_workers: int = 1,
                   max_relaunches: int = 3,
                   heartbeat_ttl: float = 6.0,
                   log_dir: str = "",
                   poll_s: float = 0.2,
                   obs_port: int = 0) -> int:
    """Elastic job orchestration: relaunch into a shrunk/regrown world.

    ≙ ElasticManager + launcher cooperating (fleet/elastic/manager.py:131
    watch loop, :217-233 restart path): workers heartbeat into a TTL'd
    FileStore (the etcd-prefix equivalent, elastic.FileStore); the
    launcher watches BOTH process liveness and heartbeats.  On a failure
    it re-rendezvouses: every surviving worker is stopped, lost ranks are
    dropped (scale-in), any pending grow request is honored up to the
    original nproc (scale-out), and a NEW generation spawns with
    renumbered ranks 0..new_world-1, a fresh coordinator port, and
    PBOX_ELASTIC_GEN bumped — workers recover via checkpoint auto-resume
    (io/checkpoint.py), exactly the reference's restart semantics.

    Loss classification (single-host stand-ins for node loss):
      * FIRST exit by SIGKILL      -> treated as a crash: the rank
                                      respawns.  On Linux an OOM-killed
                                      worker also exits -SIGKILL, and a
                                      transient OOM must not permanently
                                      shrink capacity.
      * REPEAT SIGKILL (same rank) -> the rank's "node" really is gone
                                      (or pathologically OOMs): scale-in
      * heartbeat expired, alive   -> partitioned: SIGTERM + scale-in
      * any other nonzero exit     -> crash: rank respawns in the new
                                      generation (same world size)
      * exit 0                     -> done; leaves the job quietly
    Scale-out: write the desired extra worker count into
    ``<elastic_dir>/grow`` — honored at the next (or a voluntary)
    re-rendezvous (≙ the reference watching new joiners under the np
    prefix).

    Returns 0 when every worker of the final generation exits 0; nonzero
    when the world would drop below min_workers or relaunch budget is
    exhausted.
    """
    from paddlebox_tpu.elastic import FileStore

    os.makedirs(elastic_dir, exist_ok=True)
    store = FileStore(os.path.join(elastic_dir, "members"),
                      ttl=heartbeat_ttl)
    grow_path = os.path.join(elastic_dir, "grow")
    gen = 0
    world = nproc
    relaunches = 0

    def spawn(rank: int, world_size: int, generation: int):
        env = dict(os.environ)
        env.update({
            "PBOX_RANK": str(rank),
            "PBOX_WORLD_SIZE": str(world_size),
            "PBOX_COORDINATOR":
                f"{coordinator_host}:{coordinator_base_port + generation}",
            "PBOX_ELASTIC_DIR": elastic_dir,
            "PBOX_ELASTIC_GEN": str(generation),
        })
        if obs_port:
            # rank-based, so ports are stable across generations
            # pboxlint: disable-next=PB203 -- env export to spawned workers
            env["FLAGS_obs_port"] = str(obs_port + rank)
        stdout = None
        try:
            if log_dir:
                os.makedirs(log_dir, exist_ok=True)
                stdout = open(os.path.join(
                    log_dir, f"worker-g{generation}-{rank}.log"), "ab")
            return subprocess.Popen(
                [sys.executable, script] + script_args,
                env=env, stdout=stdout,
                stderr=subprocess.STDOUT if stdout else None)
        finally:
            if stdout is not None:
                stdout.close()          # child holds its own copy

    def read_grow(peek: bool = False) -> int:
        """Parse <elastic_dir>/grow.  Malformed or non-positive requests
        are always consumed (a bad request must not be re-parsed every
        poll); a valid positive one is consumed unless peek=True — the
        voluntary path peeks first so an at-the-cap request stays pending
        for a failure re-rendezvous that CAN honor it."""
        try:
            with open(grow_path) as f:
                raw = f.read().strip()
        except FileNotFoundError:
            return 0
        try:
            val = max(0, int(raw or 0))
        except ValueError:
            print(f"[elastic] ignoring malformed grow request {raw!r}",
                  file=sys.stderr)
            val = 0
        if val == 0 or not peek:
            os.remove(grow_path)
        return val

    def stop_all(procs):
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in procs.values():
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                p.kill()

    procs = {r: spawn(r, world, gen) for r in range(world)}
    scraper: Optional[ClusterScraper] = None
    if obs_port:
        # ports are rank-stable across generations, so one scraper set
        # covers every generation up to the original nproc; dead or
        # shrunk-away ranks simply stop answering
        from paddlebox_tpu.utils import obs_server
        scraper = ClusterScraper(
            [obs_port + r for r in range(nproc)]).start()
        obs_server.start(port=obs_port + nproc)
    sigkills: Dict[int, int] = {}   # rank -> SIGKILL exits across ALL
    # generations (ranks are renumbered per generation; the single-host
    # stand-in treats rank r of every generation as the same "node")
    seen_hb: set = set()    # ranks that registered this generation — a
    # partition verdict needs a once-alive heartbeat (startup time — jax
    # import, data load — must never read as a lost node)
    hb_miss: Dict[int, int] = {}   # consecutive missing-heartbeat polls
    # required before the partition verdict: an exiting worker deletes its
    # key a few ms before its process ends — one missed poll is a race,
    # not a partition
    miss_quorum = max(3, int(heartbeat_ttl / 2 / poll_s))

    try:
        while True:
            time.sleep(poll_s)
            lost, crashed = [], []
            for r, p in list(procs.items()):
                ret = p.poll()
                if ret is None:
                    continue
                if ret == 0:
                    del procs[r]            # done — leaves quietly
                elif ret == -signal.SIGKILL:
                    # a lone SIGKILL is indistinguishable from a transient OOM
                    # kill — respawn like a crash; only a REPEAT verdict on
                    # the same rank reads as real node loss and scales in
                    sigkills[r] = sigkills.get(r, 0) + 1
                    (lost if sigkills[r] > 1 else crashed).append(r)
                else:
                    crashed.append(r)
            # sustained heartbeat loss of a live, once-registered process =
            # partitioned
            alive_hb = {int(k.split("-")[1]) for k in store.alive_keys()}
            for r, p in list(procs.items()):
                if p.poll() is None and r in seen_hb and r not in alive_hb:
                    hb_miss[r] = hb_miss.get(r, 0) + 1
                    if hb_miss[r] >= miss_quorum:
                        p.send_signal(signal.SIGTERM)
                        lost.append(r)
                else:
                    hb_miss.pop(r, None)
            seen_hb |= alive_hb

            if not procs and not lost and not crashed:
                return 0                    # final generation all done
            if lost or crashed:
                # failures spend relaunch budget
                if relaunches >= max_relaunches:
                    stop_all(procs)
                    return 75               # EX_TEMPFAIL: budget exhausted
                relaunches += 1
                grow = read_grow()
            else:
                # voluntary scale-out: free (no failure happened); a healthy
                # job must never die because a grow request arrived after the
                # failure budget was spent
                grow = read_grow(peek=True)
                if not grow:
                    continue
                if min(len(procs) + grow, nproc) <= len(procs):
                    continue                # at the nproc cap — leave pending
                read_grow()                 # honored now: consume it

            # -- re-rendezvous ------------------------------------------------
            # stop EVERYTHING first — including just-SIGTERMed partitioned
            # ranks, so they get the kill escalation + reap and can never keep
            # mutating shared state (the checkpoint) beside the new generation
            stop_all(procs)
            for r in lost + crashed:
                procs.pop(r, None)
            for k in store.alive_keys():    # clean the prefix for the new gen
                store.delete(k)
            survivors = len(procs) + len(crashed)
            new_world = min(survivors + grow, nproc)
            if new_world < min_workers:
                return 76                   # below quorum
            gen += 1
            if new_world > world:
                flight.record("elastic_grow", gen=gen, world=new_world,
                              grew=new_world - world)
            elif new_world < world:
                flight.record("elastic_scale_in", gen=gen, world=new_world,
                              lost=len(lost), crashed=len(crashed))
            flight.record("elastic_rerendezvous", gen=gen, world=new_world,
                          survivors=survivors, grow=grow)
            world = new_world
            procs = {r: spawn(r, world, gen) for r in range(world)}
            seen_hb = set()
            hb_miss = {}
    finally:
        if scraper is not None:
            scraper.stop()


class PSServerSupervisor:
    """``--auto_resume``'s server half: own a PSServer, watch it, and
    restart it in place when it dies (a chaos ``kill()``, an unhandled
    crash) — the replica-watch of ``launch()`` pulled inside one process,
    where the PS tier actually lives in tests and single-host jobs.

    Restart semantics keep exactly-once intact: the new instance binds
    the SAME port (clients retry through their backoff window and land on
    it), shares the SAME table object, and receives the dead instance's
    dedup window via ``PSServer(dedup_state=...)`` — so a client retrying
    a ``push_sparse_delta`` that applied just before the kill replays the
    cached response instead of double-applying.  With ``ckpt_root`` +
    ``reload_from_ckpt=True`` the supervisor instead reloads the last
    committed generation into the table before serving (cross-process
    semantics: rows + DEDUP.bin from ONE checkpoint, io/checkpoint.py).

    Bounded: ``max_restarts`` lifetime budget with exponential backoff
    between attempts; bind retries ride out the dead listener's socket
    lingering in TIME_WAIT.  ``stop()`` shuts the watch down and joins it
    (the managed-lifecycle thread shape, lint rule PB405)."""

    def __init__(self, table, host: str = "127.0.0.1", port: int = 0,
                 max_restarts: int = 8, backoff_base: float = 0.05,
                 backoff_cap: float = 1.0, ckpt_root: Optional[str] = None,
                 reload_from_ckpt: bool = False, poll_s: float = 0.02,
                 shard: Optional[int] = None, membership=None,
                 cluster_shard: Optional[int] = None):
        from paddlebox_tpu.ps.service import PSServer
        self._make = PSServer
        self.table = table
        self.host = host
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self.ckpt_root = ckpt_root
        self.reload_from_ckpt = reload_from_ckpt
        # cluster rank: a sharded fleet member reloads ONLY its own
        # shard-<k:03d>/ checkpoint subdirs (rows + DEDUP.bin)
        self.shard = shard
        self._backoff = (backoff_base, backoff_cap)
        self._poll_s = poll_s
        self._stop = threading.Event()
        # ``membership`` (a ServerMap) turns on epoch fencing;
        # ``cluster_shard`` is the server's index in it (-1 = pending
        # member awaiting a reshard cutover).  Defaults to ``shard``.
        cs = cluster_shard if cluster_shard is not None else (shard or 0)
        self.server = PSServer(table, host=host, port=port,
                               membership=membership, shard=cs)
        self.port = self.server.addr[1]
        self._watch = threading.Thread(target=self._run,
                                       name="pbox-ps-supervisor",
                                       daemon=True)
        self._watch.start()

    @property
    def addr(self):
        return (self.host, self.port)

    def _restart(self) -> bool:
        from paddlebox_tpu.utils.backoff import Backoff
        from paddlebox_tpu.utils.monitor import stat_add, stat_set
        old = self.server
        self.restarts += 1
        flight.record("resume_begin", role="ps_server",
                      restart=self.restarts, port=self.port)
        dedup = old.dedup_state()
        if self.ckpt_root and self.reload_from_ckpt:
            # cross-process restart semantics: distrust the in-process
            # table and take rows AND dedup window from the same committed
            # generation — a window entry for a rid whose write the reload
            # rolled back would otherwise ack a retry without its data
            from paddlebox_tpu.io.checkpoint import TrainCheckpoint
            from paddlebox_tpu.ps.service import _dedup_read
            ck = TrainCheckpoint(self.ckpt_root)
            head = ck.load_table(self.table, shard=self.shard)
            dedup = None
            if head is not None:
                sparse = os.path.join(ck._gen_dir(head), "sparse")
                if self.shard is not None:
                    sparse = os.path.join(sparse,
                                          f"shard-{self.shard:03d}")
                dedup = _dedup_read(sparse)
        bo = Backoff(base=self._backoff[0], cap=self._backoff[1],
                     deadline=30.0)
        attempt = 0
        while not self._stop.is_set():
            try:
                # the dying instance's membership may be AHEAD of what
                # this supervisor was constructed with (a reshard cutover
                # adopted a newer epoch) — carry the latest forward,
                # snapshotted atomically so a cutover racing the restart
                # cannot pair the new map with the old shard index
                membership, shard, _ = old._membership_view()
                self.server = self._make(self.table, host=self.host,
                                         port=self.port,
                                         dedup_state=dedup,
                                         membership=membership,
                                         shard=shard)
                break
            except OSError:
                # the dead listener's port may still be draining
                attempt += 1
                if not bo.sleep(attempt):
                    return False
        else:
            return False
        stat_add("ps.supervisor.restarts")
        stat_set("ps.supervisor.restart_gen", float(self.restarts))
        flight.record("resume_ok", role="ps_server",
                      restart=self.restarts, port=self.port)
        return True

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.server._dead:
                if self.restarts >= self.max_restarts:
                    flight.record("supervisor_give_up",
                                  restarts=self.restarts)
                    return
                if not self._restart():
                    return
            self._stop.wait(self._poll_s)

    def stop(self) -> None:
        """Stop watching and shut the current server down (drain)."""
        self._stop.set()
        self._watch.join(timeout=30.0)
        self.server.shutdown()


class PSFleet:
    """``--ps_servers N``: N supervised PSServers forming one sharded
    cluster — rank-stable ports (rank k binds ``port_base + k`` when a
    base is given), identically-seeded tables (fresh-row defaults are
    pure in (seed, key), so any client sees one consistent key space),
    and one :class:`PSServerSupervisor` per shard for restart-in-place
    with per-shard dedup/checkpoint handoff (``shard-<k:03d>/`` subdirs
    of the generation checkpoint, io/checkpoint.py).

    ``env_value()`` is the ``PBOX_PS_ADDRS`` export — "host:port,..."
    in rank order, which is also ServerMap order: every worker parsing
    it derives the SAME key→shard placement."""

    def __init__(self, n: int, config=None, seed: int = 0,
                 host: str = "127.0.0.1", port_base: int = 0,
                 mf_dim: int = 8, ckpt_root: Optional[str] = None,
                 reload_from_ckpt: bool = False, max_restarts: int = 8):
        from paddlebox_tpu.config import EmbeddingTableConfig
        from paddlebox_tpu.ps.host_table import ShardedHostTable
        if n < 1:
            raise ValueError("PSFleet needs n >= 1 servers")
        cfg = config or EmbeddingTableConfig(embedding_dim=mf_dim)
        self._cfg = cfg
        self._seed = seed
        self._host = host
        self._port_base = port_base
        self._ckpt_root = ckpt_root
        self._max_restarts = max_restarts
        # pboxlint: disable-next=PB803 -- fleet-level epoch mirror, not a ServerMap
        self.epoch = 0
        self.n = n
        self.sups = [self._spawn(k, n, reload_from_ckpt)
                     for k in range(n)]
        # retired (shrunk-away) supervisors stay up for a grace period
        # answering typed redirects + chunk-fate probes, then reap
        self._retired: List = []        # (mono_deadline, supervisor)
        self._apply_membership()

    def _spawn(self, k: int, n: int, reload_from_ckpt: bool = False,
               pending: bool = False):
        from paddlebox_tpu.ps.host_table import ShardedHostTable
        return PSServerSupervisor(
            ShardedHostTable(self._cfg, seed=self._seed),
            host=self._host,
            port=(self._port_base + k) if self._port_base else 0,
            shard=(k if n > 1 else None),
            cluster_shard=(-1 if pending else k),
            ckpt_root=self._ckpt_root,
            reload_from_ckpt=reload_from_ckpt,
            max_restarts=self._max_restarts)

    def _apply_membership(self) -> None:
        """Stamp the fleet's current ServerMap onto every member — the
        addresses are only all known once every server has bound, so
        membership lands right after construction (and after every
        resize), before any worker client connects."""
        from paddlebox_tpu.ps import cluster as ps_cluster
        m = ps_cluster.make_server_map(self.addrs, epoch=self.epoch)
        for k, s in enumerate(self.sups):
            s.server.membership = m
            s.server.shard = k
            s.shard = k if self.n > 1 else None

    @property
    def addrs(self):
        return [s.addr for s in self.sups]

    def env_value(self) -> str:
        from paddlebox_tpu.ps import cluster as ps_cluster
        return ps_cluster.format_addrs(self.addrs)

    def resize(self, new_n: int, workdir: str, *, rounds: int = 2,
               settle_rows: int = 0, timeout: float = 120.0,
               retire_grace: float = 5.0) -> None:
        """Live-resize the fleet to ``new_n`` shards via the key-range
        handoff (ps/reshard.py): grow spawns pending members first
        (``shard=-1`` — they answer typed redirects until the cutover
        admits them); shrink retires the tail AFTER the cutover, keeping
        the retirees up for ``retire_grace`` seconds so late clients
        still draw redirects instead of connection errors.  Serving
        continues throughout; only the moving key range blocks, briefly,
        at the freeze."""
        from paddlebox_tpu.ps import cluster as ps_cluster
        from paddlebox_tpu.ps import reshard as ps_reshard
        from paddlebox_tpu.ps.service import PSClient
        new_n = int(new_n)
        if new_n < 1:
            raise ValueError("PSFleet.resize needs new_n >= 1")
        if new_n == self.n:
            return
        grown = []
        if new_n > self.n:
            grown = [self._spawn(k, new_n, pending=True)
                     for k in range(self.n, new_n)]
            m = ps_cluster.make_server_map(self.addrs, epoch=self.epoch)
            for s in grown:
                s.server.membership = m
        new_addrs = self.addrs + [s.addr for s in grown] \
            if grown else self.addrs[:new_n]
        drv = PSClient(self.addrs, retries=None, deadline=timeout)
        try:
            drv._adopt_map(ps_cluster.make_server_map(
                self.addrs, epoch=self.epoch))
            new_map = ps_reshard.reshard(
                drv, new_addrs, workdir, rounds=rounds,
                settle_rows=settle_rows, timeout=timeout,
                manifest_root=self._ckpt_root)
        except BaseException:
            for s in grown:
                s.stop()
            raise
        finally:
            drv.close()
        now = time.monotonic()
        if new_n > self.n:
            self.sups = self.sups + grown
        else:
            self._retired += [(now + retire_grace, s)
                              for s in self.sups[new_n:]]
            self.sups = self.sups[:new_n]
        self.n = new_n
        # pboxlint: disable-next=PB803 -- fleet-level epoch mirror, not a ServerMap
        self.epoch = new_map.epoch
        for k, s in enumerate(self.sups):
            s.shard = k if new_n > 1 else None
        flight.record("ps_fleet_resize", n=new_n, epoch=self.epoch)

    def reap_retired(self, force: bool = False) -> None:
        """Stop retired supervisors whose grace elapsed (all, when
        ``force``)."""
        now = time.monotonic()
        keep = []
        for deadline, s in self._retired:
            if force or now >= deadline:
                s.stop()
            else:
                keep.append((deadline, s))
        self._retired = keep

    def stop(self) -> None:
        self.reap_retired(force=True)
        for s in self.sups:
            s.stop()


class TrainerSupervisor:
    """``--trainers N``'s per-rank half: own one fleet-trainer rank,
    watch it, and restart it when it dies — the trainer-tier mirror of
    :class:`PSServerSupervisor`.

    The factory builds a FULL fresh incarnation (runner + PSClient +
    shuffle transport) because crash recovery is process-shaped: the new
    runner reads the fleet cursor from the shared manifest, replays its
    namespaced rid groups against the checkpoint shadow, and re-joins
    the surviving ranks' barriers (trainer/fleet_runner.py protocol).
    Nothing of the dead incarnation is reused, so in-proc (test) and
    subprocess (deployment) restarts follow the same code path.

    Bounded by ``max_restarts`` with exponential backoff between
    attempts; ``join()`` surfaces the final result or re-raises the last
    error once the budget is spent.  ``stop()`` abandons the watch and
    joins the thread (PB405)."""

    def __init__(self, runner_factory, rank: int, days,
                 max_restarts: int = 3, backoff_base: float = 0.1,
                 backoff_cap: float = 2.0):
        self._factory = runner_factory
        self.rank = int(rank)
        self.days = days
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self.result = None
        self.error: Optional[BaseException] = None
        self._backoff = (float(backoff_base), float(backoff_cap))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"pbox-trainer-sup-{rank}",
            daemon=True)
        self._thread.start()

    def _run(self) -> None:
        from paddlebox_tpu.utils.backoff import Backoff
        from paddlebox_tpu.utils.monitor import stat_add, stat_observe
        bo = Backoff(base=self._backoff[0], cap=self._backoff[1])
        t_crash: Optional[float] = None
        while not self._stop.is_set():
            try:
                runner = self._factory(self.rank)
            except BaseException as e:  # noqa: BLE001 — factory = restart
                self.error = e
                runner = None
            if runner is not None:
                if t_crash is not None:
                    # MTTR from observed death to the replacement
                    # incarnation (fresh client + transport, rebuilt by
                    # the factory) entering run() — what the bench's
                    # restart_mttr_s gate measures
                    stat_observe("trainer.fleet.restart_mttr_s",
                                 time.monotonic() - t_crash)
                    t_crash = None
                try:
                    self.result = runner.run(self.days)
                    self.error = None
                    return
                except BaseException as e:  # noqa: BLE001 — any death restarts
                    self.error = e
            if self.restarts >= self.max_restarts:
                flight.record("supervisor_give_up", role="trainer",
                              rank=self.rank, restarts=self.restarts)
                return
            self.restarts += 1
            if t_crash is None:
                t_crash = time.monotonic()
            flight.record("trainer_restart", rank=self.rank,
                          restart=self.restarts,
                          error=type(self.error).__name__)
            stat_add("trainer.supervisor.restarts")
            bo.sleep(self.restarts)

    def join(self, timeout: Optional[float] = None):
        """Wait for the supervised rank to finish; returns its result or
        re-raises its terminal error (restart budget spent)."""
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError(
                f"trainer rank {self.rank} still running after "
                f"{timeout}s")
        if self.result is None and self.error is not None:
            raise self.error
        return self.result

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30.0)


class PSElasticWatcher:
    """``--ps_elastic DIR``: honor live fleet-resize requests.

    Drop a positive integer into ``<dir>/ps_grow`` (servers to add) or
    ``<dir>/ps_shrink`` (servers to remove; the fleet never shrinks
    below 1) and the watcher drives :meth:`PSFleet.resize` — snapshot,
    delta catch-up, freeze, epoch-bumped cutover — then re-exports
    ``PBOX_PS_ADDRS`` for future worker generations (live workers
    discover the new map through typed redirects + the health probe
    fall-through, no restart needed).  Requests are consumed
    best-effort: a malformed file is eaten and logged; a failed resize
    is rolled back by the driver (the fleet keeps serving the old
    epoch) and the request is dropped rather than retried forever."""

    def __init__(self, fleet: PSFleet, elastic_dir: str, workroot: str,
                 poll_s: float = 0.5, retire_grace: float = 5.0,
                 rounds: int = 2, timeout: float = 120.0):
        os.makedirs(elastic_dir, exist_ok=True)
        self.fleet = fleet
        self.dir = elastic_dir
        self.workroot = workroot
        self.retire_grace = retire_grace
        self.rounds = rounds
        self.timeout = timeout
        self._poll_s = poll_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="pbox-ps-elastic",
                                        daemon=True)
        self._thread.start()

    def _consume(self, name: str) -> int:
        """Read-and-unlink ``<dir>/<name>``; 0 when absent/malformed
        (a bad request must not be re-parsed every poll)."""
        path = os.path.join(self.dir, name)
        try:
            with open(path) as f:
                raw = f.read().strip()
        except OSError:
            return 0
        try:
            os.unlink(path)
        except OSError:
            pass
        try:
            return max(0, int(raw))
        except ValueError:
            print(f"[ps-elastic] ignoring malformed {name}: {raw!r}",
                  file=sys.stderr)
            return 0

    def _resize(self, target: int) -> None:
        workdir = os.path.join(self.workroot,
                               f"reshard-e{self.fleet.epoch + 1}")
        try:
            self.fleet.resize(target, workdir, rounds=self.rounds,
                              timeout=self.timeout,
                              retire_grace=self.retire_grace)
        except Exception as e:
            print(f"[ps-elastic] resize to {target} failed "
                  f"(fleet keeps serving epoch {self.fleet.epoch}): {e}",
                  file=sys.stderr)
            return
        from paddlebox_tpu.ps import cluster as ps_cluster
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ[ps_cluster.ADDRS_ENV] = self.fleet.env_value()
        print(f"[ps-elastic] fleet now n={self.fleet.n} "
              f"epoch={self.fleet.epoch}", file=sys.stderr)

    def _run(self) -> None:
        while not self._stop.is_set():
            grow = self._consume("ps_grow")
            if grow:
                self._resize(self.fleet.n + grow)
            shrink = self._consume("ps_shrink")
            if shrink:
                self._resize(max(1, self.fleet.n - shrink))
            self.fleet.reap_retired()
            self._stop.wait(self._poll_s)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30.0)


class ServingReplicaSupervisor:
    """PSServerSupervisor's serving-tier sibling: own a ServingReplica,
    watch it, restart it in place when it dies.  Restart keeps the
    router's world intact: the new replica binds the SAME port, inherits
    the dead instance's dedup window, and re-resolves the CURRENT xbox
    swap manifest before serving — a replica that died on day N and
    restarts after the trainer published day N+1 comes back serving
    N+1, not a stale dump.  ``stop()`` joins the watch and drains the
    replica (PB405 lifecycle)."""

    def __init__(self, config=None, xbox_path: Optional[str] = None,
                 manifest_root: Optional[str] = None, tenants=None,
                 max_inflight: Optional[int] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 max_restarts: int = 8, backoff_base: float = 0.05,
                 backoff_cap: float = 1.0, poll_s: float = 0.02,
                 watch_s: float = 0.0, seed: int = 0,
                 shard: int = 0, n_shards: int = 1,
                 ckpt_root: Optional[str] = None, hot_keys=None):
        from paddlebox_tpu.ps.serving import ServingReplica
        self._make = ServingReplica
        self.config = config
        self.xbox_path = xbox_path
        self.manifest_root = manifest_root
        self.tenants = tenants
        self.max_inflight = max_inflight
        self.host = host
        self.watch_s = watch_s
        self.seed = seed
        self.shard = int(shard)
        self.n_shards = max(1, int(n_shards))
        self.ckpt_root = ckpt_root
        self.hot_keys = hot_keys
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self._backoff = (backoff_base, backoff_cap)
        self._poll_s = poll_s
        self._stop = threading.Event()
        path, day, gen = self._resolve_dump()
        self.replica = ServingReplica(
            config=config, xbox_path=path, tenants=tenants,
            max_inflight=max_inflight, host=host, port=port,
            day=day, generation=gen, seed=seed,
            shard=self.shard, n_shards=self.n_shards,
            ckpt_root=ckpt_root, hot_keys=hot_keys)
        self.port = self.replica.addr[1]
        self._arm_watch()
        self._watch = threading.Thread(target=self._run,
                                       name="pbox-serving-supervisor",
                                       daemon=True)
        self._watch.start()

    @property
    def addr(self):
        return (self.host, self.port)

    def _resolve_dump(self):
        """(path, day, generation) of the dump to load NOW — the swap
        manifest when one is published, else the pinned --serve_xbox."""
        if self.manifest_root:
            from paddlebox_tpu.io.checkpoint import read_xbox_manifest
            man = read_xbox_manifest(self.manifest_root)
            if man:
                return (man["path"], str(man.get("day", "")),
                        int(man["generation"]))
        return self.xbox_path, "", 1

    def _arm_watch(self) -> None:
        # ckpt delta-streaming trumps day-granularity manifest polling:
        # a replica fed from a TrainCheckpoint gets pass-level freshness
        if self.ckpt_root:
            self.replica.watch_ckpt(self.ckpt_root)
        elif self.manifest_root and self.watch_s > 0:
            self.replica.watch_manifest(self.manifest_root, self.watch_s)

    def _restart(self) -> bool:
        from paddlebox_tpu.utils.backoff import Backoff
        from paddlebox_tpu.utils.monitor import stat_add
        old = self.replica
        self.restarts += 1
        flight.record("resume_begin", role="serving_replica",
                      restart=self.restarts, port=self.port)
        dedup = old.dedup_state()
        path, day, gen = self._resolve_dump()
        bo = Backoff(base=self._backoff[0], cap=self._backoff[1],
                     deadline=30.0)
        attempt = 0
        while not self._stop.is_set():
            try:
                self.replica = self._make(
                    config=self.config, xbox_path=path,
                    tenants=self.tenants, max_inflight=self.max_inflight,
                    host=self.host, port=self.port, day=day,
                    generation=gen, seed=self.seed, dedup_state=dedup,
                    shard=self.shard, n_shards=self.n_shards,
                    ckpt_root=self.ckpt_root, hot_keys=self.hot_keys)
                break
            except OSError:
                attempt += 1
                if not bo.sleep(attempt):
                    return False
        else:
            return False
        self._arm_watch()
        stat_add("serving.supervisor.restarts")
        flight.record("resume_ok", role="serving_replica",
                      restart=self.restarts, port=self.port)
        return True

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.replica._dead:
                if self.restarts >= self.max_restarts:
                    flight.record("supervisor_give_up",
                                  role="serving_replica",
                                  restarts=self.restarts)
                    return
                if not self._restart():
                    return
            self._stop.wait(self._poll_s)

    def stop(self) -> None:
        self._stop.set()
        self._watch.join(timeout=30.0)
        self.replica.shutdown()


def serve_fleet(args) -> int:
    """--serve N: run N supervised serving replicas in this process and
    block until interrupted.  Prints the replica addresses (one per
    line, ``host:port``) so a router — ``ServingRouter([...])`` or an
    external LB — can be pointed at the fleet.

    With ``--serve_shards S`` the N replicas split into S ServerMap
    shard groups (replica i serves shard i % S) and the router runs in
    ``shard_groups`` mode: per-shard fan, p2c hot-key routing, group
    failover.  ``--serve_ckpt`` feeds the fleet pass-delta freshness
    from a TrainCheckpoint instead of day-granularity xbox manifests."""
    from paddlebox_tpu.config import EmbeddingTableConfig
    from paddlebox_tpu.ps.serving import ServingRouter
    tenants = [t.strip() for t in (args.serve_tenants or "default"
                                   ).split(",") if t.strip()]
    config = EmbeddingTableConfig(embedding_dim=args.serve_mf_dim)
    n_shards = max(1, int(getattr(args, "serve_shards", 1) or 1))
    if n_shards > args.serve:
        raise SystemExit(f"--serve_shards {n_shards} needs at least that "
                         f"many replicas (--serve {args.serve})")
    ckpt_root = getattr(args, "serve_ckpt", "") or None
    sups = [ServingReplicaSupervisor(
        config=config,
        xbox_path=args.serve_xbox or None,
        manifest_root=args.serve_manifest or None,
        tenants=tenants,
        max_inflight=args.serve_max_inflight,
        watch_s=args.serve_watch_s,
        seed=args.serve_seed,
        shard=i % n_shards, n_shards=n_shards,
        ckpt_root=ckpt_root,
        max_restarts=args.max_restarts or 8)
        for i in range(args.serve)]
    for s in sups:
        print(f"[serve] replica {s.addr[0]}:{s.addr[1]} "
              f"shard={s.shard}/{n_shards} "
              f"tenants={','.join(tenants)}", file=sys.stderr)
    if n_shards > 1:
        groups = [[s.addr for s in sups if s.shard == k]
                  for k in range(n_shards)]
        router = ServingRouter(shard_groups=groups, tenant=tenants[0])
        router.refresh_hot_keys()
    else:
        router = ServingRouter([s.addr for s in sups], tenant=tenants[0])
    try:
        while True:
            time.sleep(5.0)
            router.observe_generation()    # fleet-wide swap coherence
            gens = router.generations()
            if len(gens) > 1:
                print(f"[serve] hot-swap in flight: generations {gens}",
                      file=sys.stderr)
    except KeyboardInterrupt:
        pass
    finally:
        router.close()
        for s in sups:
            s.stop()
    return 0


def main():
    ap = argparse.ArgumentParser(prog="paddlebox_tpu.launch")
    ap.add_argument("--nproc_per_node", type=int, default=1)
    ap.add_argument("--coordinator", default="127.0.0.1:12355")
    ap.add_argument("--max_restarts", type=int, default=0)
    ap.add_argument("--log_dir", default="")
    ap.add_argument("--elastic_dir", default="",
                    help="enable elastic relaunch orchestration on this "
                         "shared dir (≙ the etcd prefix)")
    ap.add_argument("--min_workers", type=int, default=1)
    ap.add_argument("--max_relaunches", type=int, default=3)
    ap.add_argument("--chaos_backend", default="",
                    help="host:port of a live PSServer; the launcher "
                         "spawns a seeded ChaosProxy (ps/faults.py) in "
                         "front of it and exports PBOX_PS_ADDR so workers "
                         "train through injected connection chaos — the "
                         "multi-process face of the chaos soak suite")
    ap.add_argument("--chaos_seed", type=int, default=0)
    # PS wire-path knobs, exported to every worker as FLAGS_* env (the
    # flag registry reads FLAGS_<name> at import): pipelined pull/push
    # stream pool, in-flight window, and payload quantization
    ap.add_argument("--ps_streams", type=int, default=None,
                    help="workers' PSClient connection-pool size "
                         "(FLAGS_ps_streams; 1 = stop-and-wait)")
    ap.add_argument("--ps_window", type=int, default=None,
                    help="max chunk frames in flight per pipelined verb "
                         "(FLAGS_ps_window)")
    ap.add_argument("--ps_wire_dtype", default="",
                    choices=("", "f32", "f16", "i8"),
                    help="wire encoding of float32 PS row payloads "
                         "(FLAGS_ps_wire_dtype; server state stays fp32)")
    ap.add_argument("--ps_table_threads", type=int, default=None,
                    help="host-table shard worker pool size on every "
                         "worker (FLAGS_ps_table_threads; per-shard "
                         "pull/write/save/load fan across it, 1 = "
                         "sequential)")
    ap.add_argument("--pack_threads", type=int, default=None,
                    help="whole-pass packer pool size on every worker "
                         "(FLAGS_pass_pack_threads; per-slot/record-range "
                         "pad+translate fan across it, bit-identical at "
                         "any setting, 1 = sequential)")
    ap.add_argument("--pass_prefetch", type=int, default=None,
                    choices=(0, 1),
                    help="pipeline the pass feed on every worker "
                         "(FLAGS_pass_prefetch): pass N+1's load/pull/"
                         "pack run in the background while pass N trains")
    ap.add_argument("--ps_device_cache", type=int, default=None,
                    choices=(0, 1),
                    help="keep the hottest embedding rows resident in "
                         "device memory across passes on every worker "
                         "(FLAGS_ps_device_cache): build_pull fetches "
                         "only cache misses over the wire; bit-identical "
                         "to off")
    ap.add_argument("--ps_device_cache_rows", type=int, default=None,
                    help="row capacity of each worker's device-resident "
                         "hot-row cache (FLAGS_ps_device_cache_rows; "
                         "ps/device_cache.py)")
    ap.add_argument("--auto_resume", type=int, default=0,
                    help="crash-recovery budget (FLAGS_auto_resume): each "
                         "worker's fleet.train_passes rolls back to the "
                         "last committed checkpoint generation and "
                         "re-drives the partial pass up to this many "
                         "times; also floors --max_restarts so respawned "
                         "workers actually get to resume.  0 = off")
    ap.add_argument("--ckpt_dir", default="",
                    help="checkpoint root for every worker "
                         "(FLAGS_ckpt_dir): generation-chained saves "
                         "after each pass + auto-resume restore from "
                         "here (io/checkpoint.py)")
    ap.add_argument("--obs_port", type=int, default=0,
                    help="observability exporter base port: worker rank r "
                         "serves /metrics + /statz + /tracez + /flightz "
                         "+ /debugz on obs_port + r (FLAGS_obs_port); "
                         "the launcher scrapes all workers and prints one "
                         "merged snapshot at job end.  0 = off")
    ap.add_argument("--obs_flight_ring", type=int, default=None,
                    help="flight-recorder ring capacity on every worker "
                         "(FLAGS_obs_flight_ring; newest-N lifecycle "
                         "events served as /flightz and embedded in "
                         "postmortems).  0 disables")
    ap.add_argument("--obs_postmortem_dir", default="",
                    help="directory for wedge-doctor postmortem bundles "
                         "(FLAGS_obs_postmortem_dir; SIGUSR1 on any "
                         "worker writes one).  empty = <tmpdir>/"
                         "pbox-postmortems")
    ap.add_argument("--obs_timeline_interval_s", type=float, default=None,
                    help="telemetry-timeline sample cadence on every "
                         "worker (FLAGS_obs_timeline_interval_s; serves "
                         "/timelinez, feeds the SLO watchdog, embeds in "
                         "postmortems).  0 = off")
    ap.add_argument("--obs_timeline_ring", type=int, default=None,
                    help="timeline ring capacity per worker "
                         "(FLAGS_obs_timeline_ring; newest-N samples)")
    ap.add_argument("--obs_slo_watchdog", type=int, default=None,
                    help="evaluate the SLO rule set on every timeline "
                         "sample (FLAGS_obs_slo_watchdog; breaches emit "
                         "latched slo_breach flight events).  1 = on")
    ap.add_argument("--obs_heat", type=int, default=None,
                    help="key-space heat sketches on every worker "
                         "(FLAGS_obs_heat; ps/heat.py serves /heatz — "
                         "hot keys, shard skew, working-set size — and "
                         "the supervisor's /clusterz merges the fleet "
                         "view).  1 = on")
    ap.add_argument("--obs_heat_topk", type=int, default=None,
                    help="heavy-hitter capacity per heat site "
                         "(FLAGS_obs_heat_topk)")
    ap.add_argument("--obs_heat_width", type=int, default=None,
                    help="count-min sketch width per heat site "
                         "(FLAGS_obs_heat_width)")
    ap.add_argument("--obs_heat_depth", type=int, default=None,
                    help="count-min sketch depth per heat site "
                         "(FLAGS_obs_heat_depth)")
    ap.add_argument("--ps_servers", type=int, default=0,
                    help="start N supervised PSServer shards in the "
                         "launcher process (one PSServerSupervisor each, "
                         "rank-stable ports, restart-in-place with "
                         "per-shard dedup/checkpoint handoff) and export "
                         "PBOX_PS_ADDRS so every worker's PSClient fans "
                         "chunked verbs across the cluster.  0 = off")
    ap.add_argument("--ps_port_base", type=int, default=0,
                    help="shard k binds ps_port_base + k (0 = ephemeral "
                         "ports; rank order stays the ServerMap order "
                         "either way)")
    ap.add_argument("--ps_mf_dim", type=int, default=8,
                    help="PS fleet table embedding_dim — must match the "
                         "training script's table config")
    ap.add_argument("--ps_seed", type=int, default=0,
                    help="PS fleet fresh-row seed; all shards share it "
                         "(defaults are pure in (seed, key), so the "
                         "cluster key space is consistent)")
    ap.add_argument("--ps_elastic", default="",
                    help="watch DIR/ps_grow and DIR/ps_shrink for live "
                         "fleet-resize requests (integer = servers to "
                         "add/remove) and drive the key-range handoff "
                         "(ps/reshard.py) without stopping training; "
                         "PBOX_PS_ADDRS is re-exported after each "
                         "cutover.  '' = off")
    ap.add_argument("--ps_reshard_rounds", type=int, default=2,
                    help="delta catch-up rounds before the reshard "
                         "freeze (>= 1)")
    ap.add_argument("--ps_retire_grace", type=float, default=5.0,
                    help="seconds a shrunk-away PS server keeps "
                         "answering typed redirects before it stops")
    ap.add_argument("--serve", type=int, default=0,
                    help="run N supervised read-only serving replicas "
                         "(ps/serving.py) instead of training workers; "
                         "needs --serve_xbox, --serve_manifest or "
                         "--serve_ckpt")
    ap.add_argument("--serve_xbox", default="",
                    help="xbox dump to serve (pinned; no hot-swap unless "
                         "--serve_manifest is also given)")
    ap.add_argument("--serve_manifest", default="",
                    help="directory holding XBOX_MANIFEST.json; replicas "
                         "load the manifest's dump and hot-swap when the "
                         "trainer publishes the next day")
    ap.add_argument("--serve_tenants", default="default",
                    help="comma-separated tenant namespaces "
                         "(FLAGS_serve_tenants)")
    ap.add_argument("--serve_max_inflight", type=int, default=None,
                    help="per-tenant admission cap; excess pulls are shed "
                         "with a typed overload error "
                         "(FLAGS_serve_max_inflight)")
    ap.add_argument("--serve_watch_s", type=float, default=2.0,
                    help="manifest poll cadence for hot-swap (0 = never "
                         "poll; swaps only via the swap verb)")
    ap.add_argument("--serve_mf_dim", type=int, default=8,
                    help="table embedding_dim — must match the trainer "
                         "that wrote the dump")
    ap.add_argument("--serve_seed", type=int, default=0,
                    help="default-row seed — must match the trainer for "
                         "bit-identical miss rows")
    ap.add_argument("--serve_shards", type=int, default=1,
                    help="split the fleet into S ServerMap shard groups "
                         "(replica i serves shard i %% S); the router "
                         "fans per shard and merges in key order")
    ap.add_argument("--serve_ckpt", default="",
                    help="TrainCheckpoint root to stream: replicas load "
                         "the manifest head's base+delta chain and hot-"
                         "patch each new save_pass generation "
                         "(pass-granularity freshness vs day-granularity "
                         "--serve_manifest)")
    ap.add_argument("--serve_hot_keys", type=int, default=None,
                    help="top-K heat-sketch keys replicated into every "
                         "shard group for p2c routing (0 = off) "
                         "(FLAGS_serving_hot_keys)")
    ap.add_argument("script", nargs="?", default="")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.serve and not args.script:
        ap.error("script is required unless --serve is given")
    # EXPORTS for the worker processes — set_flags() cannot cross the
    # process boundary, the child's flag registry reads FLAGS_* at import
    if args.ps_streams is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_ps_streams"] = str(args.ps_streams)
    if args.ps_window is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_ps_window"] = str(args.ps_window)
    if args.ps_wire_dtype:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_ps_wire_dtype"] = args.ps_wire_dtype
    if args.ps_table_threads is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_ps_table_threads"] = str(args.ps_table_threads)
    if args.pack_threads is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_pass_pack_threads"] = str(args.pack_threads)
    if args.pass_prefetch is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_pass_prefetch"] = str(args.pass_prefetch)
    if args.ps_device_cache is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_ps_device_cache"] = str(args.ps_device_cache)
    if args.ps_device_cache_rows is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_ps_device_cache_rows"] = str(
            args.ps_device_cache_rows)
    if args.obs_flight_ring is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_obs_flight_ring"] = str(args.obs_flight_ring)
    if args.obs_postmortem_dir:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_obs_postmortem_dir"] = args.obs_postmortem_dir
    if args.obs_timeline_interval_s is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_obs_timeline_interval_s"] = str(
            args.obs_timeline_interval_s)
    if args.obs_timeline_ring is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_obs_timeline_ring"] = str(args.obs_timeline_ring)
    if args.obs_slo_watchdog is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_obs_slo_watchdog"] = str(args.obs_slo_watchdog)
    if args.obs_heat is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_obs_heat"] = str(args.obs_heat)
    if args.obs_heat_topk is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_obs_heat_topk"] = str(args.obs_heat_topk)
    if args.obs_heat_width is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_obs_heat_width"] = str(args.obs_heat_width)
    if args.obs_heat_depth is not None:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_obs_heat_depth"] = str(args.obs_heat_depth)
    if args.auto_resume:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_auto_resume"] = str(args.auto_resume)
        # a worker that dies outside train_passes (import crash, OOM)
        # only resumes if the launcher respawns it: floor the respawn
        # budget so --auto_resume alone yields a self-healing job
        args.max_restarts = max(args.max_restarts, args.auto_resume)
    if args.ckpt_dir:
        # pboxlint: disable-next=PB203 -- env export to spawned workers
        os.environ["FLAGS_ckpt_dir"] = args.ckpt_dir
    if args.serve:
        if args.serve_tenants:
            # pboxlint: disable-next=PB203 -- env export to spawned workers
            os.environ["FLAGS_serve_tenants"] = args.serve_tenants
        if args.serve_max_inflight is not None:
            # pboxlint: disable-next=PB203 -- env export to spawned workers
            os.environ["FLAGS_serve_max_inflight"] = str(
                args.serve_max_inflight)
        if args.serve_hot_keys is not None:
            # pboxlint: disable-next=PB203 -- env export to spawned workers
            os.environ["FLAGS_serving_hot_keys"] = str(args.serve_hot_keys)
        if not (args.serve_xbox or args.serve_manifest or args.serve_ckpt):
            ap.error("--serve needs --serve_xbox, --serve_manifest or "
                     "--serve_ckpt")
        sys.exit(serve_fleet(args))
    ps_fleet = None
    if args.ps_servers:
        from paddlebox_tpu.ps import cluster as _ps_cluster
        ps_fleet = PSFleet(
            args.ps_servers, mf_dim=args.ps_mf_dim, seed=args.ps_seed,
            port_base=args.ps_port_base,
            ckpt_root=args.ckpt_dir or None,
            reload_from_ckpt=bool(args.ckpt_dir),
            max_restarts=max(args.max_restarts, 8))
        os.environ[_ps_cluster.ADDRS_ENV] = ps_fleet.env_value()
        for k, (h, p) in enumerate(ps_fleet.addrs):
            print(f"[ps] shard {k} {h}:{p}", file=sys.stderr)
    ps_watcher = None
    if args.ps_elastic:
        if ps_fleet is None:
            ap.error("--ps_elastic needs --ps_servers")
        ps_watcher = PSElasticWatcher(
            ps_fleet, args.ps_elastic,
            workroot=os.path.join(args.ps_elastic, "reshard"),
            retire_grace=args.ps_retire_grace,
            rounds=max(1, args.ps_reshard_rounds))
    proxy = None
    if args.chaos_backend:
        from paddlebox_tpu.ps.faults import ChaosProxy, FaultPlan
        bhost, _, bport = args.chaos_backend.rpartition(":")
        proxy = ChaosProxy((bhost or "127.0.0.1", int(bport)),
                           FaultPlan.default_chaos(args.chaos_seed))
        os.environ["PBOX_PS_ADDR"] = f"{proxy.addr[0]}:{proxy.addr[1]}"
        print(f"[chaos] proxy {proxy.addr} -> {args.chaos_backend} "
              f"(seed {args.chaos_seed})", file=sys.stderr)
    try:
        if args.elastic_dir:
            host, _, port = args.coordinator.rpartition(":")
            rc = launch_elastic(
                args.script, args.script_args, args.nproc_per_node,
                args.elastic_dir,
                coordinator_host=host or "127.0.0.1",
                coordinator_base_port=int(port) if port else 12400,
                min_workers=args.min_workers,
                max_relaunches=args.max_relaunches, log_dir=args.log_dir,
                obs_port=args.obs_port)
        else:
            rc = launch(args.script, args.script_args,
                        args.nproc_per_node, args.coordinator,
                        args.max_restarts, args.log_dir,
                        obs_port=args.obs_port)
    finally:
        if proxy is not None:
            proxy.shutdown()
        if ps_watcher is not None:
            ps_watcher.stop()
        if ps_fleet is not None:
            ps_fleet.stop()
    sys.exit(rc)


if __name__ == "__main__":
    main()
