"""Structured configs.

TPU-native replacement for the reference's three config layers (SURVEY.md §5):
protobuf descs TrainerDesc (trainer_desc.proto:21), DataFeedDesc
(data_feed.proto:17-43) and DistributedStrategy
(fleet/base/distributed_strategy.py:110) become plain dataclasses; gflags
become paddlebox_tpu.flags.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple


@dataclasses.dataclass(frozen=True)
class SlotConfig:
    """One input slot (≙ data_feed.proto Slot: name/type/is_used/is_dense).

    ``capacity`` is the static per-instance feasign capacity used to pad
    variable-length slots for XLA (the reference carries true var-len LoD;
    under jit we need fixed shapes — SURVEY.md §7 hard part (5)).
    """

    name: str
    slot_id: int = 0
    # "uint64" (sparse feasigns), "float" (dense), or "string" (aux keys
    # resolved through an InputTable into stable int indices at parse
    # time — ≙ InputTableDataFeed, data_feed.h:2224; the index plane
    # reaches the model as an extras input, gathered against a
    # ReplicaCache/dense var like ops lookup_input)
    dtype: str = "uint64"
    is_dense: bool = False
    dim: int = 1           # values per instance for dense slots
    capacity: int = 1      # max feasigns per instance for sparse slots


@dataclasses.dataclass(frozen=True)
class DataFeedConfig:
    """≙ DataFeedDesc (data_feed.proto:17-43)."""

    slots: Tuple[SlotConfig, ...]
    batch_size: int = 512
    pipe_command: str = ""          # shell preprocessor (≙ pipe_command_)
    parser: str = "multi_slot"      # "multi_slot" | "slot_feasign"
    rand_seed: int = 0
    # PV-merge rank_offset plane for rank-attention models
    # (≙ DataFeedDesc.rank_offset, data_feed.cc:1851; built per batch by
    # data/rank_offset.py — requires logkey-parsed cmatch/rank fields)
    rank_offset: bool = False
    max_rank: int = 3               # hardcoded 3 in the reference (:1858)
    # ≙ DataFeedDesc.ads_offset (data_feed.cc:3092 + GetAdsOffset:
    # the [pv_num+1] prefix offsets of each page view's ads within the
    # batch) — emitted as a static [B+1] extras plane (tail repeats the
    # real-instance count); requires pv-grouped batches like rank_offset
    ads_offset: bool = False
    # ≙ MultiSlotDesc.uid_slot: the sparse slot whose FIRST feasign is the
    # instance's user id — feeds the per-user WuAUC metrics (host-side
    # accumulation; opting in adds one preds D2H per batch, exactly the
    # reference's SyncCopyD2H in add_uid_data, metrics.cc:440)
    uid_slot: str = ""
    # ≙ DataFeedDesc.sample_rate: keep each instance with this probability
    # at load time (feed-level downsampling)
    sample_rate: float = 1.0

    def __post_init__(self):
        object.__setattr__(self, "slots", tuple(self.slots))
        dense_str = [s.name for s in self.slots
                     if s.dtype == "string" and s.is_dense]
        if dense_str:
            raise ValueError(
                f"string slots {dense_str} cannot be is_dense — they are "
                "aux index planes (InputTable), not dense features")
        if not (0.0 < self.sample_rate <= 1.0):
            raise ValueError(
                f"sample_rate must be in (0, 1], got {self.sample_rate}")
        if self.uid_slot and self.uid_slot not in {
                s.name for s in self.sparse_slots}:
            raise ValueError(
                f"uid_slot {self.uid_slot!r} is not a sparse slot")
        reserved = {"indices", "lengths", "dense", "labels", "valid",
                    "rank_offset", "ads_offset"}
        bad = [s.name for s in self.string_slots if s.name in reserved]
        if bad:
            raise ValueError(
                f"string slot names {bad} collide with reserved feed plane "
                "names — rename the slot")

    @property
    def sparse_slots(self) -> List[SlotConfig]:
        return [s for s in self.slots
                if not s.is_dense and s.dtype != "string"]

    @property
    def dense_slots(self) -> List[SlotConfig]:
        return [s for s in self.slots if s.is_dense]

    @property
    def string_slots(self) -> List[SlotConfig]:
        """Aux string-keyed slots (InputTable-resolved index planes)."""
        return [s for s in self.slots
                if s.dtype == "string" and not s.is_dense]


@dataclasses.dataclass(frozen=True)
class SparseSGDConfig:
    """Per-feature optimizer hyper-parameters.

    Field-for-field parity with OptimizerConfig
    (heter_ps/optimizer_conf.h:22-45); defaults match the reference.
    """

    optimizer: str = "adagrad"   # adagrad | adam | shared_adam | naive
    nonclk_coeff: float = 0.1
    clk_coeff: float = 1.0
    min_bound: float = -10.0
    max_bound: float = 10.0
    learning_rate: float = 0.05
    initial_g2sum: float = 3.0
    initial_range: float = 1e-4
    beta1_decay_rate: float = 0.9
    beta2_decay_rate: float = 0.999
    ada_epsilon: float = 1e-8
    mf_create_thresholds: float = 10.0
    mf_learning_rate: float = 0.05
    mf_initial_g2sum: float = 3.0
    mf_initial_range: float = 1e-4
    mf_min_bound: float = -10.0
    mf_max_bound: float = 10.0
    feature_learning_rate: float = 0.05
    nodeid_slot: int = 9008
    # per-slot mf widths (≙ CtrDymfAccessor's dynamic embedx dim,
    # ctr_dymf_accessor.h + feature_value.h:42): ((slot_id, dim), ...).
    # Lives on the SGD config because the update rules consume it (the
    # mean-square divisor / moment means use the row's true dim).
    slot_mf_dims: Tuple[Tuple[int, int], ...] = ()


@dataclasses.dataclass(frozen=True)
class AccessorConfig:
    """Feature lifecycle policy (≙ CtrCommonAccessor / ctr_accessor.h):
    show/click time-decay each pass-day, delete/shrink thresholds, save
    thresholds for base/delta dumps."""

    accessor_type: str = "ctr"       # "ctr" | "ctr_double" (f64 show/click,
                                     # ≙ DownpourCtrDoubleAccessor)
    show_click_decay_rate: float = 0.98
    delete_threshold: float = 0.8
    delete_after_unseen_days: float = 30.0
    base_threshold: float = 1.5      # save_base keeps score >= this
    delta_threshold: float = 0.25    # save_delta keeps |delta_score| >= this
    delta_keep_days: float = 16.0


@dataclasses.dataclass(frozen=True)
class EmbeddingTableConfig:
    """One logical sparse table (≙ DistributedStrategy sparse_table_configs,
    distributed_strategy.py:534-640, + CommonFeatureValue layout
    feature_value.h:44-57)."""

    name: str = "embedding"
    embedding_dim: int = 8           # mf_dim (embedx width, excl. show/clk/lr-w)
    sgd: SparseSGDConfig = dataclasses.field(default_factory=SparseSGDConfig)
    accessor: AccessorConfig = dataclasses.field(default_factory=AccessorConfig)
    shard_num: int = 16              # host-table shards (≙ memory_sparse_table.h:46)
    quant_bits: int = 0              # 0 = no embedding quantization
    expand_dim: int = 0              # NNCross second embedding width
                                     # (≙ expand_embed_dim, pull_box_extended)

    def slot_mf_dim(self, slot_id: int) -> int:
        """Slot's mf width under the dynamic-dim accessor (sgd.slot_mf_dims,
        ≙ CtrDymfAccessor); defaults to embedding_dim.  TPU-first layout:
        storage stays at embedding_dim (static shapes); a slot with dim
        d < embedding_dim trains/pulls only its first d columns — pulls
        mask the tail to zero, the optimizer scales by the row's true dim."""
        for sid, d in self.sgd.slot_mf_dims:
            if sid == slot_id:
                if d > self.embedding_dim:
                    raise ValueError(
                        f"slot {sid} mf dim {d} exceeds embedding_dim "
                        f"{self.embedding_dim}")
                return d
        return self.embedding_dim


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    """≙ TrainerDesc + BoxPSWorkerParameter (trainer_desc.proto:21,121-129)."""

    thread_num: int = 1
    dense_sync_mode: str = "allreduce"   # allreduce | async_table | sharded
    sync_weight_step: int = 1            # ≙ sync_weight_step
    # adam hyper-params of the async dense table's update thread
    # (≙ BoxPSAsynDenseTable's built-in rule, boxps_worker.cc:260-330)
    async_dense_learning_rate: float = 1e-3
    async_dense_beta1: float = 0.9
    async_dense_beta2: float = 0.999
    async_dense_eps: float = 1e-8
    dump_fields: Tuple[str, ...] = ()
    dump_path: str = ""


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Hybrid-parallel topology degrees (≙ HybridCommunicateGroup,
    fleet/base/topology.py:134-144 [dp, sharding, pp, mp] — extended with the
    TPU-first sp/ep axes the reference lacks, SURVEY.md §2.7)."""

    dp: int = 1
    sharding: int = 1
    pp: int = 1
    mp: int = 1
    sp: int = 1
    ep: int = 1

    def degrees(self):
        return {"dp": self.dp, "sharding": self.sharding, "pp": self.pp,
                "mp": self.mp, "sp": self.sp, "ep": self.ep}

    @property
    def world_size(self) -> int:
        n = 1
        for v in self.degrees().values():
            n *= v
        return n


@dataclasses.dataclass(frozen=True)
class DistributedStrategy:
    """≙ fleet.DistributedStrategy (distributed_strategy.py:110)."""

    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    amp: bool = False
    amp_dtype: str = "bfloat16"
    gradient_merge_steps: int = 1
    recompute: bool = False
    table: EmbeddingTableConfig = dataclasses.field(
        default_factory=EmbeddingTableConfig)
