"""MMoE multi-task recommender (BASELINE.md config 4: shared embedding +
expert mixture + per-task gates/towers).  apply() returns task-0 logits for
the single-label trainer; apply_multi() returns [B, num_tasks] for the
multi-task trainer path."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.layers import init_mlp, mlp_apply


class MMoE:
    def __init__(self, num_slots: int, emb_width: int, dense_dim: int,
                 num_experts: int = 4, num_tasks: int = 2,
                 expert_hidden: Sequence[int] = (64,),
                 tower_hidden: Sequence[int] = (32,)):
        self.num_slots = num_slots
        self.emb_width = emb_width
        self.dense_dim = dense_dim
        self.num_experts = num_experts
        self.num_tasks = num_tasks
        self.expert_hidden = tuple(expert_hidden)
        self.tower_hidden = tuple(tower_hidden)

    def init(self, key):
        in_dim = self.num_slots * self.emb_width + self.dense_dim
        keys = jax.random.split(key, self.num_experts + 2 * self.num_tasks)
        experts = [init_mlp(keys[i], (in_dim,) + self.expert_hidden)
                   for i in range(self.num_experts)]
        gates = [jax.random.normal(keys[self.num_experts + t],
                                   (in_dim, self.num_experts)) * 0.02
                 for t in range(self.num_tasks)]
        towers = [init_mlp(keys[self.num_experts + self.num_tasks + t],
                           (self.expert_hidden[-1],) + self.tower_hidden
                           + (1,))
                  for t in range(self.num_tasks)]
        return {"experts": experts, "gates": gates, "towers": towers}

    def apply_multi(self, params, pooled, dense):
        x = jnp.concatenate([pooled, dense], axis=-1)
        expert_out = jnp.stack(
            [jax.nn.relu(mlp_apply(e, x)) for e in params["experts"]],
            axis=1)  # [B, E, H]
        logits = []
        for t in range(self.num_tasks):
            g = jax.nn.softmax(x @ params["gates"][t], axis=-1)  # [B, E]
            mixed = jnp.einsum("be,beh->bh", g, expert_out)
            logits.append(mlp_apply(params["towers"][t], mixed)[:, 0])
        return jnp.stack(logits, axis=1)  # [B, T]

    def apply(self, params, pooled, dense):
        return self.apply_multi(params, pooled, dense)[:, 0]
