"""Minimal functional NN layers (dense path runs on the MXU in bf16-friendly
shapes; no framework dependency so models stay pure pytrees)."""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp


def init_mlp(key, sizes: Sequence[int], scale: str = "xavier"):
    params = []
    for i in range(len(sizes) - 1):
        key, sub = jax.random.split(key)
        fan_in, fan_out = sizes[i], sizes[i + 1]
        if scale == "xavier":
            bound = jnp.sqrt(6.0 / (fan_in + fan_out))
        else:
            bound = 1.0 / jnp.sqrt(fan_in)
        w = jax.random.uniform(sub, (fan_in, fan_out), jnp.float32,
                               -bound, bound)
        params.append({"w": w, "b": jnp.zeros((fan_out,), jnp.float32)})
    return params


def mlp_apply(params, x, final_act=None):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
        elif final_act is not None:
            x = final_act(x)
    return x
