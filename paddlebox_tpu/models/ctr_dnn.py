"""Plain DNN CTR model (the reference's baseline "join" model shape:
pull_box_sparse → fused_seqpool_cvm → concat dense features → MLP → sigmoid;
≙ the CTR models in python/paddle/fluid/tests/unittests/dist_fleet_ctr.py and
BASELINE.md config 1)."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.layers import init_mlp, mlp_apply


class CtrDnn:
    """Consumes the fused_seqpool_cvm output [B, S*(3+D)] + dense [B, Dd]."""

    def __init__(self, num_slots: int, emb_width: int, dense_dim: int,
                 hidden: Sequence[int] = (512, 256, 128)):
        self.num_slots = num_slots
        self.emb_width = emb_width   # 3 + mf_dim (show', click', w, embedx)
        self.dense_dim = dense_dim
        self.hidden = tuple(hidden)

    def init(self, key):
        in_dim = self.num_slots * self.emb_width + self.dense_dim
        return {"mlp": init_mlp(key, (in_dim,) + self.hidden + (1,))}

    def apply(self, params, pooled: jnp.ndarray, dense: jnp.ndarray
              ) -> jnp.ndarray:
        x = jnp.concatenate([pooled, dense], axis=-1)
        return mlp_apply(params["mlp"], x)[:, 0]  # logits [B]
