"""DeepFM over pooled slot embeddings (BASELINE.md config 2, PaddleRec
recipe): first-order = per-slot scalar weights (the pull value's embed_w
column), second-order = FM interaction over per-slot embedx vectors, deep
part = MLP over the full pooled output + dense features."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.layers import init_mlp, mlp_apply


class DeepFM:
    def __init__(self, num_slots: int, emb_width: int, dense_dim: int,
                 hidden: Sequence[int] = (400, 400, 400)):
        self.num_slots = num_slots
        self.emb_width = emb_width  # 3 + mf_dim
        self.mf_dim = emb_width - 3
        self.dense_dim = dense_dim
        self.hidden = tuple(hidden)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        in_dim = self.num_slots * self.emb_width + self.dense_dim
        return {
            "mlp": init_mlp(k1, (in_dim,) + self.hidden + (1,)),
            "dense_w": jax.random.uniform(
                k2, (self.dense_dim, 1), jnp.float32, -0.01, 0.01),
            "bias": jnp.zeros((1,), jnp.float32),
        }

    def apply(self, params, pooled: jnp.ndarray, dense: jnp.ndarray
              ) -> jnp.ndarray:
        B = pooled.shape[0]
        per_slot = pooled.reshape(B, self.num_slots, self.emb_width)
        first = jnp.sum(per_slot[:, :, 2], axis=1, keepdims=True) \
            + dense @ params["dense_w"]
        v = per_slot[:, :, 3:]                      # [B, S, D]
        sum_sq = jnp.sum(v, axis=1) ** 2            # [B, D]
        sq_sum = jnp.sum(v ** 2, axis=1)
        second = 0.5 * jnp.sum(sum_sq - sq_sum, axis=1, keepdims=True)
        deep_in = jnp.concatenate([pooled, dense], axis=-1)
        deep = mlp_apply(params["mlp"], deep_in)
        logit = params["bias"] + first + second + deep
        return logit[:, 0]
