from paddlebox_tpu.models.ctr_dnn import CtrDnn  # noqa: F401
from paddlebox_tpu.models.deepfm import DeepFM  # noqa: F401
