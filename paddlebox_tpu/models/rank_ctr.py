"""Rank-attention CTR model — the PV-learning join-phase model shape.

≙ the PaddleBox models that consume the PV-merge `rank_offset` feed
(data_feed.cc:1855 GetRankOffset) through the rank_attention op
(operators/rank_attention_op.cu): each ad attends over the other ads of
its page view with a parameter block selected by the (own rank, peer
rank) pair, and the attention output joins the MLP input.

Declares ``extra_inputs = ("rank_offset",)`` — the trainer feeds the
batch's rank_offset plane as a keyword argument (trainer.py extras
plumbing), on both the per-batch and the pass-resident paths.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.layers import init_mlp, mlp_apply
from paddlebox_tpu.ops.rank_attention import rank_attention


class RankAttentionCTR:
    extra_inputs = ("rank_offset",)

    def __init__(self, num_slots: int, emb_width: int, dense_dim: int,
                 att_out: int = 32, max_rank: int = 3,
                 hidden: Sequence[int] = (128, 64)):
        self.num_slots = num_slots
        self.emb_width = emb_width
        self.dense_dim = dense_dim
        self.att_out = att_out
        self.max_rank = max_rank
        self.hidden = tuple(hidden)
        self.in_col = num_slots * emb_width

    def init(self, key):
        k1, k2 = jax.random.split(key)
        in_dim = self.in_col + self.att_out + self.dense_dim + 1
        return {
            "mlp": init_mlp(k1, (in_dim,) + self.hidden + (1,)),
            # [max_rank*max_rank*in_col, att_out] block layout — the
            # `start = lower*max_rank + faster` addressing of
            # rank_attention.cu.h:90
            "rank_param": jax.random.uniform(
                k2, (self.max_rank * self.max_rank * self.in_col,
                     self.att_out), jnp.float32, -0.01, 0.01),
        }

    def apply(self, params, pooled: jnp.ndarray, dense: jnp.ndarray,
              rank_offset: jnp.ndarray) -> jnp.ndarray:
        att, ins_rank = rank_attention(
            pooled, rank_offset, params["rank_param"], self.max_rank)
        x = jnp.concatenate(
            [pooled, att, dense, ins_rank[:, None]], axis=-1)
        return mlp_apply(params["mlp"], x)[:, 0]
