"""Wide&Deep with fused_seqpool_cvm sequence features (BASELINE.md config 3).

Wide: sparse linear over the pooled slot outputs (the CVM-transformed
show/click cols + per-slot embed_w act as the wide crossed features) plus
dense features; Deep: MLP over pooled embeddings + dense."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.layers import init_mlp, mlp_apply


class WideDeep:
    def __init__(self, num_slots: int, emb_width: int, dense_dim: int,
                 hidden: Sequence[int] = (256, 128, 64)):
        self.num_slots = num_slots
        self.emb_width = emb_width
        self.dense_dim = dense_dim
        self.hidden = tuple(hidden)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        in_dim = self.num_slots * self.emb_width + self.dense_dim
        return {
            "mlp": init_mlp(k1, (in_dim,) + self.hidden + (1,)),
            "wide_w": jax.random.uniform(k2, (in_dim, 1), jnp.float32,
                                         -0.01, 0.01),
            "wide_b": jnp.zeros((1,), jnp.float32),
        }

    def apply(self, params, pooled, dense):
        x = jnp.concatenate([pooled, dense], axis=-1)
        wide = x @ params["wide_w"] + params["wide_b"]
        deep = mlp_apply(params["mlp"], x)
        return (wide + deep)[:, 0]
