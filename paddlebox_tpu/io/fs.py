"""Pluggable filesystem layer — local / AFS / HDFS behind one surface.

≙ the reference's fs abstraction (framework/io/fs.{h,cc}: localfs_* +
hdfs_* verbs dispatched by path prefix, with hdfs access running through
shell commands) and BoxWrapper's AFS wrapper (box_wrapper.h:721-743
dataset_name/afs path plumbing).  Model dumps, checkpoints and dataset
reads route through ``get_fs(path)`` so a job can point save_base/load at
``hdfs://...`` (or any scheme with a registered command set) without code
changes.

The remote flavor shells out exactly like the reference's hdfs_cat /
hdfs_put (fs.cc): reads stream via the configured cat command, writes pipe
through put — no client library dependency in a zero-egress image.
"""

from __future__ import annotations

import io
import os
import shlex
import subprocess
from typing import Dict, Iterator, List, Optional


class FileSystem:
    """Minimal verb set the framework needs (≙ fs.h's *_open_read/write,
    exists, list, mkdir, remove)."""

    def open_read(self, path: str) -> io.BufferedIOBase:
        raise NotImplementedError

    def open_write(self, path: str) -> io.BufferedIOBase:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def ls(self, path: str) -> List[str]:
        raise NotImplementedError

    def mkdir(self, path: str) -> None:
        raise NotImplementedError

    def remove(self, path: str) -> None:
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> None:
        """Atomic-where-possible move (the write-to-tmp-then-rename commit
        step of host_table.save).  Schemes without a move verb raise
        NotImplementedError and callers fall back to direct writes."""
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        with self.open_read(path) as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        # atomic publish is the caller's commit protocol (tmp + rename)
        # pboxlint: disable-next=PB502 -- FS primitive, not a commit
        with self.open_write(path) as f:
            f.write(data)


class LocalFS(FileSystem):
    """≙ localfs_* (fs.cc).  Accepts bare paths and file:// URLs."""

    @staticmethod
    def _strip(path: str) -> str:
        return path[7:] if path.startswith("file://") else path

    def open_read(self, path: str):
        return open(self._strip(path), "rb")

    def open_write(self, path: str):
        path = self._strip(path)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # durable callers open a *.tmp name and commit via rename()
        # pboxlint: disable-next=PB502 -- the write primitive itself
        return open(path, "wb")

    def exists(self, path: str) -> bool:
        return os.path.exists(self._strip(path))

    def ls(self, path: str) -> List[str]:
        path = self._strip(path)
        return sorted(
            os.path.join(path, p) for p in os.listdir(path))

    def mkdir(self, path: str) -> None:
        os.makedirs(self._strip(path), exist_ok=True)

    def remove(self, path: str) -> None:
        path = self._strip(path)
        if os.path.isdir(path):
            import shutil
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src: str, dst: str) -> None:
        os.replace(self._strip(src), self._strip(dst))


class ShellFS(FileSystem):
    """Remote fs through shell commands, the reference's hdfs pattern
    (fs.cc hdfs_cat/hdfs_put/hdfs_ls/hdfs_mkdir/hdfs_remove built from a
    configurable command prefix: `hadoop fs [-D ugi] -verb`).

    Commands are templates with {path}; reads stream the cat's stdout,
    writes pipe into put's stdin.
    """

    def __init__(self, cat_cmd: str, put_cmd: str, ls_cmd: str = "",
                 mkdir_cmd: str = "", exists_cmd: str = "",
                 remove_cmd: str = "", rename_cmd: str = ""):
        self.cat_cmd = cat_cmd
        self.put_cmd = put_cmd
        self.ls_cmd = ls_cmd
        self.mkdir_cmd = mkdir_cmd
        self.exists_cmd = exists_cmd
        self.remove_cmd = remove_cmd
        self.rename_cmd = rename_cmd    # template with {src} and {dst}

    @classmethod
    def hadoop(cls, fs_name: str = "", ugi: str = "",
               binary: str = "hadoop") -> "ShellFS":
        """The stock hdfs/afs command set (≙ hdfs command assembly in
        fs.cc + the AFS ugi plumbing of box_wrapper.h:721)."""
        conf = ""
        if fs_name:
            conf += f" -D fs.default.name={shlex.quote(fs_name)}"
        if ugi:
            conf += f" -D hadoop.job.ugi={shlex.quote(ugi)}"
        base = f"{binary} fs{conf}"
        return cls(cat_cmd=base + " -cat {path}",
                   put_cmd=base + " -put - {path}",
                   ls_cmd=base + " -ls {path}",
                   mkdir_cmd=base + " -mkdir -p {path}",
                   exists_cmd=base + " -test -e {path}",
                   remove_cmd=base + " -rm -r {path}",
                   rename_cmd=base + " -mv {src} {dst}")

    def _run(self, tmpl: str, path: str, **kw):
        return subprocess.Popen(tmpl.format(path=shlex.quote(path)),
                                shell=True, **kw)

    def open_read(self, path: str):
        proc = self._run(self.cat_cmd, path, stdout=subprocess.PIPE)
        return _PipeReader(proc)

    def open_write(self, path: str):
        proc = self._run(self.put_cmd, path, stdin=subprocess.PIPE)
        return _PipeWriter(proc)

    def exists(self, path: str) -> bool:
        if not self.exists_cmd:
            raise NotImplementedError("no exists_cmd configured")
        proc = self._run(self.exists_cmd, path)
        return proc.wait() == 0

    def ls(self, path: str) -> List[str]:
        if not self.ls_cmd:
            raise NotImplementedError("no ls_cmd configured")
        proc = self._run(self.ls_cmd, path, stdout=subprocess.PIPE)
        out, _ = proc.communicate()
        # hadoop -ls prints permission/size columns; path is the last field
        names = []
        for line in out.decode(errors="replace").splitlines():
            parts = line.split()
            if parts and "/" in parts[-1]:
                names.append(parts[-1])
        return names

    def mkdir(self, path: str) -> None:
        if self.mkdir_cmd:
            rc = self._run(self.mkdir_cmd, path).wait()
            if rc != 0:
                raise IOError(f"fs mkdir failed rc={rc} for {path!r}")

    def remove(self, path: str) -> None:
        if self.remove_cmd:
            rc = self._run(self.remove_cmd, path).wait()
            if rc != 0:
                raise IOError(f"fs remove failed rc={rc} for {path!r}")

    def rename(self, src: str, dst: str) -> None:
        if not self.rename_cmd:
            raise NotImplementedError("no rename_cmd configured")
        cmd = self.rename_cmd.format(src=shlex.quote(src),
                                     dst=shlex.quote(dst))
        rc = subprocess.Popen(cmd, shell=True).wait()
        if rc != 0:
            raise IOError(f"fs rename failed rc={rc} for "
                          f"{src!r} -> {dst!r}")


class _PipeReader(io.RawIOBase):
    def __init__(self, proc):
        self._proc = proc

    def readable(self):
        return True

    def read(self, n=-1):
        return self._proc.stdout.read(n)

    def readinto(self, b):
        data = self._proc.stdout.read(len(b))
        b[: len(data)] = data
        return len(data)

    def close(self):
        try:
            self._proc.stdout.close()
            rc = self._proc.wait()
            if rc != 0:
                raise IOError(f"fs read command failed rc={rc}")
        finally:
            super().close()


class _PipeWriter(io.RawIOBase):
    def __init__(self, proc):
        self._proc = proc

    def writable(self):
        return True

    def write(self, b):
        self._proc.stdin.write(b)
        return len(b)

    def close(self):
        try:
            self._proc.stdin.close()
            rc = self._proc.wait()
            if rc != 0:
                raise IOError(f"fs write command failed rc={rc}")
        finally:
            super().close()


# -- scheme registry (≙ fs_* dispatch-by-prefix, fs.cc) ---------------------

_REGISTRY: Dict[str, FileSystem] = {"": LocalFS(), "file": LocalFS()}


def register_fs(scheme: str, fs: FileSystem) -> None:
    """Register/replace the filesystem for a path scheme (e.g.
    register_fs("hdfs", ShellFS.hadoop(fs_name, ugi)) ≙ the AFS config
    handoff of box_wrapper.h:721-743)."""
    _REGISTRY[scheme.rstrip(":/")] = fs


def split_scheme(path: str):
    if "://" in path:
        scheme, rest = path.split("://", 1)
        return scheme, path
    return "", path


def get_fs(path: str) -> FileSystem:
    scheme, _ = split_scheme(path)
    fs = _REGISTRY.get(scheme)
    if fs is None:
        raise ValueError(
            f"no filesystem registered for scheme {scheme!r} "
            f"(register_fs({scheme!r}, ShellFS.hadoop(...)))")
    return fs


def open_read(path: str):
    return get_fs(path).open_read(path)


def open_write(path: str):
    return get_fs(path).open_write(path)


def exists(path: str) -> bool:
    return get_fs(path).exists(path)
