"""Checkpoint / resume orchestration.

≙ the reference's two-tier day/pass persistence (SURVEY.md §5): sparse
SaveBase/SaveDelta + dense save_persistables, re-driven by date from ops
scripts.  The rebuild adds what the reference lacked: a single
``TrainCheckpoint`` that atomically captures {dense params, optimizer state,
metric state, day/pass cursor} next to the sparse table dump so a killed job
resumes mid-day (`resume()` → last completed pass).

Layout:
  <root>/sparse/…            per-shard npz (ShardedHostTable.save mode=all)
  <root>/dense.msgpack       flax-serialized params/opt_state pytree
  <root>/STATE.json          {day_id, pass_id, step, auc_state?}
  <root>/xbox/…              serving dump (save_xbox)
"""

from __future__ import annotations

import json
import math
import os
import shutil
import tempfile
import warnings
from typing import Dict, Optional, Tuple

import numpy as np
import jax

from flax import serialization

from paddlebox_tpu.ps.pass_manager import BoxPSEngine


def _atomic_write(path: str, data: bytes) -> None:
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
    with os.fdopen(fd, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


class TrainCheckpoint:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def save(self, engine: BoxPSEngine, trainer, extra: Optional[Dict] = None
             ) -> None:
        """Capture engine table + trainer dense state + cursor."""
        sparse_dir = os.path.join(self.root, "sparse.tmp")
        if os.path.exists(sparse_dir):
            shutil.rmtree(sparse_dir)
        engine.table.save(sparse_dir, mode="all")
        final = os.path.join(self.root, "sparse")
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(sparse_dir, final)

        dense = {
            "params": jax.device_get(trainer.params),
            "opt_state": jax.device_get(trainer.opt_state),
        }
        _atomic_write(os.path.join(self.root, "dense.msgpack"),
                      serialization.to_bytes(dense))

        state = {"day_id": engine.day_id, "pass_id": engine.pass_id,
                 "phase": engine.phase}
        if extra:
            state.update(extra)
        _atomic_write(os.path.join(self.root, "STATE.json"),
                      json.dumps(state).encode())

    def resume(self, engine: BoxPSEngine, trainer) -> Optional[Dict]:
        """Restore everything; returns the cursor dict or None if no ckpt."""
        state_path = os.path.join(self.root, "STATE.json")
        if not os.path.exists(state_path):
            return None
        with open(state_path) as f:
            state = json.load(f)
        engine.table.load(os.path.join(self.root, "sparse"))
        engine.day_id = state.get("day_id")
        engine.pass_id = state.get("pass_id", 0)
        engine.phase = state.get("phase", 1)
        with open(os.path.join(self.root, "dense.msgpack"), "rb") as f:
            dense = serialization.from_bytes(
                {"params": jax.device_get(trainer.params),
                 "opt_state": jax.device_get(trainer.opt_state)},
                f.read())
        trainer.params = dense["params"]
        trainer.opt_state = dense["opt_state"]
        return state


def save_xbox(engine: BoxPSEngine, path: str, base: bool = True) -> int:
    """Serving-model dump (≙ the "xbox" base/delta format written by
    SaveBase/SaveDelta, box_wrapper.cc:1286): one line per surviving
    feature — key \\t show \\t click \\t embed_w \\t mf...  Quantization of
    embedx (quant_bits) applies here when configured.

    Row selection/masking is vectorized per shard and formatting runs in
    the native TSV writer (native/dump_writer.cc, ≙ the reference's
    native dump IO through PaddleFileMgr) with a per-row Python fallback.
    """
    from paddlebox_tpu.native import dump_writer
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    acc = engine.config.accessor
    qbits = engine.config.quant_bits
    n = 0
    fh = None if dump_writer.available() else open(path, "w")
    try:
        for shard in engine.table._shards:
            with shard.lock:
                soa = shard.soa
                score = engine.table._score(soa)
                keep = (score >= acc.base_threshold) if base else \
                    (np.abs(soa["delta_score"]) >= acc.delta_threshold)
                idx = np.nonzero(keep)[0]
                if not len(idx):
                    continue
                keys = shard.keys[idx]
                show = soa["show"][idx]
                click = soa["click"][idx]
                embed_w = soa["embed_w"][idx]
                # uncreated embedx serves zeros in training (pull_sparse
                # masks by mf_size) — dump the SAME values or the serving
                # side would see the random candidate init
                mf = np.where((soa["mf_size"][idx] > 0)[:, None],
                              soa["mf"][idx], np.float32(0))
                if qbits:
                    scale = (1 << (qbits - 1)) - 1
                    mf = np.round(mf * scale) / scale
            if fh is None:
                dump_writer.dump_rows(path, append=n > 0, keys=keys,
                                      show=show, click=click,
                                      embed_w=embed_w, mf=mf)
            else:
                for i in range(len(keys)):
                    vals = " ".join(f"{v:.6g}" for v in mf[i])
                    fh.write(f"{keys[i]}\t{show[i]:.6g}\t{click[i]:.6g}\t"
                             f"{embed_w[i]:.6g}\t{vals}\n")
            n += len(idx)
        if fh is None and n == 0:
            open(path, "w").close()     # empty dump still creates the file
    finally:
        if fh is not None:
            fh.close()
    return n


def load_xbox(engine: BoxPSEngine, path: str) -> np.ndarray:
    """Serving-side read-back of an xbox dump — the loader the reference
    keeps in its serving stack (the dump of SaveBase/SaveDelta,
    box_wrapper.cc:1286, is what the online predictor consumes).

    Writes the dumped rows into the engine's host table (optimizer state
    zero-initialized — serving never pushes) and returns the loaded keys;
    the caller then runs the normal pass lifecycle over them and
    optionally `engine.freeze_for_serving()` for int16 embedx pulls:

        keys = load_xbox(engine, path)
        engine.begin_feed_pass(); engine.add_keys(keys)
        engine.end_feed_pass(); engine.begin_pass()
        engine.freeze_for_serving()
    """
    if getattr(engine, "mode", "train") != "serving":
        warnings.warn(
            "load_xbox on a training-mode engine: the xbox dump re-derives "
            "mf_size as any(mf != 0), so a created row whose embedx "
            "trained to exactly all zeros round-trips as uncreated and "
            "would re-initialize on training resume.  Use load_checkpoint "
            "(TrainCheckpoint.resume) for training resume, or build the "
            "engine with mode='serving' for a serving path.",
            UserWarning, stacklevel=2)
    from paddlebox_tpu.native import dump_writer
    d = engine.config.embedding_dim
    native = dump_writer.load_rows(path, d)
    if native is not None:
        keys, shows, clicks, ws_, mf_mat = native
    else:
        keys, shows, clicks, ws_, mfs = [], [], [], [], []
        # Parity contract: for WRITER-PRODUCED files (save_xbox / the
        # native dump_writer — plain decimal, single-tab-separated), this
        # fallback and pbox_load_xbox give the same verdict and the same
        # reported row index.  Hand-edited exotica (hex floats, '_' digit
        # grouping, whitespace-padded fields) are outside that contract
        # and may parse differently between the two.
        with open(path) as f:
            lineno = 0      # counts parsed (non-empty) rows, exactly like
            for line in f:  # the native parser's -(row+1) — same file,
                # same reported index on native and fallback hosts
                parts = line.rstrip("\n").split("\t")
                if not line.strip():
                    continue
                lineno += 1
                if len(parts) != 5:
                    raise ValueError(
                        f"malformed xbox line {lineno}: {line[:80]!r}")
                try:
                    key = int(parts[0])
                    if not 0 <= key < 1 << 64:
                        raise ValueError("key out of uint64 range")
                    stats = [float(parts[1]), float(parts[2]),
                             float(parts[3])]
                    with np.errstate(over="ignore"):  # inf rejected below
                        mf = (np.array(parts[4].split(), np.float32)
                              if parts[4] else np.zeros((0,), np.float32))
                except ValueError as e:
                    raise ValueError(
                        f"malformed xbox line {lineno}: {line[:80]!r}"
                    ) from e
                keys.append(key)
                # reject overflow-to-inf exactly like the native parser
                # (pbox_load_xbox), so the same file parses — or fails —
                # identically on fallback-only hosts
                if not all(map(math.isfinite, stats)) or \
                        not np.all(np.isfinite(mf)):
                    raise ValueError(
                        f"malformed xbox line {lineno}: non-finite value "
                        f"in {line[:80]!r}")
                shows.append(stats[0])
                clicks.append(stats[1])
                ws_.append(stats[2])
                if len(mf) != d:
                    raise ValueError(
                        f"malformed xbox line {lineno}: mf width "
                        f"{len(mf)} != table dim {d}")
                mfs.append(mf)
        mf_mat = (np.stack(mfs) if mfs
                  else np.zeros((0, d), np.float32))
    keys = np.asarray(keys, np.uint64)
    if not len(keys):
        return keys
    shows = np.asarray(shows, np.float32)
    clicks = np.asarray(clicks, np.float32)
    ws_ = np.asarray(ws_, np.float32)
    # dedupe LAST-wins: a concatenated base+delta file naturally repeats
    # keys, and the table's upsert contract requires unique keys per call
    # (host_table.py — duplicates would double-insert)
    last = len(keys) - 1 - np.unique(keys[::-1], return_index=True)[1]
    if len(last) != len(keys):
        sel = np.sort(last)
        keys = keys[sel]
        shows, clicks, ws_ = shows[sel], clicks[sel], ws_[sel]
        mf_mat = mf_mat[sel]
    rows = engine.table.bulk_pull(keys)     # schema defaults
    rows["show"] = shows
    rows["click"] = clicks
    rows["embed_w"] = ws_
    rows["mf"] = np.asarray(mf_mat, np.float32)
    # the dump writes zeros for uncreated embedx (see save_xbox) — derive
    # mf_size so serving pulls mask exactly like training did.  SERVING-ONLY
    # contract: a created row whose embedding trained to exactly all zeros
    # round-trips as uncreated (served values identical — zeros either way),
    # but resuming TRAINING from an xbox dump would re-initialize such rows'
    # embedx; use save_checkpoint/load_checkpoint (which carry mf_size
    # explicitly) for training resume.
    created = np.any(rows["mf"] != 0.0, axis=1)
    rows["mf_size"] = np.where(created, d, 0).astype(rows["mf_size"].dtype)
    # zero every field the dump does not carry (optimizer state, scores)
    # — serving never pushes, and a delta-refresh over existing rows must
    # not resurrect their stale training state
    keep = {"show", "click", "embed_w", "mf", "mf_size", "slot"}
    for fld in rows:
        if fld not in keep:
            rows[fld] = np.zeros_like(rows[fld])
    engine.table.bulk_write(keys, rows)
    return keys
