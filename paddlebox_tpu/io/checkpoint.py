"""Checkpoint / resume orchestration — generation-chained and crash-safe.

≙ the reference's two-tier day/pass persistence (SURVEY.md §5): sparse
SaveBase/SaveDelta + dense save_persistables, re-driven by date from ops
scripts.  The rebuild adds what the reference lacked: a single
``TrainCheckpoint`` that atomically captures {dense params, optimizer state,
day/pass cursor, server dedup window} next to the sparse table dump so a
killed job resumes mid-day (`resume()` → last completed pass).

Layout (immutable generations + one atomic pointer)::

  <root>/MANIFEST.json        {"generation": n} — the ONLY mutable file,
                              swapped via tmp+rename (_atomic_write)
  <root>/gen-<n>/STATE.json   {generation, kind, chain, day_id, pass_id,
                              phase, rows, ...extra}
  <root>/gen-<n>/sparse/…     per-shard npz: the full table (kind=base)
                              or just the rows the pass wrote (kind=delta)
  <root>/gen-<n>/dense.msgpack flax-serialized params/opt_state pytree
  <root>/xbox/…               serving dump (save_xbox)

Crash-safety argument: a generation is assembled under ``gen-<n>.tmp``,
renamed to ``gen-<n>``, and only THEN does MANIFEST advance.  A crash at
any point leaves either the old MANIFEST pointing at a complete old
generation (tmp/orphan dirs are ignored and reclaimed by the next save's
GC) or the new MANIFEST pointing at a complete new one — there is no
window in which no checkpoint loads (the old layout's rmtree-then-replace
had exactly that window).

Incremental cost: ``save_pass`` writes a *delta* generation holding only
the rows the finished pass wrote (``engine._last_written``), so the
per-pass cost is proportional to the pass delta, not the table.  Every
``FLAGS_ckpt_every_passes`` generations the chain is compacted into a
fresh base; ``FLAGS_ckpt_keep`` bounds retained history (retain-K GC
never collects a generation a surviving chain still references).

Resume walks the head generation's chain: load the base wholesale, then
upsert each delta in order, then restore dense params + cursors from the
head.  When the sparse save ran through a PSServer (RemoteTableAdapter),
the server persisted its dedup window next to the shard files and the
chain load restores it — exactly-once survives a server restart
(ps/service.py).
"""

from __future__ import annotations

import json
import math
import os
import shutil
import tempfile
import time
import warnings
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax

from flax import serialization

from paddlebox_tpu import flags
from paddlebox_tpu.ps import faults
from paddlebox_tpu.ps.pass_manager import BoxPSEngine
from paddlebox_tpu.utils import flight
from paddlebox_tpu.utils.monitor import stat_add, stat_observe, stat_set

flags.define_flag(
    "ckpt_keep", 3,
    "retain-K checkpoint GC: keep the newest K committed generations "
    "(plus every older generation a surviving delta chain references)")
flags.define_flag(
    "ckpt_every_passes", 8,
    "base-compaction cadence: after this many generations on one delta "
    "chain, the next per-pass save writes a full base instead of a delta")
flags.define_flag(
    "auto_resume", 0,
    "crash-recovery budget for fleet.train_passes: on a trainer-side "
    "failure, roll back to the last committed generation and re-drive "
    "the partial pass, at most this many times per call (0 disables)")
flags.define_flag(
    "ckpt_dir", "",
    "default TrainCheckpoint root for fleet.train_passes — when set, "
    "train_passes saves a delta generation after every pass and "
    "auto-resume restores from here")

MANIFEST = "MANIFEST.json"


def _atomic_write(path: str, data: bytes) -> None:
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
    with os.fdopen(fd, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


class TrainCheckpoint:
    """Generation-chained checkpoint store (see module docstring).

    ``save``       full base generation (table mode="all" + dense + cursor)
    ``save_pass``  incremental per-pass generation: delta rows only, with
                   periodic base compaction
    ``resume``     restore table (base + delta chain), dense, cursors
    """

    def __init__(self, root: str, keep: Optional[int] = None,
                 base_every: Optional[int] = None):
        self.root = root
        self.keep = max(1, int(flags.get_flags("ckpt_keep")
                               if keep is None else keep))
        self.base_every = max(1, int(flags.get_flags("ckpt_every_passes")
                                     if base_every is None else base_every))
        os.makedirs(root, exist_ok=True)

    # -- layout helpers ------------------------------------------------------
    def _gen_dir(self, n: int) -> str:
        return os.path.join(self.root, f"gen-{n:06d}")

    def _manifest(self) -> Optional[int]:
        path = os.path.join(self.root, MANIFEST)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            gen = json.load(f).get("generation")
        # a membership-only manifest (commit_membership before any
        # checkpoint ever committed) carries generation: null
        return None if gen is None else int(gen)

    def _state(self, n: int) -> Dict:
        with open(os.path.join(self._gen_dir(n), "STATE.json")) as f:
            return json.load(f)

    def _committed(self) -> List[int]:
        """Committed generation numbers ≤ the manifest head, ascending.
        Orphans past the head (a crash between dir rename and pointer
        swap) are excluded — they never became reachable."""
        head = self._manifest()
        if head is None:
            return []
        out = []
        for name in os.listdir(self.root):
            if not name.startswith("gen-") or name.endswith(".tmp"):
                continue
            try:
                n = int(name[4:])
            except ValueError:
                continue
            if n <= head and \
                    os.path.exists(os.path.join(self.root, name,
                                                "STATE.json")):
                out.append(n)
        return sorted(out)

    # -- save ----------------------------------------------------------------
    def save(self, engine: BoxPSEngine, trainer,
             extra: Optional[Dict] = None) -> int:
        """Full checkpoint: a new BASE generation.  Returns its number."""
        return self._save_generation(engine, trainer, extra, kind="base")

    def save_pass(self, engine: BoxPSEngine, trainer,
                  extra: Optional[Dict] = None) -> int:
        """Incremental end-of-pass checkpoint: a DELTA generation holding
        only the rows the finished pass wrote (cost ∝ the pass delta).
        Falls back to a base when there is no parent chain, when the
        chain hit the compaction cadence, or when the engine has no
        written-keys record yet."""
        kind = "delta"
        head = self._manifest()
        keys = getattr(engine, "_last_written", None)
        if head is None or keys is None or len(keys) == 0:
            kind = "base"
        else:
            st = self._state(head)
            chain = st.get("chain", [head])
            # a day rollover (end_day) decays EVERY row but a delta only
            # captures the pass's written rows — chaining across the
            # boundary would roll untouched rows back to their undecayed
            # previous-day values, so the first save of a new day is a
            # full base
            if st.get("day_id") != engine.day_id \
                    or len(chain) >= self.base_every:
                kind = "base"
        return self._save_generation(engine, trainer, extra, kind=kind,
                                     delta_keys=None if kind == "base"
                                     else keys)

    def _save_generation(self, engine: BoxPSEngine, trainer,
                         extra: Optional[Dict], kind: str,
                         delta_keys: Optional[np.ndarray] = None) -> int:
        t0 = time.monotonic()
        head = self._manifest()
        gen = 0 if head is None else head + 1
        if kind == "base" or head is None:
            chain = [gen]
        else:
            chain = list(self._state(head).get("chain", [head])) + [gen]
        tmpdir = self._gen_dir(gen) + ".tmp"
        if os.path.exists(tmpdir):          # leftover of a crashed save
            shutil.rmtree(tmpdir)
        os.makedirs(tmpdir)

        sparse_dir = os.path.join(tmpdir, "sparse")
        if kind == "base":
            rows = engine.table.save(sparse_dir, mode="all")
        else:
            rows = engine.table.save(sparse_dir, mode="rows",
                                     keys=delta_keys)
            stat_add("ckpt.delta_rows", float(rows))
        if faults.ACTIVE is not None:
            # mid-WAL kill point: sparse shard files are down but the
            # generation is not yet assembled — a crash here must leave
            # the previous generation loadable
            faults.on_lifecycle("ckpt_sparse")

        dense = {
            "params": jax.device_get(trainer.params),
            "opt_state": jax.device_get(trainer.opt_state),
        }
        with open(os.path.join(tmpdir, "dense.msgpack"), "wb") as f:
            f.write(serialization.to_bytes(dense))

        # cluster topology rides in the generation record: at n > 1 the
        # sparse dir holds per-shard ``shard-<k:03d>/`` subdirs (the
        # client's save fan-out, ps/cluster.cluster_save) and THIS
        # MANIFEST advance below is the single cluster-wide commit point
        # naming all N shard heads at once
        smap = getattr(engine.table, "server_map", None)
        n_shards = getattr(smap, "n", 1)
        state = {"generation": gen, "kind": kind, "chain": chain,
                 "day_id": engine.day_id, "pass_id": engine.pass_id,
                 "phase": engine.phase, "rows": int(rows),
                 "shards": int(n_shards),
                 "ps_epoch": int(getattr(smap, "epoch", 0) or 0)}
        if extra:
            state.update(extra)
        with open(os.path.join(tmpdir, "STATE.json"), "w") as f:
            f.write(json.dumps(state))

        final = self._gen_dir(gen)
        if os.path.exists(final):
            # an orphan from a crash between dir rename and pointer swap
            # reused this number — it was never reachable, reclaim it
            shutil.rmtree(final)
        os.replace(tmpdir, final)
        if faults.ACTIVE is not None:
            # the crash window the MANIFEST swap closes: generation dir
            # complete, pointer not yet advanced → old generation loads
            faults.on_lifecycle("ckpt_commit")
        man = {"generation": gen, "shards": int(n_shards)}
        if smap is not None and getattr(smap, "epoch", 0):
            # elastic fleet: the manifest names the committed membership
            # alongside the generation head — a restart reads BOTH from
            # one atomically-swapped pointer (ps/reshard.py rollback)
            man["ps_epoch"] = int(smap.epoch)
            man["ps_addrs"] = [[h, int(p)] for h, p in smap.addrs]
        _atomic_write(os.path.join(self.root, MANIFEST),
                      json.dumps(man).encode())
        dt = time.monotonic() - t0
        stat_observe("ckpt.save_s", dt)
        stat_set("ckpt.generation", float(gen))
        flight.record("ckpt_commit", generation=gen, gen_kind=kind,
                      rows=int(rows), chain_len=len(chain),
                      save_s=round(dt, 3))
        self._gc()
        return gen

    def _gc(self) -> None:
        """Retain-K GC over committed generations: keep the newest
        ``keep`` heads plus every generation their chains reference;
        remove the rest (and stale .tmp assembly dirs)."""
        committed = self._committed()
        heads = committed[-self.keep:]
        keep: set = set()
        for h in heads:
            keep.update(self._state(h).get("chain", [h]))
        removed = []
        for n in committed:
            if n not in keep:
                shutil.rmtree(self._gen_dir(n), ignore_errors=True)
                removed.append(n)
        for name in os.listdir(self.root):
            if name.startswith("gen-") and name.endswith(".tmp"):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)
        if removed:
            stat_add("ckpt.gc_removed", float(len(removed)))
            flight.record("ckpt_gc", removed=len(removed),
                          kept=len(keep))

    # -- resume --------------------------------------------------------------
    def load_table(self, table, shard: Optional[int] = None
                   ) -> Optional[int]:
        """Table-only restore (the PSServerSupervisor's cross-process
        reload path, launch.py): walk the head generation's chain into
        ``table`` — base load, then delta upserts — without touching any
        trainer state.  ``shard`` narrows the walk to one cluster
        shard's ``shard-<k:03d>/`` subdirs (a restarting shard reloads
        ONLY its own rows + DEDUP.bin).  A server-side table also
        recovers its dedup window here (the load verb restores DEDUP.bin,
        ps/service.py).  Returns the head generation number, or None when
        empty."""
        head = self._manifest()
        if head is None:
            return None
        chain = self._state(head).get("chain", [head])

        def sparse_dir(n: int) -> str:
            p = os.path.join(self._gen_dir(n), "sparse")
            return p if shard is None else os.path.join(
                p, f"shard-{shard:03d}")

        table.load(sparse_dir(chain[0]))
        for n in chain[1:]:
            table.load(sparse_dir(n), mode="upsert")
        return head

    # -- generation readers (the serving tier's delta-stream surface) --------
    # ps/serving.py's ckpt watcher consumes committed generations row-wise
    # (filtered to its shard + hot set) without ever owning a mutable
    # ShardedHostTable, so the chain-walk internals get a public read-only
    # face here instead of the serving tier poking at _manifest/_state.
    def head(self) -> Optional[int]:
        """Committed head generation number (MANIFEST pointer), or None
        when nothing has ever committed.  Raises on a torn MANIFEST read
        (json decode) — watchers retry with bounded backoff
        (ServingReplica.watch_ckpt's manifest_retry discipline)."""
        return self._manifest()

    def gen_state(self, n: int) -> Dict:
        """STATE dict of committed generation ``n`` (kind/chain/day_id/
        pass_id/shards) — stable once the generation dir is renamed in."""
        return self._state(n)

    def gen_mtime(self, n: int) -> float:
        """Commit wall-time of generation ``n`` (its STATE.json mtime) —
        the freshness basis for serving.staleness_s."""
        return os.path.getmtime(
            os.path.join(self._gen_dir(n), "STATE.json"))

    def gen_sparse_dirs(self, n: int) -> List[str]:
        """Sparse dump dirs of generation ``n``: the flat ``sparse/`` dir
        for a single-table save, else its per-cluster-shard
        ``shard-<k:03d>/`` subdirs (cluster_save layout) — the trainer's
        shard count need not match a serving reader's, so readers walk
        every subdir and re-filter by key hash themselves."""
        base = os.path.join(self._gen_dir(n), "sparse")
        subs = sorted(
            os.path.join(base, d) for d in os.listdir(base)
            if d.startswith("shard-")
            and os.path.isdir(os.path.join(base, d))) \
            if os.path.isdir(base) else []
        return subs or [base]

    def read_gen_rows(self, n: int, template: Dict[str, np.ndarray],
                      missing_fill: Optional[Dict[str, float]] = None
                      ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """All rows of generation ``n`` as ``(keys, soa)`` arrays, field
        set conformed to ``template`` (a one-row dict giving each field's
        dtype + trailing shape — fv.default_rows_keyed output works).

        Mirrors ShardedHostTable.load's checkpoint-compat rules so a
        serving-side chain replay lands bit-identical state: fields the
        dump lacks init like fresh rows (0, or ``missing_fill``'s value
        for fields whose name ends with one of its suffixes — the adam
        beta-power trackers), and the template dtype wins over the
        dump's.  Keys are unique within one generation by construction
        (table keys are unique per shard and shards partition the key
        space), so callers may apply the dict order-free within a
        generation and in chain order across them."""
        keys_parts: List[np.ndarray] = []
        soa_parts: Dict[str, List[np.ndarray]] = {f: [] for f in template}
        for d in self.gen_sparse_dirs(n):
            if not os.path.isdir(d):
                continue
            for fname in sorted(os.listdir(d)):
                if not fname.endswith(".shard.npz"):
                    continue
                with np.load(os.path.join(d, fname)) as z:
                    part_keys = np.asarray(z["keys"], np.uint64)
                    if not len(part_keys):
                        continue
                    keys_parts.append(part_keys)
                    for f, tmpl in template.items():
                        tmpl = np.asarray(tmpl)
                        if f in z.files:
                            arr = z[f]
                            if arr.dtype != tmpl.dtype:
                                arr = arr.astype(tmpl.dtype)
                        else:
                            fill = next(
                                (v for suf, v in (missing_fill
                                                  or {}).items()
                                 if f.endswith(suf)), 0.0)
                            arr = np.full(
                                (len(part_keys),) + tmpl.shape[1:],
                                fill, tmpl.dtype)
                        soa_parts[f].append(arr)
        if not keys_parts:
            empty = {f: np.zeros((0,) + np.asarray(t).shape[1:],
                                 np.asarray(t).dtype)
                     for f, t in template.items()}
            return np.zeros(0, np.uint64), empty
        return (np.concatenate(keys_parts),
                {f: np.concatenate(parts)
                 for f, parts in soa_parts.items()})

    def read_state(self) -> Optional[Dict]:
        """The head generation's STATE dict (day/pass cursor + any
        ``extra`` the saver embedded — the fleet's per-trainer cursors)
        WITHOUT loading any table or trainer state.  A restarted fleet
        rank reads this first: mid-day it must NOT ``resume()`` (a full
        table reload would roll back other ranks' landed write-backs on
        a local table, and is redundant against a remote PS) — it only
        needs the cursor, the dense restore, and a shadow table."""
        head = self._manifest()
        if head is None:
            return None
        return self._state(head)

    def restore_dense(self, trainer) -> Optional[int]:
        """Dense-only restore (params + optimizer state) from the head
        generation — the fleet rank-restart path: sparse state lives on
        the PS tier (nothing to reload), but the trainer's dense replica
        must roll back to the last pass boundary so the restarted rank's
        slice deltas are computed from the same base every surviving
        rank used.  Returns the head generation, or None when empty."""
        head = self._manifest()
        if head is None:
            return None
        with open(os.path.join(self._gen_dir(head), "dense.msgpack"),
                  "rb") as f:
            dense = serialization.from_bytes(
                {"params": jax.device_get(trainer.params),
                 "opt_state": jax.device_get(trainer.opt_state)},
                f.read())
        trainer.params = dense["params"]
        trainer.opt_state = dense["opt_state"]
        stat_add("ckpt.dense_restores")
        return head

    def resume(self, engine: BoxPSEngine, trainer) -> Optional[Dict]:
        """Restore everything from the newest committed generation (base
        load + delta-chain upserts); returns the head STATE dict or None
        when the root holds no checkpoint."""
        head = self._manifest()
        if head is None:
            return None
        t0 = time.monotonic()
        state = self._state(head)
        chain = state.get("chain", [head])
        flight.record("resume_begin", generation=head,
                      chain_len=len(chain))
        if hasattr(engine, "reset_feed_state"):
            # abandon any half-open feed pass / pending working set from
            # the crashed run before overwriting the table under it
            engine.reset_feed_state()
        engine.table.load(os.path.join(self._gen_dir(chain[0]), "sparse"))
        for n in chain[1:]:
            engine.table.load(os.path.join(self._gen_dir(n), "sparse"),
                              mode="upsert")
        if getattr(engine, "cache", None) is not None:
            # the table just rolled back under the device cache —
            # reset_feed_state above already dropped it once, but the
            # chain load is the authoritative coherence point: every
            # resident row is now potentially stale, rebuild cold
            engine.cache.invalidate("resume")
        engine.day_id = state.get("day_id")
        engine.pass_id = state.get("pass_id", 0)
        engine.phase = state.get("phase", 1)
        with open(os.path.join(self._gen_dir(head), "dense.msgpack"),
                  "rb") as f:
            dense = serialization.from_bytes(
                {"params": jax.device_get(trainer.params),
                 "opt_state": jax.device_get(trainer.opt_state)},
                f.read())
        trainer.params = dense["params"]
        trainer.opt_state = dense["opt_state"]
        dt = time.monotonic() - t0
        stat_observe("ckpt.restore_s", dt)
        stat_set("ckpt.restore_gen", float(head))
        flight.record("resume_ok", generation=head,
                      pass_id=engine.pass_id, restore_s=round(dt, 3))
        return state


def commit_membership(root: str, server_map) -> bool:
    """Record a committed PS membership (epoch + addresses) in the
    checkpoint MANIFEST — the durable half of the reshard cutover
    (ps/reshard.py phase 5).  Atomic pointer swap like every MANIFEST
    advance: a crash before this call leaves the OLD membership in the
    manifest, and a restart resharding-on-load against it is the whole
    rollback story.  Epoch-guarded (a stale/duplicate commit no-ops);
    preserves the generation head untouched.  Returns True when the
    manifest advanced."""
    os.makedirs(root, exist_ok=True)
    path = os.path.join(root, MANIFEST)
    man: Dict = {"generation": None}
    if os.path.exists(path):
        with open(path) as f:
            man = json.load(f)
    if int(man.get("ps_epoch", 0)) >= int(server_map.epoch):
        return False
    man["ps_epoch"] = int(server_map.epoch)
    man["ps_addrs"] = [[h, int(p)] for h, p in server_map.addrs]
    man["shards"] = int(server_map.n)
    _atomic_write(path, json.dumps(man).encode())
    flight.record("membership_commit", epoch=int(server_map.epoch),
                  shards=int(server_map.n))
    return True


def read_membership(root: str):
    """The committed PS membership from ``<root>/MANIFEST.json`` as a
    ServerMap, or None when the manifest is absent or membership-less
    (pre-elastic checkpoints)."""
    from paddlebox_tpu.ps import cluster as ps_cluster
    path = os.path.join(root, MANIFEST)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        man = json.load(f)
    addrs = man.get("ps_addrs")
    if not addrs:
        return None
    return ps_cluster.make_server_map([tuple(a) for a in addrs],
                                      epoch=int(man.get("ps_epoch", 0)))


def save_xbox(engine: BoxPSEngine, path: str, base: bool = True) -> int:
    """Serving-model dump (≙ the "xbox" base/delta format written by
    SaveBase/SaveDelta, box_wrapper.cc:1286): one line per surviving
    feature — key \\t show \\t click \\t embed_w \\t mf...  Quantization of
    embedx (quant_bits) applies here when configured.

    A local engine table dumps in-process (dump_table_xbox).  An engine
    running against a remote PS — including an N-way sharded cluster —
    asks each server to dump ITS rows server-side (the ``dump_xbox``
    verb) into per-shard part files, then concatenates them; row
    ownership is disjoint by the ServerMap, so the concatenation is the
    exact union and the downstream last-wins load semantics are
    unaffected by part order.
    """
    acc = engine.config.accessor
    qbits = engine.config.quant_bits
    table = engine.table
    if not hasattr(table, "_shards") and hasattr(table, "client"):
        return _save_xbox_remote(
            table.client, getattr(table, "table", None), path, base,
            float(acc.base_threshold), float(acc.delta_threshold),
            int(qbits or 0))
    return dump_table_xbox(table, path, base=base,
                           base_threshold=float(acc.base_threshold),
                           delta_threshold=float(acc.delta_threshold),
                           quant_bits=int(qbits or 0))


def _save_xbox_remote(client, table_name: Optional[str], path: str,
                      base: bool, base_threshold: float,
                      delta_threshold: float, quant_bits: int) -> int:
    """Fan the xbox dump out across the PS cluster: every shard writes a
    ``<path>.shard-<k:03d>`` part server-side (itself tmp+rename atomic),
    then the parts concatenate under ``path + ".tmp"`` and rename into
    place — the published file appears atomically, never partially."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    n = 0
    parts = []
    for shard in range(getattr(client, "n_shards", 1)):
        part = f"{path}.shard-{shard:03d}"
        resp = client._call(
            {"cmd": "dump_xbox", "path": part, "base": base,
             "base_threshold": base_threshold,
             "delta_threshold": delta_threshold,
             "quant_bits": quant_bits, "table": table_name},
            shard=shard, timeout=120)
        n += int(resp["dumped"])
        parts.append(part)
    tmp_path = path + ".tmp"
    with open(tmp_path, "wb") as out:
        for part in parts:
            with open(part, "rb") as fh:
                shutil.copyfileobj(fh, out)
            os.remove(part)
    os.replace(tmp_path, path)
    return n


def dump_table_xbox(table, path: str, base: bool = True,
                    base_threshold: float = 0.0,
                    delta_threshold: float = 0.0,
                    quant_bits: int = 0) -> int:
    """Dump one LOCAL ShardedHostTable in the xbox TSV format — the body
    shared by the in-process save_xbox path and the server-side
    ``dump_xbox`` verb (each cluster shard dumps its own rows).

    Row selection/masking is vectorized per shard and formatting runs in
    the native TSV writer (native/dump_writer.cc, ≙ the reference's
    native dump IO through PaddleFileMgr) with a per-row Python fallback.
    The dump assembles under ``path + ".tmp"`` and renames into place so
    a crashed dump never leaves a half-written file at the final path
    (PB502 tmp+rename discipline).
    """
    from paddlebox_tpu.native import dump_writer
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    qbits = quant_bits
    n = 0
    tmp_path = path + ".tmp"
    fh = None if dump_writer.available() else open(tmp_path, "w")
    try:
        for shard in table._shards:
            with shard.lock:
                soa = shard.soa
                score = table._score(soa)
                keep = (score >= base_threshold) if base else \
                    (np.abs(soa["delta_score"]) >= delta_threshold)
                idx = np.nonzero(keep)[0]
                if not len(idx):
                    continue
                keys = shard.keys[idx]
                show = soa["show"][idx]
                click = soa["click"][idx]
                embed_w = soa["embed_w"][idx]
                # uncreated embedx serves zeros in training (pull_sparse
                # masks by mf_size) — dump the SAME values or the serving
                # side would see the random candidate init
                mf = np.where((soa["mf_size"][idx] > 0)[:, None],
                              soa["mf"][idx], np.float32(0))
                if qbits:
                    scale = (1 << (qbits - 1)) - 1
                    mf = np.round(mf * scale) / scale
            if fh is None:
                dump_writer.dump_rows(tmp_path, append=n > 0, keys=keys,
                                      show=show, click=click,
                                      embed_w=embed_w, mf=mf)
            else:
                for i in range(len(keys)):
                    vals = " ".join(f"{v:.6g}" for v in mf[i])
                    fh.write(f"{keys[i]}\t{show[i]:.6g}\t{click[i]:.6g}\t"
                             f"{embed_w[i]:.6g}\t{vals}\n")
            n += len(idx)
        if fh is None and n == 0:
            open(tmp_path, "w").close()  # empty dump still creates the file
    finally:
        if fh is not None:
            fh.close()
    os.replace(tmp_path, path)
    return n


def load_xbox(engine: BoxPSEngine, path: str) -> np.ndarray:
    """Serving-side read-back of an xbox dump — the loader the reference
    keeps in its serving stack (the dump of SaveBase/SaveDelta,
    box_wrapper.cc:1286, is what the online predictor consumes).

    Writes the dumped rows into the engine's host table (optimizer state
    zero-initialized — serving never pushes) and returns the loaded keys;
    the caller then runs the normal pass lifecycle over them and
    optionally `engine.freeze_for_serving()` for int16 embedx pulls:

        keys = load_xbox(engine, path)
        engine.begin_feed_pass(); engine.add_keys(keys)
        engine.end_feed_pass(); engine.begin_pass()
        engine.freeze_for_serving()
    """
    if getattr(engine, "mode", "train") != "serving":
        warnings.warn(
            "load_xbox on a training-mode engine: the xbox dump re-derives "
            "mf_size as any(mf != 0), so a created row whose embedx "
            "trained to exactly all zeros round-trips as uncreated and "
            "would re-initialize on training resume.  Use load_checkpoint "
            "(TrainCheckpoint.resume) for training resume, or build the "
            "engine with mode='serving' for a serving path.",
            UserWarning, stacklevel=2)
    from paddlebox_tpu.native import dump_writer
    d = engine.config.embedding_dim
    native = dump_writer.load_rows(path, d)
    if native is not None:
        keys, shows, clicks, ws_, mf_mat = native
    else:
        keys, shows, clicks, ws_, mfs = [], [], [], [], []
        # Parity contract: for WRITER-PRODUCED files (save_xbox / the
        # native dump_writer — plain decimal, single-tab-separated), this
        # fallback and pbox_load_xbox give the same verdict and the same
        # reported row index.  Hand-edited exotica (hex floats, '_' digit
        # grouping, whitespace-padded fields) are outside that contract
        # and may parse differently between the two.
        with open(path) as f:
            lineno = 0      # counts parsed (non-empty) rows, exactly like
            for line in f:  # the native parser's -(row+1) — same file,
                # same reported index on native and fallback hosts
                parts = line.rstrip("\n").split("\t")
                if not line.strip():
                    continue
                lineno += 1
                if len(parts) != 5:
                    raise ValueError(
                        f"malformed xbox line {lineno}: {line[:80]!r}")
                try:
                    key = int(parts[0])
                    if not 0 <= key < 1 << 64:
                        raise ValueError("key out of uint64 range")
                    stats = [float(parts[1]), float(parts[2]),
                             float(parts[3])]
                    with np.errstate(over="ignore"):  # inf rejected below
                        mf = (np.array(parts[4].split(), np.float32)
                              if parts[4] else np.zeros((0,), np.float32))
                except ValueError as e:
                    raise ValueError(
                        f"malformed xbox line {lineno}: {line[:80]!r}"
                    ) from e
                keys.append(key)
                # reject overflow-to-inf exactly like the native parser
                # (pbox_load_xbox), so the same file parses — or fails —
                # identically on fallback-only hosts
                if not all(map(math.isfinite, stats)) or \
                        not np.all(np.isfinite(mf)):
                    raise ValueError(
                        f"malformed xbox line {lineno}: non-finite value "
                        f"in {line[:80]!r}")
                shows.append(stats[0])
                clicks.append(stats[1])
                ws_.append(stats[2])
                if len(mf) != d:
                    raise ValueError(
                        f"malformed xbox line {lineno}: mf width "
                        f"{len(mf)} != table dim {d}")
                mfs.append(mf)
        mf_mat = (np.stack(mfs) if mfs
                  else np.zeros((0, d), np.float32))
    keys = np.asarray(keys, np.uint64)
    if not len(keys):
        return keys
    shows = np.asarray(shows, np.float32)
    clicks = np.asarray(clicks, np.float32)
    ws_ = np.asarray(ws_, np.float32)
    # dedupe LAST-wins: a concatenated base+delta file naturally repeats
    # keys, and the table's upsert contract requires unique keys per call
    # (host_table.py — duplicates would double-insert)
    last = len(keys) - 1 - np.unique(keys[::-1], return_index=True)[1]
    if len(last) != len(keys):
        sel = np.sort(last)
        keys = keys[sel]
        shows, clicks, ws_ = shows[sel], clicks[sel], ws_[sel]
        mf_mat = mf_mat[sel]
    rows = engine.table.bulk_pull(keys)     # schema defaults
    rows["show"] = shows
    rows["click"] = clicks
    rows["embed_w"] = ws_
    rows["mf"] = np.asarray(mf_mat, np.float32)
    # the dump writes zeros for uncreated embedx (see save_xbox) — derive
    # mf_size so serving pulls mask exactly like training did.  SERVING-ONLY
    # contract: a created row whose embedding trained to exactly all zeros
    # round-trips as uncreated (served values identical — zeros either way),
    # but resuming TRAINING from an xbox dump would re-initialize such rows'
    # embedx; use save_checkpoint/load_checkpoint (which carry mf_size
    # explicitly) for training resume.
    created = np.any(rows["mf"] != 0.0, axis=1)
    rows["mf_size"] = np.where(created, d, 0).astype(rows["mf_size"].dtype)
    # zero every field the dump does not carry (optimizer state, scores)
    # — serving never pushes, and a delta-refresh over existing rows must
    # not resurrect their stale training state
    keep = {"show", "click", "embed_w", "mf", "mf_size", "slot"}
    for fld in rows:
        if fld not in keep:
            rows[fld] = np.zeros_like(rows[fld])
    engine.table.bulk_write(keys, rows)
    # coherence point (hot-swap contract): the rows just changed UNDER
    # every consumer that mirrors them.  A device-resident row cache now
    # holds the retired day's values, and a PSClient's learned row-width
    # estimates were sized from the old contents — both must drop HERE,
    # not just in freeze_for_serving (a replica that load_xbox'es day N+1
    # over day N never calls freeze again).
    cache = getattr(engine, "cache", None)
    if cache is not None:
        cache.invalidate("load_xbox")
    inval = getattr(engine.table, "invalidate_row_width", None)
    if inval is not None:
        inval()
    return keys


# -- xbox swap manifest (train→serve day pointer) ---------------------------
# The dump itself lands via save_xbox's tmp+rename; this publishes WHICH
# dump is current — the trainer's last act of a day, the serving fleet's
# swap trigger (ServingReplica.watch_manifest).  Same discipline as the
# checkpoint MANIFEST: one mutable file, swapped whole via _atomic_write,
# so a reader sees the old complete pointer or the new one, never a torn
# write or a pointer to a half-written dump.
XBOX_MANIFEST = "XBOX_MANIFEST.json"


def publish_xbox_manifest(root: str, path: str, generation: int,
                          day: str = "") -> str:
    """Atomically point ``<root>/XBOX_MANIFEST.json`` at the dump at
    ``path`` (already fully written — call this AFTER save_xbox
    returns).  Returns the manifest path."""
    os.makedirs(root, exist_ok=True)
    man = os.path.join(root, XBOX_MANIFEST)
    _atomic_write(man, json.dumps(
        {"generation": int(generation), "path": path, "day": day,
         "published_unix": time.time()}).encode())
    return man


def read_xbox_manifest(root: str) -> Optional[Dict]:
    """The current swap pointer, or None when nothing is published yet.
    Raises on a malformed manifest — tmp+rename means a torn file is a
    bug upstream, not a transient to paper over."""
    man = os.path.join(root, XBOX_MANIFEST)
    if not os.path.exists(man):
        return None
    with open(man, "r") as f:
        out = json.load(f)
    if "generation" not in out or "path" not in out:
        raise ValueError(f"malformed xbox manifest {man}: {out!r}")
    return out
