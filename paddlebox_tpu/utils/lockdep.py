"""Runtime lock-order witness (the dynamic half of pboxlint PB6xx).

``FLAGS_lockdep`` off (the default): the factories return **raw**
``threading`` primitives — zero wrapper, zero hot-path cost, nothing to
reason about in production.  On: every factory-created lock is wrapped in
a ``_DepLock`` that

* keeps a per-thread list of held lock *names* (class-level fingerprints
  like ``ps.service.PSClient._lock`` — the same namespace the static
  analyzer in ``tools/pboxlint/lockgraph.py`` uses, so the two sides
  cross-validate: tier-1 asserts every runtime-observed edge exists in
  the static over-approximation),
* records an acquisition-order edge ``held → wanted`` at acquire
  *attempt* time — **before** blocking on the inner lock — so a real
  ABBA deadlock still produces its ``lock_cycle`` evidence even while
  both threads are stuck,
* runs an online DFS cycle check on every *new* edge and, on a cycle,
  emits a ``lock_cycle`` flight event (one per unique cycle — the flight
  ring's bounded-kind rule) and stores the cycle for
  ``state()``/doctor postmortems.  It never raises and never blocks a
  correct program: detection is advisory, by design.

Bookkeeping runs on plain ``threading`` primitives (never on wrapped
locks) and the flight event is emitted outside the graph lock, so the
witness cannot itself deadlock or recurse.

``threading.Condition(dep_lock)`` works unchanged: ``Condition``
duck-types through ``acquire``/``release`` (and our ``_is_owned``
delegate), so ``wait()`` correctly pops the held-set on release and
re-records the edge on reacquire.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple, Union

from paddlebox_tpu import flags
from paddlebox_tpu.utils import flight

flags.define_flag(
    "lockdep", False,
    "instrument factory-created locks with the runtime lock-order "
    "witness (per-thread held-sets, global acquisition-order graph, "
    "online cycle detection; lock_cycle flight events + doctor state). "
    "Debug/soak mode: off = raw threading primitives, zero cost")

flags.define_flag(
    "lockdep_guards", False,
    "with FLAGS_lockdep: activate the guarded-by witness — "
    "lockdep.guards(obj, field) assertion points at hot mutation sites "
    "(plus install_guard_probe sampling proxies) record (site, "
    "held-locks) observations and, against an installed static "
    "guarded-by map (pboxlint raceguard.guard_map()), emit ONE "
    "race_suspect flight event per violating site for doctor "
    "postmortems. Off (the default): guards() is a single cached-flag "
    "test, zero allocation")

# -- global witness state (plain primitives: never instrumented) ----------
_graph_lock = threading.Lock()
_edges: Dict[Tuple[str, str], Dict] = {}        # (a, b) → first witness
_cycles: List[Dict] = []
_seen_cycles: Set[Tuple[str, ...]] = set()
_held_tls = threading.local()                   # .names: List[str]
_held_by_thread: Dict[int, List[str]] = {}      # ident → alias of the list

# -- guarded-by witness state (PB9xx runtime half) ------------------------
_guards_cache: Optional[bool] = None            # lazy flag resolve
_guard_map: Dict[str, Tuple[str, ...]] = {}     # site → static guard fps
_guard_obs: Dict[str, Set[Tuple[str, ...]]] = {}  # site → held-set tuples
_guard_suspects: List[Dict] = []
_suspect_sites: Set[str] = set()


def enabled() -> bool:
    return bool(flags.get_flags("lockdep"))


def guards_enabled() -> bool:
    """Both flags on — the guards witness needs FLAGS_lockdep for its
    held-sets (raw primitives record nothing).  Resolved once and
    cached so the off-path in ``guards()`` is one global load;
    ``reset()`` clears the cache (the test fixture pattern: set flags,
    then ``lockdep.reset()``)."""
    global _guards_cache
    on = _guards_cache
    if on is None:
        on = _guards_cache = bool(
            flags.get_flags("lockdep_guards")) and enabled()
    return on


def _held() -> List[str]:
    lst = getattr(_held_tls, "names", None)
    if lst is None:
        lst = _held_tls.names = []
        with _graph_lock:
            _held_by_thread[threading.get_ident()] = lst
    return lst


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS over _edges (caller holds _graph_lock): src ⇝ dst or None."""
    stack: List[Tuple[str, List[str]]] = [(src, [src])]
    seen: Set[str] = set()
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        if node in seen:
            continue
        seen.add(node)
        for (a, b) in _edges:
            if a == node and b not in seen:
                stack.append((b, path + [b]))
    return None


def _note_edges(held: List[str], wanted: str) -> None:
    """Record held→wanted edges; on a NEW edge, check for a cycle."""
    new_cycles: List[Dict] = []
    with _graph_lock:
        for h in dict.fromkeys(held):           # dedupe, keep order
            if h == wanted:
                continue
            key = (h, wanted)
            if key in _edges:
                _edges[key]["count"] += 1
                continue
            # does wanted already reach h?  then held→wanted closes a loop
            back = _find_path(wanted, h)
            _edges[key] = {"count": 1,
                           "thread": threading.current_thread().name}
            if back is not None:
                cycle = back + [wanted]         # h ⇝ wanted → h
                sig = tuple(sorted(set(cycle)))
                if sig not in _seen_cycles:
                    _seen_cycles.add(sig)
                    info = {"cycle": cycle,
                            "edge": [h, wanted],
                            "thread": threading.current_thread().name,
                            "held": list(held)}
                    _cycles.append(info)
                    new_cycles.append(info)
    for info in new_cycles:                     # flight: outside the lock
        flight.record("lock_cycle",
                      path="→".join(info["cycle"]),
                      edge=f"{info['edge'][0]}→{info['edge'][1]}",
                      thread=info["thread"])


class _DepLock:
    """Wrapper around a threading.Lock/RLock carrying a class fingerprint.

    Edge recording happens at blocking-acquire *attempt*; the held-set
    is updated only on success.  Non-blocking probes (``acquire(False)``,
    e.g. Condition's ``_is_owned`` fallback) record nothing — a failed
    trylock cannot deadlock, and probe edges would be phantoms."""

    __slots__ = ("_inner", "name")

    def __init__(self, inner, name: str):
        self._inner = inner
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        if blocking and self.name not in held:
            _note_edges(held, self.name)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            held.append(self.name)
        return ok

    def release(self) -> None:
        held = _held()
        self._inner.release()
        # pop the most recent entry (RLock depth unwinds LIFO)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self.name:
                del held[i]
                break

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked() if hasattr(self._inner, "locked") \
            else False

    def _is_owned(self) -> bool:
        # Condition(dep_rlock) consults this instead of probe-acquiring
        inner_owned = getattr(self._inner, "_is_owned", None)
        if inner_owned is not None:
            return inner_owned()
        return self.name in _held()

    def __repr__(self) -> str:
        return f"<DepLock {self.name} {self._inner!r}>"


LockLike = Union[threading.Lock, threading.RLock, "_DepLock"]


def lock(name: str) -> LockLike:
    """A ``threading.Lock`` — instrumented iff ``FLAGS_lockdep``."""
    raw = threading.Lock()
    return _DepLock(raw, name) if enabled() else raw


def rlock(name: str) -> LockLike:
    raw = threading.RLock()
    return _DepLock(raw, name) if enabled() else raw


def condition(name: str, lock: Optional[LockLike] = None) \
        -> threading.Condition:
    """A ``threading.Condition``.  Standalone conditions own an RLock
    named ``name``; pass an existing (possibly instrumented) lock to
    share it — the shared lock keeps *its* name, exactly like the static
    analyzer's ``Condition(self._lock)`` aliasing."""
    return threading.Condition(lock if lock is not None else rlock(name))


# -- guarded-by witness (the dynamic half of pboxlint PB9xx) --------------
def _site_of(obj, field: str) -> str:
    """Runtime site name in the STATIC analyzer's namespace:
    ``ps.service.PSServer._staged`` — ``type(obj).__module__`` with the
    package prefix stripped + qualname + field, exactly the
    ``FieldInfo.site`` key raceguard.guard_map() exports."""
    cls = type(obj)
    mod = cls.__module__
    if mod.startswith("paddlebox_tpu."):
        mod = mod[len("paddlebox_tpu."):]
    return f"{mod}.{cls.__qualname__}.{field}"


def guards(obj, field: str) -> None:
    """Assertion point at a hot mutation site: records the (site,
    held-locks) observation and — when a static guarded-by map is
    installed and names this site — emits a ``race_suspect`` flight
    event (once per site) if none of the site's guards is held.
    Advisory like the cycle witness: never raises."""
    if not guards_enabled():
        return
    site = _site_of(obj, field)
    held = tuple(_held())
    suspect = None
    with _graph_lock:
        _guard_obs.setdefault(site, set()).add(held)
        want = _guard_map.get(site)
        if want is not None and site not in _suspect_sites \
                and not set(held).intersection(want):
            _suspect_sites.add(site)
            suspect = {"site": site, "held": list(held),
                       "guard": list(want),
                       "thread": threading.current_thread().name}
            _guard_suspects.append(suspect)
    if suspect is not None:                     # flight: outside the lock
        flight.record("race_suspect", site=site,
                      held=",".join(suspect["held"]) or "(none)",
                      guard=",".join(suspect["guard"]),
                      thread=suspect["thread"])


def set_guard_map(mapping: Dict[str, List[str]]) -> None:
    """Install the static guarded-by map (raceguard.guard_map() shape:
    {site: [guard fingerprints]}) that ``guards()`` checks against."""
    with _graph_lock:
        _guard_map.clear()
        for site, fps in mapping.items():
            _guard_map[site] = tuple(fps)


def guard_observations() -> Dict[str, List[List[str]]]:
    """{site: sorted list of observed held-set lists} — the runtime half
    tier-1 asserts ⊆ the static guarded-by map."""
    with _graph_lock:
        return {site: sorted(list(h) for h in obs)
                for site, obs in sorted(_guard_obs.items())}


def guard_suspects() -> List[Dict]:
    with _graph_lock:
        return [dict(s) for s in _guard_suspects]


def install_guard_probe(cls: type, fields: List[str], every: int = 1):
    """Sampling proxy for annotated classes with no inline assertion
    points: wraps ``cls.__setattr__`` so every ``every``-th store to one
    of ``fields`` runs ``guards()`` first (the held-set at store time is
    what matters).  Returns a restore callable.  The sample counter is
    deliberately unlocked — it only paces sampling."""
    watched = frozenset(fields)
    orig = cls.__setattr__
    state = {"n": 0}

    def probing(self, name, value):
        if name in watched:
            state["n"] += 1
            if state["n"] % max(1, every) == 0:
                guards(self, name)
        orig(self, name, value)

    cls.__setattr__ = probing

    def restore():
        cls.__setattr__ = orig
    return restore


# -- introspection (doctor / tests / cross-validation) --------------------
def edges() -> List[Tuple[str, str]]:
    with _graph_lock:
        return sorted(_edges)


def cycles() -> List[Dict]:
    with _graph_lock:
        return [dict(c) for c in _cycles]


def held_by_thread() -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    names = {t.ident: t.name for t in threading.enumerate()}
    with _graph_lock:
        for ident, lst in _held_by_thread.items():
            if lst:
                out[names.get(ident, str(ident))] = list(lst)
    return out


def state() -> Dict:
    """JSON-able witness snapshot for doctor postmortems."""
    with _graph_lock:
        edge_list = [{"from": a, "to": b, **info}
                     for (a, b), info in sorted(_edges.items())]
        cyc = [dict(c) for c in _cycles]
        guard = {"enabled": guards_enabled(),
                 "sites_observed": len(_guard_obs),
                 "map_installed": len(_guard_map),
                 "suspects": [dict(s) for s in _guard_suspects]}
    return {"enabled": enabled(), "edges": edge_list, "cycles": cyc,
            "held": held_by_thread(), "guards": guard}


def reset() -> None:
    """Test helper: drop all recorded edges/cycles and guard
    observations, and re-resolve the guards flag (held-sets persist —
    they mirror locks actually held right now)."""
    global _guards_cache
    with _graph_lock:
        _edges.clear()
        _cycles.clear()
        _seen_cycles.clear()
        _guard_obs.clear()
        _guard_suspects.clear()
        _suspect_sites.clear()
        _guard_map.clear()
        _guards_cache = None
