"""Flight recorder — process-wide bounded ring of typed, timestamped
events (the "what happened just before it wedged" layer the reference's
PrintSyncTimer/monitor.h never had).

Metrics (utils/monitor.py) answer "how much/how fast"; spans
(utils/trace.py) answer "where did the time go" on the happy path.  The
flight ring answers the postmortem question: *what was this process
doing right before it hung, crashed, or slowed to a crawl* — the
Dapper-style annotation log, bounded like a cockpit flight recorder.
Producers record rare, meaningful lifecycle events:

  pass/day boundaries        ps/pass_manager.py
  verb retries / give-ups    ps/service.py
  backoff sleeps             utils/backoff.py
  stream reconnects          ps/service.py
  dedup hits / evictions     ps/service.py (_DedupWindow)
  injected faults            ps/faults.py
  pool saturation            utils/workpool.py (new queue-depth hwm only)
  elastic grow/shrink        launch.py
  checkpoint save/load       ps/pass_manager.py, io/checkpoint.py
  ckpt commit / gc           io/checkpoint.py (generation chain)
  resume begin / ok          io/checkpoint.py, launch.py (supervisor)
  dedup restore              ps/service.py (checkpoint / restart handoff)
  bench phases / wedges      bench.py

Consumers: ``/flightz`` on the obs exporter (utils/obs_server.py), the
wedge doctor's postmortem bundles (utils/doctor.py), and SIGUSR1 live
interrogation.

Design constraints (same discipline as utils/trace.py):

* **Bounded memory** — a fixed-capacity deque (``FLAGS_obs_flight_ring``
  events, newest-N retention; 0 disables recording entirely).
* **Cheap when idle, free when off** — ``record()`` is one module-global
  check when disabled; when enabled it is a dict build + deque append,
  and every producer site is a RARE event (a retry, a pass boundary),
  never per-row/per-chunk hot-path work.
* **Bounded cardinality** — event *kinds* are lowercase literal tokens
  from a closed taxonomy (lint rule PB206, the flight-ring face of
  PB204's metric-name discipline).  Unbounded values (rids, paths,
  errors) belong in event FIELDS, never in the kind.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from paddlebox_tpu import flags

flags.define_flag(
    "obs_flight_ring", 2048,
    "flight-recorder ring capacity (newest-N typed lifecycle events: "
    "pass boundaries, retries, reconnects, faults, checkpoints...); "
    "served as /flightz and embedded in every postmortem bundle.  "
    "0 disables recording")


class FlightRecorder:
    """Fixed-capacity ring of event dicts.  Thread-safe; events carry a
    monotonically increasing ``seq`` so consumers can detect gaps after
    ring wrap."""

    def __init__(self, cap: int):
        self._ring: "deque[Dict]" = deque(maxlen=max(1, int(cap)))
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, kind: str, **fields) -> None:
        ev = {"kind": kind, "t": time.time(), "mono": time.monotonic(),
              "thread": threading.current_thread().name}
        if fields:
            ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)

    def events(self, n: Optional[int] = None,
               kind: Optional[str] = None) -> List[Dict]:
        """Newest-first events, optionally filtered by kind —
        ``kind`` accepts one name or a comma-separated list
        (``"slo_breach,slo_clear"``; blanks ignored)."""
        with self._lock:
            out = [dict(e) for e in reversed(self._ring)]
        if kind:
            want = {k.strip() for k in kind.split(",") if k.strip()}
            if want:
                out = [e for e in out if e["kind"] in want]
        return out if n is None else out[:max(0, int(n))]

    def counts(self) -> Dict[str, int]:
        """Events currently retained, per kind (bounded taxonomy)."""
        out: Dict[str, int] = {}
        with self._lock:
            for e in self._ring:
                out[e["kind"]] = out.get(e["kind"], 0) + 1
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0


# Module-level handle.  _UNSET defers the flag read to the first record
# so FLAGS_obs_flight_ring set after import (launch.py env export, test
# set_flags before any event) still takes effect; after init the hot
# path is one global read + is-None check.
_UNSET = object()
_RING = _UNSET
_INIT_LOCK = threading.Lock()


def _init() -> Optional[FlightRecorder]:
    global _RING
    with _INIT_LOCK:
        if _RING is _UNSET:
            cap = int(flags.get_flags("obs_flight_ring"))
            _RING = FlightRecorder(cap) if cap > 0 else None
        return _RING


def ring() -> Optional[FlightRecorder]:
    """The process-wide recorder (created from the flag on first use);
    None when FLAGS_obs_flight_ring is 0."""
    r = _RING
    return _init() if r is _UNSET else r


def reconfigure() -> Optional[FlightRecorder]:
    """Re-read FLAGS_obs_flight_ring and rebuild the ring (tests, live
    resize).  Discards retained events."""
    global _RING
    with _INIT_LOCK:
        _RING = _UNSET
    return _init()


def record(kind: str, **fields) -> None:
    """Record one typed event.  ``kind`` must be a bounded lowercase
    literal (lint rule PB206); arbitrary values go in ``fields``."""
    r = _RING
    if r is _UNSET:
        r = _init()
    if r is not None:
        r.record(kind, **fields)


def events(n: Optional[int] = None, kind: Optional[str] = None) -> List[Dict]:
    """Newest-first events of the process ring ([] when disabled)."""
    r = ring()
    return r.events(n=n, kind=kind) if r is not None else []
