"""Wall timers (≙ platform/timer.h Timer + the per-device pass timers in
box_wrapper.h:394-403 / PrintSyncTimer box_wrapper.h:795)."""

from __future__ import annotations

import threading
import time
from typing import Dict


class Timer:
    def __init__(self):
        self._start = 0.0
        self._elapsed = 0.0
        self._count = 0
        self._running = False

    def start(self) -> None:
        self._start = time.perf_counter()
        self._running = True

    def pause(self) -> None:
        if self._running:
            self._elapsed += time.perf_counter() - self._start
            self._count += 1
            self._running = False

    def reset(self) -> None:
        self._elapsed = 0.0
        self._count = 0
        self._running = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.pause()

    def elapsed_sec(self) -> float:
        extra = time.perf_counter() - self._start if self._running else 0.0
        return self._elapsed + extra

    def count(self) -> int:
        return self._count


class TimerRegistry:
    """Named timer set printed per pass (≙ DeviceBoxData timers)."""

    def __init__(self):
        self._timers: Dict[str, Timer] = {}
        self._lock = threading.Lock()

    def __call__(self, name: str) -> Timer:
        with self._lock:
            if name not in self._timers:
                self._timers[name] = Timer()
            return self._timers[name]

    def add(self, name: str, seconds: float) -> None:
        """Thread-safe accumulate for timers shared by worker pools (a bare
        ``with registry(name)`` races when two threads time the same name)."""
        with self._lock:
            if name not in self._timers:
                self._timers[name] = Timer()
            t = self._timers[name]
            t._elapsed += seconds
            t._count += 1

    def report(self) -> str:
        with self._lock:
            parts = [f"{k}={t.elapsed_sec():.3f}s/{t.count()}"
                     for k, t in sorted(self._timers.items())]
        return " ".join(parts)

    def rows(self):
        """[(name, elapsed_sec, count)] sorted by name — the structured
        face of report() (the per-pass PrintSyncTimer table renders from
        this, ps/pass_manager.py)."""
        with self._lock:
            return [(k, t.elapsed_sec(), t.count())
                    for k, t in sorted(self._timers.items())]

    def reset(self) -> None:
        with self._lock:
            for t in self._timers.values():
                t.reset()
