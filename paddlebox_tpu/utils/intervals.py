"""Interval-level feed-gap attribution (ROADMAP item 2's measurement
layer).

BENCH_r03 showed ``device_step=473090`` vs ``end_to_end=22934`` ex/s —
a ~20× gap between what the device can chew and what the host feed
delivers.  Averaged timers can't attribute that gap: host pack and
device step overlap (the PR 3 double-buffer), so summing their seconds
double-counts.  This module records *wall-clock intervals* per activity
kind and computes union/overlap-aware utilization:

* ``device`` — device-step dispatch windows (trainer step loop)
* ``pull``   — PS/host-table bulk pull of the pass working set
* ``pack``   — host-side batch packing (data/pass_feed.py, stream pack)
* ``upload`` — host→device uploads (working-set build, packed batches)
* ``write``  — working-set write-back to the DRAM tier at pass end
* ``csr``    — host-side CSR step-plan build for the ragged sparse path
  (data/pass_feed.py build_csr_plans; hidden under training when the
  PR 7 prefetcher runs it on the worker thread)

``report(since)`` merges each kind's intervals (union seconds, clipped
to the window), yielding:

* ``device_busy_frac``  = union(device) / wall — the fraction of the
  window the device had work in flight;
* ``feed_gap_ratio``    = wall / union(device) — how much faster the
  pass would run if the host feed never stalled the device (the
  interval-accounted sibling of BENCH's device_step ÷ end_to_end rate
  ratio);
* ``host_busy_s`` / ``overlap_s`` — union of host kinds and its overlap
  with device busy, so "host is slow" separates from "host is slow AND
  not hidden behind the device".

Always-on by design: recording is one deque.append of a (t0, t1) tuple
per *operation* (a step window, a pass pack — not per row), bounded by
a fixed per-kind capacity.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from paddlebox_tpu.utils.monitor import stat_add

# Closed set of activity kinds (PB204-style bounded cardinality: the
# per-kind cumulative stat below interpolates `kind` into a metric name).
KINDS = ("device", "pull", "pack", "upload", "write", "csr")
_HOST_KINDS = ("pull", "pack", "upload", "write", "csr")


def union_seconds(iv: List[Tuple[float, float]],
                  since: Optional[float] = None,
                  until: Optional[float] = None) -> float:
    """Total seconds covered by the union of [t0, t1) intervals, clipped
    to [since, until]."""
    clipped = []
    for t0, t1 in iv:
        if since is not None:
            t0 = max(t0, since)
        if until is not None:
            t1 = min(t1, until)
        if t1 > t0:
            clipped.append((t0, t1))
    if not clipped:
        return 0.0
    clipped.sort()
    total = 0.0
    cur0, cur1 = clipped[0]
    for t0, t1 in clipped[1:]:
        if t0 > cur1:
            total += cur1 - cur0
            cur0, cur1 = t0, t1
        else:
            cur1 = max(cur1, t1)
    return total + (cur1 - cur0)


def _merge(iv: List[Tuple[float, float]], since, until):
    """Clipped, sorted, coalesced copy of ``iv`` (for intersections)."""
    out = []
    for t0, t1 in iv:
        if since is not None:
            t0 = max(t0, since)
        if until is not None:
            t1 = min(t1, until)
        if t1 > t0:
            out.append((t0, t1))
    out.sort()
    merged: List[Tuple[float, float]] = []
    for t0, t1 in out:
        if merged and t0 <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], t1))
        else:
            merged.append((t0, t1))
    return merged


def _intersect_seconds(a: List[Tuple[float, float]],
                       b: List[Tuple[float, float]]) -> float:
    """Seconds where two merged interval lists overlap."""
    total = 0.0
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


class IntervalRecorder:
    """Bounded per-kind rings of (t0, t1) monotonic-clock intervals."""

    def __init__(self, cap: int = 16384):
        self._cap = int(cap)
        self._iv: Dict[str, "deque[Tuple[float, float]]"] = {
            k: deque(maxlen=self._cap) for k in KINDS}
        self._lock = threading.Lock()

    def record(self, kind: str, t0: float, t1: float) -> None:
        if t1 <= t0:
            return
        with self._lock:
            dq = self._iv.get(kind)
            if dq is None:        # unknown kind: ignore rather than grow
                return
            dq.append((t0, t1))
        stat_add(f"feed.{kind}.busy_s", t1 - t0)

    def clear(self) -> None:
        with self._lock:
            for dq in self._iv.values():
                dq.clear()

    def report(self, since: float,
               until: Optional[float] = None) -> Dict[str, float]:
        """Overlap-aware utilization over [since, until] (until defaults
        to now)."""
        if until is None:
            until = time.monotonic()
        wall = max(until - since, 1e-9)
        with self._lock:
            iv = {k: list(dq) for k, dq in self._iv.items()}
        out: Dict[str, float] = {"wall_s": wall}
        for k in KINDS:
            out[f"{k}_busy_s"] = union_seconds(iv[k], since, until)
        host_all: List[Tuple[float, float]] = []
        for k in _HOST_KINDS:
            host_all.extend(iv[k])
        host_m = _merge(host_all, since, until)
        dev_m = _merge(iv["device"], since, until)
        out["host_busy_s"] = sum(t1 - t0 for t0, t1 in host_m)
        out["overlap_s"] = _intersect_seconds(dev_m, host_m)
        # per-stage overlap: seconds of each host kind hidden behind
        # device busy — the prefetch pipeline's win is exactly these
        # going from ~0 (serial: host runs while the device idles) to
        # ≈{k}_busy_s (pipelined: pass N+1's pull/pack/upload run under
        # pass N's training)
        for k in _HOST_KINDS:
            out[f"{k}_hidden_s"] = _intersect_seconds(
                _merge(iv[k], since, until), dev_m)
        out["hidden_s"] = out["overlap_s"]
        dev = out["device_busy_s"]
        out["device_busy_frac"] = dev / wall
        # wall / device-busy: 1.0 = perfectly fed; BENCH_r03's ~20×
        # device_step/end_to_end rate gap shows up here as ~20.
        out["feed_gap_ratio"] = (wall / dev) if dev > 0 else 0.0
        return out


# Process-wide recorder — always on (bounded memory, rare appends); the
# flag-gated layers (trace/flight) stay the pattern for anything hotter.
ACTIVE = IntervalRecorder()


def record(kind: str, t0: float, t1: float) -> None:
    """Record one busy interval of activity ``kind`` (monotonic
    seconds)."""
    ACTIVE.record(kind, t0, t1)


def report(since: float, until: Optional[float] = None) -> Dict[str, float]:
    """Utilization report over [since, until] from the process
    recorder."""
    return ACTIVE.report(since, until=until)


def clear() -> None:
    ACTIVE.clear()
