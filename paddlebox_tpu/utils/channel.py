"""Bounded MPMC channel — the backbone primitive of the host data pipeline.

≙ framework/channel.h:39 (ChannelObject) with Reader/Writer adapters
(channel.h:330,382).  All pipeline stages (read -> parse -> shuffle -> merge ->
batch) hand SlotRecord batches through these.  Unlike the reference we move
numpy record *batches* (struct-of-arrays), not individual records, so Python
overhead amortizes.
"""

from __future__ import annotations

import collections
import threading

from paddlebox_tpu.utils import lockdep
from typing import Any, Iterable, List, Optional


class ChannelClosed(Exception):
    pass


class Channel:
    """Bounded blocking MPMC channel with block-write semantics.

    write/read of single items or batches; ``close()`` wakes all blocked
    readers (who then drain the remaining items and get EOF).
    """

    def __init__(self, capacity: int = 0):
        self._cap = capacity if capacity > 0 else float("inf")
        self._q: collections.deque = collections.deque()
        self._closed = False
        self._lock = lockdep.lock("utils.channel.Channel._lock")
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)

    def put(self, item: Any) -> bool:
        with self._lock:
            while len(self._q) >= self._cap and not self._closed:
                self._not_full.wait()
            if self._closed:
                return False
            self._q.append(item)
            self._not_empty.notify()
            return True

    def put_many(self, items: Iterable[Any]) -> int:
        n = 0
        for it in items:
            if not self.put(it):
                break
            n += 1
        return n

    def get(self, timeout: Optional[float] = None) -> Any:
        """Blocking read; raises ChannelClosed on EOF (closed and drained)."""
        with self._lock:
            while not self._q and not self._closed:
                if not self._not_empty.wait(timeout):
                    raise TimeoutError("channel read timed out")
            if self._q:
                item = self._q.popleft()
                self._not_full.notify()
                return item
            raise ChannelClosed()

    def get_many(self, max_items: int) -> List[Any]:
        """Read up to max_items (at least 1 unless EOF -> empty list)."""
        out: List[Any] = []
        with self._lock:
            while not self._q and not self._closed:
                self._not_empty.wait()
            while self._q and len(out) < max_items:
                out.append(self._q.popleft())
            self._not_full.notify_all()
        return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def reopen(self) -> None:
        with self._lock:
            self._closed = False

    def size(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def __iter__(self):
        while True:
            try:
                yield self.get()
            except ChannelClosed:
                return
