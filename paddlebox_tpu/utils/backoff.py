"""Shared retry backoff policy — exponential growth, jitter, deadline budget.

Every retry loop in the package sleeps through this helper instead of a
fixed ``time.sleep(const)`` (lint rule PB501, tools/pboxlint/retries.py):
a fixed sleep retries in lockstep under contention and has no overall
bound, while this policy doubles the nominal delay per attempt up to a
cap, jitters each sleep into ``[0.5, 1.0) * nominal`` so a fleet of
clients decorrelates, and charges everything against one deadline budget
so a caller can say "this verb gets 30 s total, however many attempts
that is" (≙ the reference's retry-then-fail discipline,
ps_gpu_wrapper.cc:388-419, upgraded from count-bounded to time-bounded).
"""

from __future__ import annotations

import random
import time
from typing import Optional

from paddlebox_tpu.utils import flight


class Backoff:
    """One retry episode: ``delay(attempt)`` is the pure policy math
    (unit-testable, deterministic under ``seed``), ``sleep(attempt)``
    applies it against the deadline and returns False once the budget is
    spent — the caller's signal to stop retrying and raise."""

    def __init__(self, base: float = 0.05, cap: float = 2.0,
                 deadline: Optional[float] = None,
                 seed: Optional[int] = None):
        self.base = float(base)
        self.cap = float(cap)
        self._rng = random.Random(seed)
        self._t0 = time.monotonic()
        self.deadline = None if deadline is None else float(deadline)

    def remaining(self) -> Optional[float]:
        """Seconds left in the budget (None = unbounded)."""
        if self.deadline is None:
            return None
        return self.deadline - (time.monotonic() - self._t0)

    def reset(self) -> None:
        """Restart the episode clock after PROGRESS: a long pipelined
        transfer that keeps landing frames between reconnects should
        measure its deadline from the last success, not from the first
        attempt — only sustained lack of progress exhausts the budget."""
        self._t0 = time.monotonic()

    def delay(self, attempt: int) -> float:
        """Jittered nominal delay for the given 1-based attempt number:
        ``min(cap, base * 2**(attempt-1)) * uniform(0.5, 1.0)``."""
        nominal = min(self.cap, self.base * (2 ** max(0, attempt - 1)))
        return nominal * (0.5 + self._rng.random() / 2)

    def sleep(self, attempt: int) -> bool:
        """Sleep the attempt's jittered delay, clamped to the remaining
        budget.  Returns False (without sleeping) when the budget is
        already spent."""
        d = self.delay(attempt)
        rem = self.remaining()
        if rem is not None:
            if rem <= 0:
                flight.record("backoff_exhausted", attempt=attempt)
                return False
            d = min(d, rem)
        flight.record("backoff_sleep", attempt=attempt,
                      delay_s=round(d, 4))
        time.sleep(d)
        return True
