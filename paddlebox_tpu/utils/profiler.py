"""Profiling hooks.

≙ the reference's two tracing layers (SURVEY.md §5): the new-style
host+device tracer exporting Chrome traces (platform/profiler/profiler.h,
python paddle.profiler.Profiler profiler.py:271 with scheduler states) and
the old RecordEvent spans (platform/profiler.cc) — mapped onto jax.profiler
(XLA's TraceMe/Perfetto machinery) plus the framework's TimerRegistry for
the per-pass wall-time report (≙ PrintSyncTimer box_wrapper.h:795).
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional

import jax

from paddlebox_tpu.utils import trace
from paddlebox_tpu.utils.timer import TimerRegistry


class RecordEvent:
    """≙ platform::RecordEvent span; shows up in the device trace — and,
    when the host tracer is enabled (utils/trace.py), as a host span too,
    so the merged Chrome trace carries both layers."""

    def __init__(self, name: str):
        self.name = name
        self._ctx = None
        self._span = None
        self._tracer = None

    def __enter__(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()
        self._tracer = trace.ACTIVE
        if self._tracer is not None:
            self._span = self._tracer.start_span(self.name)
        return self

    def __exit__(self, *exc):
        if self._span is not None:
            self._tracer.finish(self._span)
            self._span = None
        self._ctx.__exit__(*exc)


class Profiler:
    """≙ paddle.profiler.Profiler (profiler.py:271): scheduler-driven
    start/stop with chrome-trace export.  States: CLOSED→RECORD→CLOSED by
    step range (the reference's ProfilerState scheduler, profiler.py:34)."""

    def __init__(self, log_dir: str = "./profile_out",
                 record_steps: Optional[range] = None):
        self.log_dir = log_dir
        self.record_steps = record_steps or range(2, 7)
        self._step = 0
        self._running = False

    def start(self) -> None:
        os.makedirs(self.log_dir, exist_ok=True)
        jax.profiler.start_trace(self.log_dir)
        self._running = True

    def stop(self) -> None:
        if self._running:
            jax.profiler.stop_trace()
            self._running = False
            if trace.ACTIVE is not None:
                # merge the host span ring into the same trace collection:
                # host_spans.trace.json lands beside the XLA dump, so one
                # Perfetto load shows device ops AND PS verb spans
                trace.ACTIVE.export_chrome_trace(self.log_dir)

    def step(self) -> None:
        """Call once per train step; starts/stops per the schedule."""
        if self._step == self.record_steps.start:
            self.start()
        elif self._step == self.record_steps.stop:
            self.stop()
        self._step += 1

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


@contextlib.contextmanager
def annotate(name: str):
    with jax.profiler.TraceAnnotation(name):
        yield
