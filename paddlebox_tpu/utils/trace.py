"""Host-side span tracer — explicit start/stop spans with wire-propagated
trace context (≙ the reference's old RecordEvent span layer,
platform/profiler.cc, rebuilt Dapper-style: every span carries a
``trace_id`` shared by the whole causal chain and a ``span_id``/parent
link, and the PS wire protocol forwards ``trace_id:span_id`` so a server
dispatch span parents to the originating client span across processes —
PAPERS.md, Dapper + Prometheus exposition).

Design constraints:

* **Zero hot-path cost when disabled.**  Instrumentation sites guard on
  the module-level ``ACTIVE`` handle (the ps/faults.py pattern): one
  ``is None`` check per site, no allocation, no lock.
* **Bounded memory.**  Finished spans land in a ring buffer
  (``FLAGS_obs_trace_ring``); retention is newest-N, exactly what
  ``/tracez`` (utils/obs_server.py) serves.
* **Thread-correct.**  The open-span stack is ``threading.local``; each
  span records its thread id and monotonic-clock start/duration, so the
  Chrome-trace export lays spans out per thread like the reference's
  chrome tracing (and merges into the jax.profiler output dir —
  utils/profiler.py writes ``host_spans.trace.json`` beside the XLA
  trace on Profiler.stop()).
* **Exactly-once friendly.**  The wire context rides request RETRIES
  unchanged (the resent frame carries the same ``tctx``), and the
  server only opens a dispatch span when a verb actually EXECUTES — a
  dedup-window replay returns the cached response without a second
  span, so chaos retries never duplicate server spans.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from paddlebox_tpu import flags

flags.define_flag(
    "obs_trace", False,
    "enable the host-side span tracer at import of the worker entry "
    "points (init_distributed / obs exporter start); off = every "
    "instrumentation site is a single is-None check")
flags.define_flag(
    "obs_trace_ring", 4096,
    "finished-span ring-buffer retention of the host tracer (newest N "
    "spans; /tracez serves from this ring)")

# optional wire field carrying "trace_id:span_id" (defined here, ridden
# by ps/wire.py frames next to the PR 2 rid)
CTX_SEP = "/"


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0", "dur",
                 "tid", "attrs")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = time.monotonic()
        self.dur: Optional[float] = None
        self.tid = threading.get_ident()
        self.attrs = attrs

    def context(self) -> str:
        """The wire form: ``<trace_id>/<span_id>``."""
        return f"{self.trace_id}{CTX_SEP}{self.span_id}"

    def as_dict(self) -> Dict:
        d = {"name": self.name, "trace_id": self.trace_id,
             "span_id": self.span_id, "parent_id": self.parent_id,
             "t0": self.t0, "dur_s": self.dur, "tid": self.tid}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


def parse_context(ctx: Optional[str]):
    """``"trace/span"`` → (trace_id, span_id); None / malformed → None."""
    if not ctx or not isinstance(ctx, str) or CTX_SEP not in ctx:
        return None
    trace_id, _, span_id = ctx.partition(CTX_SEP)
    if not trace_id or not span_id:
        return None
    return trace_id, span_id


class SpanTracer:
    """Explicit start/stop span recorder with per-thread open-span
    stacks and a bounded finished-span ring."""

    def __init__(self, ring: Optional[int] = None):
        cap = int(flags.get_flags("obs_trace_ring")
                  if ring is None else ring)
        self._ring: "deque[Span]" = deque(maxlen=max(1, cap))
        self._lock = threading.Lock()
        self._tls = threading.local()
        # id space unique per process instance (spans from different
        # workers merge in the supervisor scrape without collisions)
        self._token = f"{os.getpid():x}-{os.urandom(3).hex()}"
        self._seq = 0

    def _next_id(self) -> str:
        with self._lock:
            self._seq += 1
            return f"{self._token}-{self._seq:x}"

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -- span lifecycle ------------------------------------------------------
    def start_span(self, name: str, parent: Optional[str] = None,
                   **attrs) -> Span:
        """Open a span.  ``parent`` is a wire context string
        (``trace/span``); when omitted the span nests under this
        thread's innermost open span, or roots a fresh trace."""
        parsed = parse_context(parent)
        if parsed is not None:
            trace_id, parent_id = parsed
        else:
            stack = self._stack()
            if stack:
                top = stack[-1]
                trace_id, parent_id = top.trace_id, top.span_id
            else:
                trace_id, parent_id = self._next_id(), None
        span = Span(name, trace_id, self._next_id(), parent_id, attrs)
        self._stack().append(span)
        return span

    def finish(self, span: Span) -> None:
        span.dur = time.monotonic() - span.t0
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        elif span in stack:         # out-of-order finish: drop in place
            stack.remove(span)
        with self._lock:
            self._ring.append(span)

    @contextlib.contextmanager
    def span(self, name: str, parent: Optional[str] = None, **attrs):
        s = self.start_span(name, parent=parent, **attrs)
        try:
            yield s
        finally:
            self.finish(s)

    def current_context(self) -> Optional[str]:
        """Wire context of this thread's innermost open span."""
        stack = getattr(self._tls, "stack", None)
        return stack[-1].context() if stack else None

    # -- retention / export --------------------------------------------------
    def spans(self, n: Optional[int] = None) -> List[Dict]:
        """Newest-first finished spans (bounded by the ring)."""
        with self._lock:
            out = [s.as_dict() for s in reversed(self._ring)]
        return out if n is None else out[:n]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def chrome_trace_events(self) -> List[Dict]:
        """Chrome-trace "X" (complete) events, monotonic microseconds —
        loads in chrome://tracing / Perfetto beside the XLA host trace."""
        pid = os.getpid()
        events = []
        with self._lock:
            spans = list(self._ring)
        for s in spans:
            args = {"trace_id": s.trace_id, "span_id": s.span_id}
            if s.parent_id:
                args["parent_id"] = s.parent_id
            for k, v in s.attrs.items():
                args[str(k)] = v if isinstance(v, (int, float, bool)) \
                    else str(v)
            events.append({
                "name": s.name, "ph": "X", "pid": pid, "tid": s.tid,
                "ts": s.t0 * 1e6, "dur": (s.dur or 0.0) * 1e6,
                "args": args,
            })
        return events

    def export_chrome_trace(self, path: str) -> str:
        """Write the ring as a Chrome-trace JSON file.  ``path`` may be a
        directory (e.g. the jax.profiler log_dir — the host spans merge
        into the same trace collection): the file lands inside it as
        ``host_spans.trace.json``."""
        if os.path.isdir(path):
            path = os.path.join(path, "host_spans.trace.json")
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": self.chrome_trace_events(),
                       "displayTimeUnit": "ms"}, f)
        return path


# module-level handle — the one hot-path check (≙ faults.ACTIVE)
ACTIVE: Optional[SpanTracer] = None


def enable(ring: Optional[int] = None) -> SpanTracer:
    global ACTIVE
    if ACTIVE is None:
        ACTIVE = SpanTracer(ring=ring)
    return ACTIVE


def disable() -> None:
    global ACTIVE
    ACTIVE = None


def maybe_enable_from_flags() -> Optional[SpanTracer]:
    if flags.get_flags("obs_trace"):
        return enable()
    return ACTIVE


def wire_context() -> Optional[str]:
    """Current thread's span context for stamping outgoing requests
    (None when the tracer is off or no span is open)."""
    return ACTIVE.current_context() if ACTIVE is not None else None


@contextlib.contextmanager
def span(name: str, parent: Optional[str] = None, **attrs):
    """No-op-when-disabled span context manager for call sites that
    don't want to hold a tracer reference."""
    if ACTIVE is None:
        yield None
        return
    with ACTIVE.span(name, parent=parent, **attrs) as s:
        yield s
