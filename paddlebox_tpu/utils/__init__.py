from paddlebox_tpu.utils.channel import Channel  # noqa: F401
from paddlebox_tpu.utils.timer import Timer, TimerRegistry  # noqa: F401
from paddlebox_tpu.utils.monitor import StatRegistry, stat_add, stat_get  # noqa: F401
