"""In-process observability exporter — one stdlib ``http.server`` thread
serving the process's StatRegistry and span ring (Prometheus-style pull
exposition, PAPERS.md):

  ``/metrics``  Prometheus text exposition: counters/gauges as gauges,
                histograms as summaries (quantile/sum/count lines).
  ``/statz``    the full flat JSON snapshot (counters + histogram
                percentile keys) — the machine-merge surface the
                launch.py supervisor scrapes into one job-wide view.
                ``?raw=1`` adds ``_hist_raw`` (sparse bucket counts per
                histogram) so the supervisor can merge bucket-wise.
  ``/tracez``   newest-N finished spans from the host tracer
                (utils/trace.py), JSON.
  ``/flightz``  newest-N flight-recorder events (utils/flight.py);
                ``?n=`` and ``?kind=`` filter.
  ``/timelinez`` the telemetry timeline (utils/timeline.py): index +
                SLO watchdog states, or one metric's value/rate series
                via ``?name=&n=``.
  ``/clusterz`` the job-level merged timeline — answered by the
                launch.py supervisor's cluster scraper (registered via
                ``set_clusterz_provider``); workers answer
                ``enabled=False``.
  ``/debugz``   a full wedge-doctor bundle (utils/doctor.py): all-thread
                stacks + flight ring + stat snapshot + workpool state.

``/statz`` and ``/metrics`` accept ``?prefix=`` (dotted-segment match,
monitor._prefix_match) so scrapers can pull narrow slices.

Off by default: ``FLAGS_obs_port`` = 0 starts nothing and no
instrumentation site pays more than an is-None/flag check.  launch.py
assigns ``base_port + rank`` to each worker; ``init_distributed``
starts the server from the flag, and starting the exporter also enables
the span tracer (``/tracez`` without a tracer would always be empty).
"""

from __future__ import annotations

import json
import math
import re
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from paddlebox_tpu import flags
from paddlebox_tpu.utils import doctor, flight, timeline, trace
from paddlebox_tpu.utils.monitor import Histogram, StatRegistry

flags.define_flag(
    "obs_port", 0,
    "serve /metrics (Prometheus text), /statz (JSON snapshot) and "
    "/tracez (recent spans) on 127.0.0.1:<port>; 0 = off.  launch.py "
    "--obs_port assigns base+rank per worker; starting the exporter "
    "also enables the span tracer")

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return "pbox_" + _PROM_BAD.sub("_", name)


def _prom_val(v: float) -> str:
    """Prometheus sample value: non-finite gauges render as the
    exposition-format spellings ``+Inf``/``-Inf``/``NaN`` (Python's
    ``repr`` gives ``inf``/``nan``, which scrapers reject)."""
    f = float(v)
    if math.isfinite(f):
        return repr(f)
    if math.isnan(f):
        return "NaN"
    return "+Inf" if f > 0 else "-Inf"


def render_prometheus(prefix: str = "") -> str:
    """Prometheus text exposition (version 0.0.4) of the registry:
    plain stats as gauges, histograms as summaries.  ``prefix`` narrows
    to one dotted subtree (the ``?prefix=`` scrape filter)."""
    reg = StatRegistry.instance()
    lines: List[str] = []
    for name, val in sorted(reg.counter_snapshot(prefix).items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_prom_val(val)}")
    for name, summ in sorted(reg.hist_snapshot(prefix).items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} summary")
        for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            lines.append(f'{pn}{{quantile="{q}"}} {_prom_val(summ[key])}')
        lines.append(f"{pn}_sum {_prom_val(summ['sum'])}")
        lines.append(f"{pn}_count {int(summ['count'])}")
    return "\n".join(lines) + "\n"


# reserved key carrying raw histogram buckets in a /statz?raw=1 snapshot
HIST_RAW_KEY = "_hist_raw"
# reserved key carrying the mergeable heat-sketch export (ps/heat.py's
# raw() in the utils/sketch.py merge_heat_raw schema)
HEAT_RAW_KEY = "_heat_raw"


def _heat_active():
    """The process HeatMap, or None.  Lazy: utils must not import ps at
    module level (doctor.py's embed discipline)."""
    try:
        from paddlebox_tpu.ps import heat
    except Exception:  # noqa: BLE001 — obs must not require the ps layer
        return None
    return heat.ACTIVE


def render_statz(raw: bool = False, prefix: str = "") -> str:
    """The flat JSON snapshot.  Non-finite gauges are OMITTED — bare
    ``Infinity``/``NaN`` tokens are invalid JSON and would break every
    strict consumer of the scrape.  ``raw=True`` adds ``_hist_raw``
    (sparse bucket counts per histogram) and, when heat telemetry is on,
    ``_heat_raw`` (the mergeable key-space sketch export) for bucket-wise
    supervisor merging; ``prefix`` narrows the stat keys to one dotted
    subtree so the cluster scraper (and external Prometheus) can pull
    slices instead of the full snapshot every interval."""
    reg = StatRegistry.instance()
    out: Dict = {k: v for k, v in reg.snapshot(prefix).items()
                 if math.isfinite(v)}
    if raw:
        out[HIST_RAW_KEY] = reg.hist_raw(prefix)
        hm = _heat_active()
        if hm is not None:
            out[HEAT_RAW_KEY] = hm.raw()
    return json.dumps(out, sort_keys=True)


def render_tracez(limit: int = 256) -> str:
    spans = trace.ACTIVE.spans(limit) if trace.ACTIVE is not None else []
    return json.dumps({"enabled": trace.ACTIVE is not None,
                       "spans": spans})


def render_flightz(n: int = 256, kind: Optional[str] = None) -> str:
    ring = flight.ring()
    return json.dumps({
        "enabled": ring is not None,
        "capacity": ring.capacity if ring is not None else 0,
        "counts": ring.counts() if ring is not None else {},
        "events": flight.events(n=n, kind=kind),
    }, default=str)


def render_heatz(topn: int = 100) -> str:
    """The key-space heat plane (ps/heat.py): per-site top-K keys with
    estimated rates, per-shard load shares, the fitted zipf exponent and
    the working-set curve.  ``enabled=False`` when FLAGS_obs_heat is off
    (or the ps layer isn't importable)."""
    hm = _heat_active()
    if hm is None:
        return json.dumps({"enabled": False})
    out = hm.render(topn=topn)
    out["enabled"] = True
    return json.dumps(out)


def render_timelinez(name: Optional[str] = None,
                     n: Optional[int] = None) -> str:
    """The telemetry timeline (utils/timeline.py): without ``name`` an
    index (names + watchdog states), with it one metric's value/rate
    series."""
    s = timeline.sampler()
    if name:
        return json.dumps(timeline.series(name, n=n))
    return json.dumps({
        "enabled": s is not None,
        "interval_s": s.interval_s if s is not None else 0.0,
        "len": len(s.ring) if s is not None else 0,
        "names": s.ring.names() if s is not None else [],
        "slo": {
            "states": s.watchdog.states() if s is not None else {},
            "rules": [r.describe() for r in s.watchdog.rules]
            if s is not None else [],
        },
    })


# -- /clusterz provider (supervisor-side) -----------------------------------
# launch.py's cluster scraper registers a callable here; worker processes
# have none and answer /clusterz with enabled=False.
_CLUSTERZ: Optional[object] = None


def set_clusterz_provider(fn) -> None:
    """Register ``fn(name=None, n=None) -> dict`` as the /clusterz
    source (the supervisor's ClusterScraper); None unregisters."""
    global _CLUSTERZ
    _CLUSTERZ = fn


def render_clusterz(name: Optional[str] = None,
                    n: Optional[int] = None) -> str:
    fn = _CLUSTERZ
    if fn is None:
        return json.dumps({"enabled": False})
    return json.dumps(fn(name=name, n=n), default=str)


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):        # no stderr spam per scrape
        pass

    def do_GET(self):
        path, _, qs = self.path.partition("?")
        q = urllib.parse.parse_qs(qs)
        try:
            prefix = q.get("prefix", [""])[0]
            if path == "/metrics":
                body = render_prometheus(prefix=prefix)
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/statz":
                raw = q.get("raw", ["0"])[0] not in ("", "0")
                body, ctype = render_statz(raw=raw, prefix=prefix), \
                    "application/json"
            elif path == "/tracez":
                body, ctype = render_tracez(), "application/json"
            elif path == "/flightz":
                n = int(q.get("n", ["256"])[0])
                kind = q.get("kind", [None])[0]
                body, ctype = render_flightz(n=n, kind=kind), \
                    "application/json"
            elif path == "/heatz":
                topn = int(q.get("topn", ["100"])[0])
                body, ctype = render_heatz(topn=topn), "application/json"
            elif path == "/timelinez":
                name = q.get("name", [None])[0]
                n_s = q.get("n", [None])[0]
                body, ctype = render_timelinez(
                    name=name, n=int(n_s) if n_s else None), \
                    "application/json"
            elif path == "/clusterz":
                name = q.get("name", [None])[0]
                n_s = q.get("n", [None])[0]
                body, ctype = render_clusterz(
                    name=name, n=int(n_s) if n_s else None), \
                    "application/json"
            elif path == "/debugz":
                body, ctype = doctor.render_debugz(), "application/json"
            else:
                self.send_error(404, "unknown path (want /metrics, "
                                     "/statz, /tracez, /flightz, "
                                     "/heatz, /timelinez, /clusterz, "
                                     "/debugz)")
                return
        except Exception as e:  # noqa: BLE001 — a scrape must never kill
            self.send_error(500, repr(e))
            return
        raw = body.encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)


class ObsServer:
    """One daemon HTTP thread per process; ``port=0`` binds an ephemeral
    port (tests), ``addr`` reports the bound (host, port)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._srv = ThreadingHTTPServer((host, port), _Handler)
        self._srv.daemon_threads = True
        self.addr: Tuple[str, int] = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


_SERVER: Optional[ObsServer] = None
_SERVER_LOCK = threading.Lock()


def start(port: int = 0, host: str = "127.0.0.1") -> ObsServer:
    """Start (or return) the process-wide exporter.  Also enables the
    span tracer so /tracez has a source."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is None:
            trace.enable()
            _SERVER = ObsServer(port=port, host=host)
        return _SERVER


def stop() -> None:
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            _SERVER.shutdown()
            _SERVER = None


def maybe_start_from_flags() -> Optional[ObsServer]:
    """Worker entry hook: start the exporter iff ``FLAGS_obs_port`` is
    set (launch.py exports base+rank per worker); always honors
    ``FLAGS_obs_trace`` for the tracer alone."""
    trace.maybe_enable_from_flags()
    timeline.maybe_start_from_flags()
    try:
        from paddlebox_tpu.ps import heat
        heat.maybe_enable_from_flags()
    except Exception:  # noqa: BLE001 — obs must not require the ps layer
        pass
    port = int(flags.get_flags("obs_port"))
    if port <= 0:
        return None
    return start(port=port)


# -- supervisor-side scrape/merge -------------------------------------------
def scrape(port: int, path: str = "/statz", host: str = "127.0.0.1",
           timeout: float = 2.0) -> Optional[Dict[str, float]]:
    """GET one worker's snapshot; None on any failure (a dead or
    not-yet-listening worker must not fail the supervisor)."""
    url = f"http://{host}:{port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except Exception:  # noqa: BLE001 — scrape is best-effort by contract
        return None


_MERGE_MAX_SUFFIXES = (".max", ".p50", ".p95", ".p99", "hwm")
_PCT_SUFFIXES = (".p50", ".p95", ".p99")
_PCT_QS = ((50, ".p50"), (95, ".p95"), (99, ".p99"))


def merge_snapshots(snaps: List[Dict[str, float]]) -> Dict[str, float]:
    """Fold per-worker /statz snapshots into one job-wide view: counters
    and sums ADD across workers; high-water marks take the worst (max)
    worker — a job is as slow as its slowest shard.

    Percentiles: taking the max of per-worker ``.p50/.p95/.p99`` is
    statistically wrong (the max of medians is not the median of the
    union, and tail percentiles can be badly skewed by one low-count
    worker).  When a snapshot carries ``_hist_raw`` (a ``/statz?raw=1``
    scrape), its histograms are merged BUCKET-WISE across workers and
    job-wide percentiles are recomputed exactly (up to bucket
    resolution).  Workers that predate raw export still fold in via the
    old max-of-percentiles fallback, so merged tails never understate."""
    out: Dict[str, float] = {}
    raws: Dict[str, List[Dict]] = {}
    heat_raws: List[Dict] = []
    for snap in snaps:
        if not snap:
            continue
        hr = snap.get(HIST_RAW_KEY)
        hr = hr if isinstance(hr, dict) else {}
        for name, r in hr.items():
            if isinstance(r, dict):
                raws.setdefault(name, []).append(r)
        heat_r = snap.get(HEAT_RAW_KEY)
        if isinstance(heat_r, dict):
            heat_raws.append(heat_r)
        for k, v in snap.items():
            if k == HIST_RAW_KEY or not isinstance(v, (int, float)):
                continue
            if k.startswith("heat."):
                # heat gauges are sketch-derived, not additive: summing
                # topk_share across workers is meaningless.  Raw-scraped
                # workers are recomputed from the merged sketches below;
                # max is the non-raw fallback (never understates skew)
                if v > out.get(k, float("-inf")):
                    out[k] = v
                continue
            if k.endswith(_MERGE_MAX_SUFFIXES):
                # this worker's percentile keys are recomputed from its
                # raw buckets below — don't let its per-worker
                # percentile leak into the max fallback
                if k.endswith(_PCT_SUFFIXES) and \
                        k.rsplit(".", 1)[0] in hr:
                    continue
                if v > out.get(k, float("-inf")):
                    out[k] = v
            else:
                out[k] = out.get(k, 0.0) + v
    for name, rlist in raws.items():
        h = Histogram.from_raw(rlist)
        for q, suf in _PCT_QS:
            k = name + suf
            v = h.percentile(q)
            out[k] = max(out[k], v) if k in out else v
    if heat_raws:
        # fleet heat = bucket-wise sketch merge, then the SAME derived-
        # gauge formula every worker applies locally — never a naive
        # fold of the workers' gauges
        from paddlebox_tpu.utils import sketch
        out.update(sketch.heat_gauges_from_raw(
            sketch.merge_heat_raw(heat_raws)))
    return out
