"""Process-wide stat gauges (≙ platform/monitor.h:80 StatRegistry and the
STAT_INT_ADD macros at monitor.h:137)."""

from __future__ import annotations

import threading
from typing import Dict


class StatRegistry:
    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._stats: Dict[str, float] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "StatRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def add(self, name: str, value: float) -> None:
        with self._lock:
            self._stats[name] = self._stats.get(name, 0.0) + value

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._stats[name] = value

    def max(self, name: str, value: float) -> None:
        """Keep the high-water mark of a gauge (e.g. frames in flight)."""
        with self._lock:
            cur = self._stats.get(name)
            if cur is None or value > cur:
                self._stats[name] = value

    def get(self, name: str) -> float:
        with self._lock:
            return self._stats.get(name, 0.0)

    def snapshot(self, prefix: str = "") -> Dict[str, float]:
        """All stats, or just those under a dotted prefix (e.g.
        ``snapshot("ps.fault")`` → every injected-fault counter)."""
        with self._lock:
            if not prefix:
                return dict(self._stats)
            return {k: v for k, v in self._stats.items()
                    if k.startswith(prefix)}

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


def stat_add(name: str, value: float = 1.0) -> None:
    StatRegistry.instance().add(name, value)


def stat_get(name: str) -> float:
    return StatRegistry.instance().get(name)


def stat_max(name: str, value: float) -> None:
    StatRegistry.instance().max(name, value)


def stat_snapshot(prefix: str = "") -> Dict[str, float]:
    return StatRegistry.instance().snapshot(prefix)
