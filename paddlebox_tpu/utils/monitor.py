"""Process-wide stat gauges + bounded-memory latency histograms
(≙ platform/monitor.h:80 StatRegistry and the STAT_INT_ADD macros at
monitor.h:137, grown a histogram surface for verb-latency percentiles).

Two kinds of stats live in the one registry:

* **counters/gauges** — ``stat_add``/``stat_set``/``stat_max``: a flat
  name → float map, exactly the reference's StatValue registry.
* **histograms** — ``stat_observe(name, value)``: bounded-memory
  log-bucketed distributions (quarter-octave buckets over
  ~1e-9 .. ~1e9, 242 fixed buckets, exact count/sum/min/max).
  ``snapshot()`` folds each histogram into derived keys
  ``<name>.count/.sum/.p50/.p95/.p99/.max`` so every existing consumer
  of the flat snapshot (health verb, bench result line, /statz) sees
  percentiles with zero schema change; the Prometheus exporter
  (utils/obs_server.py) reads ``hist_snapshot()`` for summary
  exposition.

``snapshot(prefix)`` matches on DOTTED-SEGMENT boundaries: ``"ps.s"``
matches ``ps.s`` and ``ps.s.*`` but never ``ps.streams.*`` (the naive
startswith used to leak sibling namespaces into prefix scrapes).

Metric names are lowercase dotted literals; dynamic parts must be
bounded fields (verb/cmd/site/... — lint rule PB204 enforces this), or
an unbounded key set grows this process-wide registry forever.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Tuple

# histogram bucket geometry: quarter-octave log buckets from 2^-30
# (~0.93ns — below any latency we time) up to 2^30 (~1.07e9 — above any
# byte count per observation we expect); values outside clamp into the
# under/overflow buckets but min/max stay exact
_HIST_LO = 2.0 ** -30
_HIST_BPB = 4                       # buckets per octave (2^(1/4) growth)
_HIST_NB = 60 * _HIST_BPB           # spans 2^-30 .. 2^30


def _bucket_index(v: float) -> int:
    if v <= _HIST_LO:
        return 0
    idx = int(math.log2(v / _HIST_LO) * _HIST_BPB) + 1
    return min(idx, _HIST_NB + 1)


def _bucket_bounds(idx: int) -> Tuple[float, float]:
    """(lower, upper) value bounds of bucket ``idx`` (1..NB)."""
    return (_HIST_LO * 2.0 ** ((idx - 1) / _HIST_BPB),
            _HIST_LO * 2.0 ** (idx / _HIST_BPB))


class Histogram:
    """Bounded-memory log-bucketed histogram: a fixed int array plus
    exact count/sum/min/max.  Percentiles interpolate at the geometric
    midpoint of the landing bucket (≤ ~9% relative bucket-width error at
    quarter-octave resolution), clamped to the observed [min, max]."""

    __slots__ = ("counts", "count", "total", "vmin", "vmax")

    def __init__(self):
        self.counts = [0] * (_HIST_NB + 2)
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    def observe(self, value: float) -> None:
        v = float(value)
        if not math.isfinite(v):
            # a single nan would poison `total` (and every later .sum /
            # mean) while leaving vmin/vmax untouched — drop it here and
            # let the registry count the drop
            return
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        self.counts[_bucket_index(v)] += 1

    def percentile(self, q: float) -> float:
        """Estimate the q-th percentile (q in [0, 100])."""
        if self.count == 0:
            return 0.0
        target = max(1.0, q / 100.0 * self.count)
        cum = 0
        for idx, c in enumerate(self.counts):
            cum += c
            if cum >= target:
                if idx == 0:
                    return min(self.vmin, _HIST_LO)
                if idx == _HIST_NB + 1:
                    return self.vmax
                lo, hi = _bucket_bounds(idx)
                est = math.sqrt(lo * hi)
                return min(max(est, self.vmin), self.vmax)
        return self.vmax

    def summary(self) -> Dict[str, float]:
        return {
            "count": float(self.count),
            "sum": self.total,
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
            "max": self.vmax if self.count else 0.0,
        }

    def raw(self) -> Dict:
        """Mergeable wire form: sparse bucket counts + exact
        count/sum/min/max.  ``/statz?raw=1`` ships this so the
        supervisor can merge histograms BUCKET-WISE across workers and
        recompute job-wide percentiles (max-of-per-worker-percentiles is
        statistically wrong — see obs_server.merge_snapshots)."""
        return {
            "b": {str(i): c for i, c in enumerate(self.counts) if c},
            "count": self.count,
            "sum": self.total,
            "min": self.vmin if self.count else 0.0,
            "max": self.vmax if self.count else 0.0,
        }

    @classmethod
    def from_raw(cls, raws: List[Dict]) -> "Histogram":
        """Rebuild one histogram from the bucket-wise sum of many
        ``raw()`` dicts (identical fixed bucket geometry on every
        worker makes this exact up to bucket resolution)."""
        h = cls()
        nb = len(h.counts)
        for r in raws:
            for i, c in (r.get("b") or {}).items():
                idx = int(i)
                if 0 <= idx < nb:
                    h.counts[idx] += int(c)
            n = int(r.get("count", 0))
            h.count += n
            h.total += float(r.get("sum", 0.0))
            if n > 0:
                h.vmin = min(h.vmin, float(r.get("min", math.inf)))
                h.vmax = max(h.vmax, float(r.get("max", -math.inf)))
        return h


def _prefix_match(key: str, prefix: str) -> bool:
    """Dotted-segment prefix: ``ps.s`` matches ``ps.s``/``ps.s.x`` but
    never ``ps.streams.x``; a trailing-dot prefix matches its subtree."""
    if not prefix or key == prefix:
        return True
    if prefix.endswith("."):
        return key.startswith(prefix)
    return key.startswith(prefix) and key[len(prefix)] == "."


class StatRegistry:
    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._stats: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "StatRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def add(self, name: str, value: float) -> None:
        with self._lock:
            self._stats[name] = self._stats.get(name, 0.0) + value

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._stats[name] = value

    def max(self, name: str, value: float) -> None:
        """Keep the high-water mark of a gauge (e.g. frames in flight)."""
        with self._lock:
            cur = self._stats.get(name)
            if cur is None or value > cur:
                self._stats[name] = value

    def observe(self, name: str, value: float) -> None:
        """Record one sample into the named histogram (created on first
        observe; bounded memory per name — see lint rule PB204 for why
        the NAME set must be bounded too).  Non-finite samples are
        dropped (they would poison ``sum``) and counted under
        ``obs.non_finite_dropped``."""
        with self._lock:
            if not math.isfinite(float(value)):
                self._stats["obs.non_finite_dropped"] = \
                    self._stats.get("obs.non_finite_dropped", 0.0) + 1.0
                return
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(value)

    def get(self, name: str) -> float:
        with self._lock:
            return self._stats.get(name, 0.0)

    def snapshot(self, prefix: str = "") -> Dict[str, float]:
        """All stats — counters plus each histogram's derived
        ``.count/.sum/.p50/.p95/.p99/.max`` keys — or just those under a
        dotted prefix, matched on segment boundaries (``"ps.s"`` never
        matches ``ps.streams.*``)."""
        with self._lock:
            out = dict(self._stats)
            hists = {n: h.summary() for n, h in self._hists.items()}
        for name, summ in hists.items():
            for k, v in summ.items():
                out[f"{name}.{k}"] = v
        if not prefix:
            return out
        return {k: v for k, v in out.items() if _prefix_match(k, prefix)}

    def hist_snapshot(self, prefix: str = "") -> Dict[str, Dict[str, float]]:
        """Histogram summaries keyed by histogram name (the Prometheus
        summary exposition source, utils/obs_server.py)."""
        with self._lock:
            names = [n for n in self._hists if _prefix_match(n, prefix)]
            return {n: self._hists[n].summary() for n in names}

    def hist_raw(self, prefix: str = "") -> Dict[str, Dict]:
        """Raw (mergeable) histogram exports keyed by name — the
        ``/statz?raw=1`` payload."""
        with self._lock:
            names = [n for n in self._hists if _prefix_match(n, prefix)]
            return {n: self._hists[n].raw() for n in names}

    def counter_snapshot(self, prefix: str = "") -> Dict[str, float]:
        """Plain counters/gauges only (no histogram-derived keys)."""
        with self._lock:
            return {k: v for k, v in self._stats.items()
                    if _prefix_match(k, prefix)}

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._hists.clear()

    def remove_prefix(self, prefix: str) -> int:
        """Drop every stat and histogram under a dotted prefix; returns
        how many were removed.  For subsystem resets (quality.reset):
        a gauge left behind by a discarded model would keep feeding the
        timeline sampler and SLO watchdog as if it were current."""
        with self._lock:
            ks = [k for k in self._stats if _prefix_match(k, prefix)]
            hs = [k for k in self._hists if _prefix_match(k, prefix)]
            for k in ks:
                del self._stats[k]
            for k in hs:
                del self._hists[k]
            return len(ks) + len(hs)


def stat_add(name: str, value: float = 1.0) -> None:
    StatRegistry.instance().add(name, value)


def stat_set(name: str, value: float) -> None:
    """Overwrite a gauge (mirrors StatRegistry.set, like stat_add/
    stat_max mirror add/max)."""
    StatRegistry.instance().set(name, value)


def stat_get(name: str) -> float:
    return StatRegistry.instance().get(name)


def stat_max(name: str, value: float) -> None:
    StatRegistry.instance().max(name, value)


def stat_observe(name: str, value: float) -> None:
    """Record one sample into a bounded-memory log-bucketed histogram;
    percentiles surface as ``<name>.p50/.p95/.p99/.max`` in snapshots."""
    StatRegistry.instance().observe(name, value)


def stat_snapshot(prefix: str = "") -> Dict[str, float]:
    return StatRegistry.instance().snapshot(prefix)
