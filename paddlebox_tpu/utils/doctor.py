"""Wedge doctor — one-call postmortem bundles for stuck processes.

BENCH_r04/r05 wedged in ``backend-init`` and left nothing but
"watchdog: phase exceeded its budget" — ten attempts, zero stacks.
``dump_state()`` collects everything a human needs to diagnose a hang
into one JSON-able dict:

* all-thread Python stacks (``sys._current_frames`` + thread names /
  daemon flags from ``threading.enumerate``),
* the flight-recorder ring (utils/flight.py — what the process was
  *doing* right before it stopped),
* the full stat snapshot (utils/monitor.py),
* workpool queue state (utils/workpool.py — queued vs active),
* pid / argv / platform breadcrumbs.

Three delivery paths:

1. **SIGUSR1** (``install()``) — live interrogation of a running
   worker: ``kill -USR1 <pid>`` writes a postmortem bundle under
   ``FLAGS_obs_postmortem_dir`` and prints its path to stderr.
   ``install()`` also enables ``faulthandler`` so hard crashes
   (segfault, deadlocked interpreter via SIGABRT) still dump native
   stacks to stderr even when this module can't run.
2. **/debugz** (utils/obs_server.py) — scrape the bundle over HTTP.
3. **bench.py phase watchdog** — on phase-budget expiry the child
   writes a postmortem file BEFORE emitting its error line and the
   supervisor records the path in ``attempt_log``, so the next TPU
   wedge ships with stacks attached.

Collection cost is irrelevant (it runs when the process is already
stuck); what matters is that it CANNOT hang: no locks are taken beyond
the registries' own short-critical-section locks, and file writes go
through a plain open/json.dump.
"""

from __future__ import annotations

import faulthandler
import json
import os
import signal
import sys
import tempfile
import threading
import time
import traceback
from typing import Dict, List, Optional

from paddlebox_tpu import flags
from paddlebox_tpu.utils import flight
from paddlebox_tpu.utils.monitor import stat_snapshot

flags.define_flag(
    "obs_postmortem_dir", "",
    "directory for wedge-doctor postmortem bundles (SIGUSR1 handler, "
    "bench.py phase watchdog); empty = <system tmpdir>/pbox-postmortems")

_FLIGHT_N = 256                     # last-N flight events per bundle


def thread_stacks() -> List[Dict]:
    """Python stacks of every live thread, newest frame last."""
    names = {t.ident: t for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        t = names.get(tid)
        entry = {
            "tid": tid,
            "name": t.name if t is not None else f"unknown-{tid}",
            "daemon": bool(t.daemon) if t is not None else None,
            "stack": [f"{fs.filename}:{fs.lineno} in {fs.name}: "
                      f"{(fs.line or '').strip()}"
                      for fs in traceback.extract_stack(frame)],
        }
        out.append(entry)
    out.sort(key=lambda e: (e["name"] != "MainThread", e["name"]))
    return out


def dump_state(reason: str = "", flight_n: int = _FLIGHT_N) -> Dict:
    """The full postmortem bundle as one JSON-able dict."""
    bundle: Dict = {
        "reason": reason,
        "time": time.time(),
        "mono": time.monotonic(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "platform": sys.platform,
        "threads": thread_stacks(),
        "flight": flight.events(n=flight_n),
        "stats": stat_snapshot(),
    }
    try:                            # lazy: workpool pulls in flags only
        from paddlebox_tpu.utils import workpool
        bundle["workpool"] = workpool.pool_state()
    except Exception as e:          # never let the doctor itself wedge
        bundle["workpool"] = {"error": repr(e)}
    try:
        from paddlebox_tpu.utils import lockdep
        if lockdep.enabled():
            bundle["lockdep"] = lockdep.state()
    except Exception as e:
        bundle["lockdep"] = {"error": repr(e)}
    try:                            # lazy: avoid an import cycle with
        from paddlebox_tpu.utils import timeline  # obs_server→doctor
        s = timeline.sampler()
        if s is not None:
            # the minutes LEADING UP TO the wedge, not just its instant
            bundle["timeline"] = {"interval_s": s.interval_s,
                                  "slo": s.watchdog.states(),
                                  "tail": timeline.tail()}
    except Exception as e:
        bundle["timeline"] = {"error": repr(e)}
    try:                            # lazy: utils never imports ps eagerly
        from paddlebox_tpu.ps import heat
        if heat.ACTIVE is not None:
            # the key-space heat tail: was the wedge a hot-key storm?
            bundle["heat"] = heat.ACTIVE.render(topn=20)
    except Exception as e:
        bundle["heat"] = {"error": repr(e)}
    return bundle


def render_debugz(reason: str = "debugz") -> str:
    """The bundle as JSON text (the /debugz obs endpoint body)."""
    return json.dumps(dump_state(reason=reason), indent=1, default=str)


def postmortem_dir() -> str:
    d = str(flags.get_flags("obs_postmortem_dir") or "")
    if not d:
        d = os.path.join(tempfile.gettempdir(), "pbox-postmortems")
    os.makedirs(d, exist_ok=True)
    return d


def write_postmortem(reason: str = "", directory: Optional[str] = None) -> str:
    """Write a postmortem bundle file; returns its path."""
    d = directory or postmortem_dir()
    os.makedirs(d, exist_ok=True)
    path = os.path.join(
        d, f"postmortem-{os.getpid()}-{int(time.time() * 1000)}.json")
    bundle = dump_state(reason=reason)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(bundle, f, indent=1, default=str)
    os.replace(tmp, path)
    flight.record("postmortem_written", path=path, reason=reason)
    return path


def _sigusr1(signum, frame) -> None:
    try:
        path = write_postmortem(reason="sigusr1")
        print(f"[doctor] postmortem: {path}", file=sys.stderr, flush=True)
    except Exception as e:
        print(f"[doctor] postmortem failed: {e!r}", file=sys.stderr,
              flush=True)


def install() -> bool:
    """Enable faulthandler + the SIGUSR1 live-interrogation handler.
    Returns True when the signal handler was installed (needs the main
    thread and a platform with SIGUSR1; safe no-op otherwise)."""
    try:
        faulthandler.enable()
    except Exception:
        pass
    try:
        signal.signal(signal.SIGUSR1, _sigusr1)
        return True
    except (ValueError, OSError, AttributeError):
        return False                # non-main thread / no SIGUSR1
