"""Bounded-memory streaming sketches for key-space heat telemetry.

The obs stack observes *time* and *verbs* (histograms, spans, flight
events, the timeline); nothing observes the *key space* — yet the whole
design is a bet on zipf skew (a tiny hot set dominates traffic, which is
why an HBM row cache over an SSD tier works at all).  This module is the
measurement substrate: streaming summaries of key frequency, heavy
hitters, distinct counts and per-shard load that are

* **bounded** — memory is fixed at construction, independent of stream
  length or key cardinality (the whole point: per-key dicts in obs code
  are banned by lint rule PB208);
* **mergeable** — every sketch has a ``raw()`` wire form and a
  ``from_raw([...])`` bucket-wise fold, the exact Histogram.raw
  discipline, so the supervisor merges per-worker sketches into one
  fleet-global view instead of taking a statistically-wrong max;
* **decayable** — ``decay(f)`` scales counts at day boundaries like
  every other day-scale score (show_click_decay), so "hot" means *hot
  lately*, not hot-ever.

Error bounds (documented contract, pinned by tests/test_heat.py):

* :class:`CountMinSketch` (width ``w``, depth ``d``): estimates never
  under-count; over-count ≤ (e/w)·N with probability ≥ 1 − e^(−d) for a
  stream of N total increments (classic CM bound; rows are indexed by
  splitmix64 mixing rather than a formal 2-universal family, so the
  bound is the design target and the zipf-stream test pins the actual
  behaviour).  Default 2048×4 ≈ 64 KB per sketch; ε ≈ 0.0013.
* :class:`SpaceSaving` (capacity ``k``): every key with true count
  > N/k is monitored; a monitored key's count over-estimates its true
  count by at most its recorded ``err`` ≤ min-count ≤ N/k.  Merging two
  sketches sums counts key-wise and re-truncates, so merged error grows
  to at most ε_a + ε_b (merge(a, b) agrees with streaming a++b within
  those bounds — associativity is tested, not assumed).
* :class:`HyperLogLog` (precision ``p``): distinct-count standard error
  ≈ 1.04/√(2^p) (~1.6 % at the default p=12, 4 KB).  A distinct count
  cannot decay; ``decay()`` resets it, so working-set estimates read
  "since the last day boundary" by contract.
* :class:`ShardLoad`: exact per-shard key counters (bounded by the
  fleet size); ``imbalance()`` = max shard load / mean shard load
  (1.0 = perfectly even, n = everything on one shard).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _mix64(x: np.ndarray, salt: np.uint64) -> np.ndarray:
    """splitmix64 finalizer over a uint64 array (vectorized, wrapping)."""
    z = (x.astype(np.uint64, copy=False) + salt).astype(np.uint64)
    z = (z ^ (z >> np.uint64(30))) * _M1
    z = (z ^ (z >> np.uint64(27))) * _M2
    return z ^ (z >> np.uint64(31))


def _row_salt(seed: int, row: int) -> np.uint64:
    """Per-row salt: splitmix64 of (seed, row) so depth rows index
    (near-)independently."""
    base = np.uint64((seed * 1_000_003 + row + 1) & 0xFFFFFFFFFFFFFFFF)
    return _mix64(np.array([base], np.uint64), _GOLDEN)[0]


def unique_with_counts(keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(unique uint64 keys, float64 counts) of one observation batch —
    the canonical sketch-update input (taps pass raw key arrays)."""
    keys = np.asarray(keys, np.uint64).ravel()
    if not len(keys):
        return keys, np.zeros((0,), np.float64)
    uniq, counts = np.unique(keys, return_counts=True)
    return uniq, counts.astype(np.float64)


class CountMinSketch:
    """Conservative frequency estimator: ``depth`` rows of ``width``
    float counters; a key increments one counter per row, estimates take
    the row-wise min.  Float cells so day-boundary decay is exact."""

    def __init__(self, width: int = 2048, depth: int = 4, seed: int = 0):
        self.width = max(8, int(width))
        self.depth = max(1, int(depth))
        self.seed = int(seed)
        self._salts = [_row_salt(self.seed, d) for d in range(self.depth)]
        self.counts = np.zeros((self.depth, self.width), np.float64)
        self.total = 0.0

    def nbytes(self) -> int:
        return int(self.counts.nbytes)

    def _rows(self, keys: np.ndarray) -> List[np.ndarray]:
        w = np.uint64(self.width)
        return [(_mix64(keys, s) % w).astype(np.int64) for s in self._salts]

    def update(self, keys: np.ndarray,
               counts: Optional[np.ndarray] = None) -> None:
        keys = np.asarray(keys, np.uint64).ravel()
        if not len(keys):
            return
        if counts is None:
            counts = np.ones((len(keys),), np.float64)
        counts = np.asarray(counts, np.float64)
        for d, idx in enumerate(self._rows(keys)):
            np.add.at(self.counts[d], idx, counts)
        self.total += float(counts.sum())

    def estimate(self, keys: np.ndarray) -> np.ndarray:
        """Row-wise-min estimates for ``keys`` (float64, ≥ true count up
        to decay; ≤ true + εN w.h.p.)."""
        keys = np.asarray(keys, np.uint64).ravel()
        if not len(keys):
            return np.zeros((0,), np.float64)
        est = None
        for d, idx in enumerate(self._rows(keys)):
            row = self.counts[d][idx]
            est = row if est is None else np.minimum(est, row)
        return est

    def epsilon(self) -> float:
        """The documented per-estimate over-count bound as a fraction of
        stream weight: e/width."""
        return math.e / self.width

    def decay(self, factor: float) -> None:
        f = float(factor)
        self.counts *= f
        self.total *= f

    def merge(self, other: "CountMinSketch") -> None:
        if (other.width, other.depth, other.seed) != \
                (self.width, self.depth, self.seed):
            raise ValueError("count-min geometry/seed mismatch")
        self.counts += other.counts
        self.total += other.total

    def raw(self) -> Dict:
        """Mergeable wire form (geometry + dense rounded cells; a 2048×4
        sketch is ~8 K numbers — one scrape, not a hot path)."""
        return {"w": self.width, "d": self.depth, "s": self.seed,
                "t": self.total,
                "c": [[round(float(v), 3) for v in row]
                      for row in self.counts]}

    @classmethod
    def from_raw(cls, raws: Sequence[Dict]) -> "CountMinSketch":
        """Cell-wise sum of many ``raw()`` exports (identical geometry
        required — the Histogram.from_raw discipline)."""
        raws = [r for r in raws if r]
        if not raws:
            return cls()
        first = raws[0]
        out = cls(width=int(first.get("w", 2048)),
                  depth=int(first.get("d", 4)),
                  seed=int(first.get("s", 0)))
        for r in raws:
            if (int(r.get("w", 0)), int(r.get("d", 0))) \
                    != (out.width, out.depth):
                continue        # foreign geometry: skip, never corrupt
            out.counts += np.asarray(r.get("c", ()), np.float64) \
                .reshape(out.depth, out.width)
            out.total += float(r.get("t", 0.0))
        return out


class SpaceSaving:
    """Top-K heavy hitters (Metwally et al.): at most ``k`` monitored
    keys; an unmonitored arrival evicts the current minimum and inherits
    its count as ``err``.  Batched updates take (unique keys, counts);
    a batch is sequentialized in ascending (count, key) order, which
    turns the eviction heap into a two-pointer merge (see ``update``) —
    O(k log k + u log u) per batch, and keys that do not survive the
    batch never touch the monitored dicts."""

    def __init__(self, k: int = 512):
        self.k = max(1, int(k))
        self._counts: Dict[int, float] = {}
        self._errs: Dict[int, float] = {}
        self.total = 0.0

    def __len__(self) -> int:
        return len(self._counts)

    def update(self, keys: np.ndarray,
               counts: Optional[np.ndarray] = None) -> None:
        keys = np.asarray(keys, np.uint64).ravel()
        if not len(keys):
            return
        if counts is None:
            counts = np.ones((len(keys),), np.float64)
        counts = np.asarray(counts, np.float64).ravel()
        self.total += float(counts.sum())
        cd, ed = self._counts, self._errs
        # Any sequentialization of a batch is a valid SpaceSaving run;
        # ours: monitored-key increments first, then unmonitored keys in
        # ascending (count, key) order.
        if cd:
            tracked = np.fromiter(cd.keys(), np.uint64, len(cd))
            hit = np.isin(keys, tracked)
            for key, c in zip(keys[hit].tolist(), counts[hit].tolist()):
                cd[key] += c
            miss = ~hit
            miss_k = keys[miss]
            miss_c = counts[miss]
        else:
            miss_k = keys
            miss_c = counts
        if not len(miss_k):
            return
        # stable by count == (count, key) order for the canonical taps
        # (unique_with_counts emits keys ascending); any input order is
        # a valid sequentialization regardless
        order = np.argsort(miss_c, kind="stable")
        miss_k = miss_k[order]
        miss_c = miss_c[order]
        free = self.k - len(cd)
        if free > 0:
            for key, c in zip(miss_k[:free].tolist(),
                              miss_c[:free].tolist()):
                cd[key] = c
                ed[key] = 0.0
            miss_k = miss_k[free:]
            miss_c = miss_c[free:]
        m_n = len(miss_k)
        if not m_n:
            return
        # Eviction cascade.  In ascending order the popped minima are
        # non-decreasing and each newcomer re-enters at min + c, also
        # non-decreasing — so the "heap" is exactly a two-pointer merge
        # of the sorted monitored counts with the FIFO of newcomers,
        # and keys that do not survive the batch never touch the dicts.
        base = sorted((c, key) for key, c in cd.items())
        a_c = np.asarray([c for c, _ in base], np.float64)
        a_k = [key for _, key in base]
        na = len(a_k)
        q = np.empty(m_n, np.float64)   # newcomer counts, creation order
        qe = np.empty(m_n, np.float64)  # inherited minima (err bounds)
        ai = 0      # originals popped
        qi = 0      # newcomers popped
        pos = 0     # newcomers created (== ai + qi: one per eviction)
        while pos < m_n:
            if ai < na and (qi >= pos or a_c[ai] <= q[qi]):
                m = float(a_c[ai])          # next min is an original
                ai += 1
                q[pos] = m + miss_c[pos]
                qe[pos] = m
                pos += 1
                continue
            # Next min is a newcomer: with `live` entries queued the
            # cascade is the lag-`live` recurrence q[n] = q[n-live]+c[n],
            # vectorizable until an original out-competes the front.
            live = pos - qi
            take = min(live, m_n - pos)
            if ai < na:
                take = min(take, int(np.searchsorted(
                    q[qi:qi + take], a_c[ai], side="left")))
            block = q[qi:qi + take]
            q[pos:pos + take] = block + miss_c[pos:pos + take]
            qe[pos:pos + take] = block
            qi += take
            pos += take
        for key in a_k[:ai]:       # originals evicted by the cascade
            del cd[key]
            ed.pop(key, None)
        for key, c, e in zip(miss_k[qi:].tolist(), q[qi:].tolist(),
                             qe[qi:].tolist()):
            cd[key] = c            # newcomers that survived the cascade
            ed[key] = e

    def top(self, n: Optional[int] = None) -> List[Tuple[int, float, float]]:
        """[(key, est_count, err)] sorted by est_count desc."""
        items = sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))
        if n is not None:
            items = items[:max(0, int(n))]
        return [(key, c, self._errs.get(key, 0.0)) for key, c in items]

    def topk_share(self, n: Optional[int] = None) -> float:
        """Fraction of total stream weight attributed to the top-``n``
        monitored keys (the skew headline: ~1.0 = hot set dominates)."""
        if self.total <= 0:
            return 0.0
        top = self.top(n)
        return min(1.0, sum(c for _, c, _ in top) / self.total)

    def decay(self, factor: float) -> None:
        f = float(factor)
        self._counts = {key: c * f for key, c in self._counts.items()}
        self._errs = {key: e * f for key, e in self._errs.items()}
        self.total *= f

    def merge(self, other: "SpaceSaving") -> None:
        """Key-wise count/err sum over the union, truncated back to the
        larger capacity — merged error ≤ ε_self + ε_other."""
        for key, c in other._counts.items():
            if key in self._counts:
                self._counts[key] += c
                self._errs[key] = self._errs.get(key, 0.0) \
                    + other._errs.get(key, 0.0)
            else:
                self._counts[key] = c
                self._errs[key] = other._errs.get(key, 0.0)
        self.total += other.total
        self.k = max(self.k, other.k)
        if len(self._counts) > self.k:
            keep = sorted(self._counts.items(),
                          key=lambda kv: (-kv[1], kv[0]))[:self.k]
            kept = {key for key, _ in keep}
            self._counts = {key: c for key, c in keep}
            self._errs = {key: e for key, e in self._errs.items()
                          if key in kept}

    def raw(self) -> Dict:
        return {"k": self.k, "t": self.total,
                "c": {str(key): round(c, 3)
                      for key, c in self._counts.items()},
                "e": {str(key): round(e, 3)
                      for key, e in self._errs.items() if e}}

    @classmethod
    def from_raw(cls, raws: Sequence[Dict]) -> "SpaceSaving":
        raws = [r for r in raws if r]
        out = cls(k=max([int(r.get("k", 1)) for r in raws] or [1]))
        for r in raws:
            part = cls(k=out.k)
            part._counts = {int(key): float(c)
                            for key, c in (r.get("c") or {}).items()}
            part._errs = {int(key): float(e)
                          for key, e in (r.get("e") or {}).items()}
            part.total = float(r.get("t", 0.0))
            out.merge(part)
        return out


class HyperLogLog:
    """Distinct-count estimator: 2^p byte registers, register = max
    leading-zero rank of hashed keys routed to it.  Merge = register-wise
    max (exact).  No decay — day boundaries reset it."""

    def __init__(self, p: int = 12, seed: int = 0):
        self.p = min(18, max(4, int(p)))
        self.m = 1 << self.p
        self.seed = int(seed)
        self._salt = _row_salt(self.seed, 97)
        self.regs = np.zeros((self.m,), np.uint8)

    def nbytes(self) -> int:
        return int(self.regs.nbytes)

    def update(self, keys: np.ndarray) -> None:
        keys = np.asarray(keys, np.uint64).ravel()
        if not len(keys):
            return
        h = _mix64(keys, self._salt)
        idx = (h >> np.uint64(64 - self.p)).astype(np.int64)
        rest = (h << np.uint64(self.p)) | np.uint64((1 << self.p) - 1)
        # rank = leading zeros of the remaining 64-p bits, + 1
        lz = np.uint64(64) - np.uint64(1) \
            - np.floor(np.log2(rest.astype(np.float64))).astype(np.uint64)
        rank = np.minimum(lz + np.uint64(1),
                          np.uint64(64 - self.p)).astype(np.uint8)
        np.maximum.at(self.regs, idx, rank)

    def estimate(self) -> float:
        m = float(self.m)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        inv = float(np.sum(np.power(2.0, -self.regs.astype(np.float64))))
        e = alpha * m * m / inv
        if e <= 2.5 * m:                      # small-range: linear counting
            zeros = int(np.count_nonzero(self.regs == 0))
            if zeros:
                return m * math.log(m / zeros)
        return e

    def reset(self) -> None:
        self.regs[:] = 0

    def merge(self, other: "HyperLogLog") -> None:
        if other.p != self.p or other.seed != self.seed:
            raise ValueError("hyperloglog precision/seed mismatch")
        np.maximum(self.regs, other.regs, out=self.regs)

    def raw(self) -> Dict:
        nz = np.nonzero(self.regs)[0]
        return {"p": self.p, "s": self.seed,
                "r": {str(int(i)): int(self.regs[i]) for i in nz}}

    @classmethod
    def from_raw(cls, raws: Sequence[Dict]) -> "HyperLogLog":
        raws = [r for r in raws if r]
        if not raws:
            return cls()
        out = cls(p=int(raws[0].get("p", 12)), seed=int(raws[0].get("s", 0)))
        for r in raws:
            if int(r.get("p", 0)) != out.p:
                continue
            for i, v in (r.get("r") or {}).items():
                idx = int(i)
                if 0 <= idx < out.m:
                    out.regs[idx] = max(out.regs[idx], int(v))
        return out


class ShardLoad:
    """Exact per-shard load accumulator (bounded by fleet size).
    ``imbalance()`` is the skew headline the resize decision reads."""

    def __init__(self, n: int = 0):
        self.loads = np.zeros((max(0, int(n)),), np.float64)

    def _ensure(self, n: int) -> None:
        if n > len(self.loads):
            grown = np.zeros((n,), np.float64)
            grown[:len(self.loads)] = self.loads
            self.loads = grown

    def add(self, shard: int, weight: float) -> None:
        shard = int(shard)
        self._ensure(shard + 1)
        self.loads[shard] += float(weight)

    def imbalance(self) -> float:
        """max shard load / mean shard load over shards that exist
        (1.0 = even; n = single-shard hotspot; 0.0 = no traffic yet)."""
        if not len(self.loads):
            return 0.0
        total = float(self.loads.sum())
        if total <= 0:
            return 0.0
        mean = total / len(self.loads)
        return float(self.loads.max()) / mean

    def shares(self) -> List[float]:
        total = float(self.loads.sum())
        if total <= 0:
            return [0.0] * len(self.loads)
        return [round(float(v) / total, 6) for v in self.loads]

    def decay(self, factor: float) -> None:
        self.loads *= float(factor)

    def merge(self, other: "ShardLoad") -> None:
        self._ensure(len(other.loads))
        self.loads[:len(other.loads)] += other.loads

    def raw(self) -> Dict:
        return {"l": [round(float(v), 3) for v in self.loads]}

    @classmethod
    def from_raw(cls, raws: Sequence[Dict]) -> "ShardLoad":
        out = cls()
        for r in raws:
            if not r:
                continue
            part = cls()
            part.loads = np.asarray(r.get("l", ()), np.float64)
            out.merge(part)
        return out


def fit_zipf_exponent(counts: Sequence[float]) -> float:
    """Least-squares slope of log(count) vs log(rank) over a sorted-desc
    count sequence → the zipf exponent estimate ``s`` in count ∝ rank^-s
    (the benches synthesize at s=1.3; /heatz reports what traffic
    actually shows).  0.0 when fewer than 3 positive counts."""
    c = [float(v) for v in counts if float(v) > 0]
    if len(c) < 3:
        return 0.0
    x = np.log(np.arange(1, len(c) + 1, dtype=np.float64))
    y = np.log(np.asarray(sorted(c, reverse=True), np.float64))
    xm, ym = x.mean(), y.mean()
    denom = float(((x - xm) ** 2).sum())
    if denom <= 0:
        return 0.0
    slope = float(((x - xm) * (y - ym)).sum()) / denom
    return round(max(0.0, -slope), 4)


# -- the heat wire schema (one process's mergeable heat state) ---------------
# {"sites": {site: {"cm":…, "tk":…, "hll":…}}, "loads":…, "cache": [h, m]}
# Merging lives HERE (pure sketch math, no ps dependency) so the
# supervisor-side merge_snapshots fold and ps/heat.py publish the SAME
# derived gauges from the same fold — "fleet heat == per-worker sketch
# merge" by construction, never a naive max.

def merge_heat_raw(raws: Sequence[Dict]) -> Dict:
    """Fold many per-process heat exports bucket-wise into one."""
    raws = [r for r in raws if isinstance(r, dict)]
    sites: Dict[str, Dict] = {}
    names = sorted({n for r in raws for n in (r.get("sites") or {})})
    for name in names:
        parts = [r["sites"][name] for r in raws
                 if name in (r.get("sites") or {})]
        sites[name] = {
            "cm": CountMinSketch.from_raw(
                [p.get("cm") for p in parts]).raw(),
            "tk": SpaceSaving.from_raw([p.get("tk") for p in parts]).raw(),
            "hll": HyperLogLog.from_raw(
                [p.get("hll") for p in parts]).raw(),
        }
    loads = ShardLoad.from_raw([r.get("loads") or {} for r in raws])
    cache = [0.0, 0.0]
    for r in raws:
        c = r.get("cache") or (0.0, 0.0)
        cache[0] += float(c[0])
        cache[1] += float(c[1])
    return {"sites": sites, "loads": loads.raw(), "cache": cache}


def heat_gauges_from_raw(raw: Dict, topn: int = 100) -> Dict[str, float]:
    """The derived heat gauges from one (possibly merged) heat export —
    the single formula both ps/heat.py and the cluster merge publish."""
    sites = raw.get("sites") or {}
    pull = sites.get("pull") or {}
    tk = SpaceSaving.from_raw([pull.get("tk")]) if pull else SpaceSaving()
    hll = HyperLogLog.from_raw([pull.get("hll")]) if pull else HyperLogLog()
    loads = ShardLoad.from_raw([raw.get("loads") or {}])
    hits, misses = (list(raw.get("cache") or (0.0, 0.0)) + [0.0, 0.0])[:2]
    denom = float(hits) + float(misses)
    return {
        "heat.topk_share": round(tk.topk_share(topn), 6),
        "heat.shard_imbalance": round(loads.imbalance(), 6),
        "heat.working_set_rows": round(hll.estimate(), 1)
        if pull else 0.0,
        "heat.cache_hot_coverage":
            round(float(hits) / denom, 6) if denom > 0 else 0.0,
    }
