"""Continuous telemetry timeline + SLO watchdog — the TIME dimension of
the obs stack.

/statz and the per-pass PrintSyncTimer report (ps/pass_manager.py) answer
"what is the state now" and "what did this pass cost"; neither answers
"what happened over the last five minutes" — the exact view the r04/r05
wedges needed and only got post hoc.  This module runs a background
sampler (≙ the reference's platform/monitor.h periodic stat collection)
that snapshots the StatRegistry on a monotonic cadence into a bounded
ring, deriving per-interval counter deltas → rates (ex/s, tx_bytes/s,
dedup-hit/s) while retaining gauge/percentile series as-is.

Consumers:

  ``/timelinez``          utils/obs_server.py — JSON series by name
  postmortem bundles      utils/doctor.py embeds ``tail()`` so every
                          bundle shows the minutes LEADING UP TO the
                          wedge, not just the instant of it
  SLO watchdog            evaluated on each sample against a small
                          declarative rule set; a sustained breach emits
                          ONE ``slo_breach`` flight event (latched per
                          rule — no event storm while breached) plus
                          ``obs.slo.*`` counters
  launch.py /clusterz     the supervisor folds per-worker scrapes into a
                          job-level :class:`TimelineRing`

Design constraints (same discipline as trace/flight):

* **Off by default** — ``FLAGS_obs_timeline_interval_s`` = 0 starts
  nothing; no instrumentation site pays anything (the sampler PULLS from
  the registry, producers are untouched).
* **Bounded memory** — newest-N samples (``FLAGS_obs_timeline_ring``).
* **Counter-reset tolerant** — a negative delta (registry reset, worker
  restart behind the same scrape port) is treated as a restart from
  zero, never a negative rate.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from paddlebox_tpu import flags
from paddlebox_tpu.utils import flight, lockdep
from paddlebox_tpu.utils.monitor import StatRegistry, stat_add, stat_set

flags.define_flag(
    "obs_timeline_interval_s", 0.0,
    "sample the stat registry into the telemetry timeline every N "
    "seconds (served at /timelinez, embedded in postmortems, input to "
    "the SLO watchdog); 0 = off, like obs_port")
flags.define_flag(
    "obs_timeline_ring", 512,
    "timeline ring capacity (newest-N samples); at the 1 s cadence the "
    "default retains ~8.5 minutes of history")
flags.define_flag(
    "obs_slo_watchdog", True,
    "evaluate the declarative SLO rule set on every timeline sample "
    "(cache hit-rate collapse, throughput stall, queue saturation, AUC "
    "drop); breaches emit one latched slo_breach flight event each and "
    "count under obs.slo.*.  Only active while the timeline sampler "
    "runs")
flags.define_flag(
    "obs_slo_auc_drop", 0.05,
    "SLO watchdog epsilon for the AUC-drop rule: breach when quality.auc "
    "falls more than this below its recent-window maximum")
flags.define_flag(
    "obs_slo_serving_p99_ms", 250.0,
    "serving-tier SLO: per-tenant pull p99 latency budget in ms "
    "(serving_rules breaches when serving.<tenant>.latency_s.p99 stays "
    "over this for the rule window)")
flags.define_flag(
    "obs_slo_heat_imbalance", 4.0,
    "SLO watchdog threshold for PS shard skew: breach when "
    "heat.shard_imbalance (max/mean shard key load, ps/heat.py) stays "
    "over this for the rule window — read /heatz before resize(new_n)")

# Keys carrying level/percentile semantics: retained as value series but
# excluded from rate derivation (a gauge moving down is not a counter
# reset).  Everything else in the registry is add()-style cumulative.
_GAUGE_SUFFIXES = (".p50", ".p95", ".p99", ".max", "hwm", "_frac",
                   "_ratio", "_rate", "_gen", "generation", ".threads",
                   "resident_rows")
_GAUGE_PREFIXES = ("quality.", "heat.")


def is_gauge_key(key: str) -> bool:
    """True for keys the rate derivation must skip (levels, marks,
    percentiles, training-quality gauges)."""
    return key.endswith(_GAUGE_SUFFIXES) or key.startswith(_GAUGE_PREFIXES)


class TimelineRing:
    """Bounded ring of registry snapshots with per-interval rate
    derivation.  Also the fold target for the supervisor's cluster
    aggregation (launch.py appends MERGED snapshots here)."""

    def __init__(self, cap: int):
        self._ring: "deque[Dict]" = deque(maxlen=max(2, int(cap)))
        self._lock = lockdep.lock("utils.timeline.TimelineRing._lock")
        self._prev: Optional[Tuple[float, Dict[str, float]]] = None
        self._seq = 0

    def append(self, stats: Dict[str, float],
               mono: Optional[float] = None,
               t: Optional[float] = None) -> Dict:
        """Fold one snapshot in; returns the stored sample (with its
        derived ``rates``)."""
        if mono is None:
            mono = time.monotonic()
        if t is None:
            t = time.time()
        rates: Dict[str, float] = {}
        with self._lock:
            if self._prev is not None:
                pmono, pstats = self._prev
                dt = mono - pmono
                if dt > 0:
                    for k, v in stats.items():
                        if is_gauge_key(k) or not isinstance(v, (int, float)):
                            continue
                        d = v - pstats.get(k, 0.0)
                        if d < 0:
                            # counter reset (registry.reset / worker
                            # restart): the counter restarted from zero,
                            # so the interval's growth is the new value
                            d = v
                        rates[k] = d / dt
            lockdep.guards(self, "_seq")
            self._seq += 1
            sample = {"seq": self._seq, "t": t, "mono": mono,
                      "stats": dict(stats), "rates": rates}
            self._ring.append(sample)
            self._prev = (mono, dict(stats))
        return sample

    def samples(self, n: Optional[int] = None) -> List[Dict]:
        """Oldest-first retained samples (last ``n`` when given)."""
        with self._lock:
            out = list(self._ring)
        return out if n is None else out[-max(0, int(n)):]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def names(self) -> List[str]:
        """Sorted union of stat names across retained samples."""
        seen = set()
        for s in self.samples():
            seen.update(s["stats"].keys())
        return sorted(seen)

    def series(self, name: str, n: Optional[int] = None) -> Dict:
        """One metric's trajectory: ``points`` = [t, value] pairs,
        ``rates`` = [t, per-second rate] pairs (counters only)."""
        points: List[List[float]] = []
        rate_points: List[List[float]] = []
        for s in self.samples(n):
            v = s["stats"].get(name)
            if v is not None:
                points.append([s["t"], float(v)])
            r = s["rates"].get(name)
            if r is not None:
                rate_points.append([s["t"], float(r)])
        return {"name": name, "points": points, "rates": rate_points}

    def tail(self, n: int = 20,
             rate_top: int = 12, stat_top: int = 12) -> List[Dict]:
        """Compact newest-``n`` view for postmortem bundles: per sample,
        the ``rate_top`` largest rates and ``stat_top`` largest stats —
        what was moving in the minutes before the wedge, without the
        full snapshot weight."""
        out = []
        for s in self.samples(n):
            rates = sorted(s["rates"].items(), key=lambda kv: -abs(kv[1]))
            stats = sorted(s["stats"].items(), key=lambda kv: -abs(kv[1]))
            out.append({
                "seq": s["seq"], "t": s["t"], "mono": s["mono"],
                "rates": {k: round(v, 6) for k, v in rates[:rate_top]},
                "stats": {k: round(v, 6) for k, v in stats[:stat_top]},
            })
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._prev = None


class SloRule:
    """One declarative SLO rule: breach when ``metric``'s series over the
    trailing ``window_s`` seconds SUSTAINS the predicate (every sample
    violates, with at least ``min_samples`` samples — one bad scrape
    never pages).

    kind: ``gauge`` evaluates the raw value series, ``rate`` the derived
    per-second rate series, ``drop`` compares the latest value against
    the window maximum (breach when it fell more than ``threshold``).
    op: ``lt`` | ``gt`` (ignored for ``drop``)."""

    KINDS = ("gauge", "rate", "drop")
    OPS = ("lt", "gt")

    def __init__(self, name: str, metric: str, *, kind: str = "gauge",
                 op: str = "lt", threshold: float = 0.0,
                 window_s: float = 30.0, min_samples: int = 3,
                 reason: str = ""):
        if kind not in self.KINDS:
            raise ValueError(f"unknown SLO rule kind {kind!r}")
        if op not in self.OPS:
            raise ValueError(f"unknown SLO rule op {op!r}")
        self.name = name
        self.metric = metric
        self.kind = kind
        self.op = op
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self.min_samples = int(min_samples)
        self.reason = reason

    def _values(self, ring: TimelineRing, now_mono: float) -> List[float]:
        key = "rates" if self.kind == "rate" else "stats"
        vals: List[float] = []
        for s in ring.samples():
            if s["mono"] < now_mono - self.window_s:
                continue
            v = s[key].get(self.metric)
            if v is not None:
                vals.append(float(v))
        return vals

    def evaluate(self, ring: TimelineRing, now_mono: float) -> bool:
        """True = currently breached."""
        vals = self._values(ring, now_mono)
        if len(vals) < self.min_samples:
            return False
        if self.kind == "drop":
            return max(vals) - vals[-1] > self.threshold
        if self.op == "lt":
            return all(v < self.threshold for v in vals)
        return all(v > self.threshold for v in vals)

    def describe(self) -> Dict:
        return {"name": self.name, "metric": self.metric,
                "kind": self.kind, "op": self.op,
                "threshold": self.threshold, "window_s": self.window_s,
                "min_samples": self.min_samples, "reason": self.reason}


class SloWatchdog:
    """Evaluates a rule set against the ring on every sample, LATCHING
    breach state per rule: the ok→breach transition emits one
    ``slo_breach`` flight event + ``obs.slo.breach`` count, the
    breach→ok transition one ``slo_clear`` — a sustained breach never
    storms the flight ring."""

    def __init__(self, rules: Sequence[SloRule]):
        self.rules = list(rules)
        self._breached: Dict[str, bool] = {r.name: False for r in self.rules}
        self._lock = lockdep.lock("utils.timeline.SloWatchdog._lock")

    def evaluate(self, ring: TimelineRing,
                 now_mono: Optional[float] = None) -> List[Dict]:
        """Run every rule; returns the transitions that fired."""
        if now_mono is None:
            now_mono = time.monotonic()
        transitions: List[Dict] = []
        with self._lock:
            for rule in self.rules:
                breached = rule.evaluate(ring, now_mono)
                was = self._breached.get(rule.name, False)
                if breached == was:
                    continue
                self._breached[rule.name] = breached
                ev = {"rule": rule.name, "metric": rule.metric,
                      "breached": breached, "threshold": rule.threshold,
                      "reason": rule.reason}
                transitions.append(ev)
                if breached:
                    stat_add("obs.slo.breach")
                    flight.record("slo_breach", rule=rule.name,
                                  metric=rule.metric,
                                  threshold=rule.threshold,
                                  reason=rule.reason)
                else:
                    stat_add("obs.slo.clear")
                    flight.record("slo_clear", rule=rule.name,
                                  metric=rule.metric)
            stat_set("obs.slo.active",
                     float(sum(1 for b in self._breached.values() if b)))
        return transitions

    def states(self) -> Dict[str, bool]:
        with self._lock:
            return dict(self._breached)


def default_rules() -> List[SloRule]:
    """The shipped rule set — conservative sustained-window predicates
    over metrics the package actually emits (lint rule PB207 cross-
    checks every metric literal here against emission sites)."""
    auc_eps = float(flags.get_flags("obs_slo_auc_drop"))
    return [
        SloRule("cache_hit_collapse", "ps.cache.hit_rate",
                kind="gauge", op="lt", threshold=0.10,
                window_s=30.0, min_samples=3,
                reason="device embedding-cache hit rate collapsed"),
        SloRule("queue_saturation", "ps.pool.table.queue_depth_hwm",
                kind="gauge", op="gt", threshold=10_000.0,
                window_s=30.0, min_samples=3,
                reason="host-table work queue saturated"),
        SloRule("throughput_stall", "trainer.step_dispatch_s.count",
                kind="rate", op="lt", threshold=1e-9,
                window_s=60.0, min_samples=5,
                reason="no device steps dispatched for a minute"),
        SloRule("auc_drop", "quality.auc",
                kind="drop", threshold=auc_eps,
                window_s=600.0, min_samples=2,
                reason="pass AUC fell below its recent-window maximum"),
        SloRule("heat_shard_imbalance", "heat.shard_imbalance",
                kind="gauge", op="gt",
                threshold=float(flags.get_flags("obs_slo_heat_imbalance")),
                window_s=30.0, min_samples=3,
                reason="PS shard key load skewed far off the mean — "
                       "a hot shard is serializing the pull fan"),
    ]


def serving_rules(tenants: Sequence[str] = ("default",)) -> List[SloRule]:
    """Per-tenant serving-tier SLO rules (ps/serving.py's metric surface)
    — appended to ``default_rules()`` by the serving entrypoints, one
    p99-latency and one shed-rate rule per configured tenant.  Tenants
    are a closed configured set, so the rule count stays bounded."""
    p99_s = float(flags.get_flags("obs_slo_serving_p99_ms")) / 1000.0
    out: List[SloRule] = []
    for t in tenants:
        out.append(SloRule(
            f"serving_{t}_p99", f"serving.{t}.latency_s.p99",
            kind="gauge", op="gt", threshold=p99_s,
            window_s=30.0, min_samples=3,
            reason=f"serving pull p99 over budget for tenant {t}"))
        out.append(SloRule(
            f"serving_{t}_shed", f"serving.{t}.shed",
            kind="rate", op="gt", threshold=1.0,
            window_s=30.0, min_samples=3,
            reason=f"admission control sustained-shedding tenant {t}"))
    return out


class TimelineSampler:
    """Background daemon sampling the process StatRegistry into a
    :class:`TimelineRing` on a monotonic cadence, running the watchdog
    on each sample.  ``stop()`` joins the thread (PB405 lifecycle)."""

    def __init__(self, interval_s: float, cap: int,
                 rules: Optional[Sequence[SloRule]] = None):
        self.interval_s = float(interval_s)
        self.ring = TimelineRing(cap)
        self.watchdog = SloWatchdog(
            default_rules() if rules is None else rules)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "TimelineSampler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="pbox-timeline", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — sampling must never die
                stat_add("obs.timeline.sample_errors")

    def sample_once(self) -> Dict:
        """One sample + watchdog evaluation (also the test surface — no
        thread needed to drive the timeline deterministically)."""
        stats = StatRegistry.instance().snapshot()
        sample = self.ring.append(stats)
        stat_add("obs.timeline.samples")
        if bool(flags.get_flags("obs_slo_watchdog")):
            self.watchdog.evaluate(self.ring, now_mono=sample["mono"])
        return sample

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()


# -- module-level handle ----------------------------------------------------
ACTIVE: Optional[TimelineSampler] = None
_LOCK = threading.Lock()


def start(interval_s: Optional[float] = None,
          cap: Optional[int] = None,
          rules: Optional[Sequence[SloRule]] = None) -> TimelineSampler:
    """Start (or return) the process-wide sampler.  Flag defaults apply
    when arguments are omitted."""
    global ACTIVE
    with _LOCK:
        if ACTIVE is None:
            if interval_s is None:
                interval_s = float(flags.get_flags("obs_timeline_interval_s"))
            if cap is None:
                cap = int(flags.get_flags("obs_timeline_ring"))
            ACTIVE = TimelineSampler(max(interval_s, 0.01), cap,
                                     rules=rules).start()
        return ACTIVE


def stop() -> None:
    global ACTIVE
    with _LOCK:
        if ACTIVE is not None:
            ACTIVE.stop()
            ACTIVE = None


def sampler() -> Optional[TimelineSampler]:
    return ACTIVE


def maybe_start_from_flags() -> Optional[TimelineSampler]:
    """Worker entry hook (called when the obs exporter starts): run the
    sampler iff ``FLAGS_obs_timeline_interval_s`` > 0."""
    interval = float(flags.get_flags("obs_timeline_interval_s"))
    if interval <= 0:
        return None
    return start(interval_s=interval)


def series(name: str, n: Optional[int] = None) -> Dict:
    """The active sampler's series for ``name`` (empty when off)."""
    s = ACTIVE
    if s is None:
        return {"name": name, "points": [], "rates": []}
    return s.ring.series(name, n=n)


def tail(n: int = 20) -> List[Dict]:
    """Compact newest-``n`` samples for postmortems ([] when off)."""
    s = ACTIVE
    return s.ring.tail(n) if s is not None else []
