"""Shared named worker pools for per-shard host-table fan-out.

≙ MemorySparseTable's ``shards_task_pool_`` (ps/table/memory_sparse_table.cc:
every Pull/Push/Save/Shrink fans one task per shard across a dedicated
thread pool).  Our ``ShardedHostTable`` used to walk shards one at a time on
the caller's thread — after the pipelined wire path made the client
bandwidth-bound, that serial walk became the floor under
``build_pull``/``end_pass_write``.  The heavy per-shard work is numpy
slicing/assignment, which releases the GIL, so fanning shards across a small
thread pool is real host parallelism; the per-shard locks make it safe and
keys are unique per call, so results are bit-identical to the sequential
walk (append order within a shard stays single-threaded).

One process-wide pool (``kind="table"``) is shared by every table so
concurrent callers (the async preload pull + the main-thread write-back)
queue against ONE bounded worker set instead of multiplying threads.
``FLAGS_ps_table_threads`` sizes it; ``1`` restores the exact sequential
path (no executor at all).

Observability (the ``ps.pool.<kind>.*`` namespace, folded into /statz and
the per-pass report):

* ``queue_depth``/``queue_depth_hwm`` — tasks submitted-but-unfinished at
  submit time: a persistently deep queue means shard tasks outpace the pool.
* ``active_hwm``/``utilization`` — workers busy at task start (utilization
  is the busy fraction of the pool, histogram → p50/p95 in snapshots).
* ``busy_s``/``tasks``/``task_s`` — cumulative busy seconds, task count and
  the per-task latency distribution.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Sequence, TypeVar

from paddlebox_tpu import flags
from paddlebox_tpu.utils import flight, lockdep
from paddlebox_tpu.utils.monitor import (stat_add, stat_max, stat_observe,
                                         stat_set)

T = TypeVar("T")
R = TypeVar("R")

flags.define_flag(
    "ps_table_threads", min(8, os.cpu_count() or 1),
    "worker threads of the shared host-table shard pool: bulk_pull/"
    "bulk_write/end_day/shrink/save/load and the ssd fault-in fan one "
    "task per shard across it (numpy shard work releases the GIL).  "
    "1 = sequential on the caller's thread; results are bit-identical "
    "at any setting")

flags.define_flag(
    "pass_pack_threads", min(4, os.cpu_count() or 1),
    "worker threads of the whole-pass packer (data/pass_feed.pack_pass): "
    "per-slot plane builds and record-range partitions of the pad/"
    "translate work fan across it, each worker writing disjoint rows of "
    "the preallocated SoA planes (numpy pad/searchsorted releases the "
    "GIL).  1 = sequential on the caller's thread; results are "
    "bit-identical at any setting")


class WorkPool:
    """A named, metered ThreadPoolExecutor wrapper with an inline
    sequential path at ``threads=1`` (and for single-item maps).

    ``map`` is the only work surface: run ``fn`` over ``items``, return
    results in item order, re-raise the first failure.  Calls from a
    worker thread of THIS pool run inline — a shard task that fans out
    again (e.g. SSD fault-in promoting rows) can never deadlock the pool
    by waiting on futures no free worker can run.
    """

    def __init__(self, threads: int, kind: str = "table"):
        self.kind = kind
        self.threads = max(1, int(threads))
        self._prefix = f"pbox-{kind}"
        self._lock = lockdep.lock("utils.workpool.WorkPool._lock")
        self._queued = 0        # submitted, not yet picked up
        self._active = 0        # running right now
        self._sat_hwm = 0       # deepest saturated queue flight-recorded
        self._ex: Optional[ThreadPoolExecutor] = None
        if self.threads > 1:
            self._ex = ThreadPoolExecutor(
                max_workers=self.threads,
                thread_name_prefix=self._prefix)
        stat_set(f"ps.pool.{self.kind}.threads", float(self.threads))

    def _run_one(self, fn: Callable[[T], R], item: T) -> R:
        with self._lock:
            lockdep.guards(self, "_active")
            self._queued -= 1
            self._active += 1
            active = self._active
        stat_max(f"ps.pool.{self.kind}.active_hwm", float(active))
        stat_observe(f"ps.pool.{self.kind}.utilization",
                     active / float(self.threads))
        t0 = time.monotonic()
        try:
            return fn(item)
        finally:
            dt = time.monotonic() - t0
            with self._lock:
                self._active -= 1
            stat_add(f"ps.pool.{self.kind}.tasks")
            stat_add(f"ps.pool.{self.kind}.busy_s", dt)
            stat_observe(f"ps.pool.{self.kind}.task_s", dt)

    def map(self, fn: Callable[[T], R], items: Sequence[T]) -> List[R]:
        items = list(items)
        n = len(items)
        ex = self._ex        # one read: a concurrent resize may None it
        if n == 0:
            return []
        # inline paths: no executor, nothing to overlap, or already ON a
        # pool worker (re-entrant fan-out must not wait on the pool)
        if (ex is None or n == 1
                or threading.current_thread().name.startswith(self._prefix)):
            return [fn(it) for it in items]
        with self._lock:
            self._queued += n
            depth = self._queued + self._active
            # flight-record saturation only on a NEW high-water mark so
            # a persistently deep queue emits O(log) events, not O(maps)
            saturated_hwm = depth > self.threads and depth > self._sat_hwm
            if saturated_hwm:
                self._sat_hwm = depth
        stat_observe(f"ps.pool.{self.kind}.queue_depth", float(depth))
        stat_max(f"ps.pool.{self.kind}.queue_depth_hwm", float(depth))
        if saturated_hwm:
            flight.record("pool_saturated", pool=self.kind, depth=depth,
                          threads=self.threads)
        futs = []
        try:
            for it in items:
                futs.append(ex.submit(self._run_one, fn, it))
        except RuntimeError:
            # executor raced a resize/shutdown (flag flip mid-flight):
            # finish what was submitted, run the REST inline — every item
            # executes exactly once (decay/append tasks are not
            # idempotent), none is dropped
            with self._lock:
                self._queued = max(0, self._queued - (n - len(futs)))
            head = [f.result() for f in futs]
            return head + [fn(it) for it in items[len(futs):]]
        return [f.result() for f in futs]

    def state(self) -> dict:
        """Queue/occupancy snapshot for the wedge doctor
        (utils/doctor.py): is a hang waiting ON the pool or IN it?"""
        with self._lock:
            return {"kind": self.kind, "threads": self.threads,
                    "queued": self._queued, "active": self._active,
                    "saturated_hwm": self._sat_hwm}

    def shutdown(self) -> None:
        if self._ex is not None:
            self._ex.shutdown(wait=False)
            self._ex = None


_POOL: Optional[WorkPool] = None
_POOL_LOCK = threading.Lock()

_PACK_POOL: Optional[WorkPool] = None
_PACK_POOL_LOCK = threading.Lock()


def table_pool() -> WorkPool:
    """The process-wide shard pool, sized by ``FLAGS_ps_table_threads``.
    Re-reads the flag on every call so tests (and live reconfiguration)
    can flip pool size between passes; a resize retires the old executor
    gracefully (in-flight maps finish or fall back inline)."""
    global _POOL
    want = max(1, int(flags.get_flags("ps_table_threads")))
    with _POOL_LOCK:
        if _POOL is None or _POOL.threads != want:
            old, _POOL = _POOL, WorkPool(want, kind="table")
            if old is not None:
                old.shutdown()
        return _POOL


def pack_pool() -> WorkPool:
    """The process-wide whole-pass pack pool, sized by
    ``FLAGS_pass_pack_threads`` — same re-read/resize contract as
    :func:`table_pool`, separate so a deep table fan-out can never starve
    the pass packer (and vice versa)."""
    global _PACK_POOL
    want = max(1, int(flags.get_flags("pass_pack_threads")))
    with _PACK_POOL_LOCK:
        if _PACK_POOL is None or _PACK_POOL.threads != want:
            old, _PACK_POOL = _PACK_POOL, WorkPool(want, kind="pack")
            if old is not None:
                old.shutdown()
        return _PACK_POOL


def pool_state() -> Optional[dict]:
    """State of the process pool WITHOUT creating it (doctor scrapes
    must not side-effect a pool into existence); None when no pool has
    been built yet."""
    with _POOL_LOCK:
        pool = _POOL
    return pool.state() if pool is not None else None
