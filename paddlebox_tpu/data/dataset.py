"""Pass-scoped in-memory dataset.

≙ Dataset/DatasetImpl/SlotRecordDataset/PadBoxSlotDataset
(data_set.h:58-568): a pass (typically ~10 min of logs) is loaded into host
memory by reader threads, optionally shuffled locally and across hosts, then
iterated as device batches while the next pass preloads
(≙ PreLoadIntoMemory data_set.cc:2219, BoxHelper overlap box_wrapper.h:1141).

The inter-host global shuffle (≙ PaddleShuffler MPI transport,
data_set.cc:2440-2648) goes through a pluggable ``ShuffleTransport``; the
in-process LoopbackTransport covers single-host and tests, a gRPC/proxy
transport covers multi-host (paddlebox_tpu/data/shuffle_transport.py).
"""

from __future__ import annotations

import concurrent.futures
import threading
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.config import DataFeedConfig
from paddlebox_tpu.data.data_feed import DataFeed
from paddlebox_tpu.data.slot_record import SlotRecordBlock
from paddlebox_tpu.utils import lockdep
from paddlebox_tpu.utils.channel import Channel
from paddlebox_tpu.utils.monitor import stat_add
from paddlebox_tpu import flags


class ShuffleTransport:
    """Cross-host record exchange (≙ boxps::PaddleShuffler)."""

    @property
    def rank(self) -> int:
        return 0

    @property
    def world_size(self) -> int:
        return 1

    def send(self, dst: int, block: SlotRecordBlock) -> None:
        raise NotImplementedError

    def drain(self) -> List[SlotRecordBlock]:
        """Blocks sent to this rank by peers (called after barrier)."""
        raise NotImplementedError

    def barrier(self) -> None:
        pass

    def set_epoch(self, epoch: int) -> None:
        """Enter a shuffle epoch (fleet fault tolerance; see
        data/shuffle_transport.py).  No-op for epoch-less transports."""

    def resync(self) -> None:
        """Ask peers to replay the current epoch (restart recovery).
        No-op for transports without a resend buffer."""

    def close(self) -> None:
        pass


class LoopbackTransport(ShuffleTransport):
    """Single-process world; optionally emulates N ranks for tests."""

    def __init__(self, world_size: int = 1, rank: int = 0, mailboxes=None,
                 barrier: Optional[threading.Barrier] = None):
        self._world = world_size
        self._rank = rank
        self._mailboxes = mailboxes if mailboxes is not None else \
            [Channel() for _ in range(world_size)]
        self._barrier = barrier

    @classmethod
    def make_world(cls, world_size: int) -> List["LoopbackTransport"]:
        boxes = [Channel() for _ in range(world_size)]
        bar = threading.Barrier(world_size)
        return [cls(world_size, r, boxes, bar) for r in range(world_size)]

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world

    def send(self, dst: int, block: SlotRecordBlock) -> None:
        self._mailboxes[dst].put(block)

    def drain(self) -> List[SlotRecordBlock]:
        out = []
        while self._mailboxes[self._rank].size():
            out.append(self._mailboxes[self._rank].get())
        return out

    def barrier(self) -> None:
        if self._barrier is not None:
            self._barrier.wait()


class SlotDataset:
    """≙ PadBoxSlotDataset (data_set.h:438)."""

    def __init__(self, feed_config: DataFeedConfig,
                 parse_ins_id: bool = False, parse_logkey: bool = False,
                 read_threads: int = 4,
                 transport: Optional[ShuffleTransport] = None,
                 input_table=None):
        self.feed_config = feed_config
        self.parse_ins_id = parse_ins_id
        self.parse_logkey = parse_logkey
        # aux string-key table shared by every reader thread (string-dtype
        # slots resolve through it at parse time — ≙ InputTableDataFeed,
        # data_feed.h:2224); auto-created when the config declares any
        self.input_table = input_table
        if feed_config.string_slots and input_table is None:
            from paddlebox_tpu.ps.aux_tables import InputTable
            self.input_table = InputTable()
        self.read_threads = read_threads
        self.transport = transport or LoopbackTransport()
        self.filelist: List[str] = []
        self._blocks: List[SlotRecordBlock] = []
        self._preload_future = None
        self._lock = lockdep.lock("data.dataset.SlotDataset._lock")
        self._rng = np.random.default_rng(feed_config.rand_seed or None)
        self._key_consumers: List[Callable[[np.ndarray], None]] = []

    # -- file list -----------------------------------------------------------
    def set_filelist(self, filelist: Sequence[str]) -> None:
        self.filelist = list(filelist)

    # -- pass feasign tap (≙ MergeInsKeys → PSAgent::AddKey data_set.cc:2293)
    def register_key_consumer(self, fn: Callable[[np.ndarray], None]) -> None:
        self._key_consumers.append(fn)

    # -- load ----------------------------------------------------------------
    def _read_all(self) -> List[SlotRecordBlock]:
        files = list(self.filelist)
        blocks: List[SlotRecordBlock] = []
        lock = lockdep.lock("data.dataset.SlotDataset._read_all.lock")

        rate = self.feed_config.sample_rate

        def read_one(path: str) -> None:
            feed = DataFeed(self.feed_config, self.parse_ins_id,
                            self.parse_logkey,
                            input_table=self.input_table)
            # per-file rng seeded by (rand_seed, path): the kept instance
            # SET is deterministic regardless of reader-thread interleaving
            import zlib
            rng_f = np.random.default_rng(
                [self.feed_config.rand_seed or 0,
                 zlib.crc32(path.encode())])
            for block in feed.read_file(path):
                if rate < 1.0:
                    # feed-level instance downsampling
                    # (≙ DataFeedDesc.sample_rate)
                    keep = np.nonzero(rng_f.random(block.n) < rate)[0]
                    block = block.select(keep)
                    if block.n == 0:
                        continue
                for consumer in self._key_consumers:
                    consumer(block.all_keys())
                with lock:
                    blocks.append(block)

        with concurrent.futures.ThreadPoolExecutor(
                max_workers=max(1, self.read_threads),
                thread_name_prefix="pbox-read") as pool:
            list(pool.map(read_one, files))
        return blocks

    def load_into_memory(self) -> None:
        self._blocks = self._read_all()
        self._pv_grouped = False   # fresh records: re-run preprocess_instance
        stat_add("stat_dataset_instances", self.instance_num())

    def preload_into_memory(self) -> None:
        """Overlap next-pass read with current training
        (≙ PreLoadIntoMemory box_wrapper.h:1141)."""
        ex = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pbox-preload")
        self._preload_future = ex.submit(self._read_all)
        ex.shutdown(wait=False)

    def wait_preload_done(self) -> None:
        if self._preload_future is not None:
            self._blocks = self._preload_future.result()
            self._preload_future = None
            self._pv_grouped = False

    def release_memory(self) -> None:
        self._blocks = []

    # -- shuffle -------------------------------------------------------------
    def local_shuffle(self) -> None:
        self._pv_grouped = False   # order destroyed; regroup afterwards
        block = SlotRecordBlock.concat(self._blocks)
        if block.n:
            block = block.permute(self._rng.permutation(block.n))
        self._blocks = [block] if block.n else []

    def global_shuffle(self, by_ins_id: bool = False) -> None:
        """Redistribute records across hosts: hash(ins_id) or random % world
        (≙ ShuffleData data_set.cc:2440 + ReceiveSuffleData :2548)."""
        self._pv_grouped = False   # order destroyed; regroup afterwards
        world = self.transport.world_size
        if world <= 1:
            return self.local_shuffle()
        if self.feed_config.string_slots:
            # aux indices are minted by THIS process's InputTable — another
            # node's table assigns different indices to the same strings,
            # so shuffled planes would gather wrong replica-cache rows.
            # (The reference resolves at feed time, after its shuffle;
            # resolve-late is the multi-host escape hatch.)
            raise ValueError(
                "global_shuffle with string (InputTable) slots is not "
                "supported: indices are process-local — shard files per "
                "worker instead, or shuffle the raw text upstream")
        merged = SlotRecordBlock.concat(self._blocks)
        if merged.n:
            if by_ins_id and merged.ins_ids is not None:
                dest = np.array([hash(i) % world for i in merged.ins_ids],
                                dtype=np.int64)
            else:
                dest = self._rng.integers(0, world, size=merged.n)
            keep = []
            for r in range(world):
                part = merged.select(np.nonzero(dest == r)[0])
                if r == self.transport.rank:
                    keep.append(part)
                elif part.n:
                    self.transport.send(r, part)
        else:
            keep = []
        self.transport.barrier()
        received = self.transport.drain()
        block = SlotRecordBlock.concat(keep + received)
        if block.n:
            block = block.permute(self._rng.permutation(block.n))
        self._blocks = [block] if block.n else []

    # -- PV / ins merge (AucRunner) -----------------------------------------
    def preprocess_instance(self) -> None:
        """Group records by search_id so a page-view trains as a unit
        (≙ PreprocessInstance data_set.cc:2648).  Records are stably sorted
        by search_id; un-keyed records keep relative order at the end.
        Afterwards ``batches()`` cuts only at page-view boundaries, so a PV
        never straddles two device batches (≙ SlotPvInstance batching —
        the batch holds whole pvs)."""
        merged = SlotRecordBlock.concat(self._blocks)
        if merged.n == 0 or merged.search_ids is None:
            return
        order = np.argsort(merged.search_ids, kind="stable")
        self._blocks = [merged.permute(order)]
        self._pv_grouped = True

    def postprocess_instance(self) -> None:
        """≙ PostprocessInstance (data_set.cc): leave PV mode — batches cut
        at fixed size again."""
        self._pv_grouped = False

    # -- iteration -----------------------------------------------------------
    def instance_num(self) -> int:
        return sum(b.n for b in self._blocks)

    def feasign_num(self) -> int:
        return sum(b.feasign_count for b in self._blocks)

    def get_blocks(self) -> List[SlotRecordBlock]:
        return self._blocks

    def batch_bounds(self, batch_size: int, drop_last: bool = False
                     ) -> List[tuple]:
        """(start, stop) record ranges of each batch over the concatenated
        block order — pv-aligned after preprocess_instance().  Copies NO
        slot data (only search_ids are concatenated), so pass-scoped
        packers can batch the merged block without a slice/re-concat
        round-trip."""
        n = sum(b.n for b in self._blocks)
        sids = [b.search_ids for b in self._blocks]
        out = []
        if getattr(self, "_pv_grouped", False) and n \
                and all(s is not None for s in sids):
            sid = sids[0] if len(sids) == 1 else np.concatenate(sids)
            # pv start positions (records are pv-sorted)
            pv_starts = np.concatenate(
                [[0], np.nonzero(sid[1:] != sid[:-1])[0] + 1, [n]])
            start_i = 0
            while pv_starts[start_i] < n:
                start = int(pv_starts[start_i])
                # furthest pv boundary within batch_size of start
                stop_i = int(np.searchsorted(pv_starts,
                                             start + batch_size, "right")) - 1
                if stop_i == start_i:   # one pv larger than the batch
                    raise ValueError(
                        f"page view of "
                        f"{int(pv_starts[start_i + 1]) - start} records "
                        f"exceeds batch_size {batch_size} — raise the "
                        "batch size or skip preprocess_instance")
                stop = int(pv_starts[stop_i])
                if not (stop - start < batch_size and drop_last
                        and stop == n):
                    out.append((start, stop))
                start_i = stop_i
            return out
        for start in range(0, n, batch_size):
            stop = min(start + batch_size, n)
            if stop - start < batch_size and drop_last:
                break
            out.append((start, stop))
        return out

    def batches(self, batch_size: int, drop_last: bool = False
                ) -> Iterator[SlotRecordBlock]:
        """Yield fixed-size record batches; the tail short batch is yielded
        unless drop_last (the device step pads it to capacity anyway).

        After preprocess_instance(), cuts land on page-view boundaries
        (short batches are padded by the trainer's valid mask) so a PV
        trains as one unit."""
        merged = SlotRecordBlock.concat(self._blocks)
        for start, stop in self.batch_bounds(batch_size, drop_last):
            yield merged.slice(start, stop)
