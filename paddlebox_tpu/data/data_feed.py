"""Slot-file readers & parsers.

≙ the DataFeed hierarchy (data_feed.h:977-2233).  Text format is the
reference's MultiSlot format (SlotRecordInMemoryDataFeed::ParseOneInstance,
data_feed.cc:2397-2500): per line, optionally ``1 <ins_id>`` and
``1 <logkey>`` prefixes, then for each configured slot in order
``<num> <v1> ... <vnum>``.  Files may be piped through a shell preprocessor
first (pipe_command ≙ fs_open_read with pipe, data_feed.cc:330).

The hot parser has a native C++ implementation (see
paddlebox_tpu/native/slot_parser.cc) loaded via ctypes; this module falls
back to a pure-Python parser when the shared object is unavailable.
"""

from __future__ import annotations

import io
import os
import subprocess
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.config import DataFeedConfig, SlotConfig
from paddlebox_tpu.data.slot_record import SlotRecordBlock
from paddlebox_tpu.utils.monitor import stat_add


def parse_logkey(log_key: str) -> Tuple[int, int, int]:
    """Decode search_id/cmatch/rank from a packed hex log key
    (≙ SlotRecordInMemoryDataFeed parser_log_key, data_feed.cc:2363-2372:
    rank = last 2 hex digits, cmatch = previous 2, search_id = rest)."""
    if len(log_key) < 4:
        return 0, 0, 0
    rank = int(log_key[-2:], 16)
    cmatch = int(log_key[-4:-2], 16)
    search_id = int(log_key[:-4], 16) if len(log_key) > 4 else 0
    return search_id, cmatch, rank


class SlotParser:
    """Parses MultiSlot text lines into SlotRecordBlocks (python fallback).

    input_table: ps.aux_tables.InputTable shared by every parser of a
    dataset — "string"-dtype slots resolve each token through it into a
    stable int index at parse time (≙ InputTableDataFeed,
    data_feed.h:2224), stored in block.aux_slots as INDICES (0 = miss
    row, the ReplicaCache convention) so they never enter all_keys()."""

    def __init__(self, config: DataFeedConfig,
                 parse_ins_id: bool = False, parse_logkey: bool = False,
                 input_table=None):
        self.config = config
        self.parse_ins_id = parse_ins_id
        self.parse_logkey = parse_logkey
        self.input_table = input_table
        if config.string_slots and input_table is None:
            raise ValueError(
                "feed config declares string slots "
                f"{[s.name for s in config.string_slots]} but no "
                "InputTable was provided to resolve them")

    def parse_block(self, lines: Sequence[str]) -> SlotRecordBlock:
        cfg = self.config
        n = len(lines)
        u_vals: dict = {s.name: [] for s in cfg.slots if s.dtype == "uint64"}
        u_lens: dict = {k: np.zeros((n,), np.int64) for k in u_vals}
        a_vals: dict = {s.name: [] for s in cfg.slots if s.dtype == "string"}
        a_lens: dict = {k: np.zeros((n,), np.int64) for k in a_vals}
        f_vals: dict = {s.name: [] for s in cfg.slots if s.dtype == "float"}
        f_lens: dict = {k: np.zeros((n,), np.int64) for k in f_vals}
        ins_ids: List[str] = [] if self.parse_ins_id or self.parse_logkey else None
        search_ids = np.zeros((n,), np.uint64) if self.parse_logkey else None
        cmatch = np.zeros((n,), np.int32) if self.parse_logkey else None
        rank = np.zeros((n,), np.int32) if self.parse_logkey else None

        for li, line in enumerate(lines):
            toks = line.split()
            pos = 0
            if self.parse_ins_id:
                assert toks[pos] == "1", "ins_id prefix must be '1 <id>'"
                ins_ids.append(toks[pos + 1])
                pos += 2
            if self.parse_logkey:
                assert toks[pos] == "1", "logkey prefix must be '1 <key>'"
                key = toks[pos + 1]
                sid, cm, rk = parse_logkey(key)
                if not self.parse_ins_id:
                    ins_ids.append(key)
                search_ids[li], cmatch[li], rank[li] = sid, cm, rk
                pos += 2
            for slot in cfg.slots:
                num = int(toks[pos]); pos += 1
                vals = toks[pos:pos + num]; pos += num
                if slot.dtype == "uint64":
                    u_vals[slot.name].append(
                        np.array([int(v) for v in vals], dtype=np.uint64))
                    u_lens[slot.name][li] = num
                elif slot.dtype == "string":
                    a_vals[slot.name].append(
                        self.input_table.get_or_insert_many(vals))
                    a_lens[slot.name][li] = num
                else:
                    f_vals[slot.name].append(
                        np.array(vals, dtype=np.float32))
                    f_lens[slot.name][li] = num

        block = SlotRecordBlock(n=n, ins_ids=ins_ids, search_ids=search_ids,
                                cmatch=cmatch, rank=rank)
        for k, parts in u_vals.items():
            off = np.zeros((n + 1,), np.int64)
            np.cumsum(u_lens[k], out=off[1:])
            block.uint64_slots[k] = (
                np.concatenate(parts) if parts else np.empty((0,), np.uint64),
                off)
        for k, parts in f_vals.items():
            off = np.zeros((n + 1,), np.int64)
            np.cumsum(f_lens[k], out=off[1:])
            block.float_slots[k] = (
                np.concatenate(parts) if parts else np.empty((0,), np.float32),
                off)
        for k, parts in a_vals.items():
            off = np.zeros((n + 1,), np.int64)
            np.cumsum(a_lens[k], out=off[1:])
            block.aux_slots[k] = (
                np.concatenate(parts) if parts else np.empty((0,), np.uint64),
                off)
        stat_add("stat_total_feasign_num_in_mem", block.feasign_count)
        return block


def open_file(path: str, pipe_command: str = "") -> io.TextIOBase:
    """≙ fs_open_read (framework/io/fs.cc): optional shell pipe, gz
    support, and scheme-dispatched remote filesystems (hdfs://... through
    the registered ShellFS — paddlebox_tpu/io/fs.py)."""
    from paddlebox_tpu.io import fs as pfs
    scheme, _ = pfs.split_scheme(path)
    if scheme and scheme != "file":
        if pipe_command:
            raise ValueError(
                "pipe_command over a remote path is not supported — "
                "preprocess into the remote store or read locally")
        raw = io.BufferedReader(pfs.open_read(path))
        if path.endswith(".gz"):
            import gzip
            return io.TextIOWrapper(gzip.GzipFile(fileobj=raw))
        return io.TextIOWrapper(raw)
    if pipe_command:
        cmd = f"cat '{path}' | {pipe_command}" if path else pipe_command
        proc = subprocess.Popen(cmd, shell=True, stdout=subprocess.PIPE)
        return io.TextIOWrapper(proc.stdout)
    if path.endswith(".gz"):
        proc = subprocess.Popen(["zcat", path], stdout=subprocess.PIPE)
        return io.TextIOWrapper(proc.stdout)
    return open(path, "r")


class DataFeed:
    """File → SlotRecordBlock stream (≙ InMemoryDataFeed::LoadIntoMemory,
    data_feed.cc:560-587)."""

    def __init__(self, config: DataFeedConfig, parse_ins_id: bool = False,
                 parse_logkey: bool = False, chunk_lines: int = 4096,
                 use_native: bool = True, input_table=None):
        self.config = config
        self.chunk_lines = chunk_lines
        self._parser = make_parser(config, parse_ins_id, parse_logkey,
                                   use_native=use_native,
                                   input_table=input_table)

    def read_file(self, path: str) -> Iterator[SlotRecordBlock]:
        with open_file(path, self.config.pipe_command) as f:
            while True:
                lines = []
                for line in f:
                    line = line.strip()
                    if line:
                        lines.append(line)
                    if len(lines) >= self.chunk_lines:
                        break
                if not lines:
                    return
                yield self._parser.parse_block(lines)


def make_parser(config: DataFeedConfig, parse_ins_id: bool = False,
                parse_logkey_: bool = False, use_native: bool = True,
                input_table=None):
    """Return the native C++ parser when built, else the python fallback.
    String (InputTable) slots force the python parser — the table's
    string→index map lives in the python process."""
    if use_native and not config.string_slots:
        try:
            from paddlebox_tpu.native import slot_parser as native_parser
            if native_parser.available():
                return native_parser.NativeSlotParser(
                    config, parse_ins_id, parse_logkey_)
        except Exception:
            pass
    return SlotParser(config, parse_ins_id, parse_logkey_,
                      input_table=input_table)


class ParserPluginManager:
    """Pluggable per-format parsers — ≙ CustomParser + DLManager
    (data_feed.h:446,682): production feeds load site-specific parser
    implementations by name at run time instead of baking every data format
    into the framework.

    Two plugin kinds, keyed by a spec string (cached like DLManager::load):
      * ``"pkg.module:factory"`` — importable python factory called as
        ``factory(config) -> parser`` where ``parser.parse_block(lines)``
        returns a SlotRecordBlock (covers the reference's ISlotParser
        surface, data_feed.h:1964);
      * ``"/path/libplugin.so:symbol"`` — a C shared library exposing the
        native block-parser ABI of native/slot_parser.cc under ``symbol``
        (dlopen'd once, ≙ DLManager caching).
    """

    def __init__(self):
        self._cache = {}

    def load(self, spec: str, config: DataFeedConfig):
        if spec in self._cache:
            factory = self._cache[spec]
            return factory(config)
        target, _, name = spec.partition(":")
        if target.endswith(".so"):
            import ctypes

            lib = ctypes.CDLL(target)  # dlopen once; symbols resolved below
            from paddlebox_tpu.native.slot_parser import NativeSlotParser

            def factory(cfg, _lib=lib, _sym=name or "pbox_parse_block"):
                p = NativeSlotParser(cfg)
                p._lib = _lib
                p._entry = _sym
                return p
        else:
            import importlib

            mod = importlib.import_module(target)
            fn = getattr(mod, name or "create_parser")

            def factory(cfg, _fn=fn):
                return _fn(cfg)

        self._cache[spec] = factory
        return factory(config)


_plugin_manager = ParserPluginManager()


def load_parser_plugin(spec: str, config: DataFeedConfig):
    """Module-level convenience over a process-wide manager (≙ the global
    DLManager instance reached through dlmanager(), data_feed.h:707)."""
    return _plugin_manager.load(spec, config)
