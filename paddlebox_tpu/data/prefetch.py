"""Pass-pipelined prefetcher — train pass N while pass N+1 feeds.

≙ the reference's pass pipeline: PreLoadIntoMemory reads the next pass's
files while the current one trains (box_wrapper.h:1141), EndFeedPass hands
the key agent to the feedpass thread pool (box_wrapper.cc:152), the
pre-build thread pulls + builds the next working set under training
(ps_gpu_wrapper.cc:907-955), and PackBatchTask packs batches asynchronously
while the GPU runs (boxps_worker.cc:1259).  BENCH_r03 measured exactly the
gap this hides: ``device_step=473090`` vs ``end_to_end=22934`` ex/s — the
device idles ~95% of the wall waiting on serial pull+pack.

``PassPrefetcher`` drives the whole next-pass feed chain on ONE background
worker thread while the trainer runs the current pass:

    worker (pass N+1):  begin_feed_pass -> load_fn() [reader threads feed
                        keys] -> end_feed_pass(async_build=True) [host
                        bulk_pull on the engine's build thread] ->
                        peek_next_mapper -> trainer.pack_pass_host
                        [fans across the pack WorkPool] -> buffer.put
    main   (pass N+1):  next_pass(): buffer.get -> engine.begin_pass
                        [adopt + ws upload + stale-row refresh] ->
                        trainer.finish_pass_feed [H2D + plans] -> train

Division of labour is deliberate:

* Host-only work (file read, key dedup, table pull, numpy pack, and —
  under ``sparse_step_path=ragged`` — the per-pass CSR plan lowering
  (pass_feed.build_csr_plans, run inside trainer.pack_pass_host)) runs on
  background threads — it releases the GIL and the device never sees it.
  The CSR build is the ragged path's only per-pass host cost; hiding it
  here is what makes the [U]-domain step effectively free to feed
  (intervals report it as ``csr_hidden_s``).
* EVERY device dispatch (working-set upload, feed H2D, plan builds) stays
  on the main thread — concurrent device dispatch from two python threads
  can deadlock single-stream runtimes (ps/pass_manager.py's async_build
  keeps the same boundary).

Bounded double buffer: the hand-off channel holds ONE packed pass, so at
most two passes are resident host-side (the training pass's device feed +
the prefetched pass's host planes) — memory is bounded at ~2 packed feeds
regardless of how many specs are queued.  The worker also gates each
spec on the PREVIOUS pass's adoption, because the engine holds a single
``_next`` working-set slot (and a single pending feed-obs window).

Bit-identity: the worker packs against ``engine.peek_next_mapper()`` —
the mapper the upcoming ``begin_pass`` will adopt.  Key translation reads
only the mapper's sorted key array, which adoption's stale-row refresh
never mutates (it rewrites working-set VALUES for keys the previous pass
wrote), so packing before adoption produces byte-identical planes to
packing after — pinned by tests/test_pass_pipeline.py, including under
fault injection.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from paddlebox_tpu import flags
from paddlebox_tpu.utils import flight, lockdep, trace
from paddlebox_tpu.utils.channel import Channel, ChannelClosed
from paddlebox_tpu.utils.monitor import stat_add, stat_observe

flags.define_flag(
    "pass_prefetch", True,
    "pipeline the pass feed: while pass N trains, pass N+1's load/key-"
    "dedup/table-pull/pack run on background threads (bounded double "
    "buffer, ~2 packed passes resident).  Device dispatch stays on the "
    "main thread; results are bit-identical to the serial pass loop")


class _Spec:
    __slots__ = ("load_fn", "tag", "keep_host", "date")

    def __init__(self, load_fn, tag, keep_host, date):
        self.load_fn = load_fn
        self.tag = tag
        self.keep_host = keep_host
        self.date = date


class PassPrefetcher:
    """Drive pass N+1's feed chain in the background while N trains.

    Usage (fleet.train_passes and bench.py's pass-cycle phase are the
    in-tree drivers)::

        pf = PassPrefetcher(engine, trainer)
        for filelist in passes:
            pf.submit(lambda fl=filelist: load(fl))   # returns the dataset
        for _ in passes:
            feed = pf.next_pass()     # engine.begin_pass done, feed ready
            trainer.train_pass(feed)
            engine.end_pass()
        pf.close()

    ``load_fn`` runs on the worker thread INSIDE an open feed pass: it
    must load the pass's data so that the engine's key sink sees every
    feasign (e.g. ``SlotDataset.load_into_memory`` with the engine
    attached), then return the loaded dataset for the pack.

    Device-cache interaction (ps/device_cache.py): ``begin_feed_pass`` —
    which runs HERE, on the worker thread — publishes the cache's
    immutable index snapshot, and the async build's miss-only pull
    intersects against that frozen view while pass N trains and folds
    back on the main thread (copy-on-write index, no torn reads).  The
    authoritative hit resolution and the device-side gather happen at
    adoption on the main thread, so a row evicted mid-overlap simply
    falls back to a wire pull.  The day-boundary drain above also orders
    ``set_date``'s cache invalidation strictly after the old day's last
    fold-back, and :meth:`abort`'s ``reset_feed_state`` rebuilds the
    cache cold.
    """

    def __init__(self, engine, trainer, keep_host: bool = False):
        self.engine = engine
        self.trainer = trainer
        self._keep_host = keep_host
        self._specs: Channel = Channel(capacity=1024)
        self._ready: Channel = Channel(capacity=1)   # the double buffer
        # pipeline position counters (one condition guards all three):
        # worker spec index vs how many passes the consumer has adopted
        # (begin_pass done) and ended (write-back done)
        self._cond = lockdep.condition("data.prefetch.PassPrefetcher._cond")
        self._adopted_n = 0
        self._ended_n = 0
        self._closing = False
        self._failed: Optional[BaseException] = None
        # recurring worker with a managed lifecycle (close() joins it) —
        # exactly the shape PB405 wants, so no suppression needed
        self._worker = threading.Thread(
            target=self._run, name="pbox-prefetch", daemon=True)
        self._worker.start()

    # -- producer side -------------------------------------------------------
    def submit(self, load_fn: Callable[[], object],
               tag: Optional[str] = None,
               keep_host: Optional[bool] = None,
               date: Optional[str] = None) -> None:
        """Queue one pass spec; the worker drives its feed chain as soon
        as the previous pass is adopted.

        date: run engine.set_date(date) before this pass's feed.  A date
        CHANGE runs end_day (whole-table decay), so the worker first
        drains the pipeline — it waits until every prior pass has ENDED
        (write-back done), which requires the consumer to end passes via
        :meth:`end_pass` (engine.end_pass alone never wakes the gate)."""
        keep = self._keep_host if keep_host is None else keep_host
        self._specs.put(_Spec(load_fn, tag, keep, date))

    def _wait(self, counter: str, need: int) -> float:
        t0 = time.monotonic()
        with self._cond:
            while getattr(self, counter) < need and not self._closing:
                self._cond.wait(timeout=1.0)
        return time.monotonic() - t0

    def _run(self) -> None:
        idx = 0
        while True:
            try:
                spec = self._specs.get()
            except ChannelClosed:
                return
            # the engine holds ONE pending working set (_next) and ONE
            # pending obs window — wait until the previous pass adopted
            # both.  Adoption happens at the START of its training, so
            # this whole chain still overlaps that training.
            gate_s = self._wait("_adopted_n", idx)
            if spec.date is not None and spec.date != self.engine.day_id:
                # day boundary: end_day decays the WHOLE table, so it must
                # order strictly between the old day's last write-back and
                # the new day's first pull — drain the pipeline
                gate_s += self._wait("_ended_n", idx)
                if not self._closing:
                    self.engine.set_date(spec.date)
            elif spec.date is not None:
                self.engine.set_date(spec.date)     # same day: no decay
            stat_observe("data.prefetch.gate_wait_s", gate_s)
            if self._closing:
                return
            idx += 1
            try:
                t0 = time.monotonic()
                with trace.span("data.prefetch.feed", tag=spec.tag or ""):
                    self.engine.begin_feed_pass()
                    dataset = spec.load_fn()
                    self.engine.end_feed_pass(async_build=True)
                    # waits for the host working-set build (bulk_pull),
                    # then packs against the mapper begin_pass will adopt
                    mapper = self.engine.peek_next_mapper()
                    arrays = self.trainer.pack_pass_host(dataset,
                                                         mapper=mapper)
                dt = time.monotonic() - t0
                stat_add("data.prefetch.passes")
                stat_observe("data.prefetch.build_s", dt)
                flight.record("prefetch_pass_ready", tag=spec.tag,
                              records=arrays.num_real, build_s=round(dt, 3))
                if not self._ready.put((arrays, dataset, spec, None)):
                    return            # closed mid-shutdown: drop and exit
            except BaseException as e:
                # surfaced at next_pass — a failed prefetch must fail THAT
                # pass, never silently train a stale working set
                self._failed = e
                flight.record("prefetch_pass_failed", tag=spec.tag,
                              error=type(e).__name__)
                self._ready.put((None, None, spec, e))
                return

    # -- consumer side -------------------------------------------------------
    def next_pass(self):
        """Block until the next prefetched pass is packed, adopt it
        (engine.begin_pass on THIS thread: ws upload + stale-row refresh)
        and finish the feed (H2D + plans).  Returns the PackedPassFeed.

        The blocked time here is the pipeline's residual — feed seconds
        the training pass could NOT hide (``data.prefetch.wait_s``)."""
        t0 = time.monotonic()
        arrays, dataset, spec, err = self._ready.get()
        stat_observe("data.prefetch.wait_s", time.monotonic() - t0)
        if err is not None:
            raise RuntimeError(
                f"pass prefetch failed (spec {spec.tag or '?'})") from err
        self.engine.begin_pass()
        feed = self.trainer.finish_pass_feed(arrays,
                                             keep_host=spec.keep_host)
        with self._cond:          # frees the worker to open the next feed
            lockdep.guards(self, "_adopted_n")
            self._adopted_n += 1
            self._cond.notify_all()
        self._last_dataset = dataset
        return feed

    def end_pass(self, need_save_delta: bool = False,
                 delta_path: str = "") -> None:
        """engine.end_pass + wake the worker's day-boundary gate.  Drivers
        that submit dated specs MUST end passes through here."""
        self.engine.end_pass(need_save_delta, delta_path)
        with self._cond:
            self._ended_n += 1
            self._cond.notify_all()

    def close(self) -> None:
        """Stop the worker and join it.  Safe after errors and mid-queue:
        unprocessed specs are dropped (their passes never began)."""
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        self._specs.close()
        self._ready.close()
        self._worker.join(timeout=30.0)

    def abort(self) -> None:
        """Crash-recovery teardown (fleet.train_passes' auto-resume tier):
        stop + join the worker like :meth:`close`, then clear the ENGINE's
        in-flight feed state — the worker may have died holding an open
        feed window or an unadopted async build, and the checkpoint
        restore that follows must start from a clean pass boundary
        (pass_manager.BoxPSEngine.reset_feed_state)."""
        self.close()
        if hasattr(self.engine, "reset_feed_state"):
            self.engine.reset_feed_state()

    def __enter__(self) -> "PassPrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
