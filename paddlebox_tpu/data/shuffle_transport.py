"""TCP transport for the inter-node global shuffle.

≙ boxps::PaddleShuffler (closed-source MPI transport driven from
data_set.cc:1910-1929 send_message_callback / ReceiveSuffleData
:2548): length-prefixed record-block messages between ranks, with DONE
markers standing in for the MPI barrier + wait_done.  Runs over plain
sockets (loopback or DCN) so the dataset shuffle works across launcher
processes without MPI.

Fault model (the trainer-fleet contract): any peer may die and be
restarted by a supervisor at any point of a shuffle.  Three mechanisms
make that survivable without losing or double-counting records:

* **Deadlines everywhere** — dials, sends and the DONE barrier all run
  under ``FLAGS_shuffle_deadline_s`` with exponential backoff; a peer
  dead past the budget raises the typed :class:`ShufflePeerDead`
  (a ``ConnectionError``) instead of hanging the pass forever.
* **Idempotent resend** — every block frame carries a (shuffle epoch,
  per-destination seq) id; the sender buffers the current epoch's
  frames and, after a reconnect, replays the whole window.  The
  receiver keeps a per-source watermark and drops already-seen seqs, so
  a replay delivers each block exactly once (TCP orders each stream and
  the replay is an in-order prefix-complete resend, which makes the
  max-seq watermark sound even across an old socket's late frames).
* **Resync** — a restarted rank (fresh process, same address) calls
  :meth:`set_epoch` with the pass's epoch and then :meth:`resync`; each
  peer replays its buffered frames + DONE for that epoch from the
  send-side buffer.  Buffers are retained until the NEXT epoch begins
  (``set_epoch``/barrier GC keeps the previous epoch), which is exactly
  as long as a crashed peer can still need them: nobody starts epoch
  e+1 before every rank finished e.

Epochs are explicit for the fleet runner (one per global pass,
monotonic); legacy callers that never call ``set_epoch`` stay on
epoch 0 — seq counters then keep growing across shuffles (the watermark
stays sound) and the barrier GCs the frame buffer each round.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu import flags
from paddlebox_tpu.data.dataset import ShuffleTransport
from paddlebox_tpu.data.slot_record import SlotRecordBlock
from paddlebox_tpu.ps import wire
from paddlebox_tpu.ps.feature_value import _keyed_hash
from paddlebox_tpu.utils import lockdep
from paddlebox_tpu.utils.backoff import Backoff
from paddlebox_tpu.utils.monitor import stat_add, stat_observe

flags.define_flag(
    "shuffle_deadline_s", 60.0,
    "total budget for any one shuffle-transport wait (dial+resend loop, "
    "DONE barrier); a peer unreachable past this raises ShufflePeerDead "
    "instead of hanging the pass")

_MSG_BLOCK = 0
_MSG_DONE = 1
_MSG_RESYNC = 2

# frame header: kind, src rank, shuffle epoch, block seq, payload length
_HDR = struct.Struct("<BIQQQ")

# Record→slice routing salt for the fleet's shuffle-by-key — deliberately
# distinct from ps/cluster.CLUSTER_SALT so the trainer partition of the
# key space decorrelates from the PS-shard partition (a slice's keys
# spread over all M shards and vice versa).
SHUFFLE_SALT = 0x5BD1E995C3E4D96F


def slice_of(keys: np.ndarray, n_slices: int) -> np.ndarray:
    """Deterministic record route: splitmix64(key ^ SHUFFLE_SALT) mod V.
    Same key → same virtual slice for every rank, every fleet size."""
    return (_keyed_hash(np.asarray(keys, np.uint64), SHUFFLE_SALT)
            % np.uint64(max(1, n_slices))).astype(np.int64)


class ShufflePeerDead(ConnectionError):
    """A shuffle peer stayed unreachable past FLAGS_shuffle_deadline_s."""


def block_to_wire(block: SlotRecordBlock) -> bytes:
    """SlotRecordBlock → typed wire frame (ps/wire.py codec — dtype/shape
    headers + raw buffers, never pickle on network bytes)."""
    msg: Dict[str, object] = {"n": block.n}
    msg["u"] = {}
    msg["uo"] = {}
    for name, (vals, offs) in block.uint64_slots.items():
        msg["u"][name] = np.asarray(vals)
        msg["uo"][name] = np.asarray(offs)
    msg["f"] = {}
    msg["fo"] = {}
    for name, (vals, offs) in block.float_slots.items():
        msg["f"][name] = np.asarray(vals)
        msg["fo"][name] = np.asarray(offs)
    if block.aux_slots:
        msg["a"] = {}
        msg["ao"] = {}
        for name, (vals, offs) in block.aux_slots.items():
            msg["a"][name] = np.asarray(vals)
            msg["ao"][name] = np.asarray(offs)
    if block.ins_ids is not None:
        if any("\x00" in i for i in block.ins_ids):
            raise ValueError("ins_ids may not contain NUL bytes")
        # explicit count disambiguates [] vs [""] (and trailing empties)
        msg["ins_ids"] = "\x00".join(block.ins_ids)
        msg["ins_ids_n"] = len(block.ins_ids)
    for f in ("search_ids", "cmatch", "rank"):
        v = getattr(block, f)
        if v is not None:
            msg[f] = np.asarray(v)
    # fleet provenance tag (slice, file idx, block seq): lets the
    # receiver re-establish one global deterministic order over blocks
    # that arrived from many senders in arbitrary interleavings
    tag = getattr(block, "shuffle_tag", None)
    if tag is not None:
        msg["tag"] = np.asarray(tag, np.uint64)
    return wire.encode(msg)


def block_from_wire(payload: bytes) -> SlotRecordBlock:
    try:
        msg = wire.decode(payload)
        blk = SlotRecordBlock(n=int(msg["n"]))
        for name, vals in msg.get("u", {}).items():
            blk.uint64_slots[name] = (vals, msg["uo"][name])
        for name, vals in msg.get("f", {}).items():
            blk.float_slots[name] = (vals, msg["fo"][name])
        for name, vals in msg.get("a", {}).items():
            blk.aux_slots[name] = (vals, msg["ao"][name])
        if "ins_ids" in msg:
            n_ids = int(msg["ins_ids_n"])
            ids = msg["ins_ids"].split("\x00") if n_ids else []
            if len(ids) != n_ids:
                raise ValueError("ins_ids count mismatch")
            blk.ins_ids = ids
        for f in ("search_ids", "cmatch", "rank"):
            if f in msg:
                setattr(blk, f, msg[f])
        if "tag" in msg:
            blk.shuffle_tag = tuple(int(x) for x in msg["tag"])
        return blk
    except wire.DecodeError:
        raise
    except (KeyError, TypeError, ValueError, AttributeError) as e:
        # decodable frame, wrong structure — same remedy as a bad frame
        raise wire.DecodeError(f"malformed block frame: {e!r}") from e


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


class TcpShuffleTransport(ShuffleTransport):
    def __init__(self, rank: int, addrs: Sequence[Tuple[str, int]]):
        self._rank = rank
        self._addrs = list(addrs)
        self._world = len(addrs)
        self._epoch = 0
        self._rx_error = None
        self._closed = False
        # receive side (all under _done_cv's lock): per-epoch pending
        # blocks + DONE sets, per-source (epoch, max-seq) watermark
        self._pending: Dict[int, List[SlotRecordBlock]] = {}
        self._done_from: Dict[int, set] = {}
        self._peer_seen: Dict[int, List[int]] = {}
        self._resync_epochs: set = set()
        self._done_lock = lockdep.lock("data.shuffle_transport.TcpShuffleTransport._done_lock")
        self._done_cv = threading.Condition(self._done_lock)
        # _conn_lock guards the registries only (PB104: never frame I/O);
        # per-destination send locks serialize frames on ONE peer's socket
        # without stalling senders to OTHER peers behind a global lock.
        # The send-side resend state (_sent/_done_sent/_seq, keyed by
        # (dst, epoch)) is mutated only under the matching dst send lock.
        self._conns: Dict[int, socket.socket] = {}
        self._accepted: List[socket.socket] = []
        self._conn_lock = lockdep.lock("data.shuffle_transport.TcpShuffleTransport._conn_lock")
        self._send_locks: Dict[int, threading.Lock] = {}
        self._sent: Dict[Tuple[int, int], List[Tuple[int, bytes]]] = {}
        self._done_sent: Dict[Tuple[int, int], bool] = {}
        self._seq: Dict[Tuple[int, int], int] = {}
        self._explicit_epoch = False

        host, port = self._addrs[rank]
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # bind under backoff: a supervisor-restarted rank re-binds its
        # OWN address while the dead incarnation's sockets drain (or, in
        # thread-mode tests, while a peer's transient dial squats the
        # port) — transient EADDRINUSE is part of the restart contract
        bo = Backoff(base=0.05, cap=1.0, deadline=self._deadline_s())
        attempt = 0
        while True:
            try:
                self._listener.bind((host, port))
                break
            except OSError:
                attempt += 1
                if not bo.sleep(attempt):
                    raise
        self._listener.listen(self._world)
        # pboxlint: disable-next=PB405 -- listener pump lives for the transport; close() unblocks it via listener shutdown
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    # ------------------------------------------------------------------
    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world

    @property
    def epoch(self) -> int:
        return self._epoch

    def _deadline_s(self) -> float:
        return float(flags.get_flags("shuffle_deadline_s"))

    # -- epochs --------------------------------------------------------------
    def set_epoch(self, epoch: int) -> None:
        """Enter a shuffle epoch (the fleet runner: one per global pass,
        monotonic).  GCs send buffers and receive state of epochs < the
        new one — the PREVIOUS epoch's buffer must survive until here so
        a peer restarted mid-epoch can still resync off it."""
        epoch = int(epoch)
        if epoch < self._epoch:
            raise ValueError(
                f"shuffle epoch must be monotonic: {epoch} < {self._epoch}")
        self._explicit_epoch = True
        for dst in range(self._world):
            if dst == self._rank:
                continue
            with self._send_lock(dst):
                for k in [k for k in self._sent if k[0] == dst
                          and k[1] < epoch]:
                    # pboxlint: disable-next=PB102 -- keys are (dst, ...)-partitioned; the per-dst send lock held above guards them
                    self._sent.pop(k, None)
                    # pboxlint: disable-next=PB102 -- per-dst send lock held (partitioned state)
                    self._done_sent.pop(k, None)
                    # pboxlint: disable-next=PB102 -- per-dst send lock held (partitioned state)
                    self._seq.pop(k, None)
        with self._done_cv:
            self._epoch = epoch
            for e in [e for e in self._pending if e < epoch]:
                del self._pending[e]
            for e in [e for e in self._done_from if e < epoch]:
                del self._done_from[e]
            self._resync_epochs = {e for e in self._resync_epochs
                                   if e >= epoch}

    def resync(self) -> None:
        """Ask every peer to replay its buffered frames for the current
        epoch — the restarted rank's first call after ``set_epoch``.
        Peers that already finished sending (and whose original frames
        died with this rank's previous process) re-deliver from their
        epoch buffer; peers still mid-send just continue normally."""
        with self._done_cv:
            self._resync_epochs.add(self._epoch)
        for dst in range(self._world):
            if dst == self._rank:
                continue
            with self._send_lock(dst):
                self._tx_frame(dst, _MSG_RESYNC, self._epoch, 0, b"")

    # -- connections ---------------------------------------------------------
    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._conn_lock:
                if self._closed:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                self._accepted.append(conn)
            # pboxlint: disable-next=PB405 -- per-peer receiver, bounded by world size; dies with its socket
            threading.Thread(target=self._recv_loop, args=(conn,),
                             daemon=True).start()

    def _recv_loop(self, conn: socket.socket):
        try:
            while True:
                head = _recv_exact(conn, _HDR.size)
                kind, src, epoch, seq, length = _HDR.unpack(head)
                if length > wire.MAX_FRAME:
                    raise ConnectionError(
                        f"oversized shuffle frame ({length} bytes)")
                payload = _recv_exact(conn, length) if length else b""
                stat_add("trainer.fleet.shuffle_rx_bytes",
                         float(_HDR.size + length))
                if kind == _MSG_BLOCK:
                    with self._done_cv:
                        seen = self._peer_seen.setdefault(src, [-1, -1])
                        if epoch > seen[0]:
                            seen[0], seen[1] = epoch, -1
                        if epoch < seen[0] or seq <= seen[1]:
                            stat_add("trainer.fleet.shuffle_rx_dup")
                            continue        # replayed frame already seen
                        seen[1] = seq
                    blk = block_from_wire(payload)
                    with self._done_cv:
                        self._pending.setdefault(epoch, []).append(blk)
                elif kind == _MSG_DONE:
                    with self._done_cv:
                        self._done_from.setdefault(epoch, set()).add(src)
                        self._done_cv.notify_all()
                elif kind == _MSG_RESYNC:
                    self._replay_for(src, epoch)
        except (ConnectionError, OSError):
            return
        except wire.DecodeError as e:
            # a corrupt frame means lost records — poison the barrier so
            # the pass FAILS loudly instead of hanging or training short
            with self._done_cv:
                lockdep.guards(self, "_rx_error")
                self._rx_error = e
                self._done_cv.notify_all()
            return

    def _conn_to(self, dst: int) -> socket.socket:
        """One dial attempt (registry-cached).  Callers needing liveness
        guarantees go through the _tx_frame reconnect loop instead."""
        with self._conn_lock:
            if self._closed:
                raise ConnectionError("transport closed")
            sock = self._conns.get(dst)
        if sock is not None:
            return sock
        # dial OUTSIDE the lock; on a connect race the loser's socket
        # closes and everyone converges on the registered one
        s = socket.create_connection(self._addrs[dst],
                                     timeout=self._deadline_s())
        with self._conn_lock:
            cur = self._conns.setdefault(dst, s)
        if cur is not s:
            try:
                s.close()
            except OSError:
                pass
        return cur

    def _drop_conn(self, dst: int) -> None:
        with self._conn_lock:
            sock = self._conns.pop(dst, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _send_lock(self, dst: int) -> threading.Lock:
        with self._conn_lock:
            lk = self._send_locks.get(dst)
            if lk is None:
                lk = self._send_locks[dst] = lockdep.lock(
                    "data.shuffle_transport.TcpShuffleTransport._send_locks")
            return lk

    # -- send side -----------------------------------------------------------
    def _sendall(self, dst: int, frame: bytes) -> None:
        sock = self._conn_to(dst)
        t0 = time.monotonic()
        sock.sendall(frame)
        stat_observe("trainer.fleet.shuffle_s", time.monotonic() - t0)
        stat_add("trainer.fleet.shuffle_tx_bytes", float(len(frame)))

    def _tx_frame(self, dst: int, kind: int, epoch: int, seq: int,
                  payload: bytes) -> None:
        """Deliver one frame, reconnect-and-replay on failure.  Caller
        holds the dst send lock.  A BLOCK/DONE frame must already be in
        the epoch buffer (the replay is what re-delivers it)."""
        frame = _HDR.pack(kind, self._rank, epoch, seq,
                          len(payload)) + payload
        try:
            self._sendall(dst, frame)
            return
        except (ConnectionError, OSError):
            self._drop_conn(dst)
        bo = Backoff(base=0.05, cap=1.0, deadline=self._deadline_s())
        attempt = 0
        while True:
            attempt += 1
            stat_add("trainer.fleet.shuffle_reconnects")
            try:
                # idempotent window replay: resend every buffered frame
                # of this epoch in order (receiver watermark drops what
                # already landed), then DONE if it was already signalled
                for s, pl in self._sent.get((dst, epoch), []):
                    self._sendall(dst, _HDR.pack(_MSG_BLOCK, self._rank,
                                                 epoch, s, len(pl)) + pl)
                if self._done_sent.get((dst, epoch)):
                    self._sendall(dst, _HDR.pack(_MSG_DONE, self._rank,
                                                 epoch, 0, 0))
                if kind == _MSG_RESYNC:
                    self._sendall(dst, frame)
                return
            except (ConnectionError, OSError) as e:
                self._drop_conn(dst)
                if not bo.sleep(attempt):
                    raise ShufflePeerDead(
                        f"shuffle peer {dst} unreachable past "
                        f"{self._deadline_s():.0f}s deadline") from e

    def _replay_for(self, dst: int, epoch: int) -> None:
        """RESYNC handler: re-deliver the requested epoch's buffered
        frames to a restarted peer (runs on the recv thread; outbound
        socket, so no interference with this conn)."""
        with self._send_lock(dst):
            frames = list(self._sent.get((dst, epoch), []))
            done = bool(self._done_sent.get((dst, epoch)))
            if not frames and not done:
                return
            # a RESYNC means the requester restarted, so any cached
            # outbound conn predates its current incarnation — drop it
            # and redial its (fresh) listener instead of writing frames
            # into a half-dead socket's buffer
            self._drop_conn(dst)
            bo = Backoff(base=0.05, cap=1.0, deadline=5.0)
            attempt = 0
            while True:
                try:
                    for s, pl in frames:
                        self._sendall(dst, _HDR.pack(
                            _MSG_BLOCK, self._rank, epoch, s, len(pl)) + pl)
                    if done:
                        self._sendall(dst, _HDR.pack(
                            _MSG_DONE, self._rank, epoch, 0, 0))
                    break
                except (ConnectionError, OSError):
                    self._drop_conn(dst)
                    attempt += 1
                    if not bo.sleep(attempt):
                        # give up without poisoning anything: the peer's
                        # barrier re-sends RESYNC while DONEs are missing
                        return
        stat_add("trainer.fleet.shuffle_resync_replays")

    # ------------------------------------------------------------------
    def send(self, dst: int, block: SlotRecordBlock) -> None:
        payload = block_to_wire(block)
        with self._send_lock(dst):
            epoch = self._epoch
            seq = self._seq.get((dst, epoch), 0)
            # pboxlint: disable-next=PB102 -- keys are (dst, ...)-partitioned; the per-dst send lock held above guards them
            self._seq[(dst, epoch)] = seq + 1
            # pboxlint: disable-next=PB102 -- per-dst send lock held (partitioned state)
            self._sent.setdefault((dst, epoch), []).append((seq, payload))
            self._tx_frame(dst, _MSG_BLOCK, epoch, seq, payload)

    def barrier(self) -> None:
        """Signal DONE to every peer, then wait for every peer's DONE
        (≙ PaddleShuffler wait_done) — bounded by
        FLAGS_shuffle_deadline_s, raising ShufflePeerDead past it."""
        t0 = time.monotonic()
        deadline = t0 + self._deadline_s()
        epoch = self._epoch
        for dst in range(self._world):
            if dst == self._rank:
                continue
            with self._send_lock(dst):
                self._done_sent[(dst, epoch)] = True
                self._tx_frame(dst, _MSG_DONE, epoch, 0, b"")
        last_nudge = t0
        while True:
            with self._done_cv:
                if self._rx_error is not None:
                    raise RuntimeError(
                        "shuffle receive failed — records lost"
                    ) from self._rx_error
                missing = sorted(
                    set(range(self._world)) - {self._rank}
                    - self._done_from.get(epoch, set()))
                resynced = epoch in self._resync_epochs
            if not missing:
                break
            left = deadline - time.monotonic()
            if left <= 0:
                raise ShufflePeerDead(
                    f"shuffle barrier timed out after "
                    f"{self._deadline_s():.0f}s (no DONE from ranks "
                    f"{missing})")
            if resynced and time.monotonic() - last_nudge >= 2.0:
                # we are a restarted rank: a peer may have replayed its
                # window into our DEAD predecessor (or the replay itself
                # raced our rebind) — keep asking until the DONE lands
                last_nudge = time.monotonic()
                for dst in missing:
                    with self._send_lock(dst):
                        try:
                            self._tx_frame(dst, _MSG_RESYNC, epoch, 0, b"")
                        except (ConnectionError, OSError):
                            pass    # peer mid-restart; next nudge retries
            with self._done_cv:
                if (self._rx_error is None
                        and len(self._done_from.get(epoch, ()))
                        < self._world - 1):
                    self._done_cv.wait(timeout=min(left, 1.0))
        stat_observe("trainer.fleet.barrier_wait_s",
                     time.monotonic() - t0)
        if not self._explicit_epoch:
            # legacy (epoch-less) callers: nobody will resync off this
            # round once the barrier released everyone — GC the window
            # (seq counters keep growing so the watermark stays sound)
            with self._done_cv:
                self._done_from.pop(epoch, None)
            for dst in range(self._world):
                if dst == self._rank:
                    continue
                with self._send_lock(dst):
                    # pboxlint: disable-next=PB102 -- keys are (dst, ...)-partitioned; the per-dst send lock held above guards them
                    self._sent.pop((dst, epoch), None)
                    # pboxlint: disable-next=PB102 -- per-dst send lock held (partitioned state)
                    self._done_sent.pop((dst, epoch), None)

    def drain(self) -> List[SlotRecordBlock]:
        with self._done_cv:
            if self._rx_error is not None:
                raise RuntimeError("shuffle receive failed — records lost"
                                   ) from self._rx_error
            return self._pending.pop(self._epoch, [])

    def close(self) -> None:
        with self._conn_lock:
            self._closed = True
        with self._conn_lock:
            conns = list(self._conns.values()) + self._accepted
            self._conns.clear()
            self._accepted = []
        # shutdown() BEFORE close(), listener included: close() alone
        # cannot release a socket another thread is blocked in
        # accept()/recv() on (the in-flight syscall pins the kernel
        # socket, so the listen port stays occupied and a
        # supervisor-restarted SAME-PROCESS rank could never rebind it).
        # shutdown(SHUT_RDWR) wakes those syscalls, then close() frees.
        for s in [self._listener] + conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
