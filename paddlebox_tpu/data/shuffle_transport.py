"""TCP transport for the inter-node global shuffle.

≙ boxps::PaddleShuffler (closed-source MPI transport driven from
data_set.cc:1910-1929 send_message_callback / ReceiveSuffleData
:2548): length-prefixed record-block messages between ranks, with DONE
markers standing in for the MPI barrier + wait_done.  Runs over plain
sockets (loopback or DCN) so the dataset shuffle works across launcher
processes without MPI.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.data.dataset import ShuffleTransport
from paddlebox_tpu.data.slot_record import SlotRecordBlock
from paddlebox_tpu.ps import wire
from paddlebox_tpu.utils import lockdep
from paddlebox_tpu.utils.channel import Channel

_MSG_BLOCK = 0
_MSG_DONE = 1


def block_to_wire(block: SlotRecordBlock) -> bytes:
    """SlotRecordBlock → typed wire frame (ps/wire.py codec — dtype/shape
    headers + raw buffers, never pickle on network bytes)."""
    msg: Dict[str, object] = {"n": block.n}
    msg["u"] = {}
    msg["uo"] = {}
    for name, (vals, offs) in block.uint64_slots.items():
        msg["u"][name] = np.asarray(vals)
        msg["uo"][name] = np.asarray(offs)
    msg["f"] = {}
    msg["fo"] = {}
    for name, (vals, offs) in block.float_slots.items():
        msg["f"][name] = np.asarray(vals)
        msg["fo"][name] = np.asarray(offs)
    if block.aux_slots:
        msg["a"] = {}
        msg["ao"] = {}
        for name, (vals, offs) in block.aux_slots.items():
            msg["a"][name] = np.asarray(vals)
            msg["ao"][name] = np.asarray(offs)
    if block.ins_ids is not None:
        if any("\x00" in i for i in block.ins_ids):
            raise ValueError("ins_ids may not contain NUL bytes")
        # explicit count disambiguates [] vs [""] (and trailing empties)
        msg["ins_ids"] = "\x00".join(block.ins_ids)
        msg["ins_ids_n"] = len(block.ins_ids)
    for f in ("search_ids", "cmatch", "rank"):
        v = getattr(block, f)
        if v is not None:
            msg[f] = np.asarray(v)
    return wire.encode(msg)


def block_from_wire(payload: bytes) -> SlotRecordBlock:
    try:
        msg = wire.decode(payload)
        blk = SlotRecordBlock(n=int(msg["n"]))
        for name, vals in msg.get("u", {}).items():
            blk.uint64_slots[name] = (vals, msg["uo"][name])
        for name, vals in msg.get("f", {}).items():
            blk.float_slots[name] = (vals, msg["fo"][name])
        for name, vals in msg.get("a", {}).items():
            blk.aux_slots[name] = (vals, msg["ao"][name])
        if "ins_ids" in msg:
            n_ids = int(msg["ins_ids_n"])
            ids = msg["ins_ids"].split("\x00") if n_ids else []
            if len(ids) != n_ids:
                raise ValueError("ins_ids count mismatch")
            blk.ins_ids = ids
        for f in ("search_ids", "cmatch", "rank"):
            if f in msg:
                setattr(blk, f, msg[f])
        return blk
    except wire.DecodeError:
        raise
    except (KeyError, TypeError, ValueError, AttributeError) as e:
        # decodable frame, wrong structure — same remedy as a bad frame
        raise wire.DecodeError(f"malformed block frame: {e!r}") from e


def _send_msg(sock: socket.socket, kind: int, payload: bytes) -> None:
    sock.sendall(struct.pack("<BQ", kind, len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


class TcpShuffleTransport(ShuffleTransport):
    def __init__(self, rank: int, addrs: Sequence[Tuple[str, int]]):
        self._rank = rank
        self._addrs = list(addrs)
        self._world = len(addrs)
        self._mail = Channel()
        self._rx_error = None
        self._done_from = set()
        self._done_lock = lockdep.lock("data.shuffle_transport.TcpShuffleTransport._done_lock")
        self._done_cv = threading.Condition(self._done_lock)
        # _conn_lock guards the registries only (PB104: never frame I/O);
        # per-destination send locks serialize frames on ONE peer's socket
        # without stalling senders to OTHER peers behind a global lock
        self._conns: Dict[int, socket.socket] = {}
        self._conn_lock = lockdep.lock("data.shuffle_transport.TcpShuffleTransport._conn_lock")
        self._send_locks: Dict[int, threading.Lock] = {}

        host, port = self._addrs[rank]
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(self._world)
        # pboxlint: disable-next=PB405 -- listener pump lives for the transport; close() unblocks it via listener shutdown
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    # ------------------------------------------------------------------
    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            # pboxlint: disable-next=PB405 -- per-peer receiver, bounded by world size; dies with its socket
            threading.Thread(target=self._recv_loop, args=(conn,),
                             daemon=True).start()

    def _recv_loop(self, conn: socket.socket):
        try:
            while True:
                head = _recv_exact(conn, 9)
                kind, length = struct.unpack("<BQ", head)
                if length > wire.MAX_FRAME:
                    raise ConnectionError(
                        f"oversized shuffle frame ({length} bytes)")
                payload = _recv_exact(conn, length) if length else b""
                if kind == _MSG_BLOCK:
                    self._mail.put(block_from_wire(payload))
                elif kind == _MSG_DONE:
                    src = struct.unpack("<I", payload)[0]
                    with self._done_cv:
                        self._done_from.add(src)
                        self._done_cv.notify_all()
        except (ConnectionError, OSError):
            return
        except wire.DecodeError as e:
            # a corrupt frame means lost records — poison the barrier so
            # the pass FAILS loudly instead of hanging or training short
            with self._done_cv:
                lockdep.guards(self, "_rx_error")
                self._rx_error = e
                self._done_cv.notify_all()
            return

    def _conn_to(self, dst: int) -> socket.socket:
        with self._conn_lock:
            sock = self._conns.get(dst)
        if sock is not None:
            return sock
        # dial OUTSIDE the lock; on a connect race the loser's socket
        # closes and everyone converges on the registered one
        s = socket.create_connection(self._addrs[dst], timeout=30)
        with self._conn_lock:
            cur = self._conns.setdefault(dst, s)
        if cur is not s:
            try:
                s.close()
            except OSError:
                pass
        return cur

    def _send_lock(self, dst: int) -> threading.Lock:
        with self._conn_lock:
            lk = self._send_locks.get(dst)
            if lk is None:
                lk = self._send_locks[dst] = lockdep.lock(
                    "data.shuffle_transport.TcpShuffleTransport._send_locks")
            return lk

    # ------------------------------------------------------------------
    def send(self, dst: int, block: SlotRecordBlock) -> None:
        payload = block_to_wire(block)
        sock = self._conn_to(dst)
        with self._send_lock(dst):
            _send_msg(sock, _MSG_BLOCK, payload)

    def barrier(self) -> None:
        """Signal DONE to every peer, then wait for every peer's DONE
        (≙ PaddleShuffler wait_done)."""
        me = struct.pack("<I", self._rank)
        for dst in range(self._world):
            if dst == self._rank:
                continue
            sock = self._conn_to(dst)
            with self._send_lock(dst):
                _send_msg(sock, _MSG_DONE, me)
        with self._done_cv:
            while len(self._done_from) < self._world - 1:
                if self._rx_error is not None:
                    raise RuntimeError(
                        "shuffle receive failed — records lost"
                    ) from self._rx_error
                if not self._done_cv.wait(timeout=60):
                    raise TimeoutError("shuffle barrier timed out")
            self._done_from.clear()

    def drain(self) -> List[SlotRecordBlock]:
        if self._rx_error is not None:
            raise RuntimeError("shuffle receive failed — records lost"
                               ) from self._rx_error
        out = []
        while self._mail.size():
            out.append(self._mail.get())
        return out

    def close(self) -> None:
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conn_lock:
            for s in self._conns.values():
                try:
                    s.close()
                except OSError:
                    pass
