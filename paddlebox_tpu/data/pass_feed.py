"""Pass-scoped device-resident batch feed — whole-pass pack, once.

≙ the reference's pass-scope GPU data path: SlotPaddleBoxDataFeed packs the
whole pass on device at feed time (data_feed.h:2036, MiniBatchGpuPack
data_feed.h:519, FillSlotValueOffsetPadBoxKernel / CopyForTensorPadBoxKernel
data_feed.cu:1210-1318) and translates keys once per pass during the build
(DedupKeysAndFillIdx, box_wrapper_impl.h:129) — so the train loop touches no
per-batch host work.

TPU-first shape of the same idea:

* HOST, once per pass (vectorized numpy over every record at once): ragged
  slot values -> translated pass-row ids (ONE searchsorted over the pass key
  array for all occurrences of all batches) -> padded [S, N*B, L] planes.
* DEVICE, once per pass: one relayout jit to the step's [N, S, L, B] layout
  plus (for the mxu path) the per-batch sort plans (ops/sorted_spmm
  build_plan mapped over batches) — the TPU equivalent of the reference
  keeping the packed pass + dedup index resident on the GPU.
* TRAIN LOOP: the jitted step takes a batch index and dynamic-slices the
  resident arrays; per-batch host work is one integer dispatch.

The per-batch host path (`data/batch_pack.py`) remains for streaming
datasets that do not fit pass-resident.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from paddlebox_tpu.config import DataFeedConfig, SlotConfig
from paddlebox_tpu.data.batch_pack import BatchPacker
from paddlebox_tpu.data.slot_record import SlotRecordBlock
from paddlebox_tpu.utils import intervals, workpool
from paddlebox_tpu.utils.monitor import stat_observe


@dataclasses.dataclass
class HostPassArrays:
    """Whole pass, packed host-side (numpy), batch-major."""

    indices: np.ndarray    # [S, N*B, L] int32 pass-local rows (0 = padding)
    lengths: np.ndarray    # [S, N*B] int32
    dense: np.ndarray      # [N*B, D] float32
    labels: np.ndarray     # [N*B] or [N*B, T] float32
    valid: np.ndarray      # [N*B] bool
    n_batches: int
    batch_size: int
    num_real: int          # real record count (pass total)
    ins_ids: Optional[list] = None
    # prebatched (pv-aligned) packs: per-batch real counts + prefix sums
    # into the real-record order (dump/ins_ids addressing); None = records
    # are densely packed and batch i holds rows [i*B, i*B + real_i)
    batch_real: Optional[np.ndarray] = None   # [N] int64
    batch_base: Optional[np.ndarray] = None   # [N] int64
    rank_offset: Optional[np.ndarray] = None  # [N*B, 1+2*max_rank] int32
    ads_offset: Optional[np.ndarray] = None   # [N, B+1] int32 pv offsets
    # InputTable-resolved aux index planes {name: [N*B, cap] int32}
    aux: Optional[Dict[str, np.ndarray]] = None
    uid: Optional[np.ndarray] = None    # [N*B] uint64 (uid_slot, HOST-side:
    #   uids never ship to device — wuauc accumulates on host)
    # ragged-path CSR step plans ({seg, inv, occ_w, u_rows, u_slot}, each
    # [N, ...]) — built host-side (build_csr_plans) so the prefetch worker
    # hides the cost under pass N's training; None until/unless built
    csr: Optional[Dict[str, np.ndarray]] = None

    def extra_planes(self) -> Dict[str, np.ndarray]:
        """Every optional per-record plane (rank_offset + aux index
        planes) — single source for upload/relayout/sharding plumbing."""
        out = {}
        if self.rank_offset is not None:
            out["rank_offset"] = self.rank_offset
        if self.aux:
            out.update(self.aux)
        return out

    def real_range(self, i: int):
        """(plane_row_lo, real_count, real_order_base) of batch i."""
        if self.batch_real is not None:
            return (i * self.batch_size, int(self.batch_real[i]),
                    int(self.batch_base[i]))
        lo = i * self.batch_size
        return lo, max(0, min(self.batch_size, self.num_real - lo)), lo


def _record_ranges(n: int, threads: int) -> List[tuple]:
    """Split [0, n) into contiguous record ranges for the pack fan-out.
    More chunks than threads (2×) smooths slot-length skew; a floor keeps
    tiny passes from paying per-task overhead.  Pure partitioning —
    workers write disjoint plane rows, so any split is bit-identical."""
    if n == 0:
        return []
    if threads <= 1:
        return [(0, n)]
    chunks = min(threads * 2, max(1, n // 4096))
    bounds = np.linspace(0, n, chunks + 1).astype(np.int64)
    return [(int(bounds[i]), int(bounds[i + 1]))
            for i in range(len(bounds) - 1) if bounds[i + 1] > bounds[i]]


def route_keys(block: SlotRecordBlock) -> np.ndarray:
    """Per-record shuffle route key for the fleet's global shuffle-by-key
    (≙ the reference's shuffle_by_uid / global_shuffle key extraction):
    the FIRST feasign of the record's first non-empty uint64 slot, slots
    visited in sorted-name order.  Both orders are properties of the data
    alone — independent of reader thread, file split, or fleet size — so
    every fleet width routes a given record identically.  Records with no
    sparse key at all route as key 0 (all land on one slice; degenerate
    but still deterministic)."""
    keys = np.zeros(block.n, dtype=np.uint64)
    found = np.zeros(block.n, dtype=bool)
    for name in sorted(block.uint64_slots):
        vals, offs = block.uint64_slots[name]
        has = offs[1:] > offs[:-1]
        take = has & ~found
        if take.any():
            keys[take] = vals[offs[:-1][take]]
            found |= has
        if found.all():
            break
    return keys


def pack_pass(blocks: Sequence[SlotRecordBlock], feed_config: DataFeedConfig,
              batch_size: int, label_slot="label",
              key_mapper=None, prebatched: bool = False,
              batch_counts: Optional[Sequence[int]] = None,
              pack_threads: Optional[int] = None,
              on_plane: Optional[Callable[[str, np.ndarray], None]] = None
              ) -> HostPassArrays:
    """Vectorized whole-pass pack: one call per slot, one key translation
    for every occurrence in the pass (vs per-batch searchsorted loops).

    prebatched: each input block IS one batch (≤ batch_size records, e.g.
    pv-aligned cuts from dataset.batches) and lands at its own batch slot,
    short batches padded — ≙ PadBoxSlotDataset's whole-pv batches feeding
    SlotPaddleBoxDataFeed.  batch_counts: same semantics but the cuts are
    given as per-batch record counts over the CONCATENATED block order
    (dataset.batch_bounds) — no per-batch block copies needed.  Otherwise
    blocks are concatenated and sliced densely every batch_size records.

    pack_threads: fan the per-slot/per-record-range pad+translate work
    across the shared pack WorkPool (None = FLAGS_pass_pack_threads; an
    explicit int uses a private pool of that size).  Every worker writes a
    DISJOINT row range of the preallocated SoA planes, so the result is
    bit-identical at any thread count (≙ the reference's per-device
    PackBatchTask threads, boxps_worker.cc:1259).

    on_plane: optional callable invoked on THIS thread as each finished
    SoA plane becomes final — upload_pass's per-plane H2D overlap hook
    (device dispatch stays on the pack coordinator thread).
    """
    t_pack = time.perf_counter()
    m_pack = time.monotonic()
    packer = BatchPacker(feed_config, batch_size, label_slot)
    own_pool = None
    if pack_threads is None:
        pool = workpool.pack_pool()
    else:
        own_pool = pool = workpool.WorkPool(max(1, int(pack_threads)),
                                            kind="pack")
    blocks = list(blocks)
    merged = SlotRecordBlock.concat(blocks)
    if batch_counts is not None:
        counts = [int(c) for c in batch_counts]
        if sum(counts) != merged.n:
            raise ValueError(
                f"batch_counts sum {sum(counts)} != {merged.n} records")
    elif prebatched:
        counts = [b.n for b in blocks]
    else:
        counts = None
    if ((feed_config.rank_offset or feed_config.ads_offset)
            and counts is None):
        # the plane builder treats each batch slice as whole page views; a
        # pv split across dense cuts would silently attend over fragment
        # peers — every entry point inherits this guard, not just the
        # trainer (≙ GetRankOffset only runs under pv merge,
        # data_feed.cc:1855)
        raise ValueError(
            "rank_offset/ads_offset require pv-aligned batches: pass "
            "prebatched blocks or batch_counts (dataset.batch_bounds)")
    if counts is not None:
        over = [c for c in counts if c > batch_size]
        if over:
            raise ValueError(
                f"prebatched block of {over[0]} records exceeds batch_size "
                f"{batch_size}")
        n_batches = max(1, len(counts))
        pos = (np.concatenate(
            [i * batch_size + np.arange(c) for i, c in enumerate(counts)])
            if counts else np.zeros((0,), np.int64)).astype(np.int64)
        batch_real = np.asarray(counts + [0] * (n_batches - len(counts)),
                                np.int64)
        batch_base = np.concatenate([[0], np.cumsum(batch_real)[:-1]])
    else:
        n_batches = max(1, -(-merged.n // batch_size))
        pos = slice(0, merged.n)   # contiguous writes on the dense path
        batch_real = batch_base = None
    n = merged.n
    nb = n_batches * batch_size
    S, L = len(packer.sparse_slots), packer.capacity

    indices = np.zeros((S, nb, L), dtype=np.int32)
    lengths = np.zeros((S, nb), dtype=np.int32)

    def rows_of(r0: int, r1: int):
        """Plane rows of record range [r0, r1) — a contiguous slice on the
        dense path, a fancy-index slice of the position map otherwise."""
        return pos[r0:r1] if isinstance(pos, np.ndarray) else slice(r0, r1)

    def pack_sparse_range(si: int, slot, r0: int, r1: int) -> None:
        values, offsets = merged.uint64_slots[slot.name]
        v = values[offsets[r0]:offsets[r1]]
        o = offsets[r0:r1 + 1] - offsets[r0]
        if key_mapper is not None:
            # translate the ragged values ONCE (real occurrences only),
            # then pad the translated int32 plane
            v = key_mapper(v)
        elif len(v) and int(v.max()) > np.iinfo(np.int32).max:
            raise ValueError(
                "pack_pass without a key_mapper stores raw feasigns in the "
                "int32 index plane; keys exceed int32 — pass the engine's "
                "PassKeyMapper (engine.mapper)")
        # _pad_ragged zero-fills positions beyond each record's length, so
        # padding already lands on the reserved zero row — no re-mask pass
        padded, lens = packer._pad_ragged(v, o, L)
        rows = rows_of(r0, r1)
        indices[si, rows] = padded
        lengths[si, rows] = lens

    try:
        # wave 1 — the heavy planes: every (sparse slot × record range)
        # pad/translate task runs concurrently, each writing a disjoint
        # [si, rows] region of the preallocated planes (bit-identical at
        # any thread count: no accumulation, no ordering)
        ranges = _record_ranges(n, pool.threads)
        pool.map(lambda t: pack_sparse_range(*t),
                 [(si, slot, r0, r1)
                  for si, slot in enumerate(packer.sparse_slots)
                  for r0, r1 in ranges])
        if on_plane is not None:
            on_plane("indices", indices)
            on_plane("lengths", lengths)

        # wave 2 — the light per-record planes, one task per plane column
        # group (dense slots / label columns / uid / aux), overlapping the
        # caller's H2D dispatch of wave 1 when on_plane is staged
        dense = np.zeros((nb, packer.dense_dim), dtype=np.float32)
        multi = np.zeros((nb, len(packer.label_slots)), np.float32)
        valid = np.zeros((nb,), dtype=bool)
        uid = np.zeros((nb,), np.uint64) if feed_config.uid_slot else None
        aux = {} if feed_config.string_slots else None

        def pack_dense(slot, col: int) -> None:
            values, offsets = merged.float_slots[slot.name]
            padded, _ = packer._pad_ragged(values, offsets, slot.dim)
            dense[pos, col:col + slot.dim] = padded

        def pack_label(t: int, name: str) -> None:
            src = merged.float_slots if name in merged.float_slots else \
                merged.uint64_slots
            if name in src:
                lv, lo = src[name]
                lp, _ = packer._pad_ragged(lv, lo, 1)
                multi[pos, t] = lp[:, 0].astype(np.float32)

        def pack_uid() -> None:
            vals, offs = merged.uint64_slots[feed_config.uid_slot]
            uid[pos] = packer._pad_ragged(vals, offs, 1)[0][:, 0]

        def pack_aux(slot) -> None:
            # InputTable index planes (≙ InputTableDataFeed,
            # data_feed.h:2224)
            vals, offs = merged.aux_slots[slot.name]
            padded, _ = packer._pad_ragged(vals, offs, slot.capacity)
            plane = np.zeros((nb, slot.capacity), np.int32)
            plane[pos] = padded.astype(np.int32)
            aux[slot.name] = plane

        tasks: List[Callable[[], None]] = []
        col = 0
        for slot in packer.dense_slots:
            tasks.append(functools.partial(pack_dense, slot, col))
            col += slot.dim
        for t, name in enumerate(packer.label_slots):
            tasks.append(functools.partial(pack_label, t, name))
        if uid is not None:
            tasks.append(pack_uid)
        if aux is not None:
            for slot in feed_config.string_slots:
                tasks.append(functools.partial(pack_aux, slot))
        pool.map(lambda fn: fn(), tasks)
        valid[pos] = True
    finally:
        if own_pool is not None:
            own_pool.shutdown()
    labels = multi if len(packer.label_slots) > 1 else multi[:, 0]
    if on_plane is not None:
        on_plane("dense", dense)
        on_plane("labels", labels)
        on_plane("valid", valid)
        if aux:
            for name, plane in aux.items():
                on_plane(name, plane)

    out = HostPassArrays(indices=indices, lengths=lengths, dense=dense,
                         labels=labels, valid=valid, n_batches=n_batches,
                         batch_size=batch_size, num_real=n,
                         ins_ids=merged.ins_ids, batch_real=batch_real,
                         batch_base=batch_base, aux=aux, uid=uid)
    # wave 3 — pv planes, vectorized over the WHOLE pass (the former
    # per-batch python loops; bit-identical, see rank_offset.py) and
    # metered apart from pad/translate cost
    t_planes = time.perf_counter()
    if feed_config.rank_offset:
        # ≙ GetRankOffset per batch (data_feed.cc:1855) — batch-local row
        # indices; meaningful under pv grouping (whole pvs per batch)
        from paddlebox_tpu.data.rank_offset import build_rank_offset_batched
        out.rank_offset = build_rank_offset_batched(
            merged.search_ids, merged.cmatch, merged.rank,
            batch_real, batch_base, batch_size, feed_config.max_rank)
        if on_plane is not None:
            on_plane("rank_offset", out.rank_offset)
    if feed_config.ads_offset:
        # ≙ GetAdsOffset per batch (data_feed.cc:3592): pv prefix offsets
        from paddlebox_tpu.data.rank_offset import build_ads_offset_batched
        out.ads_offset = build_ads_offset_batched(
            merged.search_ids, batch_real, batch_base, batch_size)
        if on_plane is not None:
            on_plane("ads_offset", out.ads_offset)
    if feed_config.rank_offset or feed_config.ads_offset:
        stat_observe("data.pass_feed.plane_build_s",
                     time.perf_counter() - t_planes)
    # pass-feed pack latency: whole-pass + amortized per-batch (the host
    # cost the pass-resident feed exists to keep out of the train loop)
    dt = time.perf_counter() - t_pack
    intervals.record("pack", m_pack, time.monotonic())
    stat_observe("data.pass_feed.pack_s", dt)
    stat_observe("data.pass_feed.batch_pack_s", dt / max(1, n_batches))
    return out


@dataclasses.dataclass
class PackedPassFeed:
    """Device-resident pass: stacked per-batch arrays + optional mxu plans.

    data layout (step-ready, so the hot loop does zero relayout):
      indices  [N, S, L, B] int32
      lengths  [N, S, B]    int32
      dense    [N, B, D]    float32
      labels   [N, B] / [N, B, T]
      valid    [N, B]       bool
    plans (mxu path): each of build_plan's outputs stacked on axis 0.
    """

    data: Dict[str, jnp.ndarray]
    n_batches: int
    batch_size: int
    num_real: int
    plans: Optional[Dict[str, jnp.ndarray]] = None
    plan_dims: object = None                # SpmmDims the plans were built for
    host: Optional[HostPassArrays] = None   # kept for dump/ins_ids paths
    uid: Optional[np.ndarray] = None        # [N*B] uint64 host-side uids
    host_labels: Optional[np.ndarray] = None  # [N*B(,T)] (uid_slot only)
    host_valid: Optional[np.ndarray] = None   # [N*B] bool (uid_slot only)

    def device_bytes(self) -> int:
        tot = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                  for a in self.data.values())
        if self.plans:
            tot += sum(int(np.prod(a.shape)) * a.dtype.itemsize
                       for a in self.plans.values())
        return tot


# module-level jits so every pass with the same geometry reuses the
# compiled relayout / plan-build executables (a fresh jit per pass would
# re-trace + re-compile — host work this path exists to eliminate)
@functools.partial(jax.jit, static_argnums=(1, 2))
def _relayout(d, N: int, B: int):
    s, nb, l = d["indices"].shape
    out = {
        # [S, N*B, L] -> [N, S, L, B]
        "indices": jnp.transpose(
            d["indices"].reshape(s, N, B, l), (1, 0, 3, 2)),
        "lengths": jnp.transpose(
            d["lengths"].reshape(s, N, B), (1, 0, 2)),
        "dense": d["dense"].reshape(N, B, -1),
        "valid": d["valid"].reshape(N, B),
    }
    lbl = d["labels"]
    out["labels"] = lbl.reshape((N, B) + lbl.shape[1:])
    if "ads_offset" in d:                   # per-BATCH plane [N, B+1]
        out["ads_offset"] = d["ads_offset"]
    for k in d:   # extra per-record planes ([N*B, w] -> [N, B, w])
        if k not in out and k != "labels":
            out[k] = d[k].reshape(N, B, -1)
    return out


@functools.partial(jax.jit, static_argnums=(1, 2))
def _build_plans(idx_all, dims, eff):
    from paddlebox_tpu.ops import sorted_spmm as sp

    def one(idx_slb):
        (rows2d, perm, inv_perm, ch, tl, fg, fs,
         first_occ) = sp.build_plan(idx_slb.reshape(-1), dims, eff)
        return {"rows2d": rows2d, "perm": perm, "inv_perm": inv_perm,
                "ch": ch, "tl": tl, "fg": fg, "fs": fs,
                "first_occ": first_occ}
    return jax.lax.map(one, idx_all)


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _build_static_planes(plans, labels_all, slot_ids, dims, eff, shape_slb):
    """Static sorted-domain payload planes (per batch, feed-time):

      bs       [p_pad_kept] int32 — pooled-grad source index b*S + s of
               each kept sorted position (the push crossing gathers the
               [B*S, 1+D] dynamic grad matrix by this)
      labelcol [p_pad_kept] f32  — the occurrence's instance label
               (g_click never changes within a pass, so it never crosses)
      slotcol  [p_pad_kept] f32  — slot id x first_occ, pre-scaled so the
               hot step's slot column is a ready constant

    Everything derives from (plan.perm, labels, slot layout) — training-
    state-independent, so it belongs to the pass build, not the hot loop
    (≙ CopyForPush reading slot/label straight from the batch layout it
    owns, box_wrapper.cu:1168)."""
    s, l, b = shape_slb
    kd = eff or dims
    p0 = dims.p_pad - kd.p_pad

    def one(plan, labels_b):
        perm_full = jnp.concatenate(
            [plan["perm"],
             jnp.zeros((dims.p_pad - dims.p,), jnp.int32)])
        perm_k = perm_full[p0:]                    # kept sorted suffix
        s_of = perm_k // (l * b)
        b_of = perm_k % b
        labels1 = labels_b if labels_b.ndim == 1 else labels_b[:, 0]
        slotcol = (jnp.take(slot_ids.astype(jnp.float32), s_of)
                   * plan["first_occ"])
        return {
            "bs": (b_of * s + s_of).astype(jnp.int32),
            "labelcol": jnp.take(labels1.astype(jnp.float32), b_of),
            "slotcol": slotcol,
        }
    return jax.lax.map(lambda args: one(*args), (plans, labels_all))


def _h2d_sharding(name: str, sharding):
    """The H2D (pre-relayout) sharding of one SoA plane — record dim split
    over the mesh's dp axes so the full pass never materializes on one
    device; ads_offset (tiny per-batch plane) replicates."""
    if sharding is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = next(iter(sharding.values())).mesh
    spec = sharding["valid"].spec[1]    # the dp axes tuple
    if name == "indices":
        return NamedSharding(mesh, P(None, spec, None))
    if name == "lengths":
        return NamedSharding(mesh, P(None, spec))
    if name in ("dense", "labels", "valid"):
        return NamedSharding(mesh, P(spec))
    if name == "ads_offset":
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(spec, None))   # rank_offset / aux planes


def _put_plane(name: str, a: np.ndarray, sharding):
    sh = _h2d_sharding(name, sharding)
    return jnp.asarray(a) if sh is None else jax.device_put(a, sh)


class PlaneStager:
    """Overlap H2D with pack: pack_pass invokes this (``on_plane``) as
    each SoA plane finishes, dispatching its ``device_put`` immediately so
    the upload hides behind the remaining host pack; ``upload_pass`` then
    skips the already-staged planes.  Dispatch happens on the pack
    coordinator's thread only — never on pool workers (concurrent device
    dispatch from several python threads can deadlock single-stream
    runtimes, ps/pass_manager.py)."""

    def __init__(self, sharding=None):
        self.sharding = sharding
        self.staged: Dict[str, jnp.ndarray] = {}

    def __call__(self, name: str, a: np.ndarray) -> None:
        t0 = time.monotonic()
        self.staged[name] = _put_plane(name, a, self.sharding)
        intervals.record("upload", t0, time.monotonic())


def upload_pass(host_arrays: HostPassArrays, keep_host: bool = False,
                sharding=None, staged=None) -> PackedPassFeed:
    """H2D once + one relayout jit into the step-ready stacked layout.

    sharding: optional {name: jax.sharding.Sharding} — under a topology the
    batch dims shard dp-wise so the resident pass is distributed, matching
    the per-batch path's _put_batch placement.  The H2D upload itself is
    already sharded (record dim split over the mesh) so the full pass never
    materializes on a single device; the relayout then runs under GSPMD and
    the result is device_put to the final batch-dim shardings.

    staged: optional PlaneStager (or its dict) holding planes whose H2D
    was already dispatched during pack — those skip the put here; with no
    stager every plane uploads all-at-once (the parallel-packer-off
    path)."""
    t_up = time.perf_counter()
    m_up = time.monotonic()
    h = host_arrays
    N, B = h.n_batches, h.batch_size
    pre = dict(getattr(staged, "staged", staged) or {})

    def put(name, a):
        if name in pre:
            return pre[name]
        return _put_plane(name, a, sharding)

    dev = {
        "indices": put("indices", h.indices),   # [S, N*B, L]
        "lengths": put("lengths", h.lengths),
        "dense": put("dense", h.dense),
        "labels": put("labels", h.labels),
        "valid": put("valid", h.valid),
    }
    for k, v in h.extra_planes().items():
        dev[k] = put(k, v)
    if h.ads_offset is not None:
        # tiny per-batch plane, replicated over the mesh (a plain
        # process-local array cannot mix with global arrays under jit)
        dev["ads_offset"] = put("ads_offset", h.ads_offset)
    data = _relayout(dev, N, B)
    if sharding is not None:
        data = {k: jax.device_put(v, sharding[k]) if k in sharding else v
                for k, v in data.items()}
    intervals.record("upload", m_up, time.monotonic())
    stat_observe("data.pass_feed.upload_s", time.perf_counter() - t_up)
    return PackedPassFeed(data=data, n_batches=N, batch_size=B,
                          num_real=h.num_real,
                          host=h if keep_host else None, uid=h.uid,
                          host_labels=h.labels if h.uid is not None else None,
                          host_valid=h.valid if h.uid is not None else None)


def precompute_plans(feed: PackedPassFeed, dims, eff=None,
                     slot_ids=None) -> None:
    """Per-batch sorted-spmm plans, built on device in one jit and kept
    resident (≙ the pass-scope dedup/index build of box_wrapper_impl.h:129:
    the sort is data-independent of the training state, so it runs once at
    pass build, never in the hot step).

    eff (sorted_spmm.trimmed_dims, shared by ALL batches so the stacked
    plan arrays are homogeneous): trim leading padding occurrences from the
    kernel worklist — the caller derives it from the max real-occurrence
    count over the pass's batches.

    slot_ids [S]: also build the static payload planes (bs/labelcol/
    slotcol — see _build_static_planes) so the push crossing moves only the
    dynamic 1+D grad columns.  Multi-task feeds (labels [N, B, T]) use
    per-task cvm columns at step time, so planes are built only for 1-D
    (or single-column) labels."""
    feed.plans = _build_plans(feed.data["indices"], dims, eff)
    feed.plan_dims = dims
    labels = feed.data["labels"]
    if slot_ids is not None and (labels.ndim == 2 or labels.shape[-1] == 1):
        n, s, l, b = feed.data["indices"].shape
        feed.plans.update(_build_static_planes(
            feed.plans, labels, jnp.asarray(slot_ids), dims, eff,
            (s, l, b)))


def _round8(n: int) -> int:
    """Pad a plan extent up to a multiple of 8 (lane-friendly, and a
    shared max keeps the stacked per-batch plan arrays homogeneous)."""
    return max(8, -(-int(n) // 8) * 8)


def build_csr_plans(indices: np.ndarray, slot_ids: Sequence[int],
                    n_batches: int, batch_size: int) -> Dict[str, np.ndarray]:
    """Per-batch CSR step plans for the ragged sparse path (host, numpy).

    Lowers each batch's padded [S, B, L] index plane to its valid-
    occurrence frontier ONCE per pass, so the jitted step never touches
    the [S, L, B] padded domain or the full-[N] working set (≙ the
    reference's pass-scope DedupKeysAndFillIdx, box_wrapper_impl.h:129 —
    dedup/index once, reuse every kernel; COGNATE's stay-in-the-nonzero-
    domain argument).  Occurrences are enumerated in the fast path's
    canonical flat order (s-major, then l, then b — exactly
    ``[S, L, B].reshape(-1)``), so per-row scatter-add summand order
    matches fast_path's and segment sums are order-reproducible.

    Returns stacked planes, one leading batch axis each:

      seg     [N, P_pad] int32 — pooled-output segment ``s*B + b`` of each
              valid occurrence (pad → 0; its payload is zeroed by occ_w)
      inv     [N, P_pad] int32 — occurrence → [U]-domain row position;
              position 0 is reserved for working-set row 0 (the all-zero
              padding row), real unique rows sit at 1.. in sorted order
      occ_w   [N, P_pad] f32  — 1.0 valid / 0.0 pad payload weight
      u_rows  [N, U_pad] int32 — sorted-unique working-set row of each
              [U]-position (u_rows[:, 0] == 0 always; pad → 0, so every
              duplicate scatter of row 0 writes identical pass-through
              values — deterministic by construction)
      u_slot  [N, U_pad] int32 — per-[U]-row merged slot id (max over the
              row's occurrence slots, matching fast_path's ``.at[].max``)

    Padding occurrences (index 0) are DROPPED, not masked: working-set
    row 0 is the reserved all-zero row in every path, so its pull
    contribution is zero and its push is suppressed (optimizer
    push_touched excludes row 0) — bit-identical to carrying them.
    """
    t0 = time.perf_counter()
    m0 = time.monotonic()
    S, NB, L = indices.shape
    B = int(batch_size)
    N = int(n_batches)
    slot_arr = np.asarray(slot_ids, dtype=np.int32)
    per = []
    p_max = u_max = 0
    for i in range(N):
        # [S, B, L] -> [S, L, B]: the fast path's flat order
        slb = np.ascontiguousarray(
            indices[:, i * B:(i + 1) * B, :].transpose(0, 2, 1))
        flatv = slb.reshape(-1)
        pos = np.flatnonzero(flatv)
        rows = flatv[pos]
        s_of = (pos // (L * B)).astype(np.int32)
        b_of = (pos % B).astype(np.int32)
        uniq = np.unique(rows).astype(np.int32)       # sorted, excludes 0
        inv = (np.searchsorted(uniq, rows) + 1).astype(np.int32)
        per.append((s_of * B + b_of, inv, uniq, s_of))
        p_max = max(p_max, pos.size)
        u_max = max(u_max, uniq.size + 1)
    P_pad, U_pad = _round8(p_max), _round8(u_max)
    seg = np.zeros((N, P_pad), np.int32)
    invp = np.zeros((N, P_pad), np.int32)
    occ_w = np.zeros((N, P_pad), np.float32)
    u_rows = np.zeros((N, U_pad), np.int32)
    u_slot = np.zeros((N, U_pad), np.int32)
    for i, (sg, inv, uniq, s_of) in enumerate(per):
        p, u = sg.size, uniq.size
        seg[i, :p] = sg
        invp[i, :p] = inv
        occ_w[i, :p] = 1.0
        u_rows[i, 1:1 + u] = uniq                      # [0] stays row 0
        np.maximum.at(u_slot[i], inv, slot_arr[s_of])
    intervals.record("csr", m0, time.monotonic())
    stat_observe("data.pass_feed.csr_build_s", time.perf_counter() - t0)
    return {"seg": seg, "inv": invp, "occ_w": occ_w,
            "u_rows": u_rows, "u_slot": u_slot}


def slice_batch(tree, i):
    """Batch i of a stacked pytree (XLA dynamic-slice inside jit)."""
    return jax.tree.map(
        lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False), tree)


def plan_tuple(p: Dict[str, jnp.ndarray]):
    """Plans dict (one batch) → the positional tuple build_plan returns —
    single source of the field order for every consumer.  When the static
    payload planes are present (precompute_plans with slot_ids) the tuple
    extends to 11 fields; mxu_path keys the narrow-crossing push on the
    length."""
    if "u_rows" in p:      # ragged-path CSR plan (build_csr_plans)
        return (p["seg"], p["inv"], p["occ_w"], p["u_rows"], p["u_slot"])
    base = (p["rows2d"], p["perm"], p["inv_perm"], p["ch"], p["tl"],
            p["fg"], p["fs"], p["first_occ"])
    if "bs" in p:
        return base + (p["bs"], p["labelcol"], p["slotcol"])
    return base
