"""PV-merge rank_offset assembly — GetRankOffset / CopyRankOffset equivalent.

≙ PaddleBoxDataFeed::GetRankOffset (data_feed.cc:1855-1903) + the device
copy CopyRankOffset (data_feed.cu:1371): under PV merge (records grouped by
search_id), each batch carries a [B, 1 + 2*max_rank] int32 plane consumed
by rank-attention models (ops/rank_attention.py):

  col 0        = own rank, or -1 (valid iff cmatch in {222, 223} and
                 1 <= rank <= max_rank — data_feed.cc:1873)
  col 2m+1/2m+2 = for each peer rank m+1 present in the pv: that peer's
                 rank and its BATCH ROW index; -1 where absent.  When a pv
                 holds several ads with the same rank the LAST one wins
                 (the reference's overwrite loop, data_feed.cc:1880-1895).

TPU-first: the reference fills the matrix with a per-pv nested loop on
host then memcpys to GPU; here the whole batch is assembled with
vectorized numpy (group runs from the pv-sorted order, last-wins via
duplicate fancy assignment) and ships with the rest of the pass pack.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

CMATCH_RANKED = (222, 223)      # data_feed.cc:1873 — join-phase ad cmatches


def build_rank_offset(search_ids: Optional[np.ndarray],
                      cmatch: Optional[np.ndarray],
                      rank: Optional[np.ndarray],
                      n: int, max_rank: int = 3) -> np.ndarray:
    """[n, 1 + 2*max_rank] int32 for one batch of pv-contiguous records.

    search_ids/cmatch/rank: per-record arrays for the batch's REAL records
    (may be shorter than n — the tail padding rows stay all -1), or None
    (no pv/logkey data parsed → all -1, matching a feed without pv merge).
    """
    col = 2 * max_rank + 1
    out = np.full((n, col), -1, np.int32)
    if search_ids is None or cmatch is None or rank is None or not len(
            search_ids):
        return out
    m = len(search_ids)
    valid = np.zeros((m,), bool)
    for c in CMATCH_RANKED:
        valid |= cmatch == c
    valid &= (rank >= 1) & (rank <= max_rank)
    r = np.where(valid, rank, -1).astype(np.int32)
    out[:m, 0] = r

    # pv groups are contiguous runs of equal search_id (preprocess_instance
    # sorts stable by search_id, dataset.py:199 ≙ PreprocessInstance)
    new_group = np.empty((m,), bool)
    new_group[0] = True
    np.not_equal(search_ids[1:], search_ids[:-1], out=new_group[1:])
    group_id = np.cumsum(new_group) - 1                   # [m]
    n_groups = int(group_id[-1]) + 1

    # per (group, rank) slot: batch row of the LAST valid ad with that rank
    # (duplicate fancy assignment keeps the last occurrence — the
    # reference's overwrite order)
    g_row = np.full((n_groups, max_rank), -1, np.int64)
    vk = np.nonzero(valid)[0]
    g_row[group_id[vk], r[vk] - 1] = vk

    rows = np.nonzero(r > 0)[0]                           # own rank valid
    peers = g_row[group_id[rows]]                         # [R, max_rank]
    present = peers >= 0
    out[rows[:, None], 1 + 2 * np.arange(max_rank)[None]] = np.where(
        present, np.arange(1, max_rank + 1)[None], -1)
    out[rows[:, None], 2 + 2 * np.arange(max_rank)[None]] = peers.astype(
        np.int32)
    return out


def build_rank_offset_batched(search_ids: Optional[np.ndarray],
                              cmatch: Optional[np.ndarray],
                              rank: Optional[np.ndarray],
                              batch_real: np.ndarray,
                              batch_base: np.ndarray,
                              batch_size: int,
                              max_rank: int = 3) -> np.ndarray:
    """[N*B, 1 + 2*max_rank] int32 for a WHOLE pass of pv-aligned batches
    in one vectorized build — bit-identical to calling
    :func:`build_rank_offset` per batch (the former pack_pass loop), but
    without N python iterations.

    search_ids/cmatch/rank index the pass's real records in concatenated
    batch order; batch_real/batch_base are the per-batch real counts and
    their prefix sums (HostPassArrays.batch_real/batch_base).
    """
    n_batches = len(batch_real)
    col = 2 * max_rank + 1
    out = np.full((n_batches * batch_size, col), -1, np.int32)
    if search_ids is None or cmatch is None or rank is None:
        return out
    m = int(batch_base[-1] + batch_real[-1]) if n_batches else 0
    if m == 0:
        return out
    batch_of = np.repeat(np.arange(n_batches), batch_real)        # [m]
    local = np.arange(m) - batch_base[batch_of]                   # in-batch
    plane_row = batch_of * batch_size + local

    valid = np.zeros((m,), bool)
    for c in CMATCH_RANKED:
        valid |= cmatch[:m] == c
    valid &= (rank[:m] >= 1) & (rank[:m] <= max_rank)
    r = np.where(valid, rank[:m], -1).astype(np.int32)
    out[plane_row, 0] = r

    # pv groups are contiguous equal-search_id runs, with a break FORCED
    # at every batch start (a pv never spans batches under pv-aligned
    # cuts, and per-batch builds could never see across the cut anyway)
    new_group = np.empty((m,), bool)
    new_group[0] = True
    np.not_equal(search_ids[1:m], search_ids[:m - 1], out=new_group[1:])
    new_group[batch_base[batch_real > 0]] = True
    group_id = np.cumsum(new_group) - 1
    n_groups = int(group_id[-1]) + 1

    # per (group, rank): BATCH-LOCAL row of the last valid ad (duplicate
    # fancy assignment keeps the last occurrence; global ascending order
    # equals per-batch ascending order, so last-wins matches the loop)
    g_row = np.full((n_groups, max_rank), -1, np.int64)
    vk = np.nonzero(valid)[0]
    g_row[group_id[vk], r[vk] - 1] = local[vk]

    rows = np.nonzero(r > 0)[0]
    peers = g_row[group_id[rows]]                         # [R, max_rank]
    present = peers >= 0
    prow = plane_row[rows][:, None]
    out[prow, 1 + 2 * np.arange(max_rank)[None]] = np.where(
        present, np.arange(1, max_rank + 1)[None], -1)
    out[prow, 2 + 2 * np.arange(max_rank)[None]] = peers.astype(np.int32)
    return out


def build_ads_offset_batched(search_ids: Optional[np.ndarray],
                             batch_real: np.ndarray,
                             batch_base: np.ndarray,
                             batch_size: int) -> np.ndarray:
    """[N, B+1] int32 pv prefix offsets for a whole pass in one build —
    bit-identical to calling :func:`build_ads_offset` per batch."""
    n_batches = len(batch_real)
    out = np.repeat(np.asarray(batch_real, np.int32)[:, None],
                    batch_size + 1, axis=1)
    m = int(batch_base[-1] + batch_real[-1]) if n_batches else 0
    if m == 0:
        return out
    if search_ids is None:
        raise ValueError(
            "ads_offset needs search_ids (parse_logkey pv data) — without "
            "them every batch would silently become one page view")
    batch_of = np.repeat(np.arange(n_batches), batch_real)
    local = np.arange(m) - batch_base[batch_of]
    new_pv = np.empty((m,), bool)
    new_pv[0] = True
    np.not_equal(search_ids[1:m], search_ids[:m - 1], out=new_pv[1:])
    new_pv[batch_base[batch_real > 0]] = True
    starts = np.nonzero(new_pv)[0]
    b_of = batch_of[starts]
    # pv ordinal within its batch: starts are sorted, so each batch's
    # starts form one contiguous run — ordinal = index − run start
    run_start = np.empty((len(starts),), bool)
    run_start[0] = True
    np.not_equal(b_of[1:], b_of[:-1], out=run_start[1:])
    seg = np.cumsum(run_start) - 1
    first_pos = np.nonzero(run_start)[0][seg]
    ordinal = np.arange(len(starts)) - first_pos
    out[b_of, ordinal] = local[starts]
    return out


def build_ads_offset(search_ids: Optional[np.ndarray], n_real: int,
                     batch_size: int) -> np.ndarray:
    """[B+1] int32 pv prefix offsets for one batch (≙ GetAdsOffset,
    data_feed.cc:3592: ads_offset[k] = first instance row of pv k, final
    entry = instance count).  Static shape: at most B pvs; unused tail
    entries repeat n_real so downstream diffs yield empty pvs."""
    out = np.full((batch_size + 1,), n_real, np.int32)
    if n_real == 0:
        out[0] = 0
        return out
    if search_ids is None:
        raise ValueError(
            "ads_offset needs search_ids (parse_logkey pv data) — without "
            "them every batch would silently become one page view")
    sid = search_ids[:n_real]
    new_pv = np.empty((n_real,), bool)
    new_pv[0] = True
    np.not_equal(sid[1:], sid[:-1], out=new_pv[1:])
    starts = np.nonzero(new_pv)[0]
    out[:len(starts)] = starts
    return out
