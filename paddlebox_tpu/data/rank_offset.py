"""PV-merge rank_offset assembly — GetRankOffset / CopyRankOffset equivalent.

≙ PaddleBoxDataFeed::GetRankOffset (data_feed.cc:1855-1903) + the device
copy CopyRankOffset (data_feed.cu:1371): under PV merge (records grouped by
search_id), each batch carries a [B, 1 + 2*max_rank] int32 plane consumed
by rank-attention models (ops/rank_attention.py):

  col 0        = own rank, or -1 (valid iff cmatch in {222, 223} and
                 1 <= rank <= max_rank — data_feed.cc:1873)
  col 2m+1/2m+2 = for each peer rank m+1 present in the pv: that peer's
                 rank and its BATCH ROW index; -1 where absent.  When a pv
                 holds several ads with the same rank the LAST one wins
                 (the reference's overwrite loop, data_feed.cc:1880-1895).

TPU-first: the reference fills the matrix with a per-pv nested loop on
host then memcpys to GPU; here the whole batch is assembled with
vectorized numpy (group runs from the pv-sorted order, last-wins via
duplicate fancy assignment) and ships with the rest of the pass pack.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

CMATCH_RANKED = (222, 223)      # data_feed.cc:1873 — join-phase ad cmatches


def build_rank_offset(search_ids: Optional[np.ndarray],
                      cmatch: Optional[np.ndarray],
                      rank: Optional[np.ndarray],
                      n: int, max_rank: int = 3) -> np.ndarray:
    """[n, 1 + 2*max_rank] int32 for one batch of pv-contiguous records.

    search_ids/cmatch/rank: per-record arrays for the batch's REAL records
    (may be shorter than n — the tail padding rows stay all -1), or None
    (no pv/logkey data parsed → all -1, matching a feed without pv merge).
    """
    col = 2 * max_rank + 1
    out = np.full((n, col), -1, np.int32)
    if search_ids is None or cmatch is None or rank is None or not len(
            search_ids):
        return out
    m = len(search_ids)
    valid = np.zeros((m,), bool)
    for c in CMATCH_RANKED:
        valid |= cmatch == c
    valid &= (rank >= 1) & (rank <= max_rank)
    r = np.where(valid, rank, -1).astype(np.int32)
    out[:m, 0] = r

    # pv groups are contiguous runs of equal search_id (preprocess_instance
    # sorts stable by search_id, dataset.py:199 ≙ PreprocessInstance)
    new_group = np.empty((m,), bool)
    new_group[0] = True
    np.not_equal(search_ids[1:], search_ids[:-1], out=new_group[1:])
    group_id = np.cumsum(new_group) - 1                   # [m]
    n_groups = int(group_id[-1]) + 1

    # per (group, rank) slot: batch row of the LAST valid ad with that rank
    # (duplicate fancy assignment keeps the last occurrence — the
    # reference's overwrite order)
    g_row = np.full((n_groups, max_rank), -1, np.int64)
    vk = np.nonzero(valid)[0]
    g_row[group_id[vk], r[vk] - 1] = vk

    rows = np.nonzero(r > 0)[0]                           # own rank valid
    peers = g_row[group_id[rows]]                         # [R, max_rank]
    present = peers >= 0
    out[rows[:, None], 1 + 2 * np.arange(max_rank)[None]] = np.where(
        present, np.arange(1, max_rank + 1)[None], -1)
    out[rows[:, None], 2 + 2 * np.arange(max_rank)[None]] = peers.astype(
        np.int32)
    return out


def build_ads_offset(search_ids: Optional[np.ndarray], n_real: int,
                     batch_size: int) -> np.ndarray:
    """[B+1] int32 pv prefix offsets for one batch (≙ GetAdsOffset,
    data_feed.cc:3592: ads_offset[k] = first instance row of pv k, final
    entry = instance count).  Static shape: at most B pvs; unused tail
    entries repeat n_real so downstream diffs yield empty pvs."""
    out = np.full((batch_size + 1,), n_real, np.int32)
    if n_real == 0:
        out[0] = 0
        return out
    if search_ids is None:
        raise ValueError(
            "ads_offset needs search_ids (parse_logkey pv data) — without "
            "them every batch would silently become one page view")
    sid = search_ids[:n_real]
    new_pv = np.empty((n_real,), bool)
    new_pv[0] = True
    np.not_equal(sid[1:], sid[:-1], out=new_pv[1:])
    starts = np.nonzero(new_pv)[0]
    out[:len(starts)] = starts
    return out
