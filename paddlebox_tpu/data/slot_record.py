"""SlotRecord storage: struct-of-arrays blocks of instances.

TPU-first redesign of the reference's per-record SlotRecordObject + arena pool
(data_feed.h:97-440: SlotValues, SlotRecordObject, SlotObjPool).  Instead of
millions of tiny heap records recycled through a pool, instances travel in
*blocks*: one contiguous (values, lod-offsets) pair per slot for a batch of
records.  This keeps host memory flat and copies vectorized — the role the
arena played for C++ — and is exactly the layout the device batch-pack wants
(SURVEY.md §7 step 2).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

Ragged = Tuple[np.ndarray, np.ndarray]  # (values [total], offsets [n+1])


def _empty_ragged(dtype) -> Ragged:
    return (np.empty((0,), dtype=dtype), np.zeros((1,), dtype=np.int64))


def _concat_ragged(parts: Sequence[Ragged], dtype) -> Ragged:
    values = np.concatenate([p[0] for p in parts]) if parts else \
        np.empty((0,), dtype=dtype)
    lens = np.concatenate([np.diff(p[1]) for p in parts]) if parts else \
        np.empty((0,), dtype=np.int64)
    offsets = np.zeros((len(lens) + 1,), dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    return values, offsets


def _select_ragged(r: Ragged, idx: np.ndarray) -> Ragged:
    values, offsets = r
    lens = np.diff(offsets)[idx]
    new_off = np.zeros((len(idx) + 1,), dtype=np.int64)
    np.cumsum(lens, out=new_off[1:])
    # gather value spans of the selected records
    starts = offsets[idx]
    total = int(new_off[-1])
    flat_idx = np.empty((total,), dtype=np.int64)
    # vectorized span expansion: for each selected record j with length l_j,
    # flat_idx[new_off[j]:new_off[j+1]] = starts[j] + [0..l_j)
    if total:
        rep_starts = np.repeat(starts - new_off[:-1], lens)
        flat_idx = np.arange(total, dtype=np.int64) + rep_starts
    return values[flat_idx], new_off


@dataclasses.dataclass
class SlotRecordBlock:
    """A batch of instances in struct-of-arrays layout."""

    n: int
    uint64_slots: Dict[str, Ragged] = dataclasses.field(default_factory=dict)
    float_slots: Dict[str, Ragged] = dataclasses.field(default_factory=dict)
    # aux index slots (InputTable-resolved string keys) — NOT feasigns:
    # excluded from all_keys() so they never register in the PS pass build
    aux_slots: Dict[str, Ragged] = dataclasses.field(default_factory=dict)
    ins_ids: Optional[List[str]] = None
    search_ids: Optional[np.ndarray] = None   # uint64, PV/AucRunner merge key
    cmatch: Optional[np.ndarray] = None       # int32
    rank: Optional[np.ndarray] = None         # int32

    # ------------------------------------------------------------------
    @property
    def feasign_count(self) -> int:
        return sum(int(v[1][-1]) for v in self.uint64_slots.values())

    def select(self, idx: np.ndarray) -> "SlotRecordBlock":
        idx = np.asarray(idx, dtype=np.int64)
        out = SlotRecordBlock(n=len(idx))
        out.uint64_slots = {k: _select_ragged(v, idx)
                            for k, v in self.uint64_slots.items()}
        out.float_slots = {k: _select_ragged(v, idx)
                           for k, v in self.float_slots.items()}
        out.aux_slots = {k: _select_ragged(v, idx)
                         for k, v in self.aux_slots.items()}
        if self.ins_ids is not None:
            out.ins_ids = [self.ins_ids[i] for i in idx]
        for f in ("search_ids", "cmatch", "rank"):
            v = getattr(self, f)
            if v is not None:
                setattr(out, f, v[idx])
        return out

    def permute(self, idx: np.ndarray) -> "SlotRecordBlock":
        return self.select(idx)

    def slice(self, start: int, stop: int) -> "SlotRecordBlock":
        return self.select(np.arange(start, min(stop, self.n)))

    @staticmethod
    def concat(blocks: Sequence["SlotRecordBlock"]) -> "SlotRecordBlock":
        blocks = [b for b in blocks if b.n > 0]
        if not blocks:
            return SlotRecordBlock(n=0)
        out = SlotRecordBlock(n=sum(b.n for b in blocks))
        u_keys = blocks[0].uint64_slots.keys()
        f_keys = blocks[0].float_slots.keys()
        out.uint64_slots = {
            k: _concat_ragged([b.uint64_slots[k] for b in blocks], np.uint64)
            for k in u_keys}
        out.float_slots = {
            k: _concat_ragged([b.float_slots[k] for b in blocks], np.float32)
            for k in f_keys}
        out.aux_slots = {
            k: _concat_ragged([b.aux_slots[k] for b in blocks], np.uint64)
            for k in blocks[0].aux_slots.keys()}
        if blocks[0].ins_ids is not None:
            out.ins_ids = [i for b in blocks for i in (b.ins_ids or [])]
        for f in ("search_ids", "cmatch", "rank"):
            if getattr(blocks[0], f) is not None:
                setattr(out, f, np.concatenate([getattr(b, f) for b in blocks]))
        return out

    def all_keys(self) -> np.ndarray:
        """Every uint64 feasign in the block (with repeats) — feeds the
        pass working-set build (≙ MergeInsKeys data_set.cc:2293)."""
        parts = [v[0] for v in self.uint64_slots.values()]
        if not parts:
            return np.empty((0,), dtype=np.uint64)
        return np.concatenate(parts)
