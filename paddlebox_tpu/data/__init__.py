from paddlebox_tpu.data.slot_record import SlotRecordBlock  # noqa: F401
from paddlebox_tpu.data.dataset import SlotDataset  # noqa: F401
