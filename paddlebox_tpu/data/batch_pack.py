"""Host-side batch assembly: SlotRecordBlock → fixed-shape device arrays.

≙ the GPU batch-pack kernels (FillSlotValueOffsetPadBoxKernel /
CopyForTensorPadBoxKernel, data_feed.cu:1210-1318) and MiniBatchGpuPack
(data_feed.h:519).  On TPU everything under jit needs static shapes
(SURVEY.md §7 hard part 5), so variable-length LoD becomes
[slot, batch, capacity] index tensors + per-(slot, ins) lengths; short
batches pad records and carry a validity mask.

Key→row translation (pass-local dense indices) happens here on the host via
the PassManager's key mapper — the TPU-first replacement for a device-side
hash probe: the device then does pure gathers/scatters that XLA lays out on
the MXU/HBM efficiently.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.config import DataFeedConfig, SlotConfig
from paddlebox_tpu.data.slot_record import SlotRecordBlock


@dataclasses.dataclass
class PackedBatch:
    """Static-shape batch, ready for device_put."""

    indices: np.ndarray       # [S, B, L] int32 — pass-local rows (0 = padding)
    lengths: np.ndarray       # [S, B] int32 — true feasign counts (<= L)
    dense: np.ndarray         # [B, D] float32 — concat of dense slots
    labels: np.ndarray        # [B] float32
    valid: np.ndarray         # [B] bool — false for padded records
    num_real: int             # records before padding
    keys: Optional[np.ndarray] = None   # [S, B, L] uint64 raw feasigns
    ins_ids: Optional[list] = None      # [num_real] instance ids (for dump)
    rank_offset: Optional[np.ndarray] = None  # [B, 1+2*max_rank] int32 (pv)
    # InputTable-resolved aux index planes [B, cap] int32 per string slot
    aux: Optional[dict] = None
    uid: Optional[np.ndarray] = None    # [B] uint64 (uid_slot, host-side)
    ads_offset: Optional[np.ndarray] = None   # [B+1] int32 pv offsets


class BatchPacker:
    def __init__(self, feed_config: DataFeedConfig, batch_size: int,
                 label_slot="label"):
        """label_slot: one slot name, or a list of names for multi-task
        labels (labels output becomes [B, T])."""
        self.config = feed_config
        self.batch_size = batch_size
        self.label_slots = ([label_slot] if isinstance(label_slot, str)
                            else list(label_slot))
        self.label_slot = self.label_slots[0]
        self.sparse_slots: List[SlotConfig] = feed_config.sparse_slots
        self.dense_slots: List[SlotConfig] = [
            s for s in feed_config.dense_slots
            if s.name not in self.label_slots]
        self.capacity = max([s.capacity for s in self.sparse_slots] or [1])
        self.dense_dim = sum(s.dim for s in self.dense_slots)

    def _pad_ragged(self, values: np.ndarray, offsets: np.ndarray,
                    cap: int):
        """ragged (values, offsets[n+1]) → padded [n, cap] + lengths [n]."""
        lens = np.diff(offsets)
        clipped = np.minimum(lens, cap).astype(np.int32)
        n = len(lens)
        col = np.arange(cap, dtype=np.int64)[None, :]
        gather = offsets[:-1, None] + col
        mask = col < clipped[:, None]
        gather = np.where(mask, gather, 0)
        if len(values) == 0:
            padded = np.zeros((n, cap), dtype=values.dtype)
        else:
            padded = np.where(mask, values[gather], values.dtype.type(0))
        return padded, clipped

    def pack(self, block: SlotRecordBlock,
             key_mapper: Optional[Callable[[np.ndarray], np.ndarray]] = None
             ) -> PackedBatch:
        B, L = self.batch_size, self.capacity
        S = len(self.sparse_slots)
        n = block.n
        assert n <= B, f"block of {n} records exceeds batch size {B}"

        keys = np.zeros((S, B, L), dtype=np.uint64)
        lengths = np.zeros((S, B), dtype=np.int32)
        for si, slot in enumerate(self.sparse_slots):
            values, offsets = block.uint64_slots[slot.name]
            padded, lens = self._pad_ragged(values, offsets, L)
            keys[si, :n] = padded
            lengths[si, :n] = lens

        dense = np.zeros((B, self.dense_dim), dtype=np.float32)
        col = 0
        for slot in self.dense_slots:
            values, offsets = block.float_slots[slot.name]
            padded, _ = self._pad_ragged(values, offsets, slot.dim)
            dense[:n, col:col + slot.dim] = padded
            col += slot.dim

        multi = np.zeros((B, len(self.label_slots)), np.float32)
        for t, name in enumerate(self.label_slots):
            if name in block.float_slots:
                lv, lo = block.float_slots[name]
                lp, _ = self._pad_ragged(lv, lo, 1)
                multi[:n, t] = lp[:, 0]
            elif name in block.uint64_slots:
                lv, lo = block.uint64_slots[name]
                lp, _ = self._pad_ragged(lv, lo, 1)
                multi[:n, t] = lp[:, 0].astype(np.float32)
        labels = multi if len(self.label_slots) > 1 else multi[:, 0]

        valid = np.zeros((B,), dtype=bool)
        valid[:n] = True

        if key_mapper is not None:
            indices = key_mapper(keys.ravel()).reshape(S, B, L).astype(np.int32)
            # padding positions & absent feasigns → row 0 (the reserved
            # zero-embedding row, ≙ FLAGS_enable_pull_box_padding_zero)
            pos_mask = (np.arange(L, dtype=np.int32)[None, None, :]
                        < lengths[:, :, None])
            indices = np.where(pos_mask, indices, 0)
        else:
            indices = np.zeros((S, B, L), dtype=np.int32)

        rank_off = None
        if self.config.rank_offset:
            from paddlebox_tpu.data.rank_offset import build_rank_offset
            rank_off = build_rank_offset(block.search_ids, block.cmatch,
                                         block.rank, B,
                                         self.config.max_rank)

        ads_off = None
        if self.config.ads_offset:
            from paddlebox_tpu.data.rank_offset import build_ads_offset
            ads_off = build_ads_offset(block.search_ids, n, B)

        uid = None
        if self.config.uid_slot:
            # first feasign of the uid slot = the instance's user id
            # (≙ MultiSlotDesc.uid_slot feeding WuAucMetricMsg)
            vals, offs = block.uint64_slots[self.config.uid_slot]
            uid = np.zeros((B,), np.uint64)
            uid[:n] = self._pad_ragged(vals, offs, 1)[0][:, 0]

        aux = None
        if self.config.string_slots:
            # InputTable index planes (≙ InputTableDataFeed feed vars,
            # data_feed.h:2224) — int32 indices, 0 = miss/pad row
            aux = {}
            for slot in self.config.string_slots:
                vals, offs = block.aux_slots[slot.name]
                plane = np.zeros((B, slot.capacity), np.int32)
                padded, _ = self._pad_ragged(vals, offs, slot.capacity)
                plane[:n] = padded.astype(np.int32)
                aux[slot.name] = plane

        return PackedBatch(indices=indices, lengths=lengths, dense=dense,
                           labels=labels, valid=valid, num_real=n, keys=keys,
                           ins_ids=block.ins_ids, rank_offset=rank_off,
                           aux=aux, uid=uid, ads_offset=ads_off)
