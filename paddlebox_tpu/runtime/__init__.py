from paddlebox_tpu.runtime.fleet_executor import (  # noqa: F401
    Carrier, ComputeInterceptor, FleetExecutor, Interceptor, Message,
    MessageBus, SinkInterceptor, SourceInterceptor, TaskNode)
