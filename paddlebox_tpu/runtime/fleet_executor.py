"""Actor-style DAG micro-runtime.

≙ distributed/fleet_executor/ (SURVEY §2.3): Carrier (carrier.{h,cc}) routes
InterceptorMessages between interceptors — Source/Compute/Amplifier/Sink
(compute_interceptor.cc, source_interceptor.cc, amplifier_interceptor.cc) —
described by TaskNodes (task_node.cc) over a brpc MessageBus
(message_bus.{h,cc}); used for heterogeneous pipeline training/inference.

TPU rebuild: same actor contract on host threads + Channels; the MessageBus
carries cross-carrier messages over the framework's TCP framing, so a task
graph can span launcher processes.  The credit-based flow control
(up/downstream buffer counts in compute_interceptor.cc) is kept: a compute
node only fires when every upstream has data and every downstream has
credit.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional

from paddlebox_tpu.utils.channel import Channel, ChannelClosed


@dataclasses.dataclass
class Message:
    src: int
    dst: int
    kind: str           # "data" | "credit" | "stop"
    payload: Any = None
    scope: int = 0      # microbatch / scope id


@dataclasses.dataclass
class TaskNode:
    task_id: int
    role: str                       # source | compute | amplifier | sink
    upstream: List[int] = dataclasses.field(default_factory=list)
    downstream: List[int] = dataclasses.field(default_factory=list)
    fn: Optional[Callable] = None   # compute payload transform
    max_runs: int = -1              # source: number of scopes to emit
    amplify: int = 1                # amplifier fan-out per input
    buffer_size: int = 2            # credits granted to each upstream


class Interceptor:
    def __init__(self, node: TaskNode, carrier: "Carrier"):
        self.node = node
        self.carrier = carrier

    def send(self, dst: int, kind: str, payload=None, scope=0):
        self.carrier.enqueue(Message(self.node.task_id, dst, kind, payload,
                                     scope))

    def handle(self, msg: Message) -> None:
        raise NotImplementedError

    def start(self) -> None:
        pass


class SourceInterceptor(Interceptor):
    """Emits max_runs scopes downstream, honoring downstream credit."""

    def __init__(self, node, carrier, generator: Callable[[int], Any]):
        super().__init__(node, carrier)
        self.generator = generator
        self.credits: Dict[int, int] = {d: 0 for d in node.downstream}
        self.emitted = 0

    def start(self):
        self._pump()

    def _pump(self):
        while (self.emitted < self.node.max_runs
               and all(c > 0 for c in self.credits.values())):
            payload = self.generator(self.emitted)
            for d in self.node.downstream:
                self.credits[d] -= 1
                self.send(d, "data", payload, scope=self.emitted)
            self.emitted += 1
        if self.emitted >= self.node.max_runs:
            for d in self.node.downstream:
                self.send(d, "stop")

    def handle(self, msg: Message):
        if msg.kind == "credit":
            self.credits[msg.src] = self.credits.get(msg.src, 0) + 1
            self._pump()


class ComputeInterceptor(Interceptor):
    """Fires fn when all upstreams delivered the scope and downstreams have
    credit (compute_interceptor.cc IsInputReady/CanWriteOutput)."""

    def __init__(self, node, carrier):
        super().__init__(node, carrier)
        self.inbox: Dict[int, Dict[int, Any]] = {}   # scope → src → payload
        self.credits: Dict[int, int] = {d: node.buffer_size
                                        for d in node.downstream}
        self.stops = 0
        self._stop_sent = False

    def start(self):
        for u in self.node.upstream:
            for _ in range(self.node.buffer_size):
                self.send(u, "credit")

    def _try_fire(self):
        ready = [s for s, m in sorted(self.inbox.items())
                 if len(m) == len(self.node.upstream)]
        for scope in ready:
            if not all(c > 0 for c in self.credits.values()):
                return
            inputs = self.inbox.pop(scope)
            args = [inputs[u] for u in self.node.upstream]
            out = self.node.fn(*args) if self.node.fn else \
                (args[0] if args else None)
            outs = [out] * self.node.amplify if \
                self.node.role == "amplifier" else [out]
            for o in outs:
                for d in self.node.downstream:
                    self.credits[d] -= 1
                    self.send(d, "data", o, scope)
            for u in self.node.upstream:
                self.send(u, "credit")

    def _maybe_forward_stop(self):
        # forward stop only once every pending scope has drained (a late
        # credit can still fire blocked scopes after upstream stop)
        if (not self._stop_sent and self.stops == len(self.node.upstream)
                and not self.inbox):
            self._stop_sent = True
            for d in self.node.downstream:
                self.send(d, "stop")

    def handle(self, msg: Message):
        if msg.kind == "data":
            self.inbox.setdefault(msg.scope, {})[msg.src] = msg.payload
            self._try_fire()
        elif msg.kind == "credit":
            self.credits[msg.src] = self.credits.get(msg.src, 0) + 1
            self._try_fire()
        elif msg.kind == "stop":
            self.stops += 1
            self._try_fire()
        self._maybe_forward_stop()


class SinkInterceptor(Interceptor):
    def __init__(self, node, carrier):
        super().__init__(node, carrier)
        self.results: List[Any] = []
        self.stops = 0

    def start(self):
        for u in self.node.upstream:
            for _ in range(self.node.buffer_size):
                self.send(u, "credit")

    def handle(self, msg: Message):
        if msg.kind == "data":
            self.results.append((msg.scope, msg.payload))
            self.send(msg.src, "credit")
        elif msg.kind == "stop":
            self.stops += 1
            if self.stops == len(self.node.upstream):
                self.carrier.signal_done()


class MessageBus:
    """Routes messages between carriers (≙ message_bus.{h,cc}).  In-process
    registry; remote carriers can be attached with a PSClient-style sender."""

    def __init__(self):
        self._carriers: Dict[int, "Carrier"] = {}
        self._remote: Dict[int, Callable[[Message], None]] = {}

    def register(self, rank: int, carrier: "Carrier"):
        self._carriers[rank] = carrier

    def register_remote(self, rank: int, sender: Callable[[Message], None]):
        self._remote[rank] = sender

    def deliver(self, rank: int, msg: Message):
        if rank in self._carriers:
            self._carriers[rank].enqueue(msg)
        elif rank in self._remote:
            self._remote[rank](msg)
        else:
            raise KeyError(f"no carrier for rank {rank}")


class Carrier:
    """Owns this rank's interceptors + the dispatch thread (carrier.cc)."""

    def __init__(self, rank: int = 0, bus: Optional[MessageBus] = None,
                 task_rank: Optional[Dict[int, int]] = None):
        self.rank = rank
        self.bus = bus or MessageBus()
        self.bus.register(rank, self)
        self.task_rank = task_rank or {}
        self.interceptors: Dict[int, Interceptor] = {}
        self._inbox = Channel()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add(self, interceptor: Interceptor):
        self.interceptors[interceptor.node.task_id] = interceptor

    def enqueue(self, msg: Message):
        dst_rank = self.task_rank.get(msg.dst, self.rank)
        if dst_rank != self.rank:
            self.bus.deliver(dst_rank, msg)
        else:
            self._inbox.put(msg)

    def signal_done(self):
        self._done.set()
        self._inbox.close()

    def run(self, timeout: float = 60.0):
        def loop():
            while True:
                try:
                    msg = self._inbox.get()
                except ChannelClosed:
                    return
                it = self.interceptors.get(msg.dst)
                if it is not None:
                    it.handle(msg)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        for it in self.interceptors.values():
            it.start()

    def wait(self, timeout: float = 60.0) -> bool:
        ok = self._done.wait(timeout)
        if self._thread is not None:
            self._thread.join(timeout=5)
        return ok


class FleetExecutor:
    """Builds the carrier from TaskNodes and runs the DAG
    (fleet_executor.cc RuntimeGraph → Carrier)."""

    def __init__(self, nodes: List[TaskNode],
                 source_generator: Callable[[int], Any]):
        self.carrier = Carrier()
        self.sinks: List[SinkInterceptor] = []
        for node in nodes:
            if node.role == "source":
                it = SourceInterceptor(node, self.carrier, source_generator)
            elif node.role == "sink":
                it = SinkInterceptor(node, self.carrier)
                self.sinks.append(it)
            else:
                it = ComputeInterceptor(node, self.carrier)
            self.carrier.add(it)

    def run(self, timeout: float = 60.0) -> List[Any]:
        self.carrier.run()
        if not self.carrier.wait(timeout):
            raise TimeoutError("fleet executor DAG did not complete")
        out = []
        for s in self.sinks:
            out.extend(p for _, p in sorted(s.results))
        return out
