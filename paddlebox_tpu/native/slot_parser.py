"""ctypes wrapper over the native parser/hash library."""

from __future__ import annotations

import ctypes
import threading
from typing import Optional

import numpy as np

from paddlebox_tpu.config import DataFeedConfig
from paddlebox_tpu.data.slot_record import SlotRecordBlock
from paddlebox_tpu.native import build

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not build.ensure_built():
            return None
        lib = ctypes.CDLL(build.lib_path())
        lib.pbox_parse_block.restype = ctypes.c_void_p
        lib.pbox_parse_block.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32)]
        lib.pbox_slot_total.restype = ctypes.c_int64
        lib.pbox_slot_total.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        for name in ("pbox_fill_slot_u64", "pbox_fill_slot_f32"):
            fn = getattr(lib, name)
            fn.restype = None
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                           ctypes.c_void_p, ctypes.c_void_p]
        lib.pbox_fill_logkeys.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                          ctypes.c_void_p, ctypes.c_void_p]
        lib.pbox_insid_bytes.restype = ctypes.c_int64
        lib.pbox_insid_bytes.argtypes = [ctypes.c_void_p]
        lib.pbox_fill_insids.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                         ctypes.c_void_p]
        lib.pbox_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class NativeSlotParser:
    """Drop-in replacement for data_feed.SlotParser.parse_block."""

    def __init__(self, config: DataFeedConfig, parse_ins_id: bool = False,
                 parse_logkey: bool = False):
        self.config = config
        self.parse_ins_id = parse_ins_id
        self.parse_logkey = parse_logkey
        self._is_float = np.array(
            [1 if s.dtype == "float" else 0 for s in config.slots], np.uint8)

    # plugin .so overrides (ParserPluginManager sets these to dlopen'd
    # site-specific parsers exposing the same ABI)
    _lib = None
    _entry = "pbox_parse_block"

    def parse_block(self, lines) -> SlotRecordBlock:
        # accessors (slot_total/fill_*) always come from the canonical lib
        # — a plugin .so only overrides the *parse* entry and must return a
        # handle compatible with the canonical block layout
        lib = _load()
        entry = getattr(self._lib, self._entry) \
            if self._lib is not None else lib.pbox_parse_block
        if self._lib is not None:
            # ctypes defaults restype to c_int (truncates the handle
            # pointer) — stamp the block-parser ABI on the plugin symbol
            entry.restype = ctypes.c_void_p
            entry.argtypes = lib.pbox_parse_block.argtypes
        buf = ("\n".join(lines) + "\n").encode()
        n_rec = ctypes.c_int64(0)
        status = ctypes.c_int32(0)
        handle = entry(
            buf, len(buf), len(self.config.slots),
            self._is_float.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            int(self.parse_ins_id), int(self.parse_logkey),
            ctypes.byref(n_rec), ctypes.byref(status))
        if not handle:
            raise ValueError(
                f"native parse failed (status={status.value}); check slot "
                f"config against the data (n_slots={len(self.config.slots)})")
        try:
            n = n_rec.value
            block = SlotRecordBlock(n=n)
            for si, slot in enumerate(self.config.slots):
                total = lib.pbox_slot_total(handle, si)
                offsets = np.empty(n + 1, np.int64)
                if slot.dtype == "float":
                    values = np.empty(total, np.float32)
                    lib.pbox_fill_slot_f32(handle, si,
                                           values.ctypes.data,
                                           offsets.ctypes.data)
                    block.float_slots[slot.name] = (values, offsets)
                else:
                    values = np.empty(total, np.uint64)
                    lib.pbox_fill_slot_u64(handle, si,
                                           values.ctypes.data,
                                           offsets.ctypes.data)
                    block.uint64_slots[slot.name] = (values, offsets)
            if self.parse_logkey:
                sids = np.empty(n, np.uint64)
                cm = np.empty(n, np.int32)
                rk = np.empty(n, np.int32)
                lib.pbox_fill_logkeys(handle, sids.ctypes.data,
                                      cm.ctypes.data, rk.ctypes.data)
                block.search_ids, block.cmatch, block.rank = sids, cm, rk
            if self.parse_ins_id or self.parse_logkey:
                nbytes = lib.pbox_insid_bytes(handle)
                chars = ctypes.create_string_buffer(max(nbytes, 1))
                offs = np.empty(n + 1, np.int64)
                lib.pbox_fill_insids(handle, chars, offs.ctypes.data)
                raw = chars.raw[:nbytes].decode()
                block.ins_ids = [raw[offs[i]:offs[i + 1]] for i in range(n)]
            from paddlebox_tpu.utils.monitor import stat_add
            stat_add("stat_total_feasign_num_in_mem", block.feasign_count)
            return block
        finally:
            lib.pbox_free(handle)


class NativeHashShard:
    """uint64 → dense-row map (see hash_shard.cc)."""

    def __init__(self, capacity_hint: int = 1024):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        if not hasattr(lib, "_hash_proto_done"):
            lib.pbox_hash_new.restype = ctypes.c_void_p
            lib.pbox_hash_new.argtypes = [ctypes.c_int64]
            lib.pbox_hash_free.argtypes = [ctypes.c_void_p]
            lib.pbox_hash_size.restype = ctypes.c_int64
            lib.pbox_hash_size.argtypes = [ctypes.c_void_p]
            for nm in ("pbox_hash_upsert", "pbox_hash_find"):
                fn = getattr(lib, nm)
                fn.restype = None
                fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_int64, ctypes.c_void_p]
            lib.pbox_hash_keys.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            lib._hash_proto_done = True
        self._lib = lib
        self._h = lib.pbox_hash_new(capacity_hint)

    def __del__(self):
        try:
            self._lib.pbox_hash_free(self._h)
        except Exception:
            pass

    def __len__(self):
        return self._lib.pbox_hash_size(self._h)

    def upsert(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, np.uint64)
        rows = np.empty(len(keys), np.int64)
        self._lib.pbox_hash_upsert(self._h, keys.ctypes.data, len(keys),
                                   rows.ctypes.data)
        return rows

    def find(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, np.uint64)
        rows = np.empty(len(keys), np.int64)
        self._lib.pbox_hash_find(self._h, keys.ctypes.data, len(keys),
                                 rows.ctypes.data)
        return rows

    def keys_by_row(self) -> np.ndarray:
        out = np.empty(len(self), np.uint64)
        self._lib.pbox_hash_keys(self._h, out.ctypes.data)
        return out
