"""Build the native runtime library (g++ → .so, loaded via ctypes).

≙ the reference's cmake native build for the framework runtime; kept
dependency-free: compiled on first import into the package dir, with an
mtime-based rebuild check.  Failures degrade gracefully to the pure-Python
fallbacks.
"""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["slot_parser.cc", "hash_shard.cc", "dump_writer.cc"]
_LIB = os.path.join(_DIR, "_libpbox_native.so")
_LOCK = threading.Lock()


def lib_path() -> str:
    return _LIB


def ensure_built(quiet: bool = True) -> bool:
    """Compile if missing/stale. Returns True when the .so is usable."""
    with _LOCK:
        srcs = [os.path.join(_DIR, s) for s in _SOURCES
                if os.path.exists(os.path.join(_DIR, s))]
        if not srcs:
            return False
        if os.path.exists(_LIB):
            lib_m = os.path.getmtime(_LIB)
            if all(os.path.getmtime(s) <= lib_m for s in srcs):
                return True
        cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
               "-std=c++17", "-o", _LIB] + srcs
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=240)
            if proc.returncode != 0:
                if not quiet:
                    print("native build failed:\n" + proc.stderr)
                return False
            return True
        except Exception:
            return False
