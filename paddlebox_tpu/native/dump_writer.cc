// Native xbox-dump TSV writer — the serving-dump IO hot path.
//
// ≙ the reference's native dump stack (SaveBase/SaveDelta write through
// boxps::PaddleFileMgr + thread pools, box_wrapper.cc:1286): formatting
// millions of "key\tshow\tclick\tembed_w\tmf..." lines in a Python loop
// is ~100k rows/s; this C++ writer formats into a grow-only buffer and
// writes once per call.  Loaded via ctypes (see io/checkpoint.py) with
// graceful Python fallback.
//
// API (C ABI):
//   pbox_dump_xbox(path, append, keys[n], show[n], click[n], embed_w[n],
//                  mf[n*d], n, d) -> rows written, or -1 on IO error.
//   show/click/embed_w are double so the ctr_double accessor's f64 stats
//   format exactly like the Python fallback (f32 inputs convert exactly).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

// %.6g-compatible float formatting (matches the Python writer's "%.6g")
inline void append_g6(std::string &out, double v) {
  char buf[32];
  int k = snprintf(buf, sizeof(buf), "%.6g", v);
  out.append(buf, k);
}

}  // namespace

extern "C" {

long long pbox_dump_xbox(const char *path, int append,
                         const uint64_t *keys, const double *show,
                         const double *click, const double *embed_w,
                         const float *mf, long long n, long long d) {
  FILE *f = fopen(path, append ? "ab" : "wb");
  if (!f) return -1;
  std::string buf;
  buf.reserve(1 << 22);
  char tmp[32];
  for (long long i = 0; i < n; ++i) {
    int k = snprintf(tmp, sizeof(tmp), "%llu",
                     static_cast<unsigned long long>(keys[i]));
    buf.append(tmp, k);
    buf.push_back('\t');
    append_g6(buf, show[i]);
    buf.push_back('\t');
    append_g6(buf, click[i]);
    buf.push_back('\t');
    append_g6(buf, embed_w[i]);
    buf.push_back('\t');
    const float *row = mf + i * d;
    for (long long j = 0; j < d; ++j) {
      if (j) buf.push_back(' ');
      append_g6(buf, row[j]);
    }
    buf.push_back('\n');
    if (buf.size() > (1u << 22)) {
      if (fwrite(buf.data(), 1, buf.size(), f) != buf.size()) {
        fclose(f);
        return -1;
      }
      buf.clear();
    }
  }
  if (!buf.empty() &&
      fwrite(buf.data(), 1, buf.size(), f) != buf.size()) {
    fclose(f);
    return -1;
  }
  if (fclose(f) != 0) return -1;
  return n;
}

}  // extern "C"
