// Native xbox-dump TSV writer — the serving-dump IO hot path.
//
// ≙ the reference's native dump stack (SaveBase/SaveDelta write through
// boxps::PaddleFileMgr + thread pools, box_wrapper.cc:1286): formatting
// millions of "key\tshow\tclick\tembed_w\tmf..." lines in a Python loop
// is ~100k rows/s; this C++ writer formats into a grow-only buffer and
// writes once per call.  Loaded via ctypes (see io/checkpoint.py) with
// graceful Python fallback.
//
// API (C ABI):
//   pbox_dump_xbox(path, append, keys[n], show[n], click[n], embed_w[n],
//                  mf[n*d], n, d) -> rows written, or -1 on IO error.
//   show/click/embed_w are double so the ctr_double accessor's f64 stats
//   format exactly like the Python fallback (f32 inputs convert exactly).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <cctype>
#include <cerrno>
#include <cmath>
#include <string>
#include <vector>

namespace {

// %.6g-compatible float formatting (matches the Python writer's "%.6g")
inline void append_g6(std::string &out, double v) {
  char buf[32];
  int k = snprintf(buf, sizeof(buf), "%.6g", v);
  out.append(buf, k);
}

}  // namespace

extern "C" {

long long pbox_dump_xbox(const char *path, int append,
                         const uint64_t *keys, const double *show,
                         const double *click, const double *embed_w,
                         const float *mf, long long n, long long d) {
  FILE *f = fopen(path, append ? "ab" : "wb");
  if (!f) return -1;
  std::string buf;
  buf.reserve(1 << 22);
  char tmp[32];
  for (long long i = 0; i < n; ++i) {
    int k = snprintf(tmp, sizeof(tmp), "%llu",
                     static_cast<unsigned long long>(keys[i]));
    buf.append(tmp, k);
    buf.push_back('\t');
    append_g6(buf, show[i]);
    buf.push_back('\t');
    append_g6(buf, click[i]);
    buf.push_back('\t');
    append_g6(buf, embed_w[i]);
    buf.push_back('\t');
    const float *row = mf + i * d;
    for (long long j = 0; j < d; ++j) {
      if (j) buf.push_back(' ');
      append_g6(buf, row[j]);
    }
    buf.push_back('\n');
    if (buf.size() > (1u << 22)) {
      if (fwrite(buf.data(), 1, buf.size(), f) != buf.size()) {
        fclose(f);
        return -1;
      }
      buf.clear();
    }
  }
  if (!buf.empty() &&
      fwrite(buf.data(), 1, buf.size(), f) != buf.size()) {
    fclose(f);
    return -1;
  }
  if (fclose(f) != 0) return -1;
  return n;
}

}  // extern "C"

extern "C" {

// Parse an xbox dump buffer into preallocated column arrays.
// buf[len] is the whole file (NUL-terminated by the caller); rows were
// counted host-side (one per newline-terminated, non-empty line).
// Returns rows parsed, or -(line_index+1) on a malformed line (wrong
// field/mf count, bad or out-of-range number) so the caller can report
// the exact line.  The strto* family skips leading whitespace INCLUDING
// newlines — every field start is checked against that (a truncated
// line must fail loud, never silently consume the next line), and every
// parse end is bounds-checked against the line.
long long pbox_load_xbox(const char *buf, long long len, uint64_t *keys,
                         double *show, double *click, double *embed_w,
                         float *mf, long long n_rows, long long d) {
  const char *p = buf;
  const char *end = buf + len;
  long long row = 0;
  while (p < end && row < n_rows) {
    const char *line_end = static_cast<const char *>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (!line_end) line_end = end;
    {  // skip blank lines, including whitespace-only separators, exactly
       // like the Python fallback's `if not line.strip(): continue`
      const char *q = p;
      while (q < line_end && isspace(static_cast<unsigned char>(*q))) ++q;
      if (q == line_end) {
        p = line_end + 1;
        continue;
      }
    }
    char *cur = const_cast<char *>(p);
    char *nxt = nullptr;
    const char *le = line_end;
    auto field_ok = [&](char *c) {
      return c < le && !isspace(static_cast<unsigned char>(*c));
    };
    // a leading '-' would silently wrap through strtoull; the fallback
    // rejects negative keys, so reject here too
    if (!field_ok(cur) || *cur == '-') return -(row + 1);
    errno = 0;
    keys[row] = strtoull(cur, &nxt, 10);
    if (nxt == cur || nxt > le || errno == ERANGE || *nxt != '\t')
      return -(row + 1);
    cur = nxt + 1;
    // ERANGE on *underflow* (subnormal/zero result) is accepted: %.6g of a
    // raw f32 training value can legitimately emit e.g. 1e-42, and Python's
    // float() loads it fine.  Non-finite results reject — both overflow
    // (1e999 -> HUGE_VAL with ERANGE) and literal inf/nan tokens (parsed
    // with errno==0) — matching the Python fallback's isfinite gate.
    double *cols[3] = {show, click, embed_w};
    for (int c3 = 0; c3 < 3; ++c3) {
      if (!field_ok(cur)) return -(row + 1);
      cols[c3][row] = strtod(cur, &nxt);
      if (nxt == cur || nxt > le || !std::isfinite(cols[c3][row]) ||
          *nxt != '\t')
        return -(row + 1);
      cur = nxt + 1;
    }
    float *out = mf + row * d;
    for (long long j = 0; j < d; ++j) {
      if (!field_ok(cur)) return -(row + 1);
      out[j] = strtof(cur, &nxt);
      if (nxt == cur || nxt > le || !std::isfinite(out[j]))
        return -(row + 1);
      cur = nxt;
      if (j + 1 < d) {
        if (*cur != ' ') return -(row + 1);
        ++cur;
      }
    }
    if (cur < line_end && *cur != '\r') return -(row + 1);
    p = line_end + 1;
    ++row;
  }
  return row;
}

}  // extern "C"
