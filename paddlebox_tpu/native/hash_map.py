"""ctypes wrapper over the native uint64→row hash (hash_shard.cc).

Two users:
* PassKeyMapper (ps/embedding.py): pass-scope key→row translation — the
  once-per-pass DedupKeysAndFillIdx equivalent (box_wrapper_impl.h:129);
  ~6x faster than np.searchsorted over a 2M-key array at 13M+ lookups.
* ShardedHostTable (ps/host_table.py): DRAM-tier key→row resolution.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

from paddlebox_tpu.native import build

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not build.ensure_built():
            return None
        lib = ctypes.CDLL(build.lib_path())
        lib.pbox_hash_new.restype = ctypes.c_void_p
        lib.pbox_hash_new.argtypes = [ctypes.c_int64]
        lib.pbox_hash_free.argtypes = [ctypes.c_void_p]
        lib.pbox_hash_size.restype = ctypes.c_int64
        lib.pbox_hash_size.argtypes = [ctypes.c_void_p]
        lib.pbox_hash_upsert.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
        lib.pbox_hash_find.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
        lib.pbox_hash_keys.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.pbox_hash_find_rows1_i32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int32]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class NativeKeyHash:
    """uint64 key → dense row id (insertion order), native open addressing."""

    def __init__(self, capacity_hint: int = 16):
        lib = _load()
        if lib is None:
            raise RuntimeError("native hash library unavailable")
        self._lib = lib
        self._h = lib.pbox_hash_new(int(capacity_hint))

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.pbox_hash_free(h)
            self._h = None

    def __len__(self) -> int:
        return int(self._lib.pbox_hash_size(self._h))

    def upsert(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        out = np.empty((len(keys),), np.int64)
        self._lib.pbox_hash_upsert(
            self._h, keys.ctypes.data_as(ctypes.c_void_p), len(keys),
            out.ctypes.data_as(ctypes.c_void_p))
        return out

    def find(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        out = np.empty((len(keys),), np.int64)
        self._lib.pbox_hash_find(
            self._h, keys.ctypes.data_as(ctypes.c_void_p), len(keys),
            out.ctypes.data_as(ctypes.c_void_p))
        return out

    def find_rows1_i32(self, keys: np.ndarray,
                       n_threads: Optional[int] = None) -> np.ndarray:
        """key → insertion-row + 1; 0 for missing and for key 0 (the
        reserved zero-embedding row).  Threaded (read-only probes)."""
        if n_threads is None:
            n_threads = min(8, os.cpu_count() or 1)
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
        out = np.empty((len(keys),), np.int32)
        self._lib.pbox_hash_find_rows1_i32(
            self._h, keys.ctypes.data_as(ctypes.c_void_p), len(keys),
            out.ctypes.data_as(ctypes.c_void_p), int(n_threads))
        return out
