// Native MultiSlot text parser — the host data-loader hot path.
//
// TPU-native equivalent of SlotRecordInMemoryDataFeed::ParseOneInstance
// (reference: paddle/fluid/framework/data_feed.cc:2397) re-designed for the
// struct-of-arrays SlotRecordBlock layout: one pass over the raw byte buffer,
// per-slot contiguous value + offset arrays, zero per-record allocations.
// Exposed as a C ABI for ctypes (no pybind11 in the image).
//
// Build: paddlebox_tpu/native/build.py (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct SlotColumn {
  bool is_float;
  std::vector<uint64_t> u64;
  std::vector<float> f32;
  std::vector<int64_t> offsets;  // n_records + 1
};

struct ParseResult {
  int64_t n_records = 0;
  std::vector<SlotColumn> slots;
  // ins ids packed back to back with offsets
  std::string ins_ids;
  std::vector<int64_t> ins_id_offsets;
  std::vector<uint64_t> search_ids;
  std::vector<int32_t> cmatch;
  std::vector<int32_t> rank;
  std::string error;
};

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

inline uint64_t parse_u64(const char*& p, const char* end) {
  uint64_t v = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    v = v * 10 + static_cast<uint64_t>(*p - '0');
    ++p;
  }
  return v;
}

inline int64_t parse_i64(const char*& p, const char* end) {
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  int64_t v = static_cast<int64_t>(parse_u64(p, end));
  return neg ? -v : v;
}

inline float parse_f32(const char*& p, const char* end) {
  char* stop = nullptr;
  float v = strtof(p, &stop);
  p = stop;
  if (p > end) p = end;
  return v;
}

// hex logkey → (search_id, cmatch, rank); layout per
// data_feed.cc parser_log_key: rank = last 2 hex chars, cmatch = prior 2.
inline void decode_logkey(const char* s, int64_t len, uint64_t* sid,
                          int32_t* cm, int32_t* rk) {
  auto hexval = [](char c) -> uint64_t {
    if (c >= '0' && c <= '9') return c - '0';
    if (c >= 'a' && c <= 'f') return c - 'a' + 10;
    if (c >= 'A' && c <= 'F') return c - 'A' + 10;
    return 0;
  };
  uint64_t v = 0;
  if (len < 4) {
    *sid = 0; *cm = 0; *rk = 0;
    return;
  }
  *rk = static_cast<int32_t>(hexval(s[len - 2]) * 16 + hexval(s[len - 1]));
  *cm = static_cast<int32_t>(hexval(s[len - 4]) * 16 + hexval(s[len - 3]));
  for (int64_t i = 0; i < len - 4; ++i) v = v * 16 + hexval(s[i]);
  *sid = v;
}

}  // namespace

extern "C" {

void* pbox_parse_block(const char* buf, int64_t buflen, int32_t n_slots,
                       const uint8_t* is_float, int32_t parse_ins_id,
                       int32_t parse_logkey, int64_t* out_n_records,
                       int32_t* out_status) {
  auto* res = new ParseResult();
  res->slots.resize(n_slots);
  for (int i = 0; i < n_slots; ++i) {
    res->slots[i].is_float = is_float[i] != 0;
    res->slots[i].offsets.push_back(0);
  }
  if (parse_ins_id || parse_logkey) res->ins_id_offsets.push_back(0);

  const char* p = buf;
  const char* end = buf + buflen;
  *out_status = 0;
  while (p < end) {
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (line_end == nullptr) line_end = end;
    const char* q = skip_ws(p, line_end);
    if (q == line_end) {  // blank line
      p = line_end + 1;
      continue;
    }
    if (parse_ins_id) {
      q = skip_ws(q, line_end);
      int64_t num = parse_i64(q, line_end);
      if (num != 1) { *out_status = 1; break; }
      q = skip_ws(q, line_end);
      const char* tok = q;
      while (q < line_end && *q != ' ') ++q;
      res->ins_ids.append(tok, static_cast<size_t>(q - tok));
      res->ins_id_offsets.push_back(
          static_cast<int64_t>(res->ins_ids.size()));
    }
    if (parse_logkey) {
      q = skip_ws(q, line_end);
      int64_t num = parse_i64(q, line_end);
      if (num != 1) { *out_status = 2; break; }
      q = skip_ws(q, line_end);
      const char* tok = q;
      while (q < line_end && *q != ' ') ++q;
      uint64_t sid; int32_t cm, rk;
      decode_logkey(tok, q - tok, &sid, &cm, &rk);
      res->search_ids.push_back(sid);
      res->cmatch.push_back(cm);
      res->rank.push_back(rk);
      if (!parse_ins_id) {
        res->ins_ids.append(tok, static_cast<size_t>(q - tok));
        res->ins_id_offsets.push_back(
            static_cast<int64_t>(res->ins_ids.size()));
      }
    }
    for (int s = 0; s < n_slots; ++s) {
      q = skip_ws(q, line_end);
      int64_t num = parse_i64(q, line_end);
      if (num <= 0 || q >= line_end) { *out_status = 3; break; }
      SlotColumn& col = res->slots[s];
      if (col.is_float) {
        for (int64_t k = 0; k < num; ++k) {
          q = skip_ws(q, line_end);
          col.f32.push_back(parse_f32(q, line_end));
        }
        col.offsets.push_back(static_cast<int64_t>(col.f32.size()));
      } else {
        for (int64_t k = 0; k < num; ++k) {
          q = skip_ws(q, line_end);
          col.u64.push_back(parse_u64(q, line_end));
        }
        col.offsets.push_back(static_cast<int64_t>(col.u64.size()));
      }
    }
    if (*out_status != 0) break;
    ++res->n_records;
    p = line_end + 1;
  }
  *out_n_records = res->n_records;
  if (*out_status != 0) {
    delete res;
    return nullptr;
  }
  return res;
}

int64_t pbox_slot_total(void* h, int32_t slot) {
  auto* res = static_cast<ParseResult*>(h);
  const SlotColumn& col = res->slots[slot];
  return col.is_float ? static_cast<int64_t>(col.f32.size())
                      : static_cast<int64_t>(col.u64.size());
}

void pbox_fill_slot_u64(void* h, int32_t slot, uint64_t* values,
                        int64_t* offsets) {
  auto* res = static_cast<ParseResult*>(h);
  const SlotColumn& col = res->slots[slot];
  memcpy(values, col.u64.data(), col.u64.size() * sizeof(uint64_t));
  memcpy(offsets, col.offsets.data(), col.offsets.size() * sizeof(int64_t));
}

void pbox_fill_slot_f32(void* h, int32_t slot, float* values,
                        int64_t* offsets) {
  auto* res = static_cast<ParseResult*>(h);
  const SlotColumn& col = res->slots[slot];
  memcpy(values, col.f32.data(), col.f32.size() * sizeof(float));
  memcpy(offsets, col.offsets.data(), col.offsets.size() * sizeof(int64_t));
}

void pbox_fill_logkeys(void* h, uint64_t* sids, int32_t* cmatch,
                       int32_t* rank) {
  auto* res = static_cast<ParseResult*>(h);
  memcpy(sids, res->search_ids.data(),
         res->search_ids.size() * sizeof(uint64_t));
  memcpy(cmatch, res->cmatch.data(), res->cmatch.size() * sizeof(int32_t));
  memcpy(rank, res->rank.data(), res->rank.size() * sizeof(int32_t));
}

int64_t pbox_insid_bytes(void* h) {
  return static_cast<int64_t>(static_cast<ParseResult*>(h)->ins_ids.size());
}

void pbox_fill_insids(void* h, char* chars, int64_t* offsets) {
  auto* res = static_cast<ParseResult*>(h);
  memcpy(chars, res->ins_ids.data(), res->ins_ids.size());
  memcpy(offsets, res->ins_id_offsets.data(),
         res->ins_id_offsets.size() * sizeof(int64_t));
}

void pbox_free(void* h) { delete static_cast<ParseResult*>(h); }

}  // extern "C"
