"""ctypes wrapper over the native xbox-dump TSV writer (dump_writer.cc).

≙ the reference's native dump IO (SaveBase/SaveDelta through
boxps::PaddleFileMgr, box_wrapper.cc:1286): io/checkpoint.save_xbox
formats per-shard row blocks through this writer (one buffered fwrite
per ~4MB) instead of a per-row Python loop; degrades gracefully to the
Python fallback when the native build is unavailable.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Optional

import numpy as np

from paddlebox_tpu.native import build

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not build.ensure_built():
            return None
        try:
            lib = ctypes.CDLL(build.lib_path())
            lib.pbox_dump_xbox.restype = ctypes.c_longlong
            lib.pbox_dump_xbox.argtypes = [
                ctypes.c_char_p, ctypes.c_int,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_longlong, ctypes.c_longlong]
            lib.pbox_load_xbox.restype = ctypes.c_longlong
            lib.pbox_load_xbox.argtypes = [
                ctypes.c_void_p, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_longlong, ctypes.c_longlong]
        except (OSError, AttributeError):
            # a stale prebuilt .so without this symbol must degrade to
            # the Python fallback, not crash the one caller that has one
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def dump_rows(path: str, append: bool, keys: np.ndarray, show: np.ndarray,
              click: np.ndarray, embed_w: np.ndarray,
              mf: np.ndarray) -> Optional[int]:
    """Write one block of xbox rows; returns rows written or None when the
    native library is unavailable (caller falls back).  Raises OSError on
    an IO failure."""
    lib = _load()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys, np.uint64)
    # f64 columns: exact for f32 inputs AND the ctr_double accessor's
    # f64 stats (an f32 round-trip could flip the 6th %.6g digit)
    show = np.ascontiguousarray(show, np.float64)
    click = np.ascontiguousarray(click, np.float64)
    embed_w = np.ascontiguousarray(embed_w, np.float64)
    mf = np.ascontiguousarray(mf, np.float32)
    n, d = mf.shape
    assert len(keys) == len(show) == len(click) == len(embed_w) == n
    wrote = lib.pbox_dump_xbox(
        path.encode(), 1 if append else 0,
        keys.ctypes.data, show.ctypes.data, click.ctypes.data,
        embed_w.ctypes.data, mf.ctypes.data, n, d)
    if wrote < 0:
        raise OSError(f"native xbox dump failed writing {path!r}")
    return int(wrote)


def load_rows(path: str, d: int):
    """Parse a whole xbox dump natively → (keys, show, click, embed_w, mf)
    arrays, or None when the native library is unavailable.  Raises
    ValueError naming the malformed line index on bad input."""
    import os
    lib = _load()
    if lib is None:
        return None
    size = os.path.getsize(path)
    buf = bytearray(size + 1)     # one allocation, NUL-terminated in place
    with open(path, "rb") as f:
        got = f.readinto(memoryview(buf)[:size])
    if got != size:
        raise OSError(f"short read loading {path!r}")
    buf[size] = 0
    upper = buf.count(b"\n", 0, size) + (
        0 if size == 0 or buf[size - 1] == 0x0A else 1)
    upper = max(upper, 1)
    keys = np.empty((upper,), np.uint64)
    show = np.empty((upper,), np.float64)
    click = np.empty((upper,), np.float64)
    embed_w = np.empty((upper,), np.float64)
    mf = np.empty((upper, max(d, 1)), np.float32)
    cbuf = (ctypes.c_char * len(buf)).from_buffer(buf)
    ret = lib.pbox_load_xbox(cbuf, size, keys.ctypes.data,
                             show.ctypes.data, click.ctypes.data,
                             embed_w.ctypes.data, mf.ctypes.data,
                             upper, d)
    if ret < 0:
        raise ValueError(
            f"malformed xbox line {-int(ret)} in {path!r} "
            f"(expected key\\tshow\\tclick\\tembed_w\\t{d} mf values)")
    n = int(ret)
    return (keys[:n], show[:n], click[:n], embed_w[:n], mf[:n])
