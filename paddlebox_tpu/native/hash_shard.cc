// Native host hash shard: uint64 key → dense row id.
//
// TPU-native counterpart of the DRAM tier's per-shard hash map
// (reference: MemorySparseTable shards, ps/table/memory_sparse_table.h:39;
// GPU-side concurrent map hashtable.h:53).  Values stay in numpy SoA arrays
// owned by Python and indexed by the dense row ids this map hands out —
// the map only does key→row translation, so the C ABI stays tiny.
//
// Open addressing, power-of-two capacity, linear probing, 0.75 max load
// (the reference's load factor, hashtable.h:211).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr uint64_t kEmpty = 0xFFFFFFFFFFFFFFFFull;

inline uint64_t mix(uint64_t k) {
  // splitmix64 finalizer — full-avalanche for clustered feasigns
  k += 0x9E3779B97F4A7C15ull;
  k = (k ^ (k >> 30)) * 0xBF58476D1CE4E5B9ull;
  k = (k ^ (k >> 27)) * 0x94D049BB133111EBull;
  return k ^ (k >> 31);
}

struct HashShard {
  std::vector<uint64_t> keys;   // capacity slots, kEmpty = free
  std::vector<int64_t> rows;
  std::vector<uint64_t> by_row;  // row id → key
  uint64_t mask = 0;
  int64_t size = 0;

  explicit HashShard(int64_t hint) {
    int64_t cap = 16;
    while (cap * 3 < hint * 4) cap <<= 1;  // cap >= hint / 0.75
    keys.assign(static_cast<size_t>(cap), kEmpty);
    rows.assign(static_cast<size_t>(cap), -1);
    mask = static_cast<uint64_t>(cap - 1);
  }

  void grow() {
    std::vector<uint64_t> old_keys;
    std::vector<int64_t> old_rows;
    old_keys.swap(keys);
    old_rows.swap(rows);
    size_t cap = old_keys.size() * 2;
    keys.assign(cap, kEmpty);
    rows.assign(cap, -1);
    mask = cap - 1;
    for (size_t i = 0; i < old_keys.size(); ++i) {
      if (old_keys[i] == kEmpty) continue;
      uint64_t slot = mix(old_keys[i]) & mask;
      while (keys[slot] != kEmpty) slot = (slot + 1) & mask;
      keys[slot] = old_keys[i];
      rows[slot] = old_rows[i];
    }
  }

  int64_t upsert(uint64_t key) {
    if ((size + 1) * 4 > static_cast<int64_t>(keys.size()) * 3) grow();
    uint64_t slot = mix(key) & mask;
    while (true) {
      if (keys[slot] == key) return rows[slot];
      if (keys[slot] == kEmpty) {
        keys[slot] = key;
        rows[slot] = size;
        by_row.push_back(key);
        return size++;
      }
      slot = (slot + 1) & mask;
    }
  }

  int64_t find(uint64_t key) const {
    uint64_t slot = mix(key) & mask;
    while (true) {
      if (keys[slot] == key) return rows[slot];
      if (keys[slot] == kEmpty) return -1;
      slot = (slot + 1) & mask;
    }
  }
};

}  // namespace

extern "C" {

void* pbox_hash_new(int64_t capacity_hint) {
  return new HashShard(capacity_hint < 16 ? 16 : capacity_hint);
}

void pbox_hash_free(void* h) { delete static_cast<HashShard*>(h); }

int64_t pbox_hash_size(void* h) { return static_cast<HashShard*>(h)->size; }

void pbox_hash_upsert(void* h, const uint64_t* in_keys, int64_t n,
                      int64_t* out_rows) {
  auto* m = static_cast<HashShard*>(h);
  for (int64_t i = 0; i < n; ++i) out_rows[i] = m->upsert(in_keys[i]);
}

void pbox_hash_find(void* h, const uint64_t* in_keys, int64_t n,
                    int64_t* out_rows) {
  auto* m = static_cast<HashShard*>(h);
  for (int64_t i = 0; i < n; ++i) out_rows[i] = m->find(in_keys[i]);
}

void pbox_hash_keys(void* h, uint64_t* out) {
  auto* m = static_cast<HashShard*>(h);
  memcpy(out, m->by_row.data(), m->by_row.size() * sizeof(uint64_t));
}

// Pass-key translation hot path (≙ DedupKeysAndFillIdx,
// box_wrapper_impl.h:129, done once per pass): key → insertion-row + 1,
// missing/zero keys → 0 (the reserved zero-embedding row).  Read-only over
// the table, so lookups fan out over threads.
void pbox_hash_find_rows1_i32(void* h, const uint64_t* in_keys, int64_t n,
                              int32_t* out_rows, int32_t n_threads) {
  auto* m = static_cast<HashShard*>(h);
  if (n_threads < 1) n_threads = 1;
  auto work = [m, in_keys, out_rows](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      uint64_t k = in_keys[i];
      int64_t row = (k == 0) ? -1 : m->find(k);
      out_rows[i] = static_cast<int32_t>(row + 1);
    }
  };
  if (n_threads == 1 || n < (1 << 16)) {
    work(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t step = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * step;
    int64_t hi = lo + step < n ? lo + step : n;
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

}  // extern "C"
