"""Tensor-parallel layers.

≙ fleet/meta_parallel/parallel_layers/mp_layers.py — VocabParallelEmbedding
(:30), ColumnParallelLinear (:95), RowParallelLinear (:171),
ParallelCrossEntropy (:251).

TPU-first: layers are functional (init/apply) and come in two flavors that
share parameters:
* GSPMD flavor: ``param_specs()`` gives PartitionSpecs; apply() is plain
  dense math + ``with_sharding_constraint`` — XLA inserts the collectives
  the reference hand-writes (identity fwd/allreduce bwd etc.).
* shard_map flavor (``apply_sharded``): explicit per-device math with
  psum/all_gather, for use inside shard_map regions (and as the executable
  spec of what GSPMD should do).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


class ColumnParallelLinear:
    """Weight [in, out] split on out (≙ mp_layers.py:95: identity fwd,
    allreduce grad; optional gather of the column-sharded output)."""

    def __init__(self, in_dim: int, out_dim: int, use_bias: bool = True,
                 gather_output: bool = True, axis: str = "mp"):
        self.in_dim, self.out_dim = in_dim, out_dim
        self.use_bias = use_bias
        self.gather_output = gather_output
        self.axis = axis

    def init(self, key) -> Dict:
        bound = jnp.sqrt(6.0 / (self.in_dim + self.out_dim))
        w = jax.random.uniform(key, (self.in_dim, self.out_dim), jnp.float32,
                               -bound, bound)
        p = {"w": w}
        if self.use_bias:
            p["b"] = jnp.zeros((self.out_dim,), jnp.float32)
        return p

    def param_specs(self) -> Dict:
        spec = {"w": P(None, self.axis)}
        if self.use_bias:
            spec["b"] = P(self.axis)
        return spec

    def apply(self, params, x):
        y = x @ params["w"]
        if self.use_bias:
            y = y + params["b"]
        return y

    def apply_sharded(self, params_local, x):
        """Inside shard_map: params_local is the [in, out/mp] shard; x is
        replicated along mp. → local [B, out/mp] (gather if configured)."""
        y = x @ params_local["w"]
        if self.use_bias:
            y = y + params_local["b"]
        if self.gather_output:
            y = lax.all_gather(y, self.axis, axis=y.ndim - 1, tiled=True)
        return y


class RowParallelLinear:
    """Weight [in, out] split on in; partial products psum-reduced
    (≙ mp_layers.py:171)."""

    def __init__(self, in_dim: int, out_dim: int, use_bias: bool = True,
                 input_is_parallel: bool = False, axis: str = "mp"):
        self.in_dim, self.out_dim = in_dim, out_dim
        self.use_bias = use_bias
        self.input_is_parallel = input_is_parallel
        self.axis = axis

    def init(self, key) -> Dict:
        bound = jnp.sqrt(6.0 / (self.in_dim + self.out_dim))
        w = jax.random.uniform(key, (self.in_dim, self.out_dim), jnp.float32,
                               -bound, bound)
        p = {"w": w}
        if self.use_bias:
            p["b"] = jnp.zeros((self.out_dim,), jnp.float32)
        return p

    def param_specs(self) -> Dict:
        spec = {"w": P(self.axis, None)}
        if self.use_bias:
            spec["b"] = P()  # bias added once after the reduce
        return spec

    def apply(self, params, x):
        y = x @ params["w"]
        if self.use_bias:
            y = y + params["b"]
        return y

    def apply_sharded(self, params_local, x):
        if not self.input_is_parallel:
            # split replicated input along features to match the row shard
            idx = lax.axis_index(self.axis)
            shard = params_local["w"].shape[0]
            x = lax.dynamic_slice_in_dim(x, idx * shard, shard, x.ndim - 1)
        y = lax.psum(x @ params_local["w"], self.axis)
        if self.use_bias:
            y = y + params_local["b"]
        return y


class VocabParallelEmbedding:
    """Embedding [vocab, dim] row-split over mp; out-of-shard rows contribute
    zeros, psum combines (≙ mp_layers.py:30-92 mask + allreduce)."""

    def __init__(self, vocab: int, dim: int, axis: str = "mp"):
        assert vocab > 0 and dim > 0
        self.vocab, self.dim = vocab, dim
        self.axis = axis

    def init(self, key) -> Dict:
        return {"w": jax.random.normal(key, (self.vocab, self.dim),
                                       jnp.float32) * 0.02}

    def param_specs(self) -> Dict:
        return {"w": P(self.axis, None)}

    def apply(self, params, ids):
        return params["w"][ids]

    def apply_sharded(self, params_local, ids):
        shard = params_local["w"].shape[0]
        start = lax.axis_index(self.axis) * shard
        local = ids - start
        in_range = (local >= 0) & (local < shard)
        local = jnp.clip(local, 0, shard - 1)
        emb = params_local["w"][local] * in_range[..., None]
        return lax.psum(emb, self.axis)


def parallel_cross_entropy(logits_local: jnp.ndarray, labels: jnp.ndarray,
                           axis: str = "mp") -> jnp.ndarray:
    """Softmax CE over class-sharded logits without materializing the full
    row (≙ ParallelCrossEntropy mp_layers.py:251 / c_softmax_with_
    cross_entropy_op): max/sum-exp/target-logit each combined by collectives.
    Use inside shard_map with logits split on the last dim."""
    n_local = logits_local.shape[-1]
    start = lax.axis_index(axis) * n_local
    gmax = lax.pmax(jnp.max(logits_local, -1), axis)
    z = jnp.exp(logits_local - gmax[..., None])
    denom = lax.psum(jnp.sum(z, -1), axis)
    local_label = labels - start
    in_range = (local_label >= 0) & (local_label < n_local)
    picked = jnp.take_along_axis(
        logits_local, jnp.clip(local_label, 0, n_local - 1)[..., None],
        axis=-1)[..., 0]
    target = lax.psum(jnp.where(in_range, picked, 0.0), axis)
    return jnp.log(denom) + gmax - target
