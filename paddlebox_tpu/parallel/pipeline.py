"""Pipeline parallelism — SPMD microbatch pipeline over the ``pp`` mesh axis.

≙ the reference's two pipeline engines: dygraph PipelineParallel 1F1B
(meta_parallel/pipeline_parallel.py:82, p2p send/recv :106-137) and the
static-graph SectionWorker schedules (section_worker.cc:149-213, GPipe-ish
mode 0 / 1F1B mode 1), plus the PipelineLayer partitioner
(parallel_layers/pp_layers.py).

TPU-first design: instead of per-rank processes exchanging tensors with
send/recv, ALL stages run in one SPMD program inside shard_map — stage
parameters are stacked [pp, ...] and sharded over the pp axis, activations
hop stage→stage via ``lax.ppermute`` (ICI neighbor), and the whole
(microbatches + bubble) schedule is a ``lax.scan``.  Because ppermute/scan
are differentiable, ``jax.grad`` of the pipelined forward IS the backward
pipeline (reverse schedule runs automatically) — no hand-written 1F1B state
machine; XLA overlaps the permute with compute.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def _f1b_tick(pp, s, M, cap, axis, params, fwd_fn, feed_of, loss_and_dy,
              carry, t):
    """One 1F1B tick, shared by the homogeneous and heterogeneous runners:
    stage s forwards microbatch t - s and backwards t - (2*pp - 2 - s),
    recomputing the forward from the stashed INPUT (recompute-in-backward),
    with activations hopping +1 and gradients -1 over the pp ring.

    fwd_fn(params, x) -> y on the runner's activation representation;
    feed_of(m) -> stage-0 input for microbatch m; loss_and_dy(y, m) ->
    (loss scalar, dL/dy) for the last stage.
    """
    perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]
    perm_bwd = [(i, (i - 1) % pp) for i in range(pp)]
    y_send, g_send, stash, g_acc, loss_acc = carry
    x_in = lax.ppermute(y_send, axis, perm_fwd)
    g_in = lax.ppermute(g_send, axis, perm_bwd)

    m_f = t - s
    m_b = t - (2 * pp - 2 - s)
    do_f = (m_f >= 0) & (m_f < M)
    do_b = (m_b >= 0) & (m_b < M)

    # ---- forward of microbatch m_f ----------------------------------------
    x_f = jnp.where(s == 0, feed_of(jnp.clip(m_f, 0, M - 1)), x_in)
    y_f = fwd_fn(params, x_f)
    y_send_new = jnp.where(do_f, y_f, y_send)
    slot_f = jnp.clip(m_f, 0, M - 1) % cap
    stash = lax.dynamic_update_index_in_dim(
        stash, jnp.where(do_f, x_f, stash[slot_f]), slot_f, 0)

    # ---- backward of m_b (recompute from stashed input) -------------------
    mb_c = jnp.clip(m_b, 0, M - 1)
    x_b = stash[mb_c % cap]
    y_b, pull = jax.vjp(fwd_fn, params, x_b)
    loss_val, dy_last = loss_and_dy(y_b, mb_c)
    dy = jnp.where(s == pp - 1, dy_last, g_in)
    d_params, d_x = pull(dy)
    g_acc = jax.tree.map(
        lambda a, d: a + jnp.where(do_b, d, jnp.zeros_like(d)),
        g_acc, d_params)
    g_send_new = jnp.where(do_b, d_x, g_send)
    loss_acc = loss_acc + jnp.where(do_b & (s == pp - 1), loss_val, 0.0)
    return (y_send_new, g_send_new, stash, g_acc, loss_acc), None


class PipelineRunner:
    """Run ``stage_fn`` (same signature per stage) as a pp-deep pipeline.

    stage_fn(stage_params, x) -> y, with x/y of identical shape (the classic
    homogeneous-stage contract the reference's SegmentLayers also assumes).
    """

    def __init__(self, stage_fn: Callable, n_stages: int, axis: str = "pp"):
        self.stage_fn = stage_fn
        self.n_stages = n_stages
        self.axis = axis

    def __call__(self, params_local, microbatches: jnp.ndarray) -> jnp.ndarray:
        """Inside shard_map.  params_local: this device's stage params
        (leading [1, ...] stage dim from the pp-sharded stack).
        microbatches: [M, Bm, ...] (replicated).  Returns [M, Bm, ...] —
        valid on the last stage (replicated back via ppermute broadcast).
        """
        pp, axis = self.n_stages, self.axis
        idx = lax.axis_index(axis)
        M = microbatches.shape[0]
        ticks = M + pp - 1
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]
        params_local = jax.tree.map(lambda a: a[0], params_local)

        x0 = jnp.zeros_like(microbatches[0])

        def tick(carry, t):
            prev_out = carry
            # activation from the previous stage (stage 0 receives garbage
            # from the wrap-around edge and ignores it)
            incoming = lax.ppermute(prev_out, axis, perm_fwd)
            feed = microbatches[jnp.minimum(t, M - 1)]
            x = jnp.where(idx == 0, feed, incoming)
            y = self.stage_fn(params_local, x)
            return y, y

        _, ys = lax.scan(tick, x0, jnp.arange(ticks))
        # last stage emitted microbatch m at tick m + pp - 1
        out = ys[pp - 1:]
        # broadcast result from the last stage to all (so loss is replicated)
        mask = (idx == pp - 1).astype(out.dtype)
        return lax.psum(out * mask, axis)


class PipelineRunner1F1B:
    """1F1B schedule (≙ SectionWorker schedule_mode=1, section_worker.cc:149
    and dygraph PipelineParallel.forward_backward_pipeline,
    pipeline_parallel.py:82): activation stash bounded by O(pp) — constant
    in the microbatch count — unlike the autodiff GPipe runner above whose
    scan saves every tick.

    SPMD formulation: one scan over T = M + 2*pp - 2 ticks; at tick t stage
    s forwards microbatch ``t - s`` and backwards microbatch
    ``t - (2*pp - 2 - s)`` (the last stage backs a microbatch immediately
    after forwarding it).  Backward recomputes the stage forward from the
    stashed *input* (recompute-in-backward, the memory-cheap 1F1B variant),
    so the stash holds at most 2*pp microbatch inputs.  Activations hop via
    ppermute(+1), gradients via ppermute(-1).

    Because the schedule runs its own backward, this runner is not meant to
    be differentiated — it *returns* (mean loss, per-stage param grads).
    """

    def __init__(self, stage_fn: Callable, loss_fn: Callable, n_stages: int,
                 axis: str = "pp"):
        self.stage_fn = stage_fn      # (stage_params, x) -> y, same shape
        self.loss_fn = loss_fn        # (y, target_mb) -> scalar (sum-able)
        self.n_stages = n_stages
        self.axis = axis

    def __call__(self, params_local, microbatches: jnp.ndarray,
                 targets: jnp.ndarray):
        """Inside shard_map.  params_local: [1, ...] stage params slice;
        microbatches [M, Bm, ...], targets [M, ...] (both replicated).
        → (mean loss over microbatches, param grads [1, ...])."""
        pp, axis = self.n_stages, self.axis
        s = lax.axis_index(axis)
        M = microbatches.shape[0]
        ticks = M + 2 * pp - 2
        cap = 2 * pp                          # stash slots (≥ max in-flight)
        params = jax.tree.map(lambda a: a[0], params_local)

        x_shape = microbatches[0]
        stash0 = jnp.zeros((cap,) + x_shape.shape, x_shape.dtype)
        g_acc0 = jax.tree.map(jnp.zeros_like, params)

        def loss_and_dy(y_b, mb_c):
            return jax.value_and_grad(self.loss_fn)(y_b, targets[mb_c])

        def tick(carry, t):
            return _f1b_tick(pp, s, M, cap, axis, params, self.stage_fn,
                             lambda m: microbatches[m], loss_and_dy,
                             carry, t)

        init = (jnp.zeros_like(x_shape), jnp.zeros_like(x_shape), stash0,
                g_acc0, jnp.float32(0.0))
        (_, _, _, g_acc, loss_acc), _ = lax.scan(tick, init,
                                                 jnp.arange(ticks))
        # loss lives on the last stage; replicate it
        loss = lax.psum(jnp.where(s == pp - 1, loss_acc, 0.0), axis) / M
        grads = jax.tree.map(lambda a: a[None] / M, g_acc)
        return loss, grads


class HeteroPipeline1F1B:
    """1F1B over HETEROGENEOUS stages — per-stage functions, param pytrees
    and activation shapes (≙ SectionWorker's per-section programs +
    schedule_mode=1, section_worker.cc:149-213, where each section runs its
    own sub-program; the reference's stages are arbitrary program slices,
    not copies of one block).

    TPU-first formulation: XLA has no MPMD inside one jit, so every device
    runs the SAME scan and selects its stage body with ``lax.switch``;
    activations cross stages through a fixed-size flattened pad buffer
    (ppermute needs one static shape), and each branch un/re-flattens its
    own signature.  Params travel as a tuple of per-stage pytrees; a device
    produces gradients only for the branch it executes, and one psum at the
    end assembles the full grad tree.  The stash is bounded at 2*pp
    microbatch INPUTS (recompute-in-backward) — constant in M, the 1F1B
    memory contract.

    Note on memory: params are replicated across pp devices here (shapes
    differ per stage, so they cannot shard as one stacked array).  For
    memory-bound homogeneous pipelines use PipelineRunner1F1B, which shards
    the stacked params over pp.
    """

    def __init__(self, stage_fns: Sequence[Callable],
                 io_shapes: Sequence[tuple], loss_fn: Callable,
                 axis: str = "pp"):
        """stage_fns[s](params_s, x_s) -> y_s; io_shapes is the chain
        [shape_0, shape_1, ..., shape_pp] with shape_s = stage s's input
        microbatch shape and shape_pp the final output shape."""
        self.stage_fns = list(stage_fns)
        self.io_shapes = [tuple(s) for s in io_shapes]
        self.loss_fn = loss_fn
        self.axis = axis
        self.n_stages = len(self.stage_fns)
        assert len(self.io_shapes) == self.n_stages + 1
        self._sizes = [int(np.prod(s)) for s in self.io_shapes]
        self.buf_len = max(self._sizes)

    # -- pad-buffer plumbing ------------------------------------------------
    def _unflatten(self, buf, shape):
        return buf[: int(np.prod(shape))].reshape(shape)

    def _flatten(self, y):
        flat = y.reshape(-1)
        return jnp.concatenate(
            [flat, jnp.zeros((self.buf_len - flat.shape[0],), flat.dtype)])

    def _fwd(self, s, params_all, x_buf):
        """switch over stage bodies: buf -> buf."""
        branches = []
        for i, fn in enumerate(self.stage_fns):
            def branch(args, i=i, fn=fn):
                p_all, buf = args
                x = self._unflatten(buf, self.io_shapes[i])
                return self._flatten(fn(p_all[i], x))
            branches.append(branch)
        return lax.switch(s, branches, (params_all, x_buf))

    def __call__(self, params_all, microbatches: jnp.ndarray,
                 targets: jnp.ndarray):
        """Inside shard_map.  params_all: tuple of per-stage pytrees
        (replicated); microbatches [M, *io_shapes[0]]; targets [M, ...].
        → (mean loss, full grad tuple — replicated)."""
        pp, axis = self.n_stages, self.axis
        s = lax.axis_index(axis)
        M = microbatches.shape[0]
        ticks = M + 2 * pp - 2
        cap = 2 * pp   # 1F1B in-flight bound: constant in M
        out_shape = self.io_shapes[-1]
        loss_fn = self.loss_fn
        dtype = microbatches.dtype   # buffers follow the activation dtype

        def fwd_fn(p_all, x_buf):
            return self._fwd(s, p_all, x_buf)

        stash0 = jnp.zeros((cap, self.buf_len), dtype)
        g_acc0 = jax.tree.map(jnp.zeros_like, params_all)
        zero_buf = jnp.zeros((self.buf_len,), dtype)

        def loss_and_dy(y_b, mb_c):
            def loss_of_buf(yb):
                return loss_fn(self._unflatten(yb, out_shape),
                               targets[mb_c])
            return jax.value_and_grad(loss_of_buf)(y_b)

        def tick(carry, t):
            return _f1b_tick(
                pp, s, M, cap, axis, params_all, fwd_fn,
                lambda m: self._flatten(microbatches[m]), loss_and_dy,
                carry, t)

        init = (zero_buf, zero_buf, stash0, g_acc0, jnp.float32(0.0))
        (_, _, _, g_acc, loss_acc), _ = lax.scan(tick, init,
                                                 jnp.arange(ticks))
        # each device holds grads for ITS stage only; one psum assembles
        # the full tuple everywhere (≙ the section programs' param grads
        # living on their own devices — replication is the SPMD cost)
        grads = jax.tree.map(lambda a: lax.psum(a, axis) / M, g_acc)
        loss = lax.psum(jnp.where(s == pp - 1, loss_acc, 0.0), axis) / M
        return loss, grads

    @property
    def stash_slots(self) -> int:
        """In-flight activation bound: 2*pp microbatch inputs, independent
        of M (the 1F1B memory contract vs GPipe's O(M))."""
        return 2 * self.n_stages


def stack_stage_params(per_stage_params: Sequence) -> object:
    """[pp] list of identical pytrees → stacked pytree with leading stage
    dim (shard over pp with PartitionSpec('pp', ...))."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def segment_layers(n_layers: int, n_stages: int) -> List[int]:
    """≙ SegmentLayers uniform partition (pp_layers.py): layer counts per
    stage, remainder spread to the earliest stages."""
    base = n_layers // n_stages
    rem = n_layers % n_stages
    return [base + (1 if i < rem else 0) for i in range(n_stages)]
