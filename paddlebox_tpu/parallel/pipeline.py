"""Pipeline parallelism — SPMD microbatch pipeline over the ``pp`` mesh axis.

≙ the reference's two pipeline engines: dygraph PipelineParallel 1F1B
(meta_parallel/pipeline_parallel.py:82, p2p send/recv :106-137) and the
static-graph SectionWorker schedules (section_worker.cc:149-213, GPipe-ish
mode 0 / 1F1B mode 1), plus the PipelineLayer partitioner
(parallel_layers/pp_layers.py).

TPU-first design: instead of per-rank processes exchanging tensors with
send/recv, ALL stages run in one SPMD program inside shard_map — stage
parameters are stacked [pp, ...] and sharded over the pp axis, activations
hop stage→stage via ``lax.ppermute`` (ICI neighbor), and the whole
(microbatches + bubble) schedule is a ``lax.scan``.  Because ppermute/scan
are differentiable, ``jax.grad`` of the pipelined forward IS the backward
pipeline (reverse schedule runs automatically) — no hand-written 1F1B state
machine; XLA overlaps the permute with compute.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp
from jax import lax


class PipelineRunner:
    """Run ``stage_fn`` (same signature per stage) as a pp-deep pipeline.

    stage_fn(stage_params, x) -> y, with x/y of identical shape (the classic
    homogeneous-stage contract the reference's SegmentLayers also assumes).
    """

    def __init__(self, stage_fn: Callable, n_stages: int, axis: str = "pp"):
        self.stage_fn = stage_fn
        self.n_stages = n_stages
        self.axis = axis

    def __call__(self, params_local, microbatches: jnp.ndarray) -> jnp.ndarray:
        """Inside shard_map.  params_local: this device's stage params
        (leading [1, ...] stage dim from the pp-sharded stack).
        microbatches: [M, Bm, ...] (replicated).  Returns [M, Bm, ...] —
        valid on the last stage (replicated back via ppermute broadcast).
        """
        pp, axis = self.n_stages, self.axis
        idx = lax.axis_index(axis)
        M = microbatches.shape[0]
        ticks = M + pp - 1
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]
        params_local = jax.tree.map(lambda a: a[0], params_local)

        x0 = jnp.zeros_like(microbatches[0])

        def tick(carry, t):
            prev_out = carry
            # activation from the previous stage (stage 0 receives garbage
            # from the wrap-around edge and ignores it)
            incoming = lax.ppermute(prev_out, axis, perm_fwd)
            feed = microbatches[jnp.minimum(t, M - 1)]
            x = jnp.where(idx == 0, feed, incoming)
            y = self.stage_fn(params_local, x)
            return y, y

        _, ys = lax.scan(tick, x0, jnp.arange(ticks))
        # last stage emitted microbatch m at tick m + pp - 1
        out = ys[pp - 1:]
        # broadcast result from the last stage to all (so loss is replicated)
        mask = (idx == pp - 1).astype(out.dtype)
        return lax.psum(out * mask, axis)


def stack_stage_params(per_stage_params: Sequence) -> object:
    """[pp] list of identical pytrees → stacked pytree with leading stage
    dim (shard over pp with PartitionSpec('pp', ...))."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def segment_layers(n_layers: int, n_stages: int) -> List[int]:
    """≙ SegmentLayers uniform partition (pp_layers.py): layer counts per
    stage, remainder spread to the earliest stages."""
    base = n_layers // n_stages
    rem = n_layers % n_stages
    return [base + (1 if i < rem else 0) for i in range(n_stages)]
