"""Collective primitives.

≙ distributed/collective/ProcessGroup.h:53-190 (AllReduce/Broadcast/AllGather/
AllToAll/ReduceScatter/Send/Recv) — but as jax named-axis collectives usable
inside ``shard_map``/``pjit``-traced code, riding ICI instead of NCCL.  The
reference's explicit P2P "walk paths" (heter_comm.h:303) map to
``lax.ppermute``; its MoE global_scatter/global_gather map to
``lax.all_to_all``.

The second half is the HOST-side trainer-fleet collective
(:class:`FleetCollective` — ≙ GlooWrapper/boxps::MPICluster): barriers
and dense-state reduction between trainer PROCESSES, riding the PS tier's
rid-dedup'd barrier/dense verbs so every operation is replay-safe across
a trainer crash + supervisor restart.  PB604 discipline applies here the
same as to locks: every wait carries a deadline, and expiry raises the
typed :class:`PeerDead` instead of hanging the fleet.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P
try:
    from jax import shard_map
    _SHARD_MAP_KW = "check_vma"
except ImportError:     # pre-0.6 jax ships it under experimental
    from jax.experimental.shard_map import shard_map
    _SHARD_MAP_KW = "check_rep"

from paddlebox_tpu import flags
from paddlebox_tpu.utils.monitor import stat_add, stat_observe

flags.define_flag(
    "fleet_deadline_s", 180.0,
    "total budget for any one trainer-fleet collective wait (barrier / "
    "dense fold); a peer absent past this raises PeerDead — sized to "
    "ride out one supervisor restart (backoff + resume replay)")

Axis = Union[str, Sequence[str]]


class PeerDead(ConnectionError):
    """A fleet peer stayed absent from a collective past the deadline."""


def namespaced_group(base: str, rank: Optional[int], tail: str) -> str:
    """Sanctioned rid-group constructor for fleet/trainer code (pboxlint
    PB806): ``<base>.t<rank>:<tail>``.  The text before the colon is the
    server dedup window's token, so all of one trainer's chunk rids share
    one window — and distinct ranks NEVER share one, which is what makes
    per-trainer replay exactly-once (rank r's re-driven chunks can only
    dedup against rank r's own landed chunks).

    ``rank=None`` is the leader-lifecycle namespace (``<base>:<tail>``):
    verbs that must be exactly-once across a leader FAILOVER (end_day)
    pin one group independent of which rank drives them.
    """
    tok = base if rank is None else f"{base}.t{rank}"
    return f"{tok}:{tail}"


class FleetCollective:
    """Replay-safe barriers + deterministic dense reduction for the
    trainer fleet, over a PSClient.

    Every barrier rid is deterministic in (rank, tag) — a restarted
    trainer re-driving its pass replays the SAME rids, so barriers it
    already joined answer from the dedup window and barriers the fleet
    is still waiting on get its registration exactly once.  Calls retry
    under FLAGS_fleet_deadline_s (riding out a peer's supervisor
    restart), with an optional ``poke`` callback between attempts — the
    runner's leader-duty hook, so a rank waiting on a dead leader can
    take over its lifecycle work instead of deadlocking.
    """

    def __init__(self, client, rank: int, world: int,
                 namespace: str = "fleet",
                 deadline_s: Optional[float] = None):
        self.client = client
        self.rank = int(rank)
        self.world = int(world)
        self.namespace = namespace
        self.deadline_s = (float(flags.get_flags("fleet_deadline_s"))
                           if deadline_s is None else float(deadline_s))

    def _rid(self, kind: str, tag: str) -> str:
        return namespaced_group(self.namespace, self.rank,
                                f"{kind}.{tag}")

    def _retry(self, tag: str, fn: Callable[[], None],
               poke: Optional[Callable[[], None]]) -> None:
        deadline = time.monotonic() + self.deadline_s
        while True:
            try:
                fn()
                return
            except ConnectionError:
                pass
            except RuntimeError as e:
                # the PS barrier window rolled back (a peer absent for
                # its 60s wait) — same remedy as a dropped connection:
                # re-drive the SAME rid until the fleet deadline
                if "timeout" not in str(e) and "timed out" not in str(e):
                    raise
            if time.monotonic() >= deadline:
                raise PeerDead(
                    f"fleet collective {tag!r} incomplete after "
                    f"{self.deadline_s:.0f}s — a peer is gone past the "
                    f"restart budget")
            stat_add("trainer.fleet.collective_retries")
            if poke is not None:
                poke()

    def barrier(self, tag: str, timeout: float = 20.0,
                poke: Optional[Callable[[], None]] = None) -> None:
        """Fleet-wide barrier named by ``tag`` (deterministic rid —
        replayable).  All ranks must pass the same sequence of barriers
        (the PS barrier is generation-matched by arrival order)."""
        t0 = time.monotonic()
        self._retry(tag, lambda: self.client.barrier(
            self.world, timeout=timeout, rid=self._rid("bar", tag)), poke)
        stat_observe("trainer.fleet.barrier_wait_s",
                     time.monotonic() - t0)

    def allreduce(self, arrs: Dict[str, np.ndarray], tag: str,
                  timeout: float = 20.0,
                  poke: Optional[Callable[[], None]] = None
                  ) -> Dict[str, np.ndarray]:
        """Cross-rank sum via the PS allreduce verb, deadline-bounded and
        replay-safe (deterministic rid).  NOTE: the server folds
        contributions in ARRIVAL order — use only where fp association
        order doesn't matter (counters, diagnostics).  Bit-critical
        folds go through :meth:`reduce_slots`."""
        t0 = time.monotonic()
        out: List[Dict[str, np.ndarray]] = []
        self._retry(tag, lambda: out.append(self.client.allreduce(
            arrs, self.world, key=tag, timeout=timeout,
            rid=self._rid("ar", tag))), poke)
        stat_observe("trainer.fleet.allreduce_wait_s",
                     time.monotonic() - t0)
        return out[-1]

    def reduce_slots(self, prefix: str, mine: Dict[int, np.ndarray],
                     n_slots: int, tag: str,
                     poke: Optional[Callable[[], None]] = None
                     ) -> List[np.ndarray]:
        """Deterministic fleet reduction: each rank publishes its owned
        slots (absolute dense writes — idempotent under restart replay),
        a barrier fences publication, then EVERY rank reads all slots in
        slot order.  The caller folds in that fixed order, so the fp
        operation sequence is identical at any fleet size — the property
        the PS allreduce verb (arrival-order summation) cannot give.
        This is the fleet's dense-grad sync path."""
        t0 = time.monotonic()
        for v in sorted(mine):
            vec = np.asarray(mine[v])
            self._retry(f"{tag}.push.{v}",
                        lambda vec=vec, v=v: self.client.push_dense(
                            f"{prefix}.{v}", vec), poke)
        self.barrier(f"{tag}.fence", poke=poke)
        out: List[np.ndarray] = []
        for v in range(n_slots):
            got: List[np.ndarray] = []
            self._retry(f"{tag}.pull.{v}",
                        lambda v=v: got.append(self.client.pull_dense(
                            f"{prefix}.{v}")), poke)
            out.append(got[-1])
        stat_observe("trainer.fleet.allreduce_wait_s",
                     time.monotonic() - t0)
        return out


def all_reduce(x, axis: Axis, op: str = "sum"):
    if op == "sum":
        return lax.psum(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    raise ValueError(f"unsupported all_reduce op: {op}")


def all_gather(x, axis: Axis, *, concat_dim: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis, axis=concat_dim, tiled=tiled)


def all_to_all(x, axis: Axis, *, split_dim: int = 0, concat_dim: int = 0,
               tiled: bool = True):
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=tiled)


def reduce_scatter(x, axis: Axis, *, scatter_dim: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def ppermute(x, axis: Axis, perm):
    return lax.ppermute(x, axis, perm)


def axis_index(axis: Axis):
    return lax.axis_index(axis)


def shift_right(x, axis: str, axis_size: int):
    """Ring shift: device i sends to i+1 (mod n). Building block of ring
    attention / pipelined CP (no reference equivalent — SURVEY.md §2.7)."""
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    return lax.ppermute(x, axis, perm)


def shard_mapped(mesh, in_specs, out_specs, check_vma: bool = False):
    """Decorator shorthand for shard_map over the framework mesh."""
    def wrap(fn):
        kw = {_SHARD_MAP_KW: check_vma}
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, **kw)
    return wrap
