"""Collective primitives.

≙ distributed/collective/ProcessGroup.h:53-190 (AllReduce/Broadcast/AllGather/
AllToAll/ReduceScatter/Send/Recv) — but as jax named-axis collectives usable
inside ``shard_map``/``pjit``-traced code, riding ICI instead of NCCL.  The
reference's explicit P2P "walk paths" (heter_comm.h:303) map to
``lax.ppermute``; its MoE global_scatter/global_gather map to
``lax.all_to_all``.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from jax import shard_map

Axis = Union[str, Sequence[str]]


def all_reduce(x, axis: Axis, op: str = "sum"):
    if op == "sum":
        return lax.psum(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    raise ValueError(f"unsupported all_reduce op: {op}")


def all_gather(x, axis: Axis, *, concat_dim: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis, axis=concat_dim, tiled=tiled)


def all_to_all(x, axis: Axis, *, split_dim: int = 0, concat_dim: int = 0,
               tiled: bool = True):
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=tiled)


def reduce_scatter(x, axis: Axis, *, scatter_dim: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def ppermute(x, axis: Axis, perm):
    return lax.ppermute(x, axis, perm)


def axis_index(axis: Axis):
    return lax.axis_index(axis)


def shift_right(x, axis: str, axis_size: int):
    """Ring shift: device i sends to i+1 (mod n). Building block of ring
    attention / pipelined CP (no reference equivalent — SURVEY.md §2.7)."""
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    return lax.ppermute(x, axis, perm)


def shard_mapped(mesh, in_specs, out_specs, check_vma: bool = False):
    """Decorator shorthand for shard_map over the framework mesh."""
    def wrap(fn):
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=check_vma)
    return wrap
