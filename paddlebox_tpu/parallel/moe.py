"""Mixture-of-Experts with expert parallelism.

≙ python/paddle/incubate/distributed/models/moe/: MoELayer (moe_layer.py:244)
with MoEScatter/MoEGather over global_scatter/global_gather all2all ops
(:88-151), and the gate zoo (models/moe/gate/): naive, switch (top-1),
gshard (top-2 + aux load-balance loss).

TPU-first formulation: the einsum dispatch/combine form — tokens one-hot
into [E, C] capacity buckets, ``lax.all_to_all`` over the ``ep`` axis moves
expert shards (exactly the reference's global_scatter), experts run batched
matmuls on [E_local, n*C, d] (MXU-friendly), then the inverse path.  No
sorting, no dynamic shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


# -- gates (≙ models/moe/gate/{naive,switch,gshard}_gate.py) ---------------

def top1_gate(logits: jnp.ndarray, capacity: int):
    """Switch-style top-1 routing → (dispatch [T,E,C], combine [T,E,C],
    aux_loss).  T = local tokens, E = global experts."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, -1)
    expert = jnp.argmax(probs, -1)                       # [T]
    onehot = jax.nn.one_hot(expert, E, dtype=probs.dtype)
    # position of each token within its expert's capacity bucket
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0      # [T,E]
    keep = (pos >= 0) & (pos < capacity)
    pos_c = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    dispatch = (jax.nn.one_hot(pos_c, capacity, dtype=probs.dtype)
                * keep[..., None] * onehot[..., None])   # [T,E,C]
    gate_val = jnp.sum(probs * onehot, -1)               # [T]
    combine = dispatch * gate_val[:, None, None]
    # switch aux loss: E * sum(fraction_tokens * fraction_probs)
    me = jnp.mean(onehot, axis=0)
    ce = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(me * ce)
    return dispatch, combine, aux


def top2_gate(logits: jnp.ndarray, capacity: int):
    """GShard top-2 gate (second expert weighted, shared capacity)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, -1)
    e1 = jnp.argmax(probs, -1)
    oh1 = jax.nn.one_hot(e1, E, dtype=probs.dtype)
    probs2 = probs * (1 - oh1)
    e2 = jnp.argmax(probs2, -1)
    oh2 = jax.nn.one_hot(e2, E, dtype=probs.dtype)
    g1 = jnp.sum(probs * oh1, -1)
    g2 = jnp.sum(probs * oh2, -1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    pos1 = jnp.cumsum(oh1, 0) * oh1 - 1.0
    # second choices queue behind all first choices of the same expert
    pos2 = (jnp.cumsum(oh2, 0) + jnp.sum(oh1, 0, keepdims=True)) * oh2 - 1.0

    def build(oh, pos, gate_val):
        keep = (pos >= 0) & (pos < capacity)
        pc = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
        d = (jax.nn.one_hot(pc, capacity, dtype=probs.dtype)
             * keep[..., None] * oh[..., None])
        return d, d * gate_val[:, None, None]

    d1, c1 = build(oh1, pos1, g1)
    d2, c2 = build(oh2, pos2, g2)
    me = jnp.mean(oh1, 0)
    ce = jnp.mean(probs, 0)
    aux = E * jnp.sum(me * ce)
    return d1 + d2, c1 + c2, aux


GATES = {"switch": top1_gate, "gshard": top2_gate, "naive": top1_gate}


# -- expert-parallel layer --------------------------------------------------

@dataclasses.dataclass
class MoEConfig:
    d_model: int
    d_hidden: int
    num_experts: int          # global expert count (divisible by ep size)
    capacity_factor: float = 1.25
    gate: str = "gshard"


class MoELayer:
    """Call apply_sharded inside shard_map with tokens sharded over `ep`.

    params["experts"]: w1 [E, d, h], b1 [E, h], w2 [E, h, d], b2 [E, d] —
    expert dim sharded over ep; params["gate"]: [d, E] replicated.
    """

    def __init__(self, config: MoEConfig, axis: str = "ep"):
        self.cfg = config
        self.axis = axis

    def init(self, key) -> Dict:
        c = self.cfg
        k1, k2, k3 = jax.random.split(key, 3)
        s1 = (6.0 / (c.d_model + c.d_hidden)) ** 0.5
        return {
            "gate": jax.random.normal(k3, (c.d_model, c.num_experts),
                                      jnp.float32) * 0.02,
            "w1": jax.random.uniform(k1, (c.num_experts, c.d_model,
                                          c.d_hidden), jnp.float32, -s1, s1),
            "b1": jnp.zeros((c.num_experts, c.d_hidden), jnp.float32),
            "w2": jax.random.uniform(k2, (c.num_experts, c.d_hidden,
                                          c.d_model), jnp.float32, -s1, s1),
            "b2": jnp.zeros((c.num_experts, c.d_model), jnp.float32),
        }

    def param_specs(self):
        from jax.sharding import PartitionSpec as P
        ax = self.axis
        return {"gate": P(), "w1": P(ax), "b1": P(ax),
                "w2": P(ax), "b2": P(ax)}

    def capacity(self, tokens_local: int, ep: int) -> int:
        c = self.cfg
        cap = int(self.cfg.capacity_factor * tokens_local * ep
                  / c.num_experts)
        return max(cap, 4)

    def apply_sharded(self, params_local, x, ep: int
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """x: [T_local, d].  params_local experts: [E/ep, ...].  Returns
        (y [T_local, d], aux_loss)."""
        c = self.cfg
        T, d = x.shape
        E = c.num_experts
        cap = self.capacity(T, ep)
        logits = x @ params_local["gate"]
        dispatch, combine, aux = GATES[c.gate](logits, cap)
        # local buckets per global expert [E, C, d]
        buckets = jnp.einsum("td,tec->ecd", x, dispatch)
        # ≙ global_scatter: all_to_all so each device holds its experts'
        # buckets from every peer: [E,C,d] → [E/ep, ep*C, d]
        # (global expert id = owner_device * e_loc + local_expert)
        e_loc = E // ep
        buckets = lax.all_to_all(buckets, self.axis, split_axis=0,
                                 concat_axis=1, tiled=True)
        h = jax.nn.relu(jnp.einsum("ecd,edh->ech", buckets,
                                   params_local["w1"])
                        + params_local["b1"][:, None, :])
        out = jnp.einsum("ech,ehd->ecd", h, params_local["w2"]) \
            + params_local["b2"][:, None, :]
        # ≙ global_gather: inverse all_to_all back to source devices
        out = lax.all_to_all(out, self.axis, split_axis=1, concat_axis=0,
                             tiled=True)  # [E, cap, d]
        y = jnp.einsum("ecd,tec->td", out, combine)
        return y, aux

    def apply_dense(self, params, x) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Unsharded golden path (all experts local) for tests."""
        c = self.cfg
        T, d = x.shape
        cap = self.capacity(T, 1)
        logits = x @ params["gate"]
        dispatch, combine, aux = GATES[c.gate](logits, cap)
        buckets = jnp.einsum("td,tec->ecd", x, dispatch)
        h = jax.nn.relu(jnp.einsum("ecd,edh->ech", buckets, params["w1"])
                        + params["b1"][:, None, :])
        out = jnp.einsum("ech,ehd->ecd", h, params["w2"]) \
            + params["b2"][:, None, :]
        y = jnp.einsum("ecd,tec->td", out, combine)
        return y, aux
