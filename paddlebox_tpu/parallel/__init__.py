from paddlebox_tpu.parallel.topology import HybridTopology  # noqa: F401
