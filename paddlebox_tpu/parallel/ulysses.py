"""Ulysses-style sequence parallelism: all_to_all head↔sequence reshard.

Absent from the reference (SURVEY.md §2.7) — TPU-first addition.  With the
sequence sharded over the ``sp`` axis, attention needs full sequence per
head; instead of gathering T, all_to_all swaps the sharded dimension:
[B, T/n, H, D] → [B, T, H/n, D], local full-sequence attention per head
subset, then the inverse all_to_all — two ICI all-to-alls instead of an
all-gather of activations (the MoE global_scatter/global_gather trick,
moe_layer.py:88-151, applied to attention heads).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
from jax import lax


def seq_to_heads(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """[B, T_local, H, D] → [B, T_global, H_local, D] (inside shard_map)."""
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def heads_to_seq(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """[B, T_global, H_local, D] → [B, T_local, H, D] (inside shard_map)."""
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis: str,
                      attn_fn: Optional[Callable] = None,
                      causal: bool = False) -> jnp.ndarray:
    """Sequence-parallel attention via head resharding (call in shard_map).

    q/k/v: [B, T_local, H, Dh] sequence-sharded on `axis`; H must be
    divisible by the axis size.  attn_fn defaults to the dense golden
    attention (ring_attention.reference_attention).
    """
    from paddlebox_tpu.parallel.ring_attention import reference_attention
    if attn_fn is None:
        attn_fn = lambda q, k, v: reference_attention(q, k, v, causal=causal)
    q = seq_to_heads(q, axis)
    k = seq_to_heads(k, axis)
    v = seq_to_heads(v, axis)
    out = attn_fn(q, k, v)           # [B, T_global, H_local, Dh]
    return heads_to_seq(out, axis)   # [B, T_local, H, Dh]
