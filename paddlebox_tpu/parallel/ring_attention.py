"""Ring attention — context parallelism over the sequence axis.

The reference has NO sequence/context parallelism (SURVEY.md §2.7: grep
verified absent) — this is a TPU-first addition designed to the same overlap
budget as HeterComm's shard-walk (§3.3): K/V blocks rotate around the mesh
axis via ``lax.ppermute`` (ICI neighbor hops) while each device accumulates
its queries' attention with a numerically-stable online softmax (flash-style
m/l running stats), so peak memory is O(T_local²) and comm overlaps compute.

Use inside shard_map with q/k/v sequence-sharded: [B, T/n, H, Dh] per device.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_attend(q, k, v, bias):
    # q [B,Tq,H,D], k/v [B,Tk,H,D] → scores [B,H,Tq,Tk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
    if bias is not None:
        scores = scores + bias
    return scores


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis: str, axis_size: int, causal: bool = False,
                   scale: Optional[float] = None) -> jnp.ndarray:
    """Per-device blockwise attention with rotating K/V (call in shard_map).

    q, k, v: [B, T_local, H, Dh]; returns [B, T_local, H, Dh].
    """
    B, Tl, H, Dh = q.shape
    if scale is None:
        scale = Dh ** -0.5
    q = q * scale
    my = lax.axis_index(axis)
    # positions of my queries (global)
    q_pos = my * Tl + jnp.arange(Tl)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, step_idx):
        k_blk, v_blk, m, l, acc = carry
        # the block currently held started at device (my - step) mod n
        src = (my - step_idx) % axis_size
        scores = _block_attend(q, k_blk, v_blk, None)  # [B,H,Tq,Tk]
        if causal:
            k_pos = src * Tl + jnp.arange(Tl)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1)              # [B,H,Tq]
        new_m = jnp.maximum(m, blk_max)
        # guard fully-masked blocks (−inf max)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        correction = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, -jnp.inf))
        correction = jnp.where(jnp.isfinite(m), correction, 0.0)
        l = l * correction + jnp.sum(p, -1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk)
        # rotate K/V to the next device
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return (k_blk, v_blk, new_m, l, acc), None

    m0 = jnp.full((B, H, Tl), -jnp.inf, q.dtype)
    l0 = jnp.zeros((B, H, Tl), q.dtype)
    acc0 = jnp.zeros((B, H, Tl, Dh), q.dtype)
    (k, v, m, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(axis_size))
    out = acc / jnp.maximum(l, 1e-30)[..., None]        # [B,H,Tq,Dh]
    return jnp.transpose(out, (0, 2, 1, 3))             # [B,Tq,H,Dh]


def reference_attention(q, k, v, causal: bool = False,
                        scale: Optional[float] = None):
    """Unsharded golden attention for tests."""
    B, T, H, Dh = q.shape
    if scale is None:
        scale = Dh ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bhqd", p, v)
    return jnp.transpose(out, (0, 2, 1, 3))
