"""Hybrid-parallel device topology over a jax Mesh.

TPU-native re-design of CommunicateTopology / HybridCommunicateGroup
(python/paddle/distributed/fleet/base/topology.py:52,134): instead of process
groups materialized from rank lists, we build one ``jax.sharding.Mesh`` with
named axes and express every parallelism as a PartitionSpec over those axes —
XLA inserts the collectives (SURVEY.md §5 "Distributed communication backend"
mapping: ICI mesh collectives ≙ NCCL rings).

Axis order is [dp, sharding, pp, mp, sp, ep] — the reference's 4-D mesh
(topology.py:141-144) extended with the sequence/context-parallel (sp) and
expert-parallel (ep) axes the reference lacks (SURVEY.md §2.7).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.config import MeshConfig

AXES: Tuple[str, ...] = ("dp", "sharding", "pp", "mp", "sp", "ep")


class HybridTopology:
    """≙ HybridCommunicateGroup (topology.py:134) on a jax Mesh."""

    def __init__(self, config: Optional[MeshConfig] = None,
                 devices: Optional[Sequence[jax.Device]] = None):
        self.config = config or MeshConfig()
        if devices is None:
            devices = jax.devices()
        degrees = [self.config.degrees()[a] for a in AXES]
        world = int(np.prod(degrees))
        if world != len(devices):
            raise ValueError(
                f"mesh degrees {dict(zip(AXES, degrees))} require {world} "
                f"devices, got {len(devices)}")
        dev_array = np.asarray(devices).reshape(degrees)
        self.mesh = Mesh(dev_array, AXES)

    # -- ≙ CommunicateTopology.get_dim / get_rank_from_stage ----------------
    def axis_size(self, axis: str) -> int:
        return self.mesh.shape[axis]

    @property
    def world_size(self) -> int:
        return self.mesh.size

    def coord(self, device: jax.Device) -> Tuple[int, ...]:
        idx = np.argwhere(self.mesh.devices == device)
        return tuple(int(i) for i in idx[0])

    # -- standard shardings -------------------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_spec(self) -> P:
        """Batch dim split over data-parallel-like axes (dp × sharding)."""
        return P(("dp", "sharding"))

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec())

    def table_spec(self) -> P:
        """Pass-working-set embedding rows sharded across non-pipeline
        devices — the TPU analogue of HeterComm's ``key % device_count``
        placement (heter_comm_inl.h:1117).

        With BOTH dp > 1 and sharding > 1 the layout flips to the
        reference's multi-node shape: sharded within a node (sharding =
        intra-node/ICI), REPLICATED across nodes (dp = node/DCN axis) —
        the layout gather_multi_node_grad assumes (heter_comm_inl.h:2131:
        every node holds the full pass, gradients sum across nodes)."""
        if self.multinode_table():
            return P(("sharding", "mp", "sp", "ep"))
        return P(("dp", "sharding", "mp", "sp", "ep"))

    def multinode_table(self) -> bool:
        """Single source for the multi-node layout predicate (table_spec
        and the trainer's mxu_sharded core must agree, or the table gets
        dp-replicated for a path that never exploits it): pure dp×sharding
        mesh with both axes real.  Size divisibility is validated by the
        trainer on top of this."""
        return (self.axis_size("dp") > 1 and self.axis_size("sharding") > 1
                and all(self.axis_size(a) == 1
                        for a in ("pp", "mp", "sp", "ep")))

    def table_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.table_spec())

    def mp_spec(self, dim: int, ndim: int) -> P:
        """Tensor-parallel weight: shard dimension `dim` of an ndim tensor
        over the mp axis (≙ Col/RowParallelLinear, mp_layers.py:95,171)."""
        spec = [None] * ndim
        spec[dim] = "mp"
        return P(*spec)

    def num_table_shards(self) -> int:
        n = 1
        for a in ("dp", "sharding", "mp", "sp", "ep"):
            n *= self.mesh.shape[a]
        return n


def single_host_topology(n: Optional[int] = None, **degrees) -> HybridTopology:
    """Convenience: build a topology over the first n local devices.  With no
    arguments: pure DP over every visible device."""
    devs = jax.devices()
    if n is not None:
        devs = devs[:n]
    if not degrees:
        degrees = {"dp": len(devs)}
    return HybridTopology(MeshConfig(**degrees), devs)
