"""ZeRO-style parameter/optimizer sharding over the ``sharding`` mesh axis.

≙ meta_parallel/sharding/: GroupShardedOptimizerStage2 (optimizer-state
slicing), GroupShardedStage2 (grad scatter + param broadcast),
GroupShardedStage3 (param slicing with on-demand gather), and the
static-graph sharding_optimizer.

TPU-first: this is mostly a *placement* problem that GSPMD solves when told
where things live (cf. "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training", PAPERS.md) —
* ``zero_spec``/``zero_sharding`` produce PartitionSpecs that slice each
  tensor's first shardable dim over the axis (stage-1/3 placement for opt
  state / params);
* ``scatter_grads`` / ``gather_params`` are the explicit shard_map
  collectives (reduce_scatter ≙ grad scatter; all_gather ≙ on-demand
  param broadcast) for stage-2/3 semantics inside hand-written regions.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddlebox_tpu.parallel.topology import HybridTopology


def zero_spec(x, axis: str = "sharding", axis_size: int = 1) -> P:
    """First dim divisible by the axis size gets sharded; else replicate."""
    for d, size in enumerate(x.shape):
        if size % axis_size == 0 and size >= axis_size:
            spec = [None] * x.ndim
            spec[d] = axis
            return P(*spec)
    return P()


def zero_sharding(tree, topo: HybridTopology, axis: str = "sharding"):
    """Pytree → NamedSharding pytree (apply with jax.device_put /
    with_sharding_constraint).  Stage-1: apply to optimizer state.
    Stage-3: apply to params too."""
    n = topo.axis_size(axis)
    return jax.tree.map(
        lambda x: NamedSharding(topo.mesh, zero_spec(x, axis, n)), tree)


def place_like(tree, shardings):
    return jax.tree.map(jax.device_put, tree, shardings)


# -- explicit shard_map building blocks (stage 2/3 semantics) --------------

def scatter_grads(grads, axis: str = "sharding"):
    """Reduce-scatter each grad's first shardable dim: every rank ends up
    with the summed shard it owns (≙ Stage2 grad scatter)."""
    n = lax.axis_size(axis)

    def one(g):
        for d, size in enumerate(g.shape):
            if size % n == 0 and size >= n:
                return lax.psum_scatter(g, axis, scatter_dimension=d,
                                        tiled=True)
        return lax.psum(g, axis)  # too small to slice: replicate-reduce

    return jax.tree.map(one, grads)


def gather_params(local_params, full_shapes, axis: str = "sharding"):
    """All-gather owned shards back to full tensors (≙ Stage3 on-demand
    param broadcast before fwd/bwd)."""
    n = lax.axis_size(axis)

    def one(p, full):
        for d, size in enumerate(full.shape):
            if size % n == 0 and size >= n and p.shape[d] * n == size:
                return lax.all_gather(p, axis, axis=d, tiled=True)
        return p

    return jax.tree.map(one, local_params, full_shapes)


class GroupShardedOptimizer:
    """Stage-2 functional wrapper: params replicated, grads reduce-scattered,
    optimizer runs on the owned shard only, updated shards all-gathered.

    Use ``update`` inside shard_map with grads entering as per-device values
    (already summed over data within the device).
    """

    def __init__(self, tx, axis: str = "sharding"):
        self.tx = tx
        self.axis = axis

    def init(self, params, axis_size: int):
        local = jax.tree.map(
            lambda p: self._slice(p, axis_size, 0), params)
        return self.tx.init(local)

    def _slice(self, p, n, idx):
        for d, size in enumerate(p.shape):
            if size % n == 0 and size >= n:
                shard = size // n
                return lax.dynamic_slice_in_dim(p, idx * shard, shard, d)
        return p

    def update(self, grads, opt_state, params):
        axis = self.axis
        n = lax.axis_size(axis)
        idx = lax.axis_index(axis)
        g_local = scatter_grads(grads, axis)
        p_local = jax.tree.map(lambda p: self._slice(p, n, idx), params)
        updates, opt_state = self.tx.update(g_local, opt_state, p_local)
        p_local = jax.tree.map(lambda p, u: p + u, p_local, updates)
        new_params = gather_params(p_local, params, axis)
        return new_params, opt_state
