"""Single-rank trainer process: the N x M deployment entry.

One OS process per trainer rank — the shape bench.py's multi_trainer
phase measures and the DEPLOY.md runbook launches.  The rank is
supervised IN-PROCESS by :class:`paddlebox_tpu.launch.TrainerSupervisor`
with a factory that rebuilds the full incarnation (PSClient + shuffle
transport + FleetRunner) per attempt, so crash-anywhere recovery is the
same code path whether ranks are threads (tests, fleet.run_trainer_fleet)
or processes (bench / production).

Spec file (``--spec``, JSON)::

    {"days": [["20260701", [["f0.txt", "f1.txt"], ...]], ...],
     "n_slots": 3, "mf_dim": 4, "dense_dim": 2}

Slots follow the e2e layout: dense ``label`` (dim 1), dense ``dense0``
(dim ``dense_dim``), then ``n_slots`` sparse slots with ids 101+.

On success prints ONE line to stdout::

    FLEETMAIN {"rank": ..., "wall_s": ..., "restarts": ...,
               "history": [...], "stats": {trainer.* snapshot}}

``stats`` is the whole-process ``trainer.`` snapshot — per-rank by
construction because each rank IS a process, which is exactly why the
bench wants subprocess trainers (thread-mode ranks would fold their
wait/byte counters into one registry)."""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional, Tuple


def _parse_addrs(s: str) -> List[Tuple[str, int]]:
    out = []
    for part in filter(None, s.split(",")):
        host, _, port = part.rpartition(":")
        out.append((host, int(port)))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--ps", required=True,
                    help="comma-separated host:port PS shard list")
    ap.add_argument("--trainer_addrs", default="",
                    help="comma-separated host:port per rank (world > 1); "
                         "use fixed non-ephemeral ports — a restarted "
                         "rank must be able to re-bind its own address")
    ap.add_argument("--workdir", required=True,
                    help="shared fleet workdir (manifest, heartbeats)")
    ap.add_argument("--spec", required=True, help="day/model spec JSON")
    ap.add_argument("--batch_size", type=int, default=128)
    ap.add_argument("--virtual_shards", type=int, default=None)
    ap.add_argument("--table_seed", type=int, default=1)
    ap.add_argument("--trainer_seed", type=int, default=2)
    ap.add_argument("--prefetch", action="store_true")
    ap.add_argument("--max_restarts", type=int, default=3)
    ap.add_argument("--client_deadline", type=float, default=60.0)
    ap.add_argument("--fault_site", default="",
                    help="arm a seeded FaultPlan kill at this lifecycle "
                         "site on the FIRST incarnation (bench chaos rep)")
    ap.add_argument("--fault_at", type=int, default=1)
    ap.add_argument("--fault_seed", type=int, default=7)
    ap.add_argument("--warm", action="store_true",
                    help="run the schedule once un-timed first (jit "
                         "compile + table residency), then re-run fresh "
                         "and report only the measured run — the bench's "
                         "critical-path basis needs compiled-steady-state "
                         "numbers, and cpu_s needs the compile excluded")
    args = ap.parse_args(argv)

    from paddlebox_tpu.config import (DataFeedConfig, EmbeddingTableConfig,
                                      SlotConfig, SparseSGDConfig)
    from paddlebox_tpu.data.shuffle_transport import TcpShuffleTransport
    from paddlebox_tpu.launch import TrainerSupervisor
    from paddlebox_tpu.models.deepfm import DeepFM
    from paddlebox_tpu.ps import faults
    from paddlebox_tpu.ps.service import PSClient
    from paddlebox_tpu.trainer.fleet_runner import FleetRunner
    from paddlebox_tpu.utils.monitor import stat_snapshot

    with open(args.spec) as f:
        spec = json.load(f)
    n_slots = int(spec.get("n_slots", 3))
    mf_dim = int(spec.get("mf_dim", 4))
    dense_dim = int(spec.get("dense_dim", 2))
    days = [(str(d), [list(fl) for fl in passes])
            for d, passes in spec["days"]]

    ps_addrs = _parse_addrs(args.ps)
    tr_addrs = _parse_addrs(args.trainer_addrs) or None
    if args.world > 1 and not tr_addrs:
        ap.error("--trainer_addrs required when --world > 1")

    tcfg = EmbeddingTableConfig(
        embedding_dim=mf_dim, shard_num=4,
        sgd=SparseSGDConfig(mf_create_thresholds=2.0))
    slots = [SlotConfig("label", dtype="float", is_dense=True, dim=1),
             SlotConfig("dense0", dtype="float", is_dense=True,
                        dim=dense_dim)]
    slots += [SlotConfig(f"slot_{i}", slot_id=101 + i, capacity=2)
              for i in range(n_slots)]
    feed = DataFeedConfig(slots=tuple(slots), batch_size=args.batch_size,
                          rand_seed=42)

    def model_fn():
        return DeepFM(num_slots=n_slots, emb_width=3 + mf_dim,
                      dense_dim=dense_dim, hidden=(16, 8))

    plans = {}
    if args.fault_site:
        plans[0] = faults.FaultPlan(seed=args.fault_seed).kill_at(
            args.fault_site, at=(args.fault_at,))

    def make_factory(workdir, faulted):
        def factory(rank: int):
            plan = plans.pop(0, None) if faulted else None  # 1st inc only
            client = PSClient(ps_addrs, deadline=args.client_deadline)
            transport = (TcpShuffleTransport(rank, tr_addrs)
                         if args.world > 1 else None)
            return FleetRunner(
                rank=rank, world=args.world, client=client,
                workdir=workdir, table_config=tcfg, model_fn=model_fn,
                feed_config=feed, batch_size=args.batch_size,
                virtual_shards=args.virtual_shards,
                table_seed=args.table_seed,
                trainer_seed=args.trainer_seed,
                prefetch=args.prefetch, transport=transport,
                fault_plan=plan)
        return factory

    if args.warm:
        # un-timed first lap: jit compile, PS row creation, conn warmup.
        # All ranks lap together (same barriers as the measured run), so
        # the measured fleet starts from an identical warm table.
        TrainerSupervisor(make_factory(args.workdir + "-warm", False),
                          args.rank, days, max_restarts=0).join()

    stats_warm = stat_snapshot("trainer.")
    cpu0 = time.process_time()
    t0 = time.monotonic()
    sup = TrainerSupervisor(make_factory(args.workdir, True), args.rank,
                            days, max_restarts=args.max_restarts)
    result = sup.join()
    wall = time.monotonic() - t0
    cpu = time.process_time() - cpu0
    out = {"rank": args.rank, "wall_s": round(wall, 3),
           "cpu_s": round(cpu, 3),     # contention-free busy basis
           "restarts": sup.restarts,
           "history": [{k: m.get(k) for k in ("loss", "auc", "batches")}
                       for m in result["history"]],
           "stats": stat_snapshot("trainer."),
           "stats_warm": stats_warm}
    print("FLEETMAIN " + json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
