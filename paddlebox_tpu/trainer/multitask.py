"""Multi-task trainer (MMoE path).

The reference trains multi-task CTR models (MMoE/shared-bottom) with one
metric set per task head (≙ multi-metric registry with name-keyed MetricMsg,
box_wrapper.h:769-792).  Step differences vs SparseTrainer: labels are
[B, T], the model exposes apply_multi → [B, T] logits, loss is the mean of
per-task masked BCE, the instance's show/click for push use task 0 (the CTR
head), and AUC accumulates per task into stacked bucket tables.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp
import optax

from paddlebox_tpu.data.batch_pack import BatchPacker
from paddlebox_tpu.metrics import auc as auc_mod
from paddlebox_tpu.metrics.auc import AucCalculator, accumulate_auc
from paddlebox_tpu.ops.seqpool_cvm import fused_seqpool_cvm
from paddlebox_tpu.ps import embedding, optimizer as sparse_opt
from paddlebox_tpu.trainer.trainer import SparseTrainer
from paddlebox_tpu.utils.channel import Channel, ChannelClosed
import threading


def make_multi_auc_state(n_tasks: int, table_size: int):
    return {
        "pos": jnp.zeros((n_tasks, table_size), jnp.float32),
        "neg": jnp.zeros((n_tasks, table_size), jnp.float32),
        "scalars": jnp.zeros((n_tasks, auc_mod.N_SCALARS), jnp.float32),
    }


class MultiTaskSparseTrainer(SparseTrainer):
    def __init__(self, engine, model, feed_config, batch_size: int,
                 label_slots: List[str], **kw):
        super().__init__(engine, model, feed_config, batch_size,
                         label_slot=label_slots[0], **kw)
        self.label_slots = label_slots
        self.n_tasks = len(label_slots)
        self.packer = BatchPacker(feed_config, batch_size,
                                  label_slot=label_slots)
        self.auc_state = make_multi_auc_state(self.n_tasks,
                                              self.auc_table_size)
        self.task_aucs = [AucCalculator(self.auc_table_size)
                          for _ in range(self.n_tasks)]

    def _build_step(self):
        sgd_cfg = self.engine.config.sgd
        use_cvm = self.use_cvm
        model = self.model
        dense_tx = self.dense_tx
        slot_ids = jnp.asarray(self.slot_ids)
        n_tasks = self.n_tasks

        def step(ws, params, opt_state, auc_state, indices, lengths, dense,
                 labels, valid, extras=None):
            emb = jax.lax.stop_gradient(embedding.pull_sparse(ws, indices))
            # show=1, click=task-0 label (the CTR head feeds the PS counters)
            ins_cvm = jnp.stack(
                [jnp.ones_like(labels[:, 0]), labels[:, 0]], axis=1)

            def loss_fn(p, e):
                pooled = fused_seqpool_cvm(e, lengths, ins_cvm, use_cvm)
                logits = model.apply_multi(p, pooled, dense)  # [B, T]
                w = valid.astype(jnp.float32)[:, None]
                per = optax.sigmoid_binary_cross_entropy(logits, labels)
                loss = jnp.sum(per * w) / jnp.maximum(jnp.sum(w) * n_tasks,
                                                      1.0)
                return loss, jax.nn.sigmoid(logits)

            (loss, preds), (d_params, d_emb) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(params, emb)

            acc = embedding.push_sparse_grads(ws, indices, d_emb, slot_ids)
            ws = sparse_opt.apply_push(ws, acc, sgd_cfg)
            updates, opt_state = dense_tx.update(d_params, opt_state, params)
            params = optax.apply_updates(params, updates)

            def upd_task(t, st):
                one = accumulate_auc(
                    {"pos": st["pos"][t], "neg": st["neg"][t],
                     "scalars": st["scalars"][t]},
                    preds[:, t], labels[:, t], valid)
                return {"pos": st["pos"].at[t].set(one["pos"]),
                        "neg": st["neg"].at[t].set(one["neg"]),
                        "scalars": st["scalars"].at[t].set(one["scalars"])}

            for t in range(n_tasks):
                auc_state = upd_task(t, auc_state)
            return ws, params, opt_state, auc_state, loss, preds[:, 0]

        self._step_fn = jax.jit(step, donate_argnums=(0, 1, 2, 3))

    def _finalize_metrics(self, auc_state):
        self.auc_state = auc_state
        per_task = self.task_metrics()
        out = dict(per_task[0])
        for t, m in enumerate(per_task):
            out[f"task{t}_auc"] = m["auc"]
        return out

    def task_metrics(self) -> List[Dict[str, float]]:
        state = jax.device_get(self.auc_state)
        out = []
        for t in range(self.n_tasks):
            calc = self.task_aucs[t]
            calc.reset()
            calc.merge_device_state({"pos": state["pos"][t],
                                     "neg": state["neg"][t],
                                     "scalars": state["scalars"][t]})
            out.append(calc.compute())
        return out

    def reset_metrics(self):
        self.auc_state = make_multi_auc_state(self.n_tasks,
                                              self.auc_table_size)
        self.auc.reset()
