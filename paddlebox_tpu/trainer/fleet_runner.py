"""Fault-tolerant multi-trainer data parallelism: the fleet runner.

≙ the reference's multi-trainer BoxPS deployment (N trainer processes ×
M PS shards, fleet_desc-driven): every trainer reads a 1/N file split,
globally shuffles records by key, trains its share of the pass against
the shared PS tier, and the fleet converges to ONE model.  This module
gives that fleet the SAME robustness contract the PS fleet already has
(ps/cluster.py + launch.PSServerSupervisor): kill any trainer at any
point — mid-shuffle, mid-train, mid-write-back, mid-fold, mid-save —
and the supervisor-restarted rank rejoins and the run converges
**bit-identically** to the never-killed run.

Determinism anchor — virtual slices
-----------------------------------
Records never partition by rank.  They partition by a fixed count of
``V = FLAGS_fleet_virtual_shards`` *virtual slices*:
``slice_of(route_keys(block), V)`` (data/shuffle_transport.SHUFFLE_SALT,
decorrelated from the PS CLUSTER_SALT).  Rank ``r`` of an ``N``-wide
fleet owns slices ``{v : v % N == r}`` — fleet width only decides
*placement* of slices, never their *content* or *order*.  Every
fp-order-sensitive reduction then runs per-slice and folds in ascending
``v``:

* each owned slice trains from the SAME pass-start dense state
  (``dense0``) on its own fresh engine, producing a dense delta ``Δ_v``
  and a metrics vector;
* sparse write-backs happen in ``V`` barrier-separated *turns*, turn
  ``v`` writing exactly slice ``v``'s delta — the server folds
  overlapping rows in slice order, not arrival order;
* the dense fold is :meth:`FleetCollective.reduce_slots`: publish owned
  ``Δ_v`` to epoch-suffixed dense slots, fence, then EVERY rank pulls
  slots ``0..V-1`` and accumulates in that fixed order
  (``final = dense0 + ΣΔ_v``) — identical fp sequence at any ``N``.

So ``N=1`` and ``N=4`` execute the *same arithmetic in the same order*;
only the wall-clock placement differs.

Crash-anywhere exactly-once
---------------------------
Every cross-process side effect is driven through a rid deterministic in
(rank, epoch, slice) — ``namespaced_group("fleet", rank, ...)`` — so a
restarted rank replays *byte-identical* requests and the PS dedup
windows collapse the duplicates:

* slice write-backs pin group ``fleet.t<r>:e<epoch>.v<v>`` before
  ``end_pass`` (landed chunks dedup, unlanded apply once);
* fleet barriers/folds ride :class:`FleetCollective` (PB604: every wait
  deadline-bounded, expiry raises the typed ``PeerDead``);
* day rollover is the 2-phase ``end_day`` under the leader-failover
  group ``fleet.day:<d>.endday`` — exactly once per day no matter how
  many leaders drive it.

A restarted rank resumes from the ONE manifest (io/checkpoint.py): it
reads the fleet cursor, rolls its dense replica back to the pass
boundary, replays the cursor pass's pulls against a **shadow table**
(the checkpoint bytes — the live table may already hold other ranks'
pass-``e`` write-backs, which the original pulls never saw), and
re-drives the pass.  The shuffle transport resyncs the epoch's frames
from the survivors' retained send buffers.

Leadership is *advisory*: the elected leader (min live rank, file
heartbeats with a background beat thread) merely drives lifecycle
duties first; any rank stuck at a duty-fenced barrier pokes the duty
closure itself, and the closures are idempotent (lease markers +
cursor checks + dedup'd rids), so a dead leader delays a save by one
poke interval instead of wedging the fleet.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu import flags
from paddlebox_tpu.config import DataFeedConfig, EmbeddingTableConfig
from paddlebox_tpu.data.dataset import SlotDataset
from paddlebox_tpu.data.data_feed import DataFeed
from paddlebox_tpu.data.pass_feed import route_keys
from paddlebox_tpu.data.shuffle_transport import slice_of
from paddlebox_tpu.data.slot_record import SlotRecordBlock
from paddlebox_tpu.io.checkpoint import TrainCheckpoint
from paddlebox_tpu.metrics.auc import AucCalculator
from paddlebox_tpu.parallel.collective import (FleetCollective,
                                               namespaced_group)
from paddlebox_tpu.ps import cluster as ps_cluster
from paddlebox_tpu.ps import faults
from paddlebox_tpu.ps.host_table import ShardedHostTable
from paddlebox_tpu.ps.pass_manager import BoxPSEngine
from paddlebox_tpu.ps.service import RemoteTableAdapter
from paddlebox_tpu.trainer.trainer import SparseTrainer
from paddlebox_tpu.utils import flight
from paddlebox_tpu.utils.backoff import Backoff
from paddlebox_tpu.utils.monitor import stat_add, stat_observe, stat_set

flags.define_flag(
    "trainers", 1,
    "trainer fleet width N: each pass's filelist splits 1/N per rank and "
    "re-partitions by record key over the shuffle transport")
flags.define_flag(
    "fleet_virtual_shards", 8,
    "virtual slice count V — the fleet's determinism anchor: records "
    "route to a fixed V slices independent of fleet width, rank r owns "
    "slices v % N == r, and every order-sensitive fold runs in ascending "
    "v.  MUST stay constant across runs being compared bit-for-bit")
flags.define_flag(
    "fleet_hb_ttl_s", 2.0,
    "trainer membership heartbeat TTL: a rank silent past this drops "
    "from the live set and leadership moves to the next live rank")

# AUC bucket resolution of the per-pass metrics fold (exact counts at
# this resolution — integer-valued f64s, so the cross-rank sum is exact)
_FOLD_BINS = 50
# metrics fold vector: [batches, loss_sum, pos[50], neg[50]]
_MVEC_LEN = 2 + 2 * _FOLD_BINS


# ---------------------------------------------------------------------------
# Membership / leader election
# ---------------------------------------------------------------------------

class _Membership:
    """File-heartbeat membership over the shared workdir (the fleet's
    cheap substitute for an external lock service): each rank renews
    ``members/hb-<r>`` from a BACKGROUND thread (a rank blocked in a
    20s-cadence barrier retry must not miss its 2s TTL), the live set is
    the ranks with a fresh beat, and the leader is the minimum live
    rank.  Election is advisory — correctness never depends on there
    being exactly one leader (duties are idempotent) — so split-brain
    during a TTL race costs a duplicate no-op, not divergence."""

    def __init__(self, workdir: str, rank: int, world: int,
                 ttl_s: Optional[float] = None):
        self.dir = os.path.join(workdir, "members")
        os.makedirs(self.dir, exist_ok=True)
        self.rank = int(rank)
        self.world = int(world)
        self.ttl_s = (float(flags.get_flags("fleet_hb_ttl_s"))
                      if ttl_s is None else float(ttl_s))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_leader: Optional[int] = None

    def _hb_path(self, r: int) -> str:
        return os.path.join(self.dir, f"hb-{r}")

    def heartbeat(self) -> None:
        tmp = self._hb_path(self.rank) + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{time.time():.6f}")
        os.replace(tmp, self._hb_path(self.rank))

    def live(self) -> set:
        now = time.time()
        out = {self.rank}
        for r in range(self.world):
            if r == self.rank:
                continue
            try:
                with open(self._hb_path(r)) as f:
                    t = float(f.read() or 0.0)
            except (OSError, ValueError):
                continue
            if now - t <= self.ttl_s:
                out.add(r)
        return out

    def leader(self) -> int:
        led = min(self.live())
        if led != self._last_leader:
            prev, self._last_leader = self._last_leader, led
            stat_set("trainer.fleet.leader", float(led))
            flight.record("leader_elect", leader=led, previous=prev,
                          observer=self.rank)
        return led

    def start(self) -> None:
        self.heartbeat()
        interval = max(0.05, self.ttl_s / 3.0)

        def beat():
            while not self._stop.wait(interval):
                try:
                    self.heartbeat()
                except OSError:
                    pass

        # pboxlint: disable-next=PB405 -- heartbeat pump for the runner's lifetime; stop() joins it
        self._thread = threading.Thread(target=beat, daemon=True,
                                        name=f"pbox-fleet-hb-{self.rank}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# ---------------------------------------------------------------------------
# Shadow table — the restarted rank's replay pull source
# ---------------------------------------------------------------------------

class _ShadowTable:
    """Engine-facing table for a crashed rank's pass REPLAY: pulls read
    the pass-boundary CHECKPOINT (what the original pulls saw) instead
    of the live PS (which may already hold other ranks' current-pass
    write-backs), while seeding the adapter's delta-snapshot with those
    same bytes so ``bulk_write`` recomputes byte-identical deltas —
    which then dedup/land exactly once under the pinned rid group.
    Keys absent from the checkpoint resolve to the shadow's
    key-deterministic fresh rows — the same rows the server materializes
    for a delta-push to a never-pulled key (ps/service.py
    push_sparse_delta), so even a pre-first-checkpoint key replays
    identically.  Everything except ``bulk_pull`` delegates to the
    adapter."""

    def __init__(self, adapter: RemoteTableAdapter,
                 shadow: ShardedHostTable):
        self._adapter = adapter
        self._shadow = shadow

    def bulk_pull(self, keys):
        rows = self._shadow.bulk_pull(np.asarray(keys, np.uint64))
        self._adapter.seed_snapshot(keys, rows)
        stat_add("trainer.fleet.shadow_pull_rows", float(len(keys)))
        return rows

    def __getattr__(self, name):
        return getattr(self._adapter, name)


def load_shadow_table(ckpt: TrainCheckpoint, config: EmbeddingTableConfig,
                      seed: int) -> ShardedHostTable:
    """Materialize the head generation's sparse state into a local
    ShardedHostTable, walking the base+delta chain AND — the cluster
    case — each generation's ``shard-<k:03d>/`` subdirs (cluster_save
    fans one logical dump over M shard subdirs; a flat
    ``load_table(shard=None)`` would read zero rows from an M>1 dump).
    Part index == key % shard_num on every PS shard (they share the
    table config), so all M dumps' ``part-i`` files upsert cleanly into
    local shard ``i``."""
    shadow = ShardedHostTable(config, seed=seed)
    head = ckpt._manifest()
    if head is None:
        return shadow
    chain = ckpt._state(head).get("chain", [head])
    for gen in chain:
        sparse = os.path.join(ckpt._gen_dir(gen), "sparse")
        width = ps_cluster.dump_width(sparse)
        if width <= 1:
            shadow.load(sparse, mode="upsert")
        else:
            for k in range(width):
                shadow.load(ps_cluster.shard_dir(sparse, k), mode="upsert")
    return shadow


# ---------------------------------------------------------------------------
# Checkpoint shim
# ---------------------------------------------------------------------------

class _CkptEngine:
    """The minimal engine surface ``TrainCheckpoint._save_generation``
    reads (table / day_id / pass_id / phase / server_map-via-table) —
    the fleet snapshots the shared adapter + the post-fold trainer, not
    any one slice engine."""

    def __init__(self, table, day_id: Optional[str], pass_id: int):
        self.table = table
        self.day_id = day_id
        self.pass_id = int(pass_id)
        self.phase = 1
        self._last_written = None


# ---------------------------------------------------------------------------
# Dense state <-> flat vector
# ---------------------------------------------------------------------------

def _flatten_dense(params, opt_state) -> Tuple[np.ndarray, list, list]:
    """(params, opt_state) -> one host f64... no: one f32 vector + the
    treedef/leaf specs needed to rebuild.  f32 keeps the fold arithmetic
    in the model's own precision (Δ accumulation in v order is then the
    exact sequence a single process would run)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(
        jax.device_get((params, opt_state)))
    specs = [(np.asarray(x).shape, np.asarray(x).dtype) for x in leaves]
    if leaves:
        flat = np.concatenate(
            [np.asarray(x, np.float32).ravel() for x in leaves])
    else:
        flat = np.zeros((0,), np.float32)
    return flat, treedef, specs


def _unflatten_dense(flat: np.ndarray, treedef, specs):
    out = []
    off = 0
    for shape, dtype in specs:
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        chunk = flat[off:off + n].reshape(shape)
        off += n
        if np.issubdtype(dtype, np.integer):
            # integer leaves (optax step counters) ride the f32 vector;
            # rint undoes the cast exactly for the magnitudes they reach
            chunk = np.rint(chunk).astype(dtype)
        else:
            chunk = chunk.astype(dtype)
        out.append(chunk)
    import jax
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# The runner
# ---------------------------------------------------------------------------

class FleetRunner:
    """One trainer rank of the N-wide fleet (see module docstring).

    ``days`` for :meth:`run` is ``[(date, [filelist, ...]), ...]`` — per
    day, the ordered passes, each pass a GLOBAL filelist (every rank
    sees the same list; rank r reads indices ``r, r+N, ...``).
    """

    def __init__(self, rank: int, world: int, client, workdir: str,
                 table_config: EmbeddingTableConfig,
                 model_fn: Callable[[], object],
                 feed_config: DataFeedConfig, batch_size: int,
                 virtual_shards: Optional[int] = None,
                 table_seed: int = 0, trainer_seed: int = 0,
                 prefetch: bool = False, transport=None,
                 fault_plan: Optional[faults.FaultPlan] = None,
                 auc_table_size: int = 100_000,
                 parse_ins_id: bool = False):
        self.rank = int(rank)
        self.world = int(world)
        self.client = client
        self.workdir = workdir
        self.table_config = table_config
        self.feed_config = feed_config
        self.batch_size = int(batch_size)
        self.table_seed = int(table_seed)
        self.prefetch = bool(prefetch)
        self.transport = transport
        self.fault_plan = fault_plan
        self.parse_ins_id = bool(parse_ins_id)
        self.V = int(flags.get_flags("fleet_virtual_shards")
                     if virtual_shards is None else virtual_shards)
        if self.V < self.world:
            raise ValueError(
                f"fleet_virtual_shards={self.V} < world={self.world}: "
                f"some ranks would own no slice — raise V (and keep it "
                f"constant across every run you compare)")
        if self.world > 1 and self.transport is None:
            raise ValueError("world > 1 requires a shuffle transport")

        os.makedirs(workdir, exist_ok=True)
        self._marker_dir = os.path.join(workdir, "saved")
        os.makedirs(self._marker_dir, exist_ok=True)

        self.adapter = RemoteTableAdapter(client, delta_mode=True)
        self._table = self.adapter          # swapped to _ShadowTable on replay
        # bootstrap engine only anchors the trainer's jit plumbing; every
        # trained slice gets its own fresh engine (rebound per slice)
        boot = BoxPSEngine(table_config, seed=self.table_seed)
        boot.table = self.adapter
        self.trainer = SparseTrainer(boot, model_fn(), feed_config,
                                     batch_size,
                                     auc_table_size=auc_table_size,
                                     seed=trainer_seed)
        self.coll = FleetCollective(client, self.rank, self.world)
        self.membership = _Membership(workdir, self.rank, self.world)
        self.ckpt = TrainCheckpoint(os.path.join(workdir, "ckpt"))
        self.history: List[Dict] = []
        stat_set("trainer.fleet.rank", float(self.rank))

    # -- faults --------------------------------------------------------------
    def _fault(self, point: str) -> None:
        plan = self.fault_plan
        if plan is None:
            return
        act = plan.fire("lifecycle", None, point)
        if act is None:
            return
        if act.kind == "delay":
            time.sleep(act.delay_s)
        elif act.kind in ("kill", "drop", "kill_server"):
            plan.killed.set()
            raise faults.InjectedFault(
                f"injected: trainer killed at fleet point ({point})")

    # -- leadership / duties -------------------------------------------------
    def _poke(self, duty: Optional[Callable[[], None]] = None
              ) -> Callable[[], None]:
        def poke():
            try:
                self.membership.heartbeat()
            except OSError:
                pass
            if duty is not None and self.membership.leader() == self.rank:
                duty()
        return poke

    def _claim(self, tag: str, lease_s: float = 30.0) -> bool:
        """Best-effort single-writer lease for a lifecycle duty: O_EXCL
        marker claims it; a claimer dead past ``lease_s`` (cursor still
        behind — the caller re-checks) gets stolen on the next poke."""
        path = os.path.join(self._marker_dir, tag)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, f"{time.time():.6f}".encode())
            os.close(fd)
            return True
        except FileExistsError:
            try:
                with open(path) as f:
                    t = float(f.read() or 0.0)
            except (OSError, ValueError):
                t = 0.0
            if time.time() - t > lease_s:
                try:
                    os.unlink(path)   # stale claim: next attempt retries
                except OSError:
                    pass
            return False

    def _cursor(self) -> Tuple[int, int, int]:
        st = self.ckpt.read_state()
        fl = (st or {}).get("fleet")
        if not fl:
            return (-1, -1, -1)
        return (int(fl["epoch"]), int(fl["day_index"]),
                int(fl["pass_index"]))

    def _save_ckpt(self, date: Optional[str], pass_id: int,
                   cursor: Tuple[int, int, int]) -> None:
        epoch, di, pi = cursor
        eng = _CkptEngine(self.adapter, day_id=date, pass_id=pass_id)
        self.ckpt.save(eng, self.trainer, extra={"fleet": {
            "epoch": epoch, "day_index": di, "pass_index": pi,
            "world": self.world, "virtual_shards": self.V,
            # all ranks advance in lockstep (barrier-fenced), so the
            # per-trainer cursor map is uniform — recorded per rank in
            # the ONE manifest for the N x M runbook's inspection tools
            "cursors": {str(r): epoch for r in range(self.world)},
            "history": self.history,
        }})
        flight.record("fleet_cursor", epoch=epoch, day_index=di,
                      pass_index=pi, rank=self.rank)

    def _duty_save(self, cursor: Tuple[int, int, int],
                   date: Optional[str], pass_id: int,
                   tag: str) -> Callable[[], None]:
        """Idempotent save duty: advance the manifest to ``cursor`` if
        nobody has yet.  Runs on the leader inline, and on any rank's
        barrier poke after a leader death (lease + cursor check keep it
        single-shot; a duplicate save would write identical bytes as a
        fresh generation — wasteful, never divergent)."""
        def duty():
            if self._cursor() >= cursor:
                return
            if not self._claim(tag):
                return
            self._save_ckpt(date, pass_id, cursor)
        return duty

    def _duty_floor(self) -> None:
        """Fresh-start floor generation: the initial base checkpoint
        every crash-recovery shadow replays against (epoch-0 deaths
        included).  Guarded by the manifest's absence rather than a
        marker — a marker writer dying pre-commit would otherwise leave
        a state nobody can recover from."""
        if self.ckpt._manifest() is not None:
            return
        if not self._claim("floor"):
            return
        if self.ckpt._manifest() is None:
            self._save_ckpt(None, 0, (0, 0, 0))

    # -- engines -------------------------------------------------------------
    def _make_engine(self, date: str) -> BoxPSEngine:
        eng = BoxPSEngine(self.table_config, seed=self.table_seed)
        eng.table = self._table
        # fresh engine: day_id is None so this only adopts the date (no
        # decay, no quality rollover — the leader's end_day duty owns
        # both, exactly once fleet-wide)
        eng.set_date(date, table_decay=False)
        return eng

    def _end_pass_with_replay(self, engine: BoxPSEngine) -> None:
        """Drive the slice write-back to completion: a dropped
        connection re-runs ``end_pass`` in place — the adapter kept the
        snapshot and the PINNED group, so the retry resends
        byte-identical chunks under identical rids (landed ones dedup).
        Budgeted by the fleet deadline, not attempt-counted (PB501)."""
        bo = Backoff(base=0.1, cap=2.0, deadline=self.coll.deadline_s)
        attempt = 0
        while True:
            try:
                engine.end_pass()
                return
            except faults.InjectedFault:
                raise
            except ConnectionError:
                attempt += 1
                stat_add("trainer.fleet.end_pass_replays")
                if not bo.sleep(attempt):
                    raise

    # -- shuffle -------------------------------------------------------------
    def _shuffle_pass(self, filelist: Sequence[str], epoch: int
                      ) -> Dict[int, List[SlotRecordBlock]]:
        """Read this rank's 1/N of the filelist, route every record to
        its virtual slice, ship non-owned slices to their owners, and
        collect what the peers shipped here.  Reading is single-threaded
        in global file order so the per-destination send sequence — and
        with it the idempotent-resend seq numbering — is deterministic:
        a restarted rank re-sends the exact frames the survivors'
        watermarks already saw."""
        if self.transport is not None:
            self.transport.set_epoch(epoch)
        local: Dict[int, List[SlotRecordBlock]] = {}
        feed = DataFeed(self.feed_config, self.parse_ins_id)
        t0 = time.monotonic()
        for fi in range(self.rank, len(filelist), self.world):
            self._fault("fleet_shuffle")
            for j, block in enumerate(feed.read_file(filelist[fi])):
                sl = slice_of(route_keys(block), self.V)
                for v in np.unique(sl):
                    sub = block.select(np.nonzero(sl == v)[0])
                    sub.shuffle_tag = (int(v), fi, j)
                    dst = int(v) % self.world
                    if dst == self.rank:
                        local.setdefault(int(v), []).append(sub)
                    else:
                        self.transport.send(dst, sub)
        if self.transport is not None:
            self.transport.barrier()
            for blk in self.transport.drain():
                v = int(blk.shuffle_tag[0])
                local.setdefault(v, []).append(blk)
        stat_observe("trainer.fleet.shuffle_s", time.monotonic() - t0)
        for v in local:
            local[v].sort(key=lambda b: b.shuffle_tag)
        return local

    # -- metrics -------------------------------------------------------------
    @staticmethod
    def _metrics_vec(result: Optional[Dict]) -> np.ndarray:
        vec = np.zeros((_MVEC_LEN,), np.float64)
        if result is None:
            return vec
        batches = float(result.get("batches", 0))
        vec[0] = batches
        vec[1] = float(result.get("loss", 0.0)) * batches
        bk = result.get("auc_buckets") or {}
        pos = np.asarray(bk.get("pos", np.zeros(_FOLD_BINS)), np.float64)
        neg = np.asarray(bk.get("neg", np.zeros(_FOLD_BINS)), np.float64)
        vec[2:2 + _FOLD_BINS] = pos
        vec[2 + _FOLD_BINS:] = neg
        return vec

    @staticmethod
    def _fold_metrics(slots: List[np.ndarray]) -> Dict[str, float]:
        acc = np.zeros((_MVEC_LEN,), np.float64)
        for s in slots:                      # ascending v — fixed order
            acc += np.asarray(s, np.float64)
        batches = acc[0]
        calc = AucCalculator(table_size=_FOLD_BINS)
        calc._pos[:] = acc[2:2 + _FOLD_BINS]
        calc._neg[:] = acc[2 + _FOLD_BINS:]
        auc = calc.compute()["auc"]
        return {
            "batches": int(batches),
            "loss": float(acc[1] / batches) if batches else 0.0,
            "auc": float(auc),
        }

    # -- one pass ------------------------------------------------------------
    def _run_pass(self, di: int, date: str, pi: int,
                  filelist: Sequence[str], epoch: int,
                  shadow: bool) -> Dict:
        """The full pass protocol (see module docstring): shuffle →
        per-slice train → pull/write fence → V write-back turns → dense
        fold → metrics fold → cursor save → pass barrier."""
        r, N, V = self.rank, self.world, self.V
        if shadow and self.transport is not None:
            # fresh process mid-epoch: ask survivors to replay their
            # retained epoch frames (our previous incarnation's inbox
            # died with it) — set_epoch first so the replays land in the
            # right window
            self.transport.set_epoch(epoch)
            self.transport.resync()
        local = self._shuffle_pass(filelist, epoch)

        owned = [v for v in range(V) if v % N == r]
        flat0, treedef, specs = _flatten_dense(self.trainer.params,
                                               self.trainer.opt_state)

        # feed + train each owned slice from the same dense0; prefetch
        # mode builds slice i+1's working set (its PULLS) while slice i
        # trains — safe before the tr fence because no write-back has
        # happened yet, so every pull still reads the pass-start table
        engines: Dict[int, BoxPSEngine] = {}
        deltas: Dict[int, np.ndarray] = {}
        results: Dict[int, Optional[Dict]] = {}

        def open_feed(v: int) -> Tuple[BoxPSEngine, SlotDataset]:
            eng = self._make_engine(date)
            eng.pass_id = epoch
            ds = SlotDataset(self.feed_config, self.parse_ins_id)
            ds._blocks = local.get(v, [])
            eng.begin_feed_pass()
            for b in ds._blocks:
                eng.add_keys(b.all_keys())
            eng.end_feed_pass(async_build=self.prefetch)
            return eng, ds

        nonempty = [v for v in owned if local.get(v)]
        pending: Dict[int, Tuple[BoxPSEngine, SlotDataset]] = {}
        if self.prefetch and nonempty:
            pending[nonempty[0]] = open_feed(nonempty[0])
        for i, v in enumerate(nonempty):
            if self.prefetch:
                eng, ds = pending.pop(v)
                if i + 1 < len(nonempty):
                    pending[nonempty[i + 1]] = open_feed(nonempty[i + 1])
            else:
                eng, ds = open_feed(v)
            eng.begin_pass()
            # restore the pass-start dense state so every slice's delta
            # is measured from the same base (slices sum, not chain)
            p0, o0 = _unflatten_dense(flat0, treedef, specs)
            self.trainer.params = p0
            self.trainer.opt_state = o0
            self.trainer.engine = eng
            self.trainer.reset_metrics()
            res = self.trainer.train_pass(ds)
            flat1, _, _ = _flatten_dense(self.trainer.params,
                                         self.trainer.opt_state)
            engines[v] = eng
            deltas[v] = flat1 - flat0
            results[v] = res
        for v in owned:
            if v not in deltas:
                deltas[v] = np.zeros_like(flat0)
                results[v] = None

        # fence: EVERY rank's pulls (feed builds) precede ANY write-back
        t_bar = time.monotonic()
        self.coll.barrier(f"tr.{epoch}", poke=self._poke())
        stat_observe("trainer.fleet.straggler_gap_s",
                     time.monotonic() - t_bar)

        # V write-back turns in ascending v: the server applies slice
        # deltas in slice order — overlapping rows fold associatively in
        # an N-independent sequence
        for v in range(V):
            if v % N == r and v in engines:
                self._fault("end_pass")
                group = namespaced_group("fleet", r, f"e{epoch}.v{v}")
                self.adapter.pin_group(engines[v].mapper.sorted_keys, group)
                self._end_pass_with_replay(engines[v])
            self.coll.barrier(f"wb.{epoch}.{v}", poke=self._poke())

        # dense fold — epoch-suffixed slot names: a twice-crashed rank
        # replaying pass e must never read pass e+1's values out of a
        # reused name.  (The server accumulates one V-vector set per
        # pass; documented retention cost, see ARCHITECTURE.md.)
        self._fault("fleet_allreduce")
        slot_vecs = self.coll.reduce_slots(
            f"fleet.d.{epoch}", {v: deltas[v] for v in owned}, V,
            tag=f"d.{epoch}", poke=self._poke())
        final = flat0.copy()
        for vec in slot_vecs:                       # ascending v
            final += np.asarray(vec, np.float32)
        p, o = _unflatten_dense(final, treedef, specs)
        self.trainer.params = p
        self.trainer.opt_state = o

        # metrics fold (same transport: exact counts, v order)
        mvecs = self.coll.reduce_slots(
            f"fleet.m.{epoch}", {v: self._metrics_vec(results[v])
                                 for v in owned}, V,
            tag=f"m.{epoch}", poke=self._poke())
        metrics = self._fold_metrics(mvecs)
        metrics.update({"day": date, "pass": pi, "epoch": epoch})
        self.history.append(metrics)

        # cursor save (leader first, any poked rank on leader death),
        # then the pass barrier — whose release proves the save landed
        cursor = (epoch + 1, di, pi + 1)
        duty = self._duty_save(cursor, date, epoch + 1,
                               tag=f"pass-e{epoch:06d}")
        if self.membership.leader() == self.rank:
            duty()
        self.coll.barrier(f"pass.{epoch}", timeout=5.0,
                          poke=self._poke(duty))
        return metrics

    # -- day end -------------------------------------------------------------
    def _day_end(self, di: int, date: str, epoch: int) -> None:
        """Two-phase day rollover, exactly once fleet-wide: the decay
        verb pins the leader-failover group (any rank may re-drive it;
        the dedup windows collapse duplicates), the cursor advances to
        (di+1, 0), and the day barrier fences the next day."""
        group = namespaced_group("fleet.day", None, f"d{di}.endday")
        save = self._duty_save((epoch, di + 1, 0), date, epoch,
                               tag=f"day-d{di:06d}")

        def duty():
            if self._cursor() >= (epoch, di + 1, 0):
                return
            self.client.end_day(table=None, group=group)
            try:
                from paddlebox_tpu.metrics import quality
                quality.end_day(date)
            except Exception:
                pass
            save()

        if self.membership.leader() == self.rank:
            duty()
        self.coll.barrier(f"day.{di}", timeout=5.0, poke=self._poke(duty))

    # -- run -----------------------------------------------------------------
    def run(self, days: Sequence[Tuple[str, Sequence[Sequence[str]]]]
            ) -> Dict:
        self.membership.start()
        try:
            return self._run(days)
        finally:
            self.membership.stop()
            if self.transport is not None:
                self.transport.close()

    def _run(self, days) -> Dict:
        st = self.ckpt.read_state()
        restarted = bool(st and st.get("fleet"))
        if not restarted:
            # fresh fleet: establish the floor generation before anyone
            # trains — the recovery anchor for epoch-0 deaths.  Inline on
            # the (believed) leader, NOT only via barrier pokes: a poke
            # fires only between retry attempts, so a first-try barrier
            # would otherwise release with no floor written at all.
            # Startup membership may elect several self-leaders for an
            # instant — the manifest-absence check + claim lease keep
            # the save single-shot regardless.
            if self.membership.leader() == self.rank:
                self._duty_floor()
            self.coll.barrier("floor", timeout=5.0,
                              poke=self._poke(self._duty_floor))
            st = self.ckpt.read_state()
        fl = (st or {}).get("fleet") or {"epoch": 0, "day_index": 0,
                                         "pass_index": 0, "history": []}
        epoch = int(fl["epoch"])
        di0 = int(fl["day_index"])
        pi0 = int(fl["pass_index"])
        self.history = list(fl.get("history", []))

        if restarted:
            flight.record("trainer_resume", rank=self.rank, epoch=epoch,
                          day_index=di0, pass_index=pi0)
            # dense rolls back to the cursor's pass boundary — the base
            # every surviving rank measured this pass's deltas from
            self.ckpt.restore_dense(self.trainer)
            # tail-barrier replay: our previous incarnation may have
            # died between the cursor save and its registration at the
            # trailing barrier(s) — survivors would wait forever.  The
            # rids are deterministic, so if we DID register, these are
            # cached acks (no double count); if not, we register now.
            self.coll.barrier("floor", timeout=5.0,
                              poke=self._poke(self._duty_floor))
            if epoch > 0:
                self.coll.barrier(f"pass.{epoch - 1}", timeout=5.0,
                                  poke=self._poke())
            if pi0 == 0 and di0 > 0:
                self.coll.barrier(f"day.{di0 - 1}", timeout=5.0,
                                  poke=self._poke())

        # the cursor pass (if mid-day) replays against the checkpoint
        # shadow: the live table may already hold other ranks' pass-e
        # write-backs, which the original pulls never saw
        shadow_first = restarted

        for di in range(di0, len(days)):
            date, passes = days[di]
            pi_start = pi0 if di == di0 else 0
            for pi in range(pi_start, len(passes)):
                if shadow_first:
                    shadow_first = False
                    shadow_tbl = load_shadow_table(
                        self.ckpt, self.table_config, self.table_seed)
                    self._table = _ShadowTable(self.adapter, shadow_tbl)
                    stat_add("trainer.fleet.shadow_replays")
                    try:
                        self._run_pass(di, date, pi, passes[pi], epoch,
                                       shadow=True)
                    finally:
                        self._table = self.adapter
                else:
                    self._run_pass(di, date, pi, passes[pi], epoch,
                                   shadow=False)
                epoch += 1
            # a rank restarted exactly at the day boundary (pass_index
            # == len) replays the day end; the dedup'd group + cursor
            # check make the replay exactly-once
            self._day_end(di, date, epoch)

        return {"history": self.history, "params": self.trainer.params,
                "opt_state": self.trainer.opt_state, "epoch": epoch,
                "rank": self.rank}
