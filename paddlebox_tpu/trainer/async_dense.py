"""Async CPU-hosted dense table — ≙ BoxPSAsynDenseTable.

Reference semantics (device_worker.h:803, boxps_worker.cc:133-372): the
dense parameters live in a CPU-side table; each worker *pulls* a snapshot
before its batch, *pushes* its dense gradients into a channel after the
backward, and a background update thread drains the channel applying an
adam rule — workers never block on each other's dense updates
(TrainerDesc async_mode, trainer_desc.proto:121).

TPU-native shape: the jitted step returns the dense grads instead of
applying them (SparseTrainer dense_sync_mode="async_table"); the host loop
pushes them here and refreshes its device snapshot every
``sync_weight_step`` batches (≙ BoxPSWorkerParameter.sync_weight_step).
Staleness is bounded by the channel capacity.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np
import jax

from paddlebox_tpu.utils import lockdep
from paddlebox_tpu.utils.channel import Channel, ChannelClosed


class AsyncDenseTable:
    def __init__(self, params, learning_rate: float = 1e-3,
                 beta1: float = 0.9, beta2: float = 0.999,
                 eps: float = 1e-8, queue_capacity: int = 64):
        self._lr = learning_rate
        self._b1, self._b2, self._eps = beta1, beta2, eps
        self._lock = lockdep.lock("trainer.async_dense.AsyncDenseTable._lock")
        self._params = jax.tree.map(lambda a: np.array(a, np.float32),
                                    params)
        self._m = jax.tree.map(np.zeros_like, self._params)
        self._v = jax.tree.map(np.zeros_like, self._params)
        self._t = 0
        self._pushed = 0
        self._applied = 0
        self._error: Optional[BaseException] = None
        self._ch: Channel = Channel(capacity=queue_capacity)
        self._thread = threading.Thread(target=self._update_loop,
                                        daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    def pull(self):
        """Snapshot → host pytree (≙ PullDense, boxps_worker.cc:226)."""
        with self._lock:
            return jax.tree.map(np.copy, self._params)

    def push(self, grads) -> None:
        """Enqueue one batch's dense grads (≙ PushDense → channel,
        boxps_worker.cc:252); blocks only when the channel is full."""
        self._pushed += 1
        self._ch.put(jax.tree.map(lambda a: np.asarray(a, np.float32),
                                  grads))

    def _update_loop(self) -> None:
        """≙ AsyncUpdate/ThreadUpdate (boxps_worker.cc:260-330): drain the
        channel, merge whatever is queued, apply one adam step."""
        try:
            self._update_loop_inner()
        except BaseException as e:  # surface in drain(), don't die silently
            self._error = e

    def _update_loop_inner(self) -> None:
        while True:
            try:
                g = self._ch.get()
            except ChannelClosed:
                return
            with self._lock:
                self._t += 1
                t = self._t
                bc1 = 1.0 - self._b1 ** t
                bc2 = 1.0 - self._b2 ** t

                def upd(p, m, v, gr):
                    m[:] = self._b1 * m + (1 - self._b1) * gr
                    v[:] = self._b2 * v + (1 - self._b2) * gr * gr
                    p[:] = p - self._lr * (m / bc1) / (
                        np.sqrt(v / bc2) + self._eps)
                    return p

                jax.tree.map(upd, self._params, self._m, self._v, g)
                self._applied += 1

    # ------------------------------------------------------------------
    def drain(self) -> None:
        """Block until every pushed batch has been *applied* (an empty
        channel alone can still have one item mid-apply in the thread).
        Raises instead of spinning forever if the update thread died."""
        while self._applied < self._pushed:
            if self._error is not None:
                raise RuntimeError(
                    "async dense update thread failed with "
                    f"{self._pushed - self._applied} pushes pending"
                ) from self._error
            if not self._thread.is_alive():
                raise RuntimeError(
                    "async dense update thread exited with "
                    f"{self._pushed - self._applied} pushes pending")
            threading.Event().wait(0.002)

    def finalize(self):
        """Stop the update thread and return the final parameters
        (≙ Finalize copying the table back, boxps_worker.cc:214)."""
        self.drain()
        self._ch.close()
        self._thread.join(timeout=5.0)
        return self.pull()
