"""Graph-embedding training over the sparse PS — the GNN mode's loop.

≙ the reference's graph-learning mode (SURVEY §2.2: GpuPsGraphTable +
graph_gpu_wrapper walks feeding the SAME sparse embedding PS the CTR
trainers use — the walk engine produces (center, context) pairs and the
node embeddings live as PS feature rows).  The loop: random walks over
the device-resident CSR graph → skip-gram window pairs → pull node mf
rows from the pass working set → sampled-softmax/NCE loss → adagrad on
the touched rows' mf (the mf/mf_g2sum rule of optimizer.cuh.h:31 applied
to the graph embedding field).

TPU-first: one donated jit step over static-shape [B] pair batches —
pulls are row gathers on the pass-dense working set, the push is the
grad of the NCE loss scattered by XLA, and walks/pair extraction are
jit programs on device (graph/graph_table.py).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp

from paddlebox_tpu.graph.graph_table import GraphTable
from paddlebox_tpu.ps.pass_manager import BoxPSEngine


def walk_pairs(walks: jnp.ndarray, window: int) -> jnp.ndarray:
    """[W, L] node walks → [P, 2] (center, context) pairs within the
    window (≙ the skip-gram pair extraction the walk engine feeds);
    static P = W * (L - 1 ... ) with invalid (-1-padded) pairs kept and
    masked by the caller via ids < 0."""
    w, l = walks.shape
    pairs = []
    for off in range(1, window + 1):
        a = walks[:, :-off].reshape(-1)
        b = walks[:, off:].reshape(-1)
        pairs.append(jnp.stack([a, b], 1))
        pairs.append(jnp.stack([b, a], 1))
    return jnp.concatenate(pairs, axis=0)


class GraphEmbeddingTrainer:
    """Skip-gram-with-negatives over PS-resident node embeddings."""

    def __init__(self, engine: BoxPSEngine, graph: GraphTable,
                 n_negatives: int = 5, learning_rate: float = 0.05,
                 window: int = 2, seed: int = 0):
        self.engine = engine
        self.graph = graph
        self.k = n_negatives
        self.lr = learning_rate
        self.window = window
        self._key = jax.random.PRNGKey(seed)
        self._step = None
        self._step_keys = None

    # -- node id → pass row translation (host, once per pass) --------------
    def node_rows(self, nodes: np.ndarray) -> np.ndarray:
        """Dense graph node ids → pass working-set rows (nodes are
        feasigns: the graph and the PS share the key space)."""
        return self.engine.mapper(np.asarray(nodes, np.uint64))

    def _build_step(self):
        lr, k = self.lr, self.k
        # negatives draw from REAL keys only: the working set is padded to
        # a size bucket, and phantom padding rows would both weaken the
        # NCE signal and accumulate updates end_pass silently discards
        n_real = self.engine.num_keys
        self._step_keys = n_real

        def step(ws, key, centers, contexts):
            """centers/contexts [B] pass rows (0 = padding row, masked)."""
            valid = ((centers > 0) & (contexts > 0)).astype(jnp.float32)
            negs = jax.random.randint(key, (centers.shape[0], k), 1,
                                      n_real + 1)

            def loss_fn(mf):
                u = mf[centers]                      # [B, D]
                v = mf[contexts]
                vn = mf[negs]                        # [B, K, D]
                pos = jax.nn.log_sigmoid(
                    jnp.sum(u * v, -1))              # [B]
                neg = jax.nn.log_sigmoid(
                    -jnp.einsum("bd,bkd->bk", u, vn)).sum(-1)
                denom = jnp.maximum(valid.sum(), 1.0)
                return -jnp.sum((pos + neg) * valid) / denom

            loss, g = jax.value_and_grad(loss_fn)(ws["mf"])
            # adagrad on the embedding field (the mf/mf_g2sum rule of
            # optimizer.cuh.h:31 — row 0 reserved, untouched rows keep
            # exact-zero grads so their state never moves)
            g = g.at[0].set(0.0)
            g2 = ws["mf_g2sum"] + jnp.sum(g * g, -1) / g.shape[1]
            scale = lr / (jnp.sqrt(g2) + 1e-8)
            ws = dict(ws)
            ws["mf"] = ws["mf"] - g * scale[:, None]
            ws["mf_g2sum"] = g2
            return ws, loss

        self._step = jax.jit(step, donate_argnums=(0,))

    def train_pairs(self, pairs_rows: jnp.ndarray,
                    batch_size: int = 4096) -> float:
        """One epoch over [P, 2] pass-row pairs; returns mean loss."""
        if self._step is None or self._step_keys != self.engine.num_keys:
            self._build_step()
        ws = self.engine.ws
        losses = []
        p = pairs_rows.shape[0]
        for lo in range(0, p, batch_size):
            chunk = pairs_rows[lo:lo + batch_size]
            if chunk.shape[0] < batch_size:   # static-shape tail pad
                pad = jnp.zeros((batch_size - chunk.shape[0], 2),
                                chunk.dtype)
                chunk = jnp.concatenate([chunk, pad])
            self._key, sub = jax.random.split(self._key)
            ws, loss = self._step(ws, sub, chunk[:, 0], chunk[:, 1])
            losses.append(loss)
        self.engine.ws = ws
        return float(jnp.mean(jnp.stack(losses))) if losses else float("nan")

    def train_walks(self, starts: np.ndarray, length: int = 8,
                    batch_size: int = 4096,
                    seed: Optional[int] = None) -> float:
        """Walks → pairs → one training epoch (the full graph-mode loop).
        seed None (default) advances the trainer's own RNG so repeated
        epochs explore NEW walks; pass an explicit seed to reproduce."""
        if seed is None:
            self._key, wk = jax.random.split(self._key)
        else:
            wk = jax.random.PRNGKey(seed)
        walks = self.graph.random_walk(
            jnp.asarray(starts, jnp.int32), length, key=wk)
        pairs = walk_pairs(walks, self.window)      # dense node ids, -1 pad
        flat = np.asarray(pairs).reshape(-1)
        ok = flat >= 0
        rows = np.zeros_like(flat, dtype=np.int32)
        rows[ok] = self.node_rows(flat[ok])
        rows = rows.reshape(pairs.shape)
        # drop pairs with any invalid side (walk dead-ends) and self-pairs
        # (stuck walks repeat their node — training u.u would just inflate
        # norms)
        both = (rows > 0).all(axis=1) & (rows[:, 0] != rows[:, 1])
        return self.train_pairs(jnp.asarray(rows[both]), batch_size)
