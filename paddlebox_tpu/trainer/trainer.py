"""Training loop driver — the train_from_dataset path.

≙ BoxPSTrainer::Run → BoxPSWorker::TrainFiles (boxps_trainer.cc:282,
boxps_worker.cc:1278): per-batch pack → pull_sparse → ops → push grads →
dense sync → AUC.  TPU-first structure: the whole per-batch pipeline is ONE
jitted, donated function (pull gather + fused seqpool/cvm + MLP fwd/bwd +
scatter-push + sparse optimizer + dense optimizer + AUC bucket update), so
XLA fuses it and the working set never leaves HBM.  Host threads only pack
and prefetch batches (≙ PackBatchTask boxps_worker.cc:1259) through a
bounded Channel.

Dense sync: under a dp-sharded mesh the batch mean IS the global mean, so the
dense gradient allreduce (≙ BoxWrapper::SyncDense NCCL allreduce,
boxps_worker.cc:1191) is implicit in GSPMD — no hand-written collective.
"""

from __future__ import annotations

import threading
import time
from functools import lru_cache, partial
from typing import Dict, Iterator, Optional

import numpy as np
import jax
import jax.numpy as jnp
import optax

from paddlebox_tpu.config import DataFeedConfig, TrainerConfig
from paddlebox_tpu.data.batch_pack import BatchPacker, PackedBatch
from paddlebox_tpu.data.dataset import SlotDataset
from paddlebox_tpu.data.pass_feed import (PackedPassFeed, plan_tuple,
                                          slice_batch)
from paddlebox_tpu.metrics.auc import (AucCalculator, WuAucCalculator,
                                       accumulate_auc, make_auc_state)
from paddlebox_tpu.ops.seqpool_cvm import fused_seqpool_cvm
from paddlebox_tpu.parallel.topology import HybridTopology
from paddlebox_tpu.ps import embedding, optimizer as sparse_opt
from paddlebox_tpu.ps.pass_manager import BoxPSEngine
from paddlebox_tpu.utils import intervals, trace
from paddlebox_tpu.utils.channel import Channel, ChannelClosed
from paddlebox_tpu.utils.monitor import stat_observe, stat_snapshot
from paddlebox_tpu.utils.timer import TimerRegistry
from paddlebox_tpu import flags


class SparseTrainer:
    def __init__(self, engine: BoxPSEngine, model, feed_config: DataFeedConfig,
                 batch_size: int, label_slot: str = "label",
                 dense_optimizer=None, use_cvm: bool = True,
                 topology: Optional[HybridTopology] = None,
                 auc_table_size: int = 100_000,
                 trainer_config: Optional[TrainerConfig] = None,
                 amp: bool = False, fast_path: bool = True,
                 sparse_path: str = "auto", seed: int = 0):
        self.engine = engine
        self.model = model
        self.packer = BatchPacker(feed_config, batch_size, label_slot)
        self.batch_size = batch_size
        self.use_cvm = use_cvm
        self.topology = topology
        self.trainer_config = trainer_config or TrainerConfig()
        self.amp = amp  # bf16 MXU compute for the dense net (master f32)
        self.fast_path = fast_path  # tiling-aware pipeline (ps/fast_path.py)
        # "mxu" (sorted-SpMM kernels), "ragged" (CSR [U]-domain step),
        # "fast", "reference", or "auto"; FLAGS_sparse_step_path overrides
        # an "auto" construction (flag stays inert when the caller picked
        # a concrete path explicitly)
        if sparse_path == "auto" \
                and flags.get_flags("sparse_step_path") != "auto":
            sparse_path = flags.get_flags("sparse_step_path")
        self.sparse_path = sparse_path
        self.timers = TimerRegistry()
        self.slot_ids = np.array(
            [s.slot_id for s in feed_config.sparse_slots], np.int32)

        # dynamic per-slot mf dims (≙ CtrDymfAccessor): mask [S, 3+D] that
        # zeroes each slot's unused tail columns in the pooled features —
        # gradients through the mask zero themselves, so push/optimizer see
        # exact-zero tail grads with no extra work in the hot loop
        self._dym_mask = None
        if engine.config.sgd.slot_mf_dims:
            d_max = engine.config.embedding_dim
            m = np.ones((len(self.slot_ids), 3 + d_max), np.float32)
            for i, sid in enumerate(self.slot_ids):
                m[i, 3 + engine.config.slot_mf_dim(int(sid)):] = 0.0
            self._dym_mask = jnp.asarray(m)

        # models declaring extra feed inputs (e.g. RankAttentionCTR's
        # rank_offset) must have the feed actually produce them — fail at
        # construction, not with an in-trace TypeError mid-pass
        need = set(getattr(model, "extra_inputs", ()))
        have = ({"rank_offset", "ads_offset"}
                | {s.name for s in feed_config.string_slots})
        unknown = need - have
        if unknown:
            raise ValueError(
                f"model.extra_inputs {sorted(unknown)} are not feed planes "
                f"this feed supplies (available: {sorted(have)})")
        if "ads_offset" in need and not feed_config.ads_offset:
            raise ValueError(
                "model requires the ads_offset plane — set "
                "DataFeedConfig(ads_offset=True) (and call "
                "dataset.preprocess_instance())")
        if "rank_offset" in need:
            if not feed_config.rank_offset:
                raise ValueError(
                    "model requires the rank_offset plane — set "
                    "DataFeedConfig(rank_offset=True) (and call "
                    "dataset.preprocess_instance() so batches hold whole "
                    "page views)")
            mr = getattr(model, "max_rank", None)
            if mr is not None and mr != feed_config.max_rank:
                raise ValueError(
                    f"model.max_rank={mr} != DataFeedConfig.max_rank="
                    f"{feed_config.max_rank}: rank_param blocks would be "
                    "mis-addressed")

        self.dense_tx = dense_optimizer or optax.adam(1e-3)
        self.params = model.init(jax.random.PRNGKey(seed))
        self.opt_state = self.dense_tx.init(self.params)
        # ≙ BoxPSAsynDenseTable (dense_sync_mode="async_table"): dense
        # params live in a CPU table updated by a background thread; the
        # jitted step only *computes* dense grads
        self.async_dense = None
        if self.trainer_config.dense_sync_mode == "async_table":
            if dense_optimizer is not None:
                raise ValueError(
                    "dense_sync_mode='async_table' uses the table's own "
                    "adam rule (TrainerConfig.async_dense_*); an explicit "
                    "dense_optimizer would be silently ignored")
            from paddlebox_tpu.trainer.async_dense import AsyncDenseTable
            tc = self.trainer_config
            self.async_dense = AsyncDenseTable(
                self.params, learning_rate=tc.async_dense_learning_rate,
                beta1=tc.async_dense_beta1, beta2=tc.async_dense_beta2,
                eps=tc.async_dense_eps)
        self.auc_table_size = auc_table_size
        self.auc_state = make_auc_state(auc_table_size)
        self.auc = AucCalculator(auc_table_size)
        # per-user metrics (≙ WuAucMetricMsg via MultiSlotDesc.uid_slot):
        # host-side records — opting in syncs preds per batch, exactly the
        # reference's add_uid_data D2H (metrics.cc:440)
        self.wuauc = (WuAucCalculator() if feed_config.uid_slot else None)
        self._step_fn = None
        self._packed_step_fn = None
        self._packed_sig = None
        # set by the step builders, cleared by the first dispatch after a
        # (re)build: jax.jit traces+compiles on that call, so its latency
        # is compile cost, not steady-state dispatch — it gets its own
        # metric (trainer.step_compile_s) to keep the SLO throughput-stall
        # rule and the dispatch p99 on steady-state numbers only
        self._compile_pending = False
        self._mxu_crossing = ("take", "take")
        self._check_nan = flags.get_flags("check_nan_inf")

        if topology is not None:
            self._batch_sharding = topology.batch_sharding()
            self._replicated = topology.replicated()
        else:
            self._batch_sharding = None
            self._replicated = None

    # ------------------------------------------------------------------
    def _resolve_path(self) -> str:
        """Resolve sparse_path='auto' against the live working set; the
        concrete value is what bench/tests assert against (a silent
        fallback to a slow path must be observable)."""
        assert self.engine.ws is not None, \
            "engine pass lifecycle must run before building the step " \
            "(begin_feed_pass/add_keys/end_feed_pass/begin_pass)"
        if embedding.is_quantized(self.engine.ws):
            raise ValueError(
                "the working set is serving-frozen (int16 embedx, "
                "pull-only); training requires the f32 store — rebuild "
                "the pass (end_feed_pass/begin_pass)")
        path = self.sparse_path
        has_ex = "mf_ex" in self.engine.ws
        is_adagrad = self.engine.config.sgd.optimizer == "adagrad"
        if path == "auto":
            if not self.fast_path:
                # fast_path=False is the documented escape hatch to the
                # numerically-exact reference step — honor it
                path = "reference"
            elif has_ex and self._dym_mask is not None:
                # no path trains mf_ex under per-slot dynamic dims (fast/
                # reference pull only 3+D columns) — fail with the clear
                # error instead of an in-jit shape mismatch downstream
                raise ValueError(
                    "extended (mf_ex) tables do not compose with per-slot "
                    "dynamic mf dims — drop slot_mf_dims or the expand "
                    "embedding")
            elif self.topology is None:
                # extended (mf_ex) tables ride the mxu kernels too — the
                # ex columns join the feature-major table/payload
                path = "mxu"
            elif self._mxu_shardable():
                # explicit HeterComm-style exchange: row-sharded table,
                # all_gather(ids) + per-device sorted-SpMM kernels +
                # psum_scatter(values) inside shard_map
                # (≙ heter_comm_inl.h:1296,1730 sharded pull/push in the
                # hot loop)
                path = "mxu_sharded"
            elif has_ex:
                # fast/reference pull only 3+D columns — an extended model
                # would shape-error inside jit; demand an mxu-capable
                # layout instead of falling through
                raise ValueError(
                    "extended (mf_ex) tables need the mxu or mxu_sharded "
                    "path — this topology does not satisfy "
                    "_mxu_shardable (pure dp×sharding mesh, divisible "
                    "batch/table)")
            elif is_adagrad:
                path = "fast"
            else:
                path = "reference"
        return path

    def _mxu_shardable(self) -> bool:
        """mxu_sharded wants the HeterComm-symmetric layout: every device
        holds a batch shard AND a table shard, on a pure dp×sharding mesh
        (pp/mp/sp/ep all 1) with evenly divisible batch and table.  With
        BOTH axes > 1 the multi-node layout applies (table sharded over
        `sharding`, replicated over `dp` — topology.table_spec), so the
        table must divide by the sharding degree only."""
        if self.topology is None:
            return False
        t = self.topology
        if any(t.axis_size(a) != 1 for a in ("pp", "mp", "sp", "ep")):
            return False
        n_dev = t.axis_size("dp") * t.axis_size("sharding")
        n_tbl = (t.axis_size("sharding") if t.multinode_table() else n_dev)
        return (self.batch_size % n_dev == 0
                and self.engine.ws["show"].shape[0] % n_tbl == 0)

    def _validate_path(self, path: str) -> None:
        """Reject configs a path cannot honor — both the per-batch and the
        packed builders go through here, so an invalid explicit path raises
        instead of silently training wrong."""
        has_ex = "mf_ex" in self.engine.ws
        is_adagrad = self.engine.config.sgd.optimizer == "adagrad"
        if path == "mxu":
            if has_ex and self._dym_mask is not None:
                raise ValueError(
                    "sparse_path='mxu' with an extended (mf_ex) table does "
                    "not compose with per-slot dynamic mf dims — drop "
                    "slot_mf_dims or the expand embedding")
        elif path == "mxu_sharded":
            if has_ex and self._dym_mask is not None:
                raise ValueError(
                    "sparse_path='mxu_sharded' with an extended (mf_ex) "
                    "table does not compose with per-slot dynamic mf dims "
                    "— drop slot_mf_dims or the expand embedding")
            if not self._mxu_shardable():
                raise ValueError(
                    "sparse_path='mxu_sharded' needs a topology with a "
                    "pure dp×sharding mesh (pp/mp/sp/ep == 1) and batch/"
                    "table sizes divisible by the device count")
        elif path == "fast":
            if not is_adagrad:
                raise ValueError(
                    "sparse_path='fast' implements the adagrad rule only "
                    f"(got {self.engine.config.sgd.optimizer!r})")
        elif path == "ragged":
            if has_ex:
                raise ValueError(
                    "sparse_path='ragged' pulls only the 3+D pooled "
                    "columns — extended (mf_ex) tables need the mxu or "
                    "mxu_sharded path")
            if self.topology is not None:
                raise ValueError(
                    "sparse_path='ragged' builds its CSR step plans "
                    "host-side against a single-host working set — use "
                    "mxu_sharded under a topology")
        elif path == "reference":
            if self.async_dense is not None:
                raise ValueError(
                    "dense_sync_mode='async_table' requires the mxu, "
                    "mxu_sharded or fast sparse path")
        else:
            raise ValueError(f"unknown sparse_path {path!r}")

    def _crossing_modes(self, s: int, l: int, b: int,
                        eff_p_pad: int = None, planes: bool = False):
        """Resolve the sorted<->canonical crossing lowering per direction
        (ops/crossing.py): pull's take emits p canonical rows, push's take
        emits only the trimmed width — auto mode times each on the live
        backend once per geometry.

        planes: the plan carries static payload planes, so the push
        crossing moves only the 1+D dynamic columns (gathered from the
        [B*S, 1+D] pooled-grad matrix); the pull crossing always drops the
        mf_size column (premasked in the sorted domain)."""
        from paddlebox_tpu.ops import crossing as cx
        from paddlebox_tpu.ps.mxu_path import _ex_dim
        p = s * l * b
        d = int(self.engine.ws["mf"].shape[1]) + _ex_dim(self.engine.ws)
        backend = jax.default_backend()
        dt = ("bfloat16" if flags.get_flags("mxu_crossing_bf16")
              else "float32")
        pull = cx.best_mode(p, p, 3 + d, backend, dt)
        if planes:
            push = cx.best_mode(eff_p_pad or p, p, 1 + d, backend, dt)
        else:
            # legacy payload carries the exact slot column — bf16 never
            # applies there (mxu_path.push_and_update)
            push = cx.best_mode(eff_p_pad or p, p, 4 + d, backend)
        return (pull, push)

    def _build_step(self):
        """Per-batch jitted step: takes [S, B, L] indices from the host
        packer (transposed + planned in-step)."""
        path = self._resolve_path()
        self._validate_path(path)
        if path == "ragged":
            raise ValueError(
                "sparse_path='ragged' requires the pass-resident feed "
                "(build_pass_feed / train_pass(feed)) — the streaming "
                "per-batch path has no host CSR plan build")
        crossing = ("take", "take")
        if path == "mxu":
            crossing = self._crossing_modes(
                len(self.packer.sparse_slots), self.packer.capacity,
                self.batch_size)
        self._mxu_crossing = crossing
        core = self._make_core(path, crossing)

        def step(ws, params, opt_state, auc_state, indices, lengths, dense,
                 labels, valid, extras):
            idx_slb = jnp.transpose(indices, (0, 2, 1))    # [S, L, B]
            return core(ws, params, opt_state, auc_state, idx_slb, lengths,
                        dense, labels, valid, None, extras)

        self._step_fn = jax.jit(step, donate_argnums=(0, 1, 2, 3))
        self._compile_pending = True

    def _pooled_dense_half(self):
        """Shared back half of the pooled-based steps (mxu/fast): dense
        fwd/bwd + dense optimizer + AUC, returning the pooled grads for the
        sparse push."""
        use_cvm = self.use_cvm
        model = self.model
        dense_tx = self.dense_tx
        amp = self.amp
        dym_mask = self._dym_mask

        apply_dense = self.async_dense is None

        def half(params, opt_state, auc_state, pooled, dense, labels, valid,
                 extras=None):
            B = pooled.shape[0]
            kw = {k: extras[k]
                  for k in getattr(model, "extra_inputs", ())} \
                if extras else {}

            def loss_fn(p, pooled_in):
                if dym_mask is not None:
                    pooled_in = pooled_in * dym_mask[None]
                x = pooled_in if use_cvm else pooled_in[:, :, 2:]
                x = x.reshape(B, -1)
                if amp:
                    p_c = jax.tree.map(lambda a: a.astype(jnp.bfloat16), p)
                    logits = model.apply(
                        p_c, x.astype(jnp.bfloat16),
                        dense.astype(jnp.bfloat16), **kw).astype(jnp.float32)
                else:
                    logits = model.apply(p, x, dense, **kw)
                w = valid.astype(jnp.float32)
                per = optax.sigmoid_binary_cross_entropy(logits, labels)
                loss = jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1.0)
                return loss, jax.nn.sigmoid(logits)

            (loss, preds), (d_params, d_pooled) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(params, pooled)
            if apply_dense:
                updates, opt_state = dense_tx.update(d_params, opt_state,
                                                     params)
                params = optax.apply_updates(params, updates)
            auc_state = accumulate_auc(auc_state, preds, labels, valid)
            return (params, opt_state, auc_state, loss, preds, d_pooled,
                    d_params)

        return half

    def _make_core(self, path: str, crossing=("take", "take")):
        """Shared per-path step body, used by BOTH the per-batch and the
        pass-resident builders (single source of step semantics).

        core(ws, params, opt_state, auc_state, idx_slb, lengths, dense,
             labels, valid, plan) -> (ws, params, opt_state, auc_state,
             loss, preds[, d_params])
        idx_slb is [S, L, B]; plan is a precomputed sorted-spmm plan for the
        mxu path (None → mask + build in-step); crossing = (pull, push)
        sorted<->canonical lowerings for the mxu path (ops/crossing.py).
        """
        sgd_cfg = self.engine.config.sgd
        use_cvm = self.use_cvm
        slot_ids = jnp.asarray(self.slot_ids)
        async_dense = self.async_dense is not None

        if path == "mxu":
            # Sorted-SpMM step (ps/mxu_path.py): the pull/push embedding
            # traffic runs as MXU one-hot matmuls instead of XLA's serial
            # gather/scatter
            from paddlebox_tpu.ps import mxu_path
            interpret = jax.default_backend() == "cpu"
            half = self._pooled_dense_half()

            def core(ws, params, opt_state, auc_state, idx_slb, lengths,
                     dense, labels, valid, plan, extras=None):
                s, l, b = idx_slb.shape
                # geometry from the *traced* working set, so per-pass table
                # resizes retrace with correct dims (and correct sentinel)
                dims = mxu_path.make_dims(s * l * b, ws["show"].shape[0])
                if plan is None:
                    # the packer parks padding at row 0 (batch_pack.py);
                    # the mask makes in-step planning safe for hand-built
                    # batches too.  Precomputed plans were built from
                    # pack_pass output, which guarantees the same.
                    idx_slb = jnp.where(jnp.arange(l)[None, :, None]
                                        < lengths[:, None, :], idx_slb, 0)
                    plan = mxu_path.build_plan(idx_slb, dims)
                pooled = jax.lax.stop_gradient(mxu_path.pull_pool_cvm(
                    ws, plan, dims, (s, l, b), use_cvm, interpret=interpret,
                    crossing=crossing[0]))
                (params, opt_state, auc_state, loss, preds, d_pooled,
                 d_params) = half(params, opt_state, auc_state, pooled,
                                  dense, labels, valid, extras)
                ins_cvm = jnp.stack([jnp.ones_like(labels), labels], axis=1)
                ws = mxu_path.push_and_update(ws, plan, dims, idx_slb,
                                              d_pooled, ins_cvm, slot_ids,
                                              sgd_cfg, interpret=interpret,
                                              crossing=crossing[1])
                out = (ws, params, opt_state, auc_state, loss, preds)
                return out + ((d_params,) if async_dense else ())
            return core

        if path == "mxu_sharded":
            # the multi-chip hot loop as explicit HeterComm-equivalent
            # exchange (≙ heter_comm_inl.h:1296 pull_merge_sparse, :1730
            # push merge, :2027 gather_one_node_grad): table row-sharded in
            # contiguous blocks over every device, batch dp-sharded; pull =
            # all_gather(ids) + local sorted-SpMM gather + psum_scatter;
            # push = all_gather(ids, payload) + local sorted-SpMM merge;
            # optimizer runs GSPMD-elementwise on the row-sharded table.
            from paddlebox_tpu.ps import mxu_path
            from paddlebox_tpu.ps import sharded_embedding as se
            from jax.sharding import PartitionSpec as P
            interpret = jax.default_backend() == "cpu"
            half = self._pooled_dense_half()
            mesh = self.topology.mesh
            # multi-node layout when both axes are real: table sharded over
            # `sharding` (intra-node/ICI), replicated over `dp` (node/DCN),
            # push merges per node then psums across nodes
            # (≙ gather_one_node_grad + gather_multi_node_grad,
            # heter_comm_inl.h:2027,2131); otherwise one flat pool
            batch_axes, tbl_axes, n_tbl, _, multinode = \
                self._sharded_layout()
            tbl_spec1 = P(tbl_axes)
            tbl_spec2 = P(tbl_axes, None)

            # pull and push need the IDENTICAL sorted-SpMM plan; build it
            # ONCE per step in its own shard_map (each device's plan rides
            # a leading dim split over every device) instead of sorting
            # twice (≙ split_input_to_shard building the shard index once,
            # heter_comm_inl.h:1117)
            plan_specs = (P(batch_axes, None, None),) + (P(batch_axes),) * 7

            def core(ws, params, opt_state, auc_state, idx_slb, lengths,
                     dense, labels, valid, plan, extras=None):
                s, l, b = idx_slb.shape
                d_main = ws["mf"].shape[1]
                dx = mxu_path._ex_dim(ws)
                d = d_main + dx
                n_rows = ws["show"].shape[0]
                rows_loc = n_rows // n_tbl
                idx_slb = jnp.where(jnp.arange(l)[None, :, None]
                                    < lengths[:, None, :], idx_slb, 0)
                ex_args = (ws["mf_ex"],) if dx else ()
                ex_specs = (tbl_spec2,) if dx else ()

                if plan is not None:
                    # pass-resident per-device plans (build_pass_feed)
                    splan = plan
                else:
                    def plan_local(idx_loc):
                        _, pl = se.local_plan(idx_loc.reshape(-1), rows_loc,
                                              tbl_axes)
                        return pl

                    splan = jax.shard_map(
                        plan_local, mesh=mesh,
                        in_specs=(P(None, None, batch_axes),),
                        out_specs=plan_specs,
                        check_vma=False)(idx_slb)

                def pull_local(show, click, embed_w, mf, mf_size,
                               idx_loc, *rest):
                    mf_ex = (rest[0].T,) if dx else ()
                    pl = rest[1:] if dx else rest
                    tab = jnp.concatenate(
                        [show[None], click[None], embed_w[None], mf.T,
                         *mf_ex, mf_size.astype(jnp.float32)[None]], axis=0)
                    # multinode: the node's replica serves its own batch
                    # shard — ids/values travel over ICI only
                    vals = se.pull_rows_sharded_mxu(
                        tab, idx_loc.reshape(-1), tbl_axes,
                        interpret=interpret, plan=pl)
                    b_loc = idx_loc.shape[2]
                    return vals.T.reshape(s, l, b_loc, 3 + d + 1)

                v = jax.shard_map(
                    pull_local, mesh=mesh,
                    in_specs=(tbl_spec1, tbl_spec1, tbl_spec1, tbl_spec2,
                              tbl_spec1, P(None, None, batch_axes))
                    + ex_specs + plan_specs,
                    out_specs=P(None, None, batch_axes, None),
                    check_vma=False)(
                    ws["show"], ws["click"], ws["embed_w"], ws["mf"],
                    ws["mf_size"], idx_slb, *ex_args, *splan)
                pooled = jax.lax.stop_gradient(
                    mxu_path.pool_cvm_values(v, use_cvm))
                (params, opt_state, auc_state, loss, preds, d_pooled,
                 d_params) = half(params, opt_state, auc_state, pooled,
                                  dense, labels, valid, extras)
                ins_cvm = jnp.stack([jnp.ones_like(labels), labels], axis=1)
                payload = mxu_path.push_payload(d_pooled, ins_cvm, slot_ids,
                                                (s, l, b))   # [S,L,B,D+4]

                def push_local(idx_loc, pay_loc, *pl):
                    p_loc = idx_loc.size
                    pay_fm = pay_loc.reshape(p_loc, d + 4).T  # [D+4, P_loc]
                    if multinode:
                        return se.push_rows_sharded_mxu_multinode(
                            idx_loc.reshape(-1), pay_fm, rows_loc,
                            tbl_axes, "dp", interpret=interpret,
                            first_only_col=d + 3, plan=pl)
                    return se.push_rows_sharded_mxu(
                        idx_loc.reshape(-1), pay_fm, rows_loc, tbl_axes,
                        interpret=interpret, first_only_col=d + 3, plan=pl)

                delta = jax.shard_map(
                    push_local, mesh=mesh,
                    in_specs=(P(None, None, batch_axes),
                              P(None, None, batch_axes, None)) + plan_specs,
                    out_specs=P(None, tbl_axes),
                    check_vma=False)(idx_slb, payload, *splan)  # [D+4, n_rows]
                acc = mxu_path.acc_from_delta(delta, n_rows, d_main=d_main)
                ws = sparse_opt.apply_push(ws, acc, sgd_cfg)
                out = (ws, params, opt_state, auc_state, loss, preds)
                return out + ((d_params,) if async_dense else ())
            return core

        if path == "ragged":
            # CSR [U]-domain step (ps/ragged_path.py): the pass was
            # lowered to per-batch CSR plans at feed build; the step
            # touches only the valid-occurrence frontier and the batch's
            # unique rows — never the padded [S, L, B] domain, never a
            # full-[N] sweep
            from paddlebox_tpu.ps import ragged_path
            half = self._pooled_dense_half()

            def core(ws, params, opt_state, auc_state, idx_slb, lengths,
                     dense, labels, valid, plan, extras=None):
                if plan is None:
                    raise ValueError(
                        "sparse_path='ragged' needs the pass-resident "
                        "feed's CSR plans (build_pass_feed) — they cannot "
                        "be built in-trace")
                s, l, b = idx_slb.shape
                pooled = jax.lax.stop_gradient(ragged_path.pull_pool_cvm(
                    ws, plan, (s, l, b), use_cvm))
                (params, opt_state, auc_state, loss, preds, d_pooled,
                 d_params) = half(params, opt_state, auc_state, pooled,
                                  dense, labels, valid, extras)
                ins_cvm = jnp.stack([jnp.ones_like(labels), labels], axis=1)
                ws = ragged_path.push_and_update(ws, plan, d_pooled,
                                                 ins_cvm, (s, l, b), sgd_cfg)
                out = (ws, params, opt_state, auc_state, loss, preds)
                return out + ((d_params,) if async_dense else ())
            return core

        if path == "fast":
            # tiling-aware step (ps/fast_path.py docstring); numerically
            # identical to the reference step — tests/test_fast_path.py
            from paddlebox_tpu.ps import fast_path
            half = self._pooled_dense_half()

            def core(ws, params, opt_state, auc_state, idx_slb, lengths,
                     dense, labels, valid, plan, extras=None):
                prelude = fast_path.step_prelude(idx_slb, lengths)
                pooled = jax.lax.stop_gradient(
                    fast_path.pull_pool_cvm(ws, idx_slb, lengths, use_cvm,
                                            prelude=prelude))
                (params, opt_state, auc_state, loss, preds, d_pooled,
                 d_params) = half(params, opt_state, auc_state, pooled,
                                  dense, labels, valid, extras)
                ins_cvm = jnp.stack([jnp.ones_like(labels), labels], axis=1)
                ws = fast_path.push_and_update(ws, idx_slb, lengths,
                                               d_pooled, ins_cvm, slot_ids,
                                               sgd_cfg, prelude=prelude)
                out = (ws, params, opt_state, auc_state, loss, preds)
                return out + ((d_params,) if async_dense else ())
            return core

        model, dense_tx, amp = self.model, self.dense_tx, self.amp
        dym_mask = self._dym_mask

        def core(ws, params, opt_state, auc_state, idx_slb, lengths, dense,
                 labels, valid, plan, extras=None):
            indices = jnp.transpose(idx_slb, (0, 2, 1))    # [S, B, L]
            # 1. pull (≙ PullSparseCaseGPU box_wrapper_impl.h:25)
            emb = jax.lax.stop_gradient(embedding.pull_sparse(ws, indices))
            ins_cvm = jnp.stack([jnp.ones_like(labels), labels], axis=1)
            kw = {k: extras[k]
                  for k in getattr(model, "extra_inputs", ())} \
                if extras else {}

            # 2-3. forward + backward over (dense params, pulled embeddings)
            def loss_fn(p, e):
                pooled = fused_seqpool_cvm(e, lengths, ins_cvm, use_cvm)
                if dym_mask is not None:
                    # fused_seqpool_cvm emits [B, S*E] flattened; with
                    # use_cvm=False the 2 cvm columns are dropped first
                    m = dym_mask if use_cvm else dym_mask[:, 2:]
                    pooled = pooled * m.reshape(-1)[None]
                if amp:
                    # bf16 compute, f32 master weights (strategy.amp —
                    # ≙ fleet amp meta-optimizer; MXU runs 2x+ in bf16)
                    p_c = jax.tree.map(
                        lambda a: a.astype(jnp.bfloat16), p)
                    logits = model.apply(
                        p_c, pooled.astype(jnp.bfloat16),
                        dense.astype(jnp.bfloat16), **kw).astype(jnp.float32)
                else:
                    logits = model.apply(p, pooled, dense, **kw)
                w = valid.astype(jnp.float32)
                per = optax.sigmoid_binary_cross_entropy(logits, labels)
                loss = jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1.0)
                return loss, jax.nn.sigmoid(logits)

            (loss, preds), (d_params, d_emb) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(params, emb)

            # 4-6. push + sparse optimizer (≙ PushSparseGradCaseGPU +
            # SparseAdagrad, box_wrapper_impl.h:373, optimizer.cuh.h:31)
            acc = embedding.push_sparse_grads(ws, indices, d_emb, slot_ids)
            ws = sparse_opt.apply_push(ws, acc, sgd_cfg)

            # dense update (≙ SyncDense/async dense table,
            # boxps_worker.cc:1191-1253 — implicit psum via GSPMD)
            updates, opt_state = dense_tx.update(d_params, opt_state, params)
            params = optax.apply_updates(params, updates)

            # 7. metrics on device (≙ AddAucMonitor boxps_worker.cc:1337)
            auc_state = accumulate_auc(auc_state, preds, labels, valid)
            return ws, params, opt_state, auc_state, loss, preds

        return core

    # ------------------------------------------------------------------
    # pass-resident path (≙ SlotPaddleBoxDataFeed whole-pass GPU pack,
    # data_feed.h:2036 + data_feed.cu:1210-1318): the step takes a batch
    # INDEX and dynamic-slices device-resident stacked arrays; plans for
    # the mxu path are precomputed at pass-build time, so the hot step
    # contains no sorts and no host work at all.
    def pack_pass_host(self, dataset: SlotDataset, mapper=None,
                       on_plane=None) -> "pass_feed.HostPassArrays":
        """Host half of :meth:`build_pass_feed`: pack + translate the
        whole pass into SoA planes.  No device dispatch (unless the caller
        passes an ``on_plane`` stager) and no dependence on the ADOPTED
        working set — with an explicit ``mapper`` (e.g.
        ``engine.peek_next_mapper()``) the prefetcher runs this on a
        background thread while the previous pass still trains."""
        from paddlebox_tpu.data import pass_feed as pf
        self._require_pv_for_rank(dataset)
        label = (self.packer.label_slots
                 if len(self.packer.label_slots) > 1 else self.packer.label_slot)
        # pv-grouped datasets batch on page-view boundaries (a pv trains as
        # one unit, ≙ PadBoxSlotDataset whole-pv batches) — hand the pass
        # pack the cut COUNTS over the merged order (batch_bounds copies no
        # slot data; slicing + re-concatenating blocks would copy the pass
        # twice)
        counts = None
        if getattr(dataset, "_pv_grouped", False):
            counts = [hi - lo
                      for lo, hi in dataset.batch_bounds(self.batch_size)]
        arrays = pf.pack_pass(dataset.get_blocks(), self.packer.config,
                              self.batch_size, label,
                              key_mapper=(self.engine.mapper if mapper is None
                                          else mapper),
                              batch_counts=counts, on_plane=on_plane)
        if self.sparse_path == "ragged":
            # lower the packed pass to CSR here so the PR 7 prefetcher's
            # worker thread hides the build under pass N's training ("auto"
            # never resolves to ragged, so the attribute check is exact)
            arrays.csr = pf.build_csr_plans(arrays.indices, self.slot_ids,
                                            arrays.n_batches,
                                            arrays.batch_size)
        return arrays

    def pass_shardings(self, arrays) -> Optional[dict]:
        """The resident pass's target shardings under a topology (batch
        dims dp-wise, mirroring _put_batch) — None single-device."""
        if self.topology is None:
            return None
        t = self.topology
        dp = ("dp", "sharding")
        shardings = {
            "indices": t.sharding(None, None, None, dp),  # [N,S,L,B]
            "lengths": t.sharding(None, None, dp),        # [N,S,B]
            "dense": t.sharding(None, dp, None),          # [N,B,D]
            "labels": (t.sharding(None, dp) if arrays.labels.ndim == 1
                       else t.sharding(None, dp, None)),
            "valid": t.sharding(None, dp),
        }
        for k in arrays.extra_planes():
            shardings[k] = t.sharding(None, dp, None)
        return shardings

    def finish_pass_feed(self, arrays, keep_host: bool = False,
                         staged=None) -> PackedPassFeed:
        """Device half of :meth:`build_pass_feed`: upload + relayout the
        packed planes and (mxu paths) precompute per-batch plans.  Needs
        the pass's working set ADOPTED (plan dims read ws height), so the
        prefetcher calls this on the MAIN thread right after
        engine.begin_pass()."""
        from paddlebox_tpu.data import pass_feed as pf
        assert self.engine.ws is not None, "engine lifecycle must run first"
        keep = keep_host or bool(self.trainer_config.dump_path)
        feed = pf.upload_pass(arrays, keep_host=keep,
                              sharding=self.pass_shardings(arrays),
                              staged=staged)
        path = self._resolve_path()
        if path == "mxu":
            from paddlebox_tpu.ops import sorted_spmm as sp
            from paddlebox_tpu.ps import mxu_path
            n, s, l, b = feed.data["indices"].shape
            dims = mxu_path.make_dims(s * l * b,
                                      self.engine.ws["show"].shape[0])
            # padding occurrences (row 0) are dead kernel work — trim the
            # plans to the widest batch's real-occurrence count (host
            # lengths are exact, so this is a static bound for the pass)
            per_batch = arrays.lengths.reshape(s, n, b).sum(axis=(0, 2))
            eff = sp.trimmed_dims(dims, int(per_batch.max()))
            pf.precompute_plans(feed, dims, eff, slot_ids=self.slot_ids)
        elif path == "mxu_sharded":
            self._precompute_sharded_plans(feed)
        elif path == "ragged":
            # fail at feed-build time, not first train step: an invalid
            # config (mf_ex / topology) should not cost a CSR build first
            self._validate_path(path)
            csr = arrays.csr
            if csr is None:
                # serial path (no prefetch worker ran pack_pass_host with
                # the ragged path selected) — build now, same plans
                csr = pf.build_csr_plans(arrays.indices, self.slot_ids,
                                         arrays.n_batches,
                                         arrays.batch_size)
            feed.plans = {k: jnp.asarray(v) for k, v in csr.items()}
            feed.plan_dims = self._ragged_plan_key(feed)
        return feed

    def build_pass_feed(self, dataset: SlotDataset,
                        keep_host: bool = False) -> PackedPassFeed:
        """Pack + translate + upload the whole pass, and (mxu path)
        precompute the per-batch sorted-spmm plans.  Runs at pass-build
        time — the train loop then touches no per-batch host work.
        Composition of pack_pass_host + finish_pass_feed (the prefetcher
        drives the halves on separate threads)."""
        assert self.engine.ws is not None, "engine lifecycle must run first"
        arrays = self.pack_pass_host(dataset)
        return self.finish_pass_feed(arrays, keep_host=keep_host)

    def _sharded_layout(self):
        """(batch_axes, tbl_axes, n_tbl, rows_loc, multinode) of the
        mxu_sharded exchange — single source for the core, the pass-plan
        builder and the stale-plan check."""
        batch_axes = ("dp", "sharding")
        multinode = self.topology.multinode_table()
        tbl_axes = ("sharding",) if multinode else batch_axes
        n_tbl = 1
        for a in tbl_axes:
            n_tbl *= self.topology.axis_size(a)
        n_rows = self.engine.ws["show"].shape[0]
        return batch_axes, tbl_axes, n_tbl, n_rows // n_tbl, multinode

    def _precompute_sharded_plans(self, feed: PackedPassFeed) -> None:
        """Pass-resident per-device exchange plans: each device's localized
        sorted-SpMM plan for every batch, built once at pass build (the
        multi-chip twin of precompute_plans — the hot step then contains
        no sorts on ANY path; ≙ the pass-scope shard index of
        split_input_to_shard, heter_comm_inl.h:1117).

        Footprint: plans are UNTRIMMED (sharded exchanges localize ids
        per device, so padding does not sort to a droppable prefix) and
        scale as n_batches x n_devices x gathered-P — the byte count is
        logged; chunked residency is the escape hatch if a pass outgrows
        HBM (split the pass into several feeds)."""
        batch_axes, tbl_axes, n_tbl, rows_loc, _ = self._sharded_layout()
        build = _sharded_plan_builder(self.topology.mesh, batch_axes,
                                      tbl_axes, rows_loc)
        pl = build(feed.data["indices"])
        feed.plans = {"rows2d": pl[0], "perm": pl[1], "inv_perm": pl[2],
                      "ch": pl[3], "tl": pl[4], "fg": pl[5], "fs": pl[6],
                      "first_occ": pl[7]}
        feed.plan_dims = self._sharded_plan_key(feed)
        import logging
        logging.getLogger(__name__).info(
            "sharded pass plans resident: %.0f MB global "
            "(n_batches x n_devices x gathered-P, untrimmed)",
            sum(int(np.prod(a.shape)) * a.dtype.itemsize
                for a in feed.plans.values()) / 1e6)

    def _sharded_plan_key(self, feed: PackedPassFeed):
        """Identity of the exchange geometry sharded plans were built
        for (feed shape, table height, tbl axes layout) — any change makes
        resident plans silently corrupting, so the packed loop compares
        this before every pass."""
        _, tbl_axes, n_tbl, _, _ = self._sharded_layout()
        return ("mxu_sharded", tuple(feed.data["indices"].shape),
                self.engine.ws["show"].shape[0], tbl_axes, n_tbl)

    def _ragged_plan_key(self, feed: PackedPassFeed):
        """Identity of the geometry a feed's CSR plans were built for
        (feed shape + table height): u_rows are pass-local working-set
        rows, so a table resize makes resident plans silently corrupting
        — the packed loop compares this before every pass."""
        return ("ragged", tuple(feed.data["indices"].shape),
                self.engine.ws["show"].shape[0])

    def _require_pv_for_rank(self, dataset) -> None:
        """rank_offset is only meaningful when every batch holds WHOLE page
        views (the reference emits it exclusively under pv merge) — a pv
        split across dense batch cuts would silently see only its
        fragment's peers, so refuse loudly instead."""
        if (self.packer.config.rank_offset
                or self.packer.config.ads_offset) \
                and not getattr(dataset, "_pv_grouped", False):
            raise ValueError(
                "DataFeedConfig(rank_offset/ads_offset) requires "
                "pv-grouped batches — call dataset.preprocess_instance() "
                "before training (≙ GetRankOffset's whole-pv batches, "
                "data_feed.cc:1855)")

    def _packed_signature(self, feed: PackedPassFeed):
        """Trace-structural key of the packed step for a feed: path, plan
        presence, async flag, crossing modes, table height, feed geometry.
        Shared by the builder and the train loop so a stale comparison can
        never skip (or force) a rebuild."""
        path = self._resolve_path()
        with_plans = feed.plans is not None
        n, s, l, b = feed.data["indices"].shape
        exch_bf16 = (flags.get_flags("sharded_exchange_bf16")
                     if path == "mxu_sharded" else False)
        crossing = ("take", "take")
        planes = with_plans and "bs" in feed.plans
        if path == "mxu":
            eff_p_pad = None
            if with_plans:
                r = feed.plans["rows2d"].shape      # [N, n_chunks, 1, c]
                eff_p_pad = int(r[1]) * int(r[3])
            crossing = self._crossing_modes(s, l, b, eff_p_pad, planes)
        cross_bf16 = bool(flags.get_flags("mxu_crossing_bf16"))
        return (path, with_plans, self.async_dense is not None, crossing,
                exch_bf16, self.engine.ws["show"].shape[0], (n, s, l, b),
                planes, cross_bf16)

    def _build_packed_step(self, feed: PackedPassFeed):
        """Thin wrapper over the same per-path core as _build_step: slice
        the resident arrays (and the precomputed plan) by batch index."""
        sig = self._packed_signature(feed)
        path, with_plans, _, crossing = sig[:4]
        self._validate_path(path)
        self._mxu_crossing = crossing
        core = self._make_core(path, crossing)

        def step(ws, params, opt_state, auc_state, i, data, plans):
            bt = slice_batch(data, i)
            plan = plan_tuple(slice_batch(plans, i)) if with_plans else None
            extras = {k: bt[k] for k in bt
                      if k not in ("indices", "lengths", "dense", "labels",
                                   "valid")}
            return core(ws, params, opt_state, auc_state, bt["indices"],
                        bt["lengths"], bt["dense"], bt["labels"],
                        bt["valid"], plan, extras)

        self._packed_step_fn = jax.jit(step, donate_argnums=(0, 1, 2, 3))
        self._compile_pending = True
        # n_rows + feed geometry drive retrace via shapes, but the plan
        # presence/path/async/crossing flags are trace-structural — key them
        self._packed_sig = sig

    def _train_packed(self, feed: PackedPassFeed,
                      progress=None) -> Dict[str, float]:
        """Device-resident train loop: per-batch host work is one int32
        dispatch (≙ the reference train loop consuming pre-packed GPU
        batches, data_feed.h:519 MiniBatchGpuPack)."""
        path = self._resolve_path()
        async_dense = self.async_dense is not None
        if feed.plans is not None and path == "mxu":
            # plans encode the table geometry (sentinel tile, worklist);
            # a cross-pass resize makes them silently corrupting, not just
            # stale — refuse and demand a rebuilt feed
            from paddlebox_tpu.ps import mxu_path
            n, s, l, b = feed.data["indices"].shape
            cur = mxu_path.make_dims(s * l * b,
                                     self.engine.ws["show"].shape[0])
            if cur != feed.plan_dims:
                raise ValueError(
                    "PackedPassFeed plans were built for table dims "
                    f"{feed.plan_dims}, but the working set now needs "
                    f"{cur} — rebuild the feed (build_pass_feed) after a "
                    "table resize")
        elif feed.plans is not None and path == "mxu_sharded":
            cur = self._sharded_plan_key(feed)
            if cur != feed.plan_dims:
                raise ValueError(
                    "PackedPassFeed sharded plans were built for "
                    f"{feed.plan_dims}, but the exchange now needs {cur} — "
                    "rebuild the feed (build_pass_feed) after a table or "
                    "mesh change")
        elif feed.plans is not None and path == "ragged":
            cur = self._ragged_plan_key(feed)
            if cur != feed.plan_dims:
                raise ValueError(
                    "PackedPassFeed CSR plans were built for "
                    f"{feed.plan_dims}, but the pass now needs {cur} — "
                    "rebuild the feed (build_pass_feed) after a table "
                    "resize")
        if self._packed_step_fn is None \
                or self._packed_sig != self._packed_signature(feed):
            self._build_packed_step(feed)
        if self.wuauc is not None and (feed.uid is None
                                       or feed.host_labels is None):
            raise ValueError(
                "uid_slot is configured but this feed carries no host "
                "uids/labels — build it with build_pass_feed")
        engine = self.engine
        ws, params = engine.ws, self.params
        opt_state, auc_state = self.opt_state, self.auc_state
        plans = feed.plans if feed.plans is not None else {}
        losses = []
        n_batches = 0
        dump_file = None
        if self.trainer_config.dump_path:
            if feed.host is None:
                raise ValueError(
                    "dump_path requires build_pass_feed(keep_host=True)")
            import os
            os.makedirs(self.trainer_config.dump_path, exist_ok=True)
            dump_file = open(
                f"{self.trainer_config.dump_path}/dump-pass-"
                f"{self.engine.pass_id}.txt", "w")
        try:
            for i in range(feed.n_batches):
                t_step = time.perf_counter()
                m_step = time.monotonic()
                with self.timers("step"):
                    out = self._packed_step_fn(ws, params, opt_state,
                                               auc_state, np.int32(i),
                                               feed.data, plans)
                # device-busy window for feed-gap attribution (dispatch
                # window; on async backends the device may still be
                # executing past it — a lower bound, not an overcount)
                intervals.record("device", m_step, time.monotonic())
                # per-batch dispatch latency distribution (the loss
                # readback below is the sync point, so this is dispatch
                # cost, not device step time); the first dispatch after a
                # (re)build is jit compile — its own metric
                dt_step = time.perf_counter() - t_step
                if self._compile_pending:
                    self._compile_pending = False
                    stat_observe("trainer.step_compile_s", dt_step)
                else:
                    stat_observe("trainer.step_dispatch_s", dt_step)
                if async_dense:
                    (ws, params, opt_state, auc_state, loss, preds,
                     d_params) = out
                    self.async_dense.push(d_params)
                    if (i + 1) % max(
                            self.trainer_config.sync_weight_step, 1) == 0:
                        params = jax.device_put(self.async_dense.pull())
                else:
                    ws, params, opt_state, auc_state, loss, preds = out
                if self._check_nan and not np.isfinite(float(loss)):
                    raise FloatingPointError(f"NaN/Inf loss at batch {i}")
                if dump_file is not None:
                    h = feed.host
                    lo, cnt, base = h.real_range(i)
                    if cnt:
                        p = np.asarray(preds)[:cnt]
                        lbl = np.asarray(h.labels[lo:lo + cnt])
                        ids = (h.ins_ids[base:base + cnt] if h.ins_ids
                               else [""] * cnt)
                        for j in range(cnt):
                            dump_file.write(
                                f"{ids[j]}\t{lbl[j]:g}\t{p[j]:.6f}\n")
                if self.wuauc is not None:
                    sl = slice(i * feed.batch_size,
                               (i + 1) * feed.batch_size)
                    lbl = feed.host_labels[sl]
                    if lbl.ndim > 1:
                        lbl = lbl[:, 0]
                    self.wuauc.add_data(np.asarray(preds), lbl,
                                        feed.uid[sl], feed.host_valid[sl])
                losses.append(loss)
                n_batches += 1
                if progress is not None:
                    progress(n_batches)
        finally:
            if dump_file is not None:
                dump_file.close()
            self._save_state(ws, params, opt_state, auc_state)
        if async_dense:
            self.async_dense.drain()
            self.params = jax.device_put(self.async_dense.pull())
        out = self._finalize_metrics(self.auc_state)
        out["batches"] = n_batches
        # one stacked device->host sync, not one RPC per batch scalar
        out["loss"] = float(jnp.mean(jnp.stack(losses))) \
            if losses else float("nan")
        return out

    def _save_state(self, ws, params, opt_state, auc_state):
        """The step donates ws/params/opt/auc buffers, so the objects held
        at entry are dead after the first step — save the latest state even
        on failure, or the engine is left pointing at deleted buffers.  A
        failure inside the step may have consumed (donated) its inputs with
        no output produced: save each state group only if its buffers are
        still alive, else None — later use then fails with a clear
        lifecycle error (rebuild the pass / reload the checkpoint), not a
        cryptic deleted-buffer crash."""
        def _alive(tree):
            return all(not (hasattr(leaf, "is_deleted") and leaf.is_deleted())
                       for leaf in jax.tree.leaves(tree))

        self.engine.ws = ws if _alive(ws) else None
        self.params = params if _alive(params) else None
        self.opt_state = opt_state if _alive(opt_state) else None
        self.auc_state = auc_state if _alive(auc_state) else None

    # ------------------------------------------------------------------
    def _put_batch(self, batch: PackedBatch):
        arrs = (batch.indices, batch.lengths, batch.dense, batch.labels,
                batch.valid)
        extras = {}
        if batch.rank_offset is not None:
            extras["rank_offset"] = batch.rank_offset
        if batch.aux:
            extras.update(batch.aux)
        repl_extras = {}
        if batch.ads_offset is not None:
            repl_extras["ads_offset"] = batch.ads_offset
        if self._batch_sharding is None:
            ex = {k: jnp.asarray(v) for k, v in extras.items()}
            ex.update({k: jnp.asarray(v) for k, v in repl_extras.items()})
            return tuple(jnp.asarray(a) for a in arrs) + (ex,)
        out = []
        for i, a in enumerate(arrs):
            if i == 0:  # [S,B,L] — batch dim 1
                sh = self.topology.sharding(None, ("dp", "sharding"), None)
            elif i == 1:
                sh = self.topology.sharding(None, ("dp", "sharding"))
            else:
                sh = self._batch_sharding
            out.append(jax.device_put(a, sh))
        ex_sh = self.topology.sharding(("dp", "sharding"), None)
        ex = {k: jax.device_put(v, ex_sh) for k, v in extras.items()}
        ex.update({k: jax.device_put(v, self._replicated)
                   for k, v in repl_extras.items()})
        return tuple(out) + (ex,)

    def train_pass(self, dataset: SlotDataset, prefetch: int = 4,
                   pack_threads: int = 1,
                   progress=None) -> Dict[str, float]:
        """Run one full pass over the dataset (≙ TrainFiles loop).

        Packing runs in background threads feeding a bounded channel so the
        device step overlaps with host batch assembly.  pack_threads > 1
        fans batch assembly over a thread pool (numpy releases the GIL)
        while the bounded channel of ordered futures preserves batch order
        (≙ the per-device PackBatchTask threads, boxps_worker.cc:1259).

        progress, if given, is called as progress(n_batches_done) after
        every device step — bench/driver heartbeat hook.

        A PackedPassFeed (build_pass_feed) routes to the device-resident
        loop instead — zero per-batch host work.
        """
        t0 = time.perf_counter()
        with trace.span("trainer.train_pass", pass_id=self.engine.pass_id):
            if isinstance(dataset, PackedPassFeed):
                stats = self._train_packed(dataset, progress)
            else:
                stats = self._train_stream(dataset, prefetch, pack_threads,
                                           progress)
        dt = time.perf_counter() - t0
        # "train" seconds land in the ENGINE's registry so the per-pass
        # PrintSyncTimer report shows pull/train/write side by side
        self.engine.timers.add("train", dt)
        stat_observe("trainer.train_pass_s", dt)
        if getattr(self.engine, "cache", None) is not None:
            # this pass's HBM-tier hit rate (set at adoption) rides along
            # with the training metrics for drivers like fleet/bench
            stats["cache_hit_rate"] = stat_snapshot("ps.cache.").get(
                "ps.cache.hit_rate", 0.0)
        return stats

    def _train_stream(self, dataset: SlotDataset, prefetch: int,
                      pack_threads: int, progress) -> Dict[str, float]:
        """Per-batch host-pack path of train_pass (streaming datasets)."""
        self._require_pv_for_rank(dataset)
        if self._step_fn is None:
            self._build_step()
        engine = self.engine
        assert engine.ws is not None, "call engine lifecycle first"
        mapper = engine.mapper
        ch = Channel(capacity=prefetch)

        import concurrent.futures
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, pack_threads),
            thread_name_prefix="pbox-pack")

        def pack_one(block):
            t0 = time.perf_counter()
            m0 = time.monotonic()
            b = self.packer.pack(block, key_mapper=mapper)
            intervals.record("pack", m0, time.monotonic())
            self.timers.add("pack", time.perf_counter() - t0)
            return b

        def packer_thread():
            try:
                for block in dataset.batches(self.batch_size):
                    if not ch.put(pool.submit(pack_one, block)):
                        break  # consumer closed the channel (failed pass)
            finally:
                ch.close()

        t = threading.Thread(target=packer_thread, daemon=True)
        t.start()

        ws, params = engine.ws, self.params
        opt_state, auc_state = self.opt_state, self.auc_state
        n_batches = 0
        losses = []
        dump_file = None
        if self.trainer_config.dump_path:
            # ≙ TrainerDesc dump_fields/dump_path (trainer_desc.proto:38-40,
            # DumpWorkField): per-instance "ins_id\tlabel\tpred" lines
            import os
            os.makedirs(self.trainer_config.dump_path, exist_ok=True)
            dump_file = open(
                f"{self.trainer_config.dump_path}/dump-pass-"
                f"{self.engine.pass_id}.txt", "w")
        try:
            while True:
                try:
                    batch = ch.get().result()
                except ChannelClosed:
                    break
                dev = self._put_batch(batch)
                t_step = time.perf_counter()
                m_step = time.monotonic()
                with self.timers("step"):
                    out = self._step_fn(ws, params, opt_state, auc_state,
                                        *dev)
                intervals.record("device", m_step, time.monotonic())
                # same per-batch dispatch distribution as the packed loop:
                # the SLO watchdog's throughput-stall rule rates this
                # counter, so BOTH train paths must feed it — and both
                # route the first post-build dispatch (jit compile) to
                # trainer.step_compile_s instead
                dt_step = time.perf_counter() - t_step
                if self._compile_pending:
                    self._compile_pending = False
                    stat_observe("trainer.step_compile_s", dt_step)
                else:
                    stat_observe("trainer.step_dispatch_s", dt_step)
                if self.async_dense is not None:
                    (ws, params, opt_state, auc_state, loss, preds,
                     d_params) = out
                    # ≙ PushDense (boxps_worker.cc:252): grads to the table
                    self.async_dense.push(d_params)
                    if (n_batches + 1) % max(
                            self.trainer_config.sync_weight_step, 1) == 0:
                        # ≙ PullDense snapshot refresh (boxps_worker.cc:1301)
                        params = jax.device_put(self.async_dense.pull())
                else:
                    ws, params, opt_state, auc_state, loss, preds = out
                if self._check_nan and not np.isfinite(float(loss)):
                    raise FloatingPointError(
                        f"NaN/Inf loss at batch {n_batches}")
                if dump_file is not None:
                    p = np.asarray(preds)[:batch.num_real]
                    lbl = batch.labels[:batch.num_real]
                    ids = batch.ins_ids or [""] * batch.num_real
                    for i in range(batch.num_real):
                        dump_file.write(f"{ids[i]}\t{lbl[i]:g}\t{p[i]:.6f}\n")
                if self.wuauc is not None:
                    lblh = (batch.labels if batch.labels.ndim == 1
                            else batch.labels[:, 0])
                    self.wuauc.add_data(np.asarray(preds), lblh,
                                        batch.uid, batch.valid)
                losses.append(loss)
                n_batches += 1
                if progress is not None:
                    progress(n_batches)
        finally:
            # on any exit — including a pack-future exception or the NaN
            # guard — unblock the producer (close is idempotent; its own
            # finally also closes), reap it, cancel queued packs, and never
            # leak the dump file across failed passes
            ch.close()
            t.join()
            pool.shutdown(wait=False, cancel_futures=True)
            if dump_file is not None:
                dump_file.close()
            self._save_state(ws, params, opt_state, auc_state)
        if self.async_dense is not None:
            self.async_dense.drain()
            params = jax.device_put(self.async_dense.pull())
            self.params = params

        out = self._finalize_metrics(auc_state)
        out["batches"] = n_batches
        # one stacked device->host sync, not one RPC per batch scalar
        out["loss"] = float(jnp.mean(jnp.stack(losses))) \
            if losses else float("nan")
        return out

    def _finalize_metrics(self, auc_state) -> Dict[str, float]:
        self.auc.reset()
        self.auc.merge_device_state(jax.device_get(auc_state))
        out = self.auc.compute()
        # compact folded pos/neg export: the windowed-AUC / PSI-drift
        # monitors (metrics/quality.py) retain this across passes instead
        # of the 1M-bucket tables
        pos, neg = self.auc.folded_buckets()
        out["auc_buckets"] = {"pos": pos.tolist(), "neg": neg.tolist()}
        if self.wuauc is not None:
            w = self.wuauc.compute()
            out["uauc"] = w["uauc"]
            out["wuauc"] = w["wuauc"]
            out["wuauc_users"] = w["user_cnt"]
            # per-pass metric: drop the raw records (≙ reset_records) —
            # unlike the O(table_size) AUC buckets they grow per record
            self.wuauc.reset()
        return out

    def reset_metrics(self):
        self.auc_state = make_auc_state(self.auc_table_size)
        self.auc.reset()
        if self.wuauc is not None:
            self.wuauc.reset()


@lru_cache(maxsize=None)
def _sharded_plan_builder(mesh, batch_axes, tbl_axes, rows_loc: int):
    """Cached jitted pass-plan builder (one trace per exchange geometry —
    a fresh jit per pass would re-trace the shard_map'd sort pipeline at
    every pass build)."""
    from jax.sharding import PartitionSpec as P
    from paddlebox_tpu.ps import sharded_embedding as se
    plan_specs = (P(batch_axes, None, None),) + (P(batch_axes),) * 7

    @jax.jit
    def build(idx_all):
        def one(idx_slb):
            def plan_local(idx_loc):
                _, pl = se.local_plan(idx_loc.reshape(-1), rows_loc,
                                      tbl_axes)
                return pl
            return jax.shard_map(
                plan_local, mesh=mesh,
                in_specs=(P(None, None, batch_axes),),
                out_specs=plan_specs, check_vma=False)(idx_slb)
        return jax.lax.map(one, idx_all)

    return build
