from paddlebox_tpu.trainer.trainer import SparseTrainer  # noqa: F401
