"""Global flag registry.

TPU-native equivalent of the reference's gflags layer
(paddle/fluid/platform/flags.cc — e.g. the PaddleBox block at flags.cc:946-975:
enable_pullpush_dedup_keys, padbox_record_pool_max_size,
padbox_dataset_shuffle_thread_num, ...).  Flags are plain Python values with
defaults, overridable by environment variables ``FLAGS_<name>`` at first read
and programmatically via :func:`set_flags` (mirroring ``paddle.set_flags``).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict

_LOCK = threading.Lock()
_DEFS: Dict[str, Any] = {}
_VALUES: Dict[str, Any] = {}


def define_flag(name: str, default: Any, help_str: str = "") -> None:
    with _LOCK:
        if name in _DEFS:
            return
        _DEFS[name] = (default, help_str)
        env = os.environ.get("FLAGS_" + name)
        if env is not None:
            _VALUES[name] = _coerce(env, default)
        else:
            _VALUES[name] = default


def _coerce(text: str, default: Any) -> Any:
    if isinstance(default, bool):
        return text.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(text)
    if isinstance(default, float):
        return float(text)
    return text


def get_flags(name: str) -> Any:
    with _LOCK:
        if name not in _VALUES:
            raise KeyError(f"undefined flag: {name}")
        return _VALUES[name]


def set_flags(flags: Dict[str, Any]) -> None:
    with _LOCK:
        for k, v in flags.items():
            if k not in _DEFS:
                raise KeyError(f"undefined flag: {k}")
            _VALUES[k] = v


def all_flags() -> Dict[str, Any]:
    with _LOCK:
        return dict(_VALUES)


# ---------------------------------------------------------------------------
# Core flag set (parity with the PaddleBox block, flags.cc:946-975, plus
# TPU-specific knobs).
# ---------------------------------------------------------------------------
# pboxlint: disable-next=PB205 -- paper-fidelity registry entry (PaddleBox parity), not yet wired
define_flag("enable_pullpush_dedup_keys", True,
            "dedup minibatch keys before pull/push (flags.cc:946)")
# pboxlint: disable-next=PB205 -- paper-fidelity registry entry (PaddleBox parity), not yet wired
define_flag("enable_pull_box_padding_zero", True,
            "key 0 pulls a zero embedding (flags.cc:950)")
# pboxlint: disable-next=PB205 -- paper-fidelity registry entry (PaddleBox parity), not yet wired
define_flag("record_pool_max_size", 2_000_000,
            "SlotRecord arena cap (flags.cc:956 padbox_record_pool_max_size)")
# pboxlint: disable-next=PB205 -- paper-fidelity registry entry (PaddleBox parity), not yet wired
define_flag("dataset_shuffle_thread_num", 20,
            "global-shuffle sender threads (flags.cc:966)")
# pboxlint: disable-next=PB205 -- paper-fidelity registry entry (PaddleBox parity), not yet wired
define_flag("dataset_merge_thread_num", 20,
            "shuffle-receiver merge threads (flags.cc:968)")
# pboxlint: disable-next=PB205 -- paper-fidelity registry entry (PaddleBox parity), not yet wired
define_flag("auc_runner_mode", False,
            "enable AucRunner slot-replacement eval (flags.cc:972)")
define_flag("check_nan_inf", False,
            "per-batch NaN/Inf scan of model outputs (boxps_worker.cc:1326)")
# pboxlint: disable-next=PB205 -- paper-fidelity registry entry (PaddleBox parity), not yet wired
define_flag("feed_pass_thread_num", 8,
            "threads used to extract pass feasigns (box_wrapper.h:873 uses 30)")
# pboxlint: disable-next=PB205 -- paper-fidelity registry entry (PaddleBox parity), not yet wired
define_flag("pass_build_chunk", 500_000,
            "host->device pass-build chunk size (ps_gpu_wrapper.cc:757)")
# pboxlint: disable-next=PB205 -- paper-fidelity registry entry (PaddleBox parity), not yet wired
define_flag("tpu_batch_key_capacity", 0,
            "static per-batch key capacity; 0 = derive from data feed config")
define_flag("sharded_exchange_bf16", False,
            "move the mxu_sharded exchange's VALUE traffic (pull "
            "psum_scatter + push payload all_gather) in bfloat16 — halves "
            "ICI bytes at ~1e-2 relative error (EQuARX-style reduced-"
            "precision collectives; ids/plans stay exact).  Read at step-BUILD "
            "time: the packed loop retraces on a change, but a live "
            "streaming step keeps its compiled value")
define_flag("mxu_crossing", "auto",
            "sorted<->canonical crossing lowering for the mxu sparse path: "
            "take | sort | auto (auto = time both once per geometry on the "
            "live backend; ops/crossing.py)")
define_flag("ps_device_cache", False,
            "keep the hottest embedding rows resident in device memory "
            "across passes (the HBM tier of the HBM/DRAM/SSD store, "
            "≙ HeterPS fleet/heter_ps).  build_pull then fetches only "
            "cache MISSES over the wire; hits are gathered device-side "
            "into the pass working set.  Bit-identical to cache-off — "
            "the cache is write-back at pass granularity and never a "
            "second source of truth across a checkpoint commit")
define_flag("ps_device_cache_rows", 262_144,
            "row capacity of the device-resident hot-row cache "
            "(ps/device_cache.py); admission/eviction ranks by the "
            "day-scale delta_score stats plus pass recency")
define_flag("sparse_step_path", "auto",
            "jitted sparse step lowering: auto | fast | mxu | ragged "
            "(trainer/trainer.py).  'ragged' keeps per-step sparse math in "
            "the [P_valid]/[U] nonzero domain via host-built CSR plans "
            "(ps/ragged_path.py); 'fast'/'mxu' are the padded-dense paths; "
            "'auto' defers to the trainer's topology/optimizer-driven "
            "resolution.  Bit-identity across paths is the contract")
define_flag("mxu_crossing_bf16", False,
            "move the mxu path's sorted<->canonical crossings in bfloat16 "
            "— halves the bytes of the dominant step cost (BENCH_r03: two "
            "~8.2ms crossings of a 34.6ms step) at ~4e-3 relative error on "
            "pulled values / push grads; the optimizer still accumulates "
            "f32.  Read at step-BUILD time, like sharded_exchange_bf16")
