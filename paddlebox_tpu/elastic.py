"""Elastic membership / failure detection.

≙ ElasticManager (fleet/elastic/manager.py:131): ranks register under a
watch prefix with a TTL'd heartbeat, a watcher notices scale-in/out or dead
ranks and triggers restart/re-rendezvous.  The reference uses etcd
(manager.py:217-233 key writes); zero-egress TPU pods get a shared-filesystem
store instead (NFS/GCS-fuse in production, tmpdir in tests) — same contract:
register, heartbeat, watch, notify.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional


class FileStore:
    """TTL'd key registry on a shared directory (≙ the etcd prefix)."""

    def __init__(self, root: str, ttl: float = 10.0):
        self.root = root
        self.ttl = ttl
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key.replace("/", "_") + ".json")

    def put(self, key: str, value: Dict) -> None:
        tmp = self._path(key) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"value": value, "ts": time.time()}, f)
        os.replace(tmp, self._path(key))

    def get(self, key: str) -> Optional[Dict]:
        try:
            with open(self._path(key)) as f:
                rec = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None
        if time.time() - rec["ts"] > self.ttl:
            return None
        return rec["value"]

    def alive_keys(self) -> List[str]:
        out = []
        for fn in os.listdir(self.root):
            if not fn.endswith(".json"):
                continue
            key = fn[:-5]
            if self.get(key) is not None:
                out.append(key)
        return sorted(out)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass


class ElasticManager:
    """Register + heartbeat this rank; watch membership; fire callbacks on
    change (≙ manager.py watch loop + scale in/out decision)."""

    def __init__(self, store: FileStore, rank: int, world_size: int,
                 heartbeat_interval: float = 2.0):
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.interval = heartbeat_interval
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._callbacks: List[Callable[[List[str]], None]] = []
        self._last_members: Optional[List[str]] = None

    def register(self) -> None:
        self.store.put(f"rank-{self.rank:05d}",
                       {"rank": self.rank, "host": os.uname().nodename,
                        "pid": os.getpid()})

    def on_membership_change(self, fn: Callable[[List[str]], None]) -> None:
        self._callbacks.append(fn)

    def start(self) -> None:
        self.register()

        def heartbeat():
            while not self._stop.wait(self.interval):
                self.register()

        def watch():
            while not self._stop.wait(self.interval / 2):
                members = self.store.alive_keys()
                if self._last_members is not None and \
                        members != self._last_members:
                    for fn in self._callbacks:
                        fn(members)
                self._last_members = members

        for target in (heartbeat, watch):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        self.store.delete(f"rank-{self.rank:05d}")

    def healthy(self) -> bool:
        return len(self.store.alive_keys()) == self.world_size
