"""Shard-parallel host-table execution engine (utils/workpool.py +
ps/host_table.py): bit-identity across pool sizes, capacity-doubling
growth amortization, concurrent pull/upsert stress, the pooled-table
chaos day (composes with the exactly-once retry protocol), delta-save
atomicity, lock-wait observability, pool metrics in /statz and the
per-pass report, and the ≥2x pull+write microbench on multi-core hosts.
"""

import os
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu import flags
from paddlebox_tpu.config import (AccessorConfig, EmbeddingTableConfig,
                                  SparseSGDConfig)
from paddlebox_tpu.ps.host_table import ShardedHostTable
from paddlebox_tpu.utils import workpool
from paddlebox_tpu.utils.monitor import StatRegistry, stat_snapshot

_DEFAULT_THREADS = min(8, os.cpu_count() or 1)


@pytest.fixture(autouse=True)
def _pool_reset():
    StatRegistry.instance().reset()
    yield
    flags.set_flags({"ps_table_threads": _DEFAULT_THREADS})
    workpool.table_pool()


def set_threads(n: int) -> None:
    flags.set_flags({"ps_table_threads": n})
    assert workpool.table_pool().threads == max(1, n)


def make_table(shard_num=8, dim=8, seed=7, **acc):
    return ShardedHostTable(EmbeddingTableConfig(
        embedding_dim=dim, shard_num=shard_num,
        accessor=AccessorConfig(**acc)), seed=seed)


def table_state(t: ShardedHostTable):
    """Exact per-shard state: (keys, soa) copies in shard order."""
    out = []
    for s in t._shards:
        with s.lock:
            out.append((s.keys.copy(),
                        {f: v.copy() for f, v in s.soa.items()}))
    return out


def assert_states_equal(a, b):
    assert len(a) == len(b)
    for (ka, sa), (kb, sb) in zip(a, b):
        np.testing.assert_array_equal(ka, kb)
        assert set(sa) == set(sb)
        for f in sa:
            np.testing.assert_array_equal(sa[f], sb[f], err_msg=f)


def drive_workload(t: ShardedHostTable, tmp_path=None):
    """A deterministic multi-phase workload touching every pooled verb."""
    rng = np.random.default_rng(0)
    pulls = []
    for step in range(4):
        keys = np.unique(rng.integers(1, 5000, 600).astype(np.uint64))
        rows = t.bulk_pull(keys)
        pulls.append({f: v.copy() for f, v in rows.items()})
        rows["show"] += np.float32(step + 1)
        rows["click"] += np.float32(1.0)
        rows["mf"] += np.float32(0.25)
        rows["unseen_days"][:] = 0.0
        t.bulk_write(keys, rows)
    t.end_day()
    removed = t.shrink()
    if tmp_path is not None:
        saved = t.save(str(tmp_path), mode="all")
        t2 = make_table(shard_num=t.shard_num, dim=t.mf_dim)
        loaded = t2.load(str(tmp_path))
        assert loaded == saved == t.size()
        assert_states_equal(table_state(t), table_state(t2))
    return pulls, removed


def test_pool_sizes_bit_identical(tmp_path):
    """The whole verb surface — pull/write/end_day/shrink/save/load —
    produces bit-identical tables and pulls at pool size 1 vs N."""
    set_threads(1)
    t1 = make_table(delete_threshold=0.05)
    pulls1, removed1 = drive_workload(t1, tmp_path / "seq")
    state1 = table_state(t1)

    set_threads(4)
    t4 = make_table(delete_threshold=0.05)
    pulls4, removed4 = drive_workload(t4, tmp_path / "par")
    assert removed1 == removed4
    for p1, p4 in zip(pulls1, pulls4):
        for f in p1:
            np.testing.assert_array_equal(p1[f], p4[f], err_msg=f)
    assert_states_equal(state1, table_state(t4))


def test_growth_amortized_append():
    """Repeated-pass upsert of fresh keys must NOT reallocate every SoA
    array per call: capacity doubling keeps reallocations O(log rows)."""
    t = make_table(shard_num=4, dim=4)
    calls = 200
    for step in range(calls):
        keys = np.arange(step * 256 + 1, (step + 1) * 256 + 1, dtype=np.uint64)
        rows = t.bulk_pull(keys)
        t.bulk_write(keys, rows)
    grows, appends = t.grow_stats()
    assert appends == calls * t.shard_num       # every call appended
    # the old np.concatenate path reallocated once per append call; the
    # doubling buffers need ~log2(rows_per_shard / 64) reallocations
    assert grows <= t.shard_num * 16, (grows, appends)
    assert grows < appends / 8
    # buffers stay consistent: views match logical size, capacity >= size
    for s in t._shards:
        assert len(s.keys) == s.size <= s.capacity
        for f, v in s.soa.items():
            assert len(v) == s.size, f


def test_overwrite_only_upsert_never_grows():
    t = make_table(shard_num=2, dim=4)
    keys = np.arange(1, 1001, dtype=np.uint64)
    rows = t.bulk_pull(keys)
    t.bulk_write(keys, rows)
    grows0, _ = t.grow_stats()
    for _ in range(20):                      # pure overwrites
        rows["show"] += 1.0
        t.bulk_write(keys, rows)
    grows1, _ = t.grow_stats()
    assert grows1 == grows0
    np.testing.assert_allclose(
        t.bulk_pull(keys)["show"], rows["show"])


def test_concurrent_preload_pull_vs_upsert_stress():
    """The pipelined engine's shape: a preload thread bulk_pulls while the
    main thread bulk_writes — through a real multi-thread pool.  The final
    table must hold exactly the written values, and every pull must return
    internally consistent rows (never a torn row)."""
    set_threads(4)
    t = make_table(shard_num=8, dim=8)
    rng = np.random.default_rng(1)
    stop = threading.Event()
    errors = []

    def puller():
        prng = np.random.default_rng(2)
        try:
            while not stop.is_set():
                keys = np.unique(
                    prng.integers(1, 20_000, 512).astype(np.uint64))
                rows = t.bulk_pull(keys)
                # written rows always carry show == click (the writer's
                # invariant below); fresh defaults carry 0 == 0
                np.testing.assert_array_equal(rows["show"], rows["click"])
        except Exception as e:  # surfaced after join
            errors.append(e)

    th = threading.Thread(target=puller, daemon=True)
    th.start()
    written = {}
    for step in range(30):
        keys = np.unique(rng.integers(1, 20_000, 512).astype(np.uint64))
        rows = t.bulk_pull(keys)
        val = np.float32(step + 1)
        rows["show"][:] = val
        rows["click"][:] = val
        t.bulk_write(keys, rows)
        for k in keys.tolist():
            written[k] = val
    stop.set()
    th.join(timeout=30)
    assert not th.is_alive() and not errors, errors
    all_keys = np.array(sorted(written), np.uint64)
    back = t.bulk_pull(all_keys)
    np.testing.assert_array_equal(
        back["show"], np.array([written[k] for k in all_keys.tolist()],
                               np.float32))
    # pool-induced queueing on hot shards is now visible: lock WAIT
    # histograms sit beside the hold-time ones
    snap = stat_snapshot("ps.host_table")
    assert snap.get("ps.host_table.pull_lock_wait_s.count", 0) > 0
    assert snap.get("ps.host_table.write_lock_wait_s.count", 0) > 0
    assert snap.get("ps.host_table.write_lock_hold_s.count", 0) > 0


def test_ssd_fault_in_pooled_matches_sequential(tmp_path):
    """Spill + batched fault-in through the pool vs sequentially: same
    promoted rows, same values, same residency split."""
    from paddlebox_tpu.ps.ssd_table import SSDTieredTable

    def run(threads, sub):
        set_threads(threads)
        host = make_table(shard_num=8, dim=4)
        tiered = SSDTieredTable(host, str(tmp_path / sub))
        keys = np.arange(1, 2001, dtype=np.uint64)
        rows = host.bulk_pull(keys)
        rows["show"][:1000] = 0.1
        rows["show"][1000:] = 100.0
        host.bulk_write(keys, rows)
        spilled = tiered.spill(score_threshold=1.0)
        pull = tiered.bulk_pull(np.arange(1, 2001, 7, dtype=np.uint64))
        return spilled, host.size(), tiered.total_size(), pull

    s1, h1, t1, p1 = run(1, "seq")
    s4, h4, t4, p4 = run(4, "par")
    assert (s1, h1, t1) == (s4, h4, t4)
    for f in p1:
        np.testing.assert_array_equal(p1[f], p4[f], err_msg=f)


def test_delta_save_is_atomic_per_shard(tmp_path):
    """A mid-save filesystem failure must not lose deltas: each shard
    writes to a tmp name + renames, and delta_score resets only after its
    shard file landed."""
    from paddlebox_tpu.io import fs as pfs

    set_threads(1)                 # deterministic failure ordering
    t = make_table(shard_num=4, dim=4, delta_threshold=0.0)
    keys = np.arange(1, 401, dtype=np.uint64)
    rows = t.bulk_pull(keys)
    rows["delta_score"][:] = 3.0
    rows["show"][:] = 5.0
    t.bulk_write(keys, rows)

    broken = "part-00002"

    class FailingFS(pfs.LocalFS):
        @staticmethod
        def _strip(path):
            if path.startswith("failfs://"):
                path = path[len("failfs://"):]
            return pfs.LocalFS._strip(path)

        def open_write(self, path):
            if broken in path:
                raise IOError("disk full (injected)")
            return super().open_write(path)

    pfs.register_fs("failfs", FailingFS())
    try:
        with pytest.raises(IOError, match="disk full"):
            t.save(f"failfs://{tmp_path}/delta", mode="delta")
    finally:
        pfs.register_fs("failfs", pfs.LocalFS())  # defuse for other users
    # the failed shard kept its deltas; no torn shard file is visible
    assert not os.path.exists(
        str(tmp_path / "delta" / f"{broken}.shard.npz"))
    failed_shard = t._shards[2]
    assert (failed_shard.soa["delta_score"] == 3.0).all()
    # shards whose file landed DID reset (write happened before the fail)
    landed = [i for i in range(4) if i != 2 and t._shards[i].size]
    assert any((t._shards[i].soa["delta_score"] == 0.0).all()
               for i in landed)
    # a clean retry completes and leaves no tmp litter
    n = t.save(str(tmp_path / "delta2"), mode="delta")
    assert n > 0
    files = sorted(os.listdir(tmp_path / "delta2"))
    assert files and all(f.endswith(".shard.npz") for f in files)
    for s in t._shards:
        assert (s.soa["delta_score"] == 0.0).all()


def test_pool_metrics_in_statz_and_pass_report():
    """Queue-depth/utilization metrics reach /statz and the per-pass
    report (the acceptance surface of the PR 4 observability fold-in)."""
    import json
    import urllib.request

    from paddlebox_tpu.ps.pass_manager import BoxPSEngine
    from paddlebox_tpu.utils import obs_server

    set_threads(4)
    eng = BoxPSEngine(EmbeddingTableConfig(embedding_dim=4, shard_num=8))
    eng.begin_feed_pass()
    eng.add_keys(np.arange(1, 4001, dtype=np.uint64))
    eng.end_feed_pass()
    eng.begin_pass()
    eng.ws["show"] = eng.ws["show"] + 1.0
    eng.end_pass()

    report = eng.pass_report()
    assert "pool table:" in report
    assert "queue_hwm=" in report and "busy=" in report

    srv = obs_server.ObsServer(port=0)
    try:
        url = f"http://127.0.0.1:{srv.addr[1]}/statz"
        with urllib.request.urlopen(url, timeout=5) as resp:
            snap = json.loads(resp.read().decode())
    finally:
        srv.shutdown()
    assert snap.get("ps.pool.table.tasks", 0) > 0
    assert "ps.pool.table.queue_depth_hwm" in snap
    assert "ps.pool.table.utilization.p95" in snap
    assert snap.get("ps.pool.table.threads") == 4.0


def test_chaos_day_through_pooled_table():
    """A fast chaos day (in-process fault hooks: dropped acks, delays,
    truncated frames) against a POOLED server table must stay
    bit-identical to the fault-free pooled run — the shard pool composes
    with the exactly-once retry protocol."""
    from paddlebox_tpu.ps import faults
    from paddlebox_tpu.ps.pass_manager import BoxPSEngine
    from paddlebox_tpu.ps.service import PSClient, PSServer, \
        RemoteTableAdapter

    set_threads(4)

    def run_day(plan) -> np.ndarray:
        table = make_table(shard_num=8, dim=4)
        server = PSServer(table)
        client = PSClient(server.addr, retries=None, retry_sleep=0.01,
                          deadline=30.0)
        if plan is not None:
            faults.install(plan)
        try:
            engine = BoxPSEngine(EmbeddingTableConfig(
                embedding_dim=4, shard_num=8))
            engine.table = RemoteTableAdapter(client, delta_mode=True)
            for p in range(3):
                rng = np.random.default_rng(100 + p)
                engine.begin_feed_pass()
                engine.add_keys(np.unique(
                    rng.integers(1, 500, 150).astype(np.uint64)))
                engine.end_feed_pass()
                engine.begin_pass()
                engine.ws["show"] = engine.ws["show"] + float(p + 1)
                engine.ws["mf"] = engine.ws["mf"] + 0.5
                engine.end_pass()
        finally:
            faults.uninstall()
        keys = np.arange(1, 500, dtype=np.uint64)
        out = client.pull_sparse(keys)
        client.close()
        server.shutdown()
        digest = np.concatenate([np.asarray(v, np.float64).ravel()
                                 for _, v in sorted(out.items())])
        return digest

    flags.set_flags({"ps_fault_injection": True})
    try:
        baseline = run_day(None)
        chaos = run_day(faults.FaultPlan.default_chaos(seed=5))
    finally:
        flags.set_flags({"ps_fault_injection": False})
    np.testing.assert_array_equal(baseline, chaos)


@pytest.mark.skipif(len(os.sched_getaffinity(0)) < 4,
                    reason="speedup microbench needs a multi-core host")
def test_microbench_pull_write_2x_speedup():
    """bulk_pull + bulk_write over 8 shards must run ≥2x faster at
    FLAGS_ps_table_threads=4 than =1 (the numpy gather/scatter releases
    the GIL), with bit-identical final table state."""
    SHARDS, DIM, N = 8, 32, 200_000
    rng = np.random.default_rng(3)
    keys = np.unique(rng.integers(1, 2**62, N).astype(np.uint64))

    def build(threads):
        set_threads(threads)
        t = make_table(shard_num=SHARDS, dim=DIM)
        rows = t.bulk_pull(keys)
        t.bulk_write(keys, rows)          # populate (append path)
        return t

    def timed(t):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            rows = t.bulk_pull(keys)
            rows["show"] += 1.0
            t.bulk_write(keys, rows)      # steady-state overwrite
            best = min(best, time.perf_counter() - t0)
        return best

    t_seq = build(1)
    s_seq = timed(t_seq)
    t_par = build(4)
    s_par = timed(t_par)
    assert_states_equal(table_state(t_seq), table_state(t_par))
    speedup = s_seq / s_par
    assert speedup >= 2.0, f"speedup {speedup:.2f}x (seq {s_seq:.3f}s, " \
                           f"par {s_par:.3f}s)"
