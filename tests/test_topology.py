import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.config import MeshConfig
from paddlebox_tpu.parallel.topology import HybridTopology, single_host_topology
from paddlebox_tpu.parallel import collective
from jax.sharding import PartitionSpec as P
from jax import shard_map


def test_mesh_degrees():
    topo = HybridTopology(MeshConfig(dp=2, mp=4))
    assert topo.world_size == 8
    assert topo.axis_size("dp") == 2
    assert topo.axis_size("mp") == 4
    assert topo.axis_size("pp") == 1


def test_bad_degrees_raises():
    with pytest.raises(ValueError):
        HybridTopology(MeshConfig(dp=3))


def test_batch_sharding_places_data():
    topo = single_host_topology(dp=8)
    x = jnp.arange(16.0).reshape(16, 1)
    xs = jax.device_put(x, topo.batch_sharding())
    assert len(xs.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(xs), np.arange(16.0).reshape(16, 1))


def test_all_reduce_inside_shard_map():
    topo = single_host_topology(dp=8)
    x = jnp.ones((8, 4))

    def f(xs):
        return collective.all_reduce(jnp.sum(xs), "dp")

    g = shard_map(f, mesh=topo.mesh, in_specs=P("dp"), out_specs=P(),
                  check_vma=False)
    assert float(g(x)) == 32.0


def test_all_to_all_roundtrip():
    topo = single_host_topology(dp=8)
    n = 8
    x = jnp.arange(n * n, dtype=jnp.float32).reshape(n, n)

    def f(xs):  # xs: [1, n] block per device
        y = collective.all_to_all(xs, "dp", split_dim=1, concat_dim=0)
        z = collective.all_to_all(y, "dp", split_dim=0, concat_dim=1)
        return z

    g = shard_map(f, mesh=topo.mesh, in_specs=P("dp", None),
                  out_specs=P("dp", None), check_vma=False)
    np.testing.assert_allclose(np.asarray(g(x)), np.asarray(x))


def test_ring_shift():
    topo = single_host_topology(dp=8)
    x = jnp.arange(8.0).reshape(8, 1)

    def f(xs):
        return collective.shift_right(xs, "dp", 8)

    g = shard_map(f, mesh=topo.mesh, in_specs=P("dp"), out_specs=P("dp"),
                  check_vma=False)
    out = np.asarray(g(x)).ravel()
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))
