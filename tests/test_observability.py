"""Observability layer: histogram percentiles vs a numpy reference,
snapshot prefix-boundary semantics, the /metrics + /statz + /tracez
exporter round trip, wire-propagated trace context surviving the
pipelined multi-stream path and chaos retries WITHOUT duplicate server
spans, the per-pass PrintSyncTimer report, the health-verb stats
sub-dict, and the PB204 metric-name lint rule."""

import json
import textwrap
import urllib.request

import numpy as np
import pytest

from paddlebox_tpu import flags
from paddlebox_tpu.config import EmbeddingTableConfig
from paddlebox_tpu.ps import faults
from paddlebox_tpu.ps.host_table import ShardedHostTable
from paddlebox_tpu.ps.pass_manager import BoxPSEngine
from paddlebox_tpu.ps.service import PSClient, PSServer, RemoteTableAdapter
from paddlebox_tpu.utils import obs_server, trace
from paddlebox_tpu.utils.monitor import (Histogram, StatRegistry, stat_add,
                                         stat_get, stat_observe, stat_set,
                                         stat_snapshot)

CFG = dict(embedding_dim=4, shard_num=4)


@pytest.fixture(autouse=True)
def _clean():
    StatRegistry.instance().reset()
    trace.disable()
    yield
    faults.uninstall()
    trace.disable()
    flags.set_flags({"ps_fault_injection": False, "obs_pass_report": False})


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as r:
        return r.read().decode("utf-8")


# ---------------------------------------------------------------------------
# histograms + registry semantics
# ---------------------------------------------------------------------------
def test_histogram_percentiles_match_numpy_reference():
    rng = np.random.default_rng(42)
    # latency-shaped data spanning several orders of magnitude
    vals = rng.lognormal(mean=-6.0, sigma=1.6, size=50_000)
    h = Histogram()
    for v in vals:
        h.observe(v)
    s = h.summary()
    assert s["count"] == len(vals)
    assert s["sum"] == pytest.approx(vals.sum())
    assert s["max"] == vals.max()                       # exact, not bucketed
    for q in (50, 95, 99):
        ref = np.percentile(vals, q)
        est = h.percentile(q)
        # quarter-octave log buckets: ≤ ~9% bucket-width error, leave
        # headroom for within-bucket distribution skew
        assert abs(est - ref) / ref < 0.20, (q, est, ref)


def test_histogram_extremes_and_empty():
    h = Histogram()
    assert h.percentile(50) == 0.0
    h.observe(0.0)                      # underflow bucket
    h.observe(1e12)                     # overflow bucket
    assert h.summary()["max"] == 1e12
    assert h.percentile(99) == 1e12
    assert h.count == 2


def test_stat_observe_snapshot_keys():
    for v in (0.001, 0.002, 0.004):
        stat_observe("t.lat_s", v)
    s = stat_snapshot("t.lat_s")
    assert s["t.lat_s.count"] == 3.0
    assert s["t.lat_s.max"] == 0.004
    assert s["t.lat_s.p50"] > 0
    # histogram keys participate in prefix scrapes like counters
    assert "t.lat_s.p99" in stat_snapshot("t.")


def test_snapshot_prefix_matches_dotted_segments_only():
    stat_add("ps.s.y", 2.0)
    stat_add("ps.streams.x", 1.0)
    stat_add("ps.s", 7.0)
    assert set(stat_snapshot("ps.s")) == {"ps.s", "ps.s.y"}
    assert set(stat_snapshot("ps.streams")) == {"ps.streams.x"}
    assert set(stat_snapshot("ps.")) == {"ps.s", "ps.s.y", "ps.streams.x"}
    assert set(stat_snapshot("")) >= {"ps.s", "ps.s.y", "ps.streams.x"}


def test_stat_set_overwrites():
    stat_add("g.v", 5.0)
    stat_set("g.v", 2.0)
    assert stat_get("g.v") == 2.0
    stat_set("g.fresh", 1.5)
    assert stat_get("g.fresh") == 1.5


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------
def test_tracer_nesting_ring_and_chrome_export(tmp_path):
    tr = trace.enable(ring=8)
    with trace.span("a.parent") as sp:
        parent_ctx = sp.context()
        with trace.span("a.child"):
            pass
    spans = tr.spans()
    by_name = {s["name"]: s for s in spans}
    assert by_name["a.child"]["trace_id"] == by_name["a.parent"]["trace_id"]
    assert by_name["a.child"]["parent_id"] == by_name["a.parent"]["span_id"]
    # explicit parent (the wire form) adopts trace id across "processes"
    with trace.span("b.remote", parent=parent_ctx):
        pass
    remote = tr.spans()[0]
    assert remote["trace_id"] == by_name["a.parent"]["trace_id"]
    # ring retention is bounded
    for i in range(50):
        with trace.span("c.spam"):
            pass
    assert len(tr.spans()) == 8
    out = tr.export_chrome_trace(str(tmp_path))
    events = json.load(open(out))["traceEvents"]
    assert len(events) == 8 and all(e["ph"] == "X" for e in events)


def test_tracer_disabled_is_noop():
    assert trace.ACTIVE is None
    assert trace.wire_context() is None
    with trace.span("x.y") as s:
        assert s is None


# ---------------------------------------------------------------------------
# exporter round trip
# ---------------------------------------------------------------------------
def test_metrics_statz_tracez_roundtrip():
    stat_add("rt.counter", 3.0)
    for v in (0.01, 0.02, 0.03, 0.04):
        stat_observe("rt.lat_s", v)
    tr = trace.enable()
    with trace.span("rt.span"):
        pass
    srv = obs_server.ObsServer(port=0)
    try:
        port = srv.addr[1]
        metrics = _get(port, "/metrics")
        assert "# TYPE pbox_rt_counter gauge" in metrics
        assert "pbox_rt_counter 3.0" in metrics
        assert "# TYPE pbox_rt_lat_s summary" in metrics
        assert 'pbox_rt_lat_s{quantile="0.99"}' in metrics
        assert "pbox_rt_lat_s_count 4" in metrics
        statz = json.loads(_get(port, "/statz"))
        assert statz["rt.counter"] == 3.0
        assert statz["rt.lat_s.count"] == 4.0
        assert statz["rt.lat_s.max"] == 0.04
        tracez = json.loads(_get(port, "/tracez"))
        assert tracez["enabled"]
        assert any(s["name"] == "rt.span" for s in tracez["spans"])
        # unknown path → 404, server survives
        with pytest.raises(urllib.error.HTTPError):
            _get(port, "/nope")
        assert json.loads(_get(port, "/statz"))["rt.counter"] == 3.0
    finally:
        srv.shutdown()
        assert tr is trace.ACTIVE or trace.ACTIVE is None


def test_merge_snapshots_sums_counters_maxes_quantiles():
    a = {"ps.client.retry": 2.0, "ps.x.latency_s.p99": 0.5,
         "ps.client.inflight_hwm": 3.0}
    b = {"ps.client.retry": 1.0, "ps.x.latency_s.p99": 0.9,
         "ps.client.inflight_hwm": 8.0}
    m = obs_server.merge_snapshots([a, b])
    assert m["ps.client.retry"] == 3.0              # summed
    assert m["ps.x.latency_s.p99"] == 0.9           # worst worker
    assert m["ps.client.inflight_hwm"] == 8.0       # hwm


# ---------------------------------------------------------------------------
# wire-propagated trace context (composes with ps/faults.py plans)
# ---------------------------------------------------------------------------
def test_trace_context_survives_pipeline_and_chaos_without_dup_spans():
    """A pipelined multi-chunk delta push under an ack-drop fault: the
    retry resolves through the dedup window, every server span carries
    the client's trace_id, and NO rid gets a second server span."""
    tr = trace.enable()
    flags.set_flags({"ps_fault_injection": True})
    table = ShardedHostTable(EmbeddingTableConfig(**CFG), seed=0)
    srv = PSServer(table)
    try:
        client = PSClient(srv.addr, retries=None, retry_sleep=0.01,
                          backoff_cap=0.1, deadline=30,
                          max_frame=1 << 13, streams=4, window=8)
        keys = np.unique(np.random.default_rng(0)
                         .integers(1, 5000, 3000).astype(np.uint64))
        rows = client.pull_sparse(keys, create=True)
        d = {f: np.zeros_like(v) for f, v in rows.items()}
        d["show"] = np.ones(len(keys), np.float32)
        # first server send dropped: applied-but-unacked → the resend
        # MUST dedup (no re-execution, hence no second span)
        faults.install(faults.FaultPlan(seed=7)
                       .drop("send", role="server", at=(1,)))
        client.push_sparse_delta(keys, d)
        faults.uninstall()
    finally:
        faults.uninstall()
        srv.shutdown()

    assert stat_get("ps.server.dedup_hit") >= 1      # the retry deduped
    assert stat_get("ps.client.inflight_hwm") > 1    # really pipelined
    spans = tr.spans()
    bulk = [s for s in spans
            if s["name"] == "ps.client.push_sparse_delta.bulk"]
    assert len(bulk) == 1
    server = [s for s in spans
              if s["name"] == "ps.server.push_sparse_delta"]
    assert len(server) > 1                           # multi-chunk
    rids = [s["attrs"]["rid"] for s in server]
    assert len(rids) == len(set(rids)), "duplicate server span for a rid"
    assert all(s["trace_id"] == bulk[0]["trace_id"] for s in server)
    assert all(s["parent_id"] == bulk[0]["span_id"] for s in server)
    # client + server latency histograms recorded on both sides
    snap = stat_snapshot("ps.")
    assert snap["ps.client.push_sparse_delta.latency_s.count"] > 0
    assert snap["ps.server.push_sparse_delta.latency_s.p50"] > 0


def test_single_rpc_verbs_trace_and_observe():
    tr = trace.enable()
    table = ShardedHostTable(EmbeddingTableConfig(**CFG), seed=0)
    srv = PSServer(table)
    try:
        client = PSClient(srv.addr)
        client.barrier(1, timeout=10)
        h = client.health()
        assert "stats" in h
    finally:
        srv.shutdown()
    spans = tr.spans()
    cli = [s for s in spans if s["name"] == "ps.client.barrier"]
    sv = [s for s in spans if s["name"] == "ps.server.barrier"]
    assert len(cli) == 1 and len(sv) == 1
    assert sv[0]["trace_id"] == cli[0]["trace_id"]
    assert sv[0]["parent_id"] == cli[0]["span_id"]
    assert stat_get("ps.client.barrier.latency_s.count") == 0.0  # counter ns
    assert stat_snapshot("ps.client.barrier.latency_s")[
        "ps.client.barrier.latency_s.count"] == 1.0


# ---------------------------------------------------------------------------
# health verb: liveness doubles as a metrics pull
# ---------------------------------------------------------------------------
def test_health_carries_stats_subdict():
    table = ShardedHostTable(EmbeddingTableConfig(**CFG), seed=0)
    srv = PSServer(table)
    try:
        client = PSClient(srv.addr)
        keys = np.arange(1, 50, dtype=np.uint64)
        client.pull_sparse(keys)
        h = client.health()
        stats = h["stats"]
        assert isinstance(stats, dict)
        # server-side latency histogram of the pull we just did, pulled
        # REMOTELY with FLAGS_obs_port off
        assert stats["ps.server.pull_sparse.latency_s.count"] >= 1.0
        assert all(isinstance(v, float) for v in stats.values())
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# per-pass PrintSyncTimer report
# ---------------------------------------------------------------------------
def _drive_one_pass(engine, day, p):
    rng = np.random.default_rng(1000 * day + p)
    keys = np.unique(rng.integers(1, 400, size=120).astype(np.uint64))
    engine.begin_feed_pass()
    engine.add_keys(keys)
    engine.end_feed_pass()
    engine.begin_pass()
    engine.ws["show"] = engine.ws["show"] + 1.0
    engine.end_pass()


def test_pass_report_prints_table(capsys):
    table = ShardedHostTable(EmbeddingTableConfig(**CFG), seed=0)
    srv = PSServer(table)
    try:
        client = PSClient(srv.addr)
        engine = BoxPSEngine(EmbeddingTableConfig(**CFG))
        engine.table = RemoteTableAdapter(client, delta_mode=True)
        engine.set_date("20260801")
        flags.set_flags({"obs_pass_report": True})
        _drive_one_pass(engine, 0, 0)
        out = capsys.readouterr().out
        assert "PrintSyncTimer pass 1 day 20260801" in out
        assert "build_pull" in out and "dump_to_cpu" in out
        assert "wire tx_bytes:" in out and "pull_sparse=" in out
        assert "inflight_hwm=" in out
        # second pass reports ITS OWN deltas, not cumulative seconds
        _drive_one_pass(engine, 0, 1)
        out2 = capsys.readouterr().out
        assert "PrintSyncTimer pass 2" in out2
        counts = [ln for ln in out2.splitlines() if "build_pull" in ln]
        assert counts and counts[0].split()[-1] == "1"   # 1 this pass
    finally:
        flags.set_flags({"obs_pass_report": False})
        srv.shutdown()


def test_pass_report_off_by_default(capsys):
    engine = BoxPSEngine(EmbeddingTableConfig(**CFG))
    _drive_one_pass(engine, 0, 0)
    assert "PrintSyncTimer" not in capsys.readouterr().out


# ---------------------------------------------------------------------------
# the acceptance soak: chaos day with the exporter live
# ---------------------------------------------------------------------------
def _chaos_day_with_exporter(days, passes):
    trace.enable()
    flags.set_flags({"ps_fault_injection": True})
    srv_obs = obs_server.ObsServer(port=0)
    table = ShardedHostTable(EmbeddingTableConfig(**CFG), seed=0)
    srv = PSServer(table)
    try:
        client = PSClient(srv.addr, retries=None, retry_sleep=0.01,
                          backoff_cap=0.1, deadline=30,
                          max_frame=1 << 13, streams=4, window=8)
        # preamble (the test_ps_faults/test_chaos_soak pattern): one pull
        # (server send 0), then a delta push whose ack (server send 1) is
        # dropped — applied-but-unacked, so the retry MUST dedup
        pre = np.array([999_001, 999_002], np.uint64)
        rows = client.pull_sparse(pre, create=True)
        d = {f: np.zeros_like(v) for f, v in rows.items()}
        faults.install(faults.FaultPlan(seed=11)
                       .drop("send", role="server", at=(1,))
                       .drop("send", role="client", at=(5,))
                       .delay("send", 0.001, role="client", prob=0.05))
        client.pull_sparse(pre)
        client.push_sparse_delta(pre, d)
        engine = BoxPSEngine(EmbeddingTableConfig(**CFG))
        engine.table = RemoteTableAdapter(client, delta_mode=True)
        for day in range(days):
            engine.set_date(f"2026080{day + 1}")
            for p in range(passes):
                _drive_one_pass(engine, day, p)
        faults.uninstall()
        port = srv_obs.addr[1]
        metrics = _get(port, "/metrics")
        statz = json.loads(_get(port, "/statz"))
        tracez = json.loads(_get(port, "/tracez"))
        return metrics, statz, tracez
    finally:
        faults.uninstall()
        srv.shutdown()
        srv_obs.shutdown()


def _assert_soak_observability(metrics, statz, tracez):
    # non-zero verb-latency histograms served over /metrics
    assert 'pbox_ps_server_pull_sparse_latency_s{quantile="0.99"}' in metrics
    assert statz["ps.server.pull_sparse.latency_s.count"] > 0
    assert statz["ps.client.push_sparse_delta.latency_s.count"] > 0
    assert statz["ps.server.dedup_hit"] >= 1
    # /tracez server dispatch spans carry the originating client trace_id
    spans = tracez["spans"]
    server = [s for s in spans if s["name"].startswith("ps.server.")]
    client_b = [s for s in spans if s["name"].endswith(".bulk")]
    assert server and client_b
    client_traces = {s["trace_id"] for s in client_b}
    linked = [s for s in server if s["trace_id"] in client_traces]
    assert linked, "no server span carries a client trace id"
    # dedup-protected verbs must never span twice for one rid (an
    # idempotent pull retry legitimately RE-EXECUTES and re-spans — only
    # the exactly-once verbs promise one execution, hence one span)
    rid_names = {}
    for s in server:
        if s["name"] == "ps.server.push_sparse_delta":
            key = s["attrs"].get("rid")
            rid_names[key] = rid_names.get(key, 0) + 1
    dup = {k: n for k, n in rid_names.items() if n > 1 and k is not None}
    assert not dup, f"duplicate server spans under chaos retry: {dup}"


def test_chaos_day_with_exporter_fast():
    _assert_soak_observability(*_chaos_day_with_exporter(1, 2))


@pytest.mark.slow
def test_chaos_soak_with_exporter_two_days():
    """Acceptance: a 2-day x 3-pass chaos soak with the exporter live
    serves non-zero verb-latency histograms on /metrics and /tracez
    spans whose server dispatch spans carry the client's trace_id."""
    _assert_soak_observability(*_chaos_day_with_exporter(2, 3))


# ---------------------------------------------------------------------------
# PB204 lint rule
# ---------------------------------------------------------------------------
def test_pb204_flags_unbounded_dynamic_names():
    from paddlebox_tpu.tools.pboxlint import lint_source

    def codes(src):
        return [f.code for f in lint_source(textwrap.dedent(src))]

    bad = codes("""
        from paddlebox_tpu.utils.monitor import stat_add
        def f(key):
            stat_add(f"ps.keys.{key}", 1.0)
    """)
    # PB204 flags the unbounded dynamic name; PB208 additionally names the
    # raw-feature-key disease and its sketch cure on the same site
    assert bad == ["PB204", "PB208"]
    assert codes("""
        from paddlebox_tpu.utils.monitor import stat_add
        def f(rid):
            stat_add("ps.rid." + rid)
    """) == ["PB204"]
    assert codes("""
        from paddlebox_tpu.utils.monitor import stat_add
        stat_add("ps.Server.Latency", 1.0)
    """) == ["PB204"]
    # bounded fields pass: a verb/cmd's value set is the wire protocol's
    assert codes("""
        from paddlebox_tpu.utils.monitor import stat_add, stat_observe
        def f(verb, msg, hit):
            stat_add(f"ps.wire.{verb}.tx_bytes", 1.0)
            stat_observe(f"ps.server.{msg['cmd']}.latency_s", 0.1)
            stat_add(f"ps.fault.{hit.kind}")
    """) == []
    # span starters are covered too
    assert codes("""
        import paddlebox_tpu.utils.trace as trace
        def f(key):
            with trace.span(f"pass.{key}"):
                pass
    """) == ["PB204", "PB208"]
    # suppression with a reason works like every other rule
    assert codes("""
        from paddlebox_tpu.utils.monitor import stat_add
        def f(key):
            stat_add(f"ps.keys.{key}")  # pboxlint: disable=PB204,PB208 -- test
    """) == []
