"""bench.py harness logic: watchdog, partial emission, JSON contract.

The driver's only view of a round's performance is bench.py's LAST stdout
line — these tests pin the contract the driver depends on: always exactly
one parseable JSON object with metric/value/unit/vs_baseline, a watchdog
that emits the best partial value instead of hanging, and non-finite
floats sanitized to null.  Run in-process (module import, no subprocess)
with the phase clock manipulated directly.
"""

import importlib.util
import io
import json
import os
import sys
import time

import pytest


@pytest.fixture()
def bench(monkeypatch):
    """A fresh bench module per test (module-level _STATE is global)."""
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _last_json(capture: io.StringIO):
    lines = [ln for ln in capture.getvalue().splitlines() if ln.strip()]
    assert lines, "bench printed nothing"
    return json.loads(lines[-1])


def test_emit_contract(bench, monkeypatch):
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    bench.emit(123.456, final=True, basis="end_to_end", stage="full")
    line = _last_json(out)
    assert line["metric"] == bench.METRIC
    assert line["value"] == 123.5
    assert line["unit"] == "examples/s"
    assert line["vs_baseline"] == round(123.456 / 1e6, 4)
    assert line["basis"] == "end_to_end"
    assert bench._STATE["done"] is True


def test_emit_sanitizes_non_finite(bench, monkeypatch):
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    bench.emit(0.0, final=True,
               partial={"auc": float("nan"), "e2e": float("inf")})
    line = _last_json(out)  # must parse under strict JSON
    assert line["partial"]["auc"] is None
    assert line["partial"]["e2e"] is None


def test_best_prefers_e2e_over_smoke(bench):
    bench.record(smoke_device_step=10.0)
    assert bench._best() == 10.0
    bench.record(device_step=50.0)
    assert bench._best() == 50.0
    bench.record(e2e=40.0)
    assert bench._best() == 40.0   # e2e is the headline even if smaller


def test_watchdog_emits_partial_on_expired_phase(bench, monkeypatch):
    """A wedged phase must produce the best partial value + the phase name,
    not a hang or a bare 0.0."""
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    exited = {}

    def fake_exit(code):
        exited["code"] = code
        raise SystemExit                        # always escape the loop

    monkeypatch.setattr(os, "_exit", fake_exit)
    bench.record(device_step=473091.0)
    bench.set_phase("full:e2e", budget_s=-1)    # already expired
    with pytest.raises(SystemExit):
        bench._watchdog()
    line = _last_json(out)
    assert line["value"] == 473091.0
    assert "full:e2e" in line["error"]
    assert line["last_phase"] == "full:e2e"
    assert exited["code"] == 0


def test_watchdog_respects_done_flag(bench, monkeypatch):
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    bench._STATE["done"] = True
    t0 = time.time()
    bench._watchdog()                           # returns promptly, no emit
    assert time.time() - t0 < 10


def test_phase_budget_capped_by_global_deadline(bench):
    hard = bench.T0 + bench.TOTAL_BUDGET - 20
    bench.set_phase("x", budget_s=10 ** 9)
    assert bench._STATE["deadline"] <= hard


# -- supervisor: killable backend init (the round-4 failure mode) -----------

BENCH_PATH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


def _run_bench(env_extra, timeout):
    import subprocess
    env = dict(os.environ)
    env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, BENCH_PATH], capture_output=True, text=True,
        env=env, timeout=timeout)
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert lines, f"no stdout; stderr tail: {proc.stderr[-800:]}"
    return json.loads(lines[-1]), proc.stderr


def test_supervisor_kills_hung_backend_and_reports(tmp_path):
    """A jax.devices() hang must not eat the whole budget: the supervisor
    kills the wedged child, retries, and still prints one parseable JSON
    line with the wedge named."""
    line, err = _run_bench({
        "BENCH_TEST_HANG_INIT": "1",
        "BENCH_BACKEND_ATTEMPT_S": "5",
        "BENCH_TIMEOUT_S": "60"}, timeout=90)
    assert line["value"] == 0.0
    assert "wedged" in line.get("error", "")
    assert line["supervisor_attempts"] >= 2      # it retried
    assert "killing" in err
    log = line.get("attempt_log")
    assert log and len(log) == line["supervisor_attempts"]
    assert all(e["last_phase"] == "backend-init" for e in log)


def test_supervisor_recovers_from_transient_hang(tmp_path):
    """First attempt wedges (transient tunnel failure), second succeeds:
    the recorded result is the successful smoke run, not 0.0."""
    marker = str(tmp_path / "hang_once")
    open(marker, "w").close()
    line, _err = _run_bench({
        "BENCH_TEST_HANG_INIT_ONCE": marker,
        "BENCH_FORCE_CPU": "1",
        "BENCH_SMOKE_ONLY": "1",
        "BENCH_BACKEND_ATTEMPT_S": "10",
        "BENCH_TIMEOUT_S": "240"}, timeout=260)
    assert line["value"] > 0
    assert "error" not in line
    assert line["supervisor_attempts"] == 2
    assert line["stage"] == "smoke"


def test_supervisor_falls_back_to_cpu_after_wedge():
    """BENCH_r05 failure mode: a persistently wedged accelerator platform
    ate all 10 attempts and the round recorded 0.0.  After the FIRST
    wedged attempt the supervisor must fall back to JAX_PLATFORMS=cpu so
    later attempts reach a live backend.  BENCH_TEST_FAIL_AFTER_INIT
    stops the run right after backend-up (twice → deterministic-failure
    early exit), keeping the test fast while proving the fallback child
    really initialized a cpu backend."""
    line, err = _run_bench({
        "BENCH_TEST_HANG_UNLESS_CPU": "1",
        "BENCH_TEST_FAIL_AFTER_INIT": "post-fallback-marker",
        "BENCH_BACKEND_ATTEMPT_S": "5",
        "BENCH_TIMEOUT_S": "150"}, timeout=170)
    assert "falling back to JAX_PLATFORMS=cpu" in err
    assert "backend up: cpu" in err                 # fallback reached a backend
    assert line.get("platform_fallback") == "cpu"
    assert "post-fallback-marker" in line.get("error", "")
    # the final JSON names each attempt's platform and dying phase —
    # a failed round is diagnosable from the result line alone
    log = line.get("attempt_log")
    assert log and log[0]["platform"] == "default"
    assert log[0]["last_phase"] == "backend-init"
    assert all(e["platform"] == "cpu" for e in log[1:])


def test_better_prefers_clean_full_over_higher_value_smoke(bench):
    smoke = {"metric": bench.METRIC, "value": 9999.0, "stage": "smoke"}
    full = {"metric": bench.METRIC, "value": 1200.0, "stage": "full"}
    assert bench._better(smoke, full) is full
    assert bench._better(full, smoke) is full
    # error-free full still beats an errored full partial with more value
    part = {"metric": bench.METRIC, "value": 99999.0, "stage": "full",
            "error": "watchdog: ..."}
    assert bench._better(part, full) is full
    # an error line beats the bare backend-up marker at equal value
    up = {"metric": bench.METRIC, "value": 0.0, "stage": "backend-up"}
    err = {"metric": bench.METRIC, "value": 0.0, "error": "died"}
    assert bench._better(up, err) is err
    assert bench._better(err, up) is err


def test_supervisor_stops_on_repeated_deterministic_failure():
    """A post-backend failure that repeats identically must stop the retry
    loop (deterministic, not transient) — and the final line carries it."""
    line, err = _run_bench({
        "BENCH_FORCE_CPU": "1",
        "BENCH_TEST_FAIL_AFTER_INIT": "boom-deterministic",
        "BENCH_BACKEND_ATTEMPT_S": "30",
        "BENCH_TIMEOUT_S": "600"}, timeout=300)
    assert "boom-deterministic" in line.get("error", "")
    assert line["supervisor_attempts"] <= 2      # stopped early, not 20


# -- wedge postmortems + feed-gap + compare mode -----------------------------

def test_watchdog_writes_postmortem_before_error_line(bench, monkeypatch,
                                                      tmp_path):
    """Phase-budget expiry must leave a stack bundle on disk BEFORE the
    error line, and the line must carry its path."""
    from paddlebox_tpu import flags
    from paddlebox_tpu.utils import doctor  # registers obs_postmortem_dir
    assert doctor is not None
    flags.set_flags({"obs_postmortem_dir": str(tmp_path)})
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)

    def fake_exit(code):
        raise SystemExit

    monkeypatch.setattr(os, "_exit", fake_exit)
    try:
        bench.record(device_step=1000.0)
        bench.set_phase("full:compile", budget_s=-1)
        with pytest.raises(SystemExit):
            bench._watchdog()
    finally:
        flags.set_flags({"obs_postmortem_dir": ""})
    line = _last_json(out)
    pm = line["postmortem"]
    assert pm and os.path.exists(pm), line
    bundle = json.load(open(pm))
    assert "full:compile" in bundle["reason"]
    assert any(t["name"] == "MainThread" for t in bundle["threads"])
    assert isinstance(bundle["stats"], dict)


def test_wedged_child_ships_postmortem_bundle(tmp_path):
    """The acceptance scenario: a simulated post-backend wedge.  The
    child's watchdog writes a postmortem naming the stuck phase and the
    stuck thread, and the supervisor's attempt_log carries its path."""
    pm_dir = str(tmp_path / "pm")
    line, _err = _run_bench({
        "BENCH_FORCE_CPU": "1",
        "BENCH_TEST_WEDGE_PHASE": "1",
        "BENCH_TEST_WEDGE_BUDGET_S": "3",
        "FLAGS_obs_postmortem_dir": pm_dir,
        "BENCH_BACKEND_ATTEMPT_S": "60",
        "BENCH_TIMEOUT_S": "150"}, timeout=200)
    assert "wedge-sim" in line.get("error", ""), line
    log = line.get("attempt_log")
    assert log, line
    pm = log[0].get("postmortem")
    assert pm and os.path.exists(pm), log
    bundle = json.load(open(pm))
    assert "wedge-sim" in bundle["reason"]
    sleeper = [t for t in bundle["threads"] if t["name"] == "wedge-sleeper"]
    assert sleeper, [t["name"] for t in bundle["threads"]]
    assert any("sleep" in fr for fr in sleeper[0]["stack"])
    # last-N flight events rode along, including the phase trail
    phases = [e for e in bundle["flight"] if e["kind"] == "bench_phase"]
    assert any(e["phase"] == "wedge-sim" for e in phases)
    assert isinstance(bundle["stats"], dict)


def _result_file(path, value, gap, obs=None, wrapper=False):
    line = {"metric": "paddlebox_steady_examples_per_sec", "value": value,
            "unit": "examples/s", "vs_baseline": round(value / 1e6, 4),
            "final": True, "feed_gap_ratio": gap,
            "obs_stats": obs or {}}
    obj = {"n": 3, "cmd": "python bench.py", "rc": 0, "tail": "",
           "parsed": line} if wrapper else line
    path.write_text(json.dumps(obj))
    return str(path)


def test_compare_flags_throughput_regression(bench, monkeypatch, tmp_path):
    old = _result_file(tmp_path / "old.json", 1000.0, 2.0,
                       obs={"ps.client.retry": 1.0})
    new = _result_file(tmp_path / "new.json", 800.0, 2.0,
                       obs={"ps.client.retry": 9.0}, wrapper=True)
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    rc = bench.compare(old, new, threshold=0.05)
    assert rc == 1
    rep = json.loads(out.getvalue())
    assert rep["ok"] is False
    assert any("value" in r for r in rep["regressions"])
    assert rep["value"]["delta_frac"] == pytest.approx(-0.2)
    # obs movers beyond threshold are surfaced (informational)
    assert "ps.client.retry" in rep["obs_deltas"]


def test_compare_flags_feed_gap_regression_and_threshold(bench, monkeypatch,
                                                         tmp_path):
    old = _result_file(tmp_path / "old.json", 1000.0, 2.0)
    new = _result_file(tmp_path / "new.json", 1010.0, 3.0)
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    assert bench.compare(old, new, threshold=0.05) == 1   # gap +50%
    assert bench.compare(old, new, threshold=0.6) == 0    # within 60%


def test_compare_feed_gap_gate_skipped_when_device_idle(bench, monkeypatch,
                                                        tmp_path):
    """CPU-basis records carry device_busy_frac ~ 0: the gap ratio's
    denominator is milliseconds of device time, so a timing wobble
    swings it by double digits — the gate must not arm (the delta is
    still reported, flagged degenerate).  A real device measurement
    keeps it armed."""
    def rf(path, gap, db):
        path.write_text(json.dumps(
            {"metric": "m", "value": 1000.0, "final": True,
             "feed_gap_ratio": gap, "device_busy_frac": db}))
        return str(path)

    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    assert bench.compare(rf(tmp_path / "o1.json", 2.0, 0.0001),
                         rf(tmp_path / "n1.json", 3.0, 0.0002),
                         threshold=0.05) == 0
    rep = json.loads(out.getvalue())
    assert rep["feed_gap_ratio"]["degenerate"] is True
    monkeypatch.setattr(sys, "stdout", io.StringIO())
    assert bench.compare(rf(tmp_path / "o2.json", 2.0, 0.5),
                         rf(tmp_path / "n2.json", 3.0, 0.5),
                         threshold=0.05) == 1


def test_compare_flags_sparse_share_regression(bench, monkeypatch, tmp_path):
    """step_ms.sparse_share creeping back up is the padded-dense
    regression class the ragged path eliminated — compare gates it."""
    def rf(path, share):
        path.write_text(json.dumps(
            {"metric": "m", "value": 1000.0, "final": True,
             "step_ms": {"sparse_share": share}}))
        return str(path)

    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    assert bench.compare(rf(tmp_path / "o1.json", 0.40),
                         rf(tmp_path / "n1.json", 0.60),
                         threshold=0.05) == 1
    rep = json.loads(out.getvalue())
    assert any("sparse_share" in r for r in rep["regressions"])
    assert rep["sparse_share"]["delta_frac"] == pytest.approx(0.5)
    monkeypatch.setattr(sys, "stdout", io.StringIO())
    assert bench.compare(rf(tmp_path / "o2.json", 0.40),
                         rf(tmp_path / "n2.json", 0.41),
                         threshold=0.05) == 0


def test_compare_cli_dispatch(tmp_path):
    import subprocess
    old = _result_file(tmp_path / "old.json", 1000.0, 2.0)
    new = _result_file(tmp_path / "new.json", 990.0, 2.1)
    proc = subprocess.run(
        [sys.executable, BENCH_PATH, "--compare", old, new,
         "--threshold=0.1"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout)["ok"] is True
    bad = subprocess.run(
        [sys.executable, BENCH_PATH, "--compare", old],
        capture_output=True, text=True, timeout=60)
    assert bad.returncode == 2                    # usage error


def test_supervisor_smoke_line_never_shadows_dead_full_run():
    """A clean MID-RUN smoke line must not pass for the round result when
    the child dies before the full run: the final line keeps the smoke
    value (best partial evidence) but carries an error naming the death."""
    line, _err = _run_bench({
        "BENCH_FORCE_CPU": "1",
        "BENCH_TEST_DIE_AFTER_SMOKE": "1",
        "BENCH_BACKEND_ATTEMPT_S": "30",
        "BENCH_TIMEOUT_S": "360"}, timeout=380)
    assert line.get("error"), line                # never a clean fake
    assert line["value"] > 0                      # smoke evidence kept
    assert line.get("stage") == "smoke"
