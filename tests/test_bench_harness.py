"""bench.py harness logic: watchdog, partial emission, JSON contract.

The driver's only view of a round's performance is bench.py's LAST stdout
line — these tests pin the contract the driver depends on: always exactly
one parseable JSON object with metric/value/unit/vs_baseline, a watchdog
that emits the best partial value instead of hanging, and non-finite
floats sanitized to null.  Run in-process (module import, no subprocess)
with the phase clock manipulated directly.
"""

import importlib.util
import io
import json
import os
import sys
import time

import pytest


@pytest.fixture()
def bench(monkeypatch):
    """A fresh bench module per test (module-level _STATE is global)."""
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _last_json(capture: io.StringIO):
    lines = [ln for ln in capture.getvalue().splitlines() if ln.strip()]
    assert lines, "bench printed nothing"
    return json.loads(lines[-1])


def test_emit_contract(bench, monkeypatch):
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    bench.emit(123.456, final=True, basis="end_to_end", stage="full")
    line = _last_json(out)
    assert line["metric"] == bench.METRIC
    assert line["value"] == 123.5
    assert line["unit"] == "examples/s"
    assert line["vs_baseline"] == round(123.456 / 1e6, 4)
    assert line["basis"] == "end_to_end"
    assert bench._STATE["done"] is True


def test_emit_sanitizes_non_finite(bench, monkeypatch):
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    bench.emit(0.0, final=True,
               partial={"auc": float("nan"), "e2e": float("inf")})
    line = _last_json(out)  # must parse under strict JSON
    assert line["partial"]["auc"] is None
    assert line["partial"]["e2e"] is None


def test_best_prefers_e2e_over_smoke(bench):
    bench.record(smoke_device_step=10.0)
    assert bench._best() == 10.0
    bench.record(device_step=50.0)
    assert bench._best() == 50.0
    bench.record(e2e=40.0)
    assert bench._best() == 40.0   # e2e is the headline even if smaller


def test_watchdog_emits_partial_on_expired_phase(bench, monkeypatch):
    """A wedged phase must produce the best partial value + the phase name,
    not a hang or a bare 0.0."""
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    exited = {}

    def fake_exit(code):
        exited["code"] = code
        raise SystemExit                        # always escape the loop

    monkeypatch.setattr(os, "_exit", fake_exit)
    bench.record(device_step=473091.0)
    bench.set_phase("full:e2e", budget_s=-1)    # already expired
    with pytest.raises(SystemExit):
        bench._watchdog()
    line = _last_json(out)
    assert line["value"] == 473091.0
    assert "full:e2e" in line["error"]
    assert line["last_phase"] == "full:e2e"
    assert exited["code"] == 0


def test_watchdog_respects_done_flag(bench, monkeypatch):
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    bench._STATE["done"] = True
    t0 = time.time()
    bench._watchdog()                           # returns promptly, no emit
    assert time.time() - t0 < 10


def test_phase_budget_capped_by_global_deadline(bench):
    hard = bench.T0 + bench.TOTAL_BUDGET - 20
    bench.set_phase("x", budget_s=10 ** 9)
    assert bench._STATE["deadline"] <= hard
