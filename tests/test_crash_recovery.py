"""Crash-safe training acceptance (ISSUE 9): generation-chained
checkpoints, restart-durable exactly-once, and auto-resume proven by
kill-anywhere chaos.

The contract under test: with a ``TrainCheckpoint`` + an auto-resume
budget, a seeded kill at ANY lifecycle point — end-of-pass write-back,
mid-checkpoint sparse dump, the MANIFEST crash window, a mid-verb server
death — rolls the world back to the last committed generation and the
re-driven run converges to a final table + dense-params state
BIT-IDENTICAL to the fault-free baseline.  Exactly-once survives server
restarts two ways, both pinned here: the in-process dedup-window handoff
(launch.PSServerSupervisor) and the checkpoint's DEDUP.bin.
"""

import os
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu import fleet, flags
from paddlebox_tpu.config import EmbeddingTableConfig, SparseSGDConfig
from paddlebox_tpu.io.checkpoint import TrainCheckpoint
from paddlebox_tpu.launch import PSServerSupervisor
from paddlebox_tpu.models.deepfm import DeepFM
from paddlebox_tpu.ps import faults
from paddlebox_tpu.ps.host_table import ShardedHostTable
from paddlebox_tpu.ps.pass_manager import BoxPSEngine
from paddlebox_tpu.ps.service import PSClient, PSServer, RemoteTableAdapter
from paddlebox_tpu.trainer.trainer import SparseTrainer
from paddlebox_tpu.utils import flight
from paddlebox_tpu.utils.monitor import StatRegistry, stat_get
from tests.test_pass_pipeline import _simple_cfg, _write_slot_file

N_PASSES = 3
KEYS = np.array([11, 23, 35], np.uint64)


@pytest.fixture(autouse=True)
def _clean():
    StatRegistry.instance().reset()
    flags.set_flags({"ps_fault_injection": True})
    yield
    faults.uninstall()
    flags.set_flags({"ps_fault_injection": False})


def _table_cfg() -> EmbeddingTableConfig:
    return EmbeddingTableConfig(
        embedding_dim=4, shard_num=4,
        sgd=SparseSGDConfig(mf_create_thresholds=0.0))


def _fresh(table=None):
    """A deterministic engine/dataset/trainer trio (seeded init, one
    reader thread, no shuffle) so re-driven passes replay bit-for-bit."""
    cfg = _simple_cfg()
    eng = BoxPSEngine(_table_cfg(), seed=0)
    if table is not None:
        eng.table = table
    ds = fleet.BoxPSDataset(cfg, engine=eng, read_threads=1)
    model = DeepFM(num_slots=4, emb_width=3 + 4, dense_dim=3, hidden=(8,))
    tr = SparseTrainer(eng, model, cfg, batch_size=32, seed=0,
                       sparse_path="fast")
    return eng, ds, tr


def _table_state(table):
    keys = np.sort(np.concatenate([s.keys for s in table._shards]))
    return keys, table.bulk_pull(keys)


def _assert_same_table(table_a, table_b):
    ka, sa = _table_state(table_a)
    kb, sb = _table_state(table_b)
    np.testing.assert_array_equal(ka, kb)
    assert set(sa) == set(sb)
    for f in sa:
        np.testing.assert_array_equal(np.asarray(sa[f]), np.asarray(sb[f]),
                                      err_msg=f"table field {f!r}")


def _assert_same_params(tr_a, tr_b):
    import jax
    for pa, pb in zip(jax.tree_util.tree_leaves(tr_a.params),
                      jax.tree_util.tree_leaves(tr_b.params)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))


@pytest.fixture(scope="module")
def pass_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("crash-passes")
    files = []
    for p in range(N_PASSES):
        path = str(d / f"p{p}.txt")
        _write_slot_file(path, np.random.default_rng(p), 48)
        files.append([path])
    return files


@pytest.fixture(scope="module")
def baseline(pass_files):
    """Fault-free reference run — the state every chaos run must hit."""
    eng, ds, tr = _fresh()
    metrics = fleet.train_passes(tr, ds, pass_files, date="20260801",
                                 prefetch=False)
    return eng, tr, metrics


# ---------------------------------------------------------------------------
# Kill-at-lifecycle-point resume: bit-identity through the outer tier.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("point,hit,prefetch", [
    ("end_pass", 1, False),      # pass-1 write-back dies, serial loop
    ("end_pass", 1, True),       # same death through the prefetcher
    ("ckpt_sparse", 1, False),   # mid-checkpoint: shards down, gen not
                                 # assembled — previous gen must load
    ("ckpt_commit", 1, False),   # the MANIFEST crash window: gen dir
                                 # complete, pointer not yet swapped
])
def test_kill_point_resume_bit_identical(pass_files, baseline, tmp_path,
                                         point, hit, prefetch):
    base_eng, base_tr, base_metrics = baseline
    ck = TrainCheckpoint(str(tmp_path / "ckpt"))
    eng, ds, tr = _fresh()
    faults.install(faults.FaultPlan(seed=13).kill_at(point, at=(hit,)))
    metrics = fleet.train_passes(tr, ds, pass_files, date="20260801",
                                 prefetch=prefetch, checkpoint=ck,
                                 resume=4)
    faults.uninstall()

    assert len(metrics) == N_PASSES
    assert all(m is not None for m in metrics)
    np.testing.assert_array_equal(
        [m["loss"] for m in metrics],
        [m["loss"] for m in base_metrics])
    _assert_same_table(base_eng.table, eng.table)
    _assert_same_params(base_tr, tr)
    assert stat_get("ps.fleet.auto_resume") >= 1
    assert stat_get("ps.fault.lifecycle.kill") >= 1
    assert flight.events(kind="resume_ok")
    # crashed assembly dirs never survive the recovery cycle
    assert not [n for n in os.listdir(ck.root) if n.endswith(".tmp")]


def test_completed_day_rerun_is_noop(pass_files, tmp_path):
    """A fresh incarnation resuming a COMPLETED day skips every pass via
    the checkpointed cursor (None placeholders keep indices aligned) and
    leaves the restored table byte-identical."""
    ck = TrainCheckpoint(str(tmp_path / "ckpt"))
    eng, ds, tr = _fresh()
    m1 = fleet.train_passes(tr, ds, pass_files, date="20260801",
                            prefetch=False, checkpoint=ck, resume=2)
    assert all(m is not None for m in m1)

    eng2, ds2, tr2 = _fresh()
    m2 = fleet.train_passes(tr2, ds2, pass_files, date="20260801",
                            prefetch=False, checkpoint=ck, resume=2)
    assert m2 == [None] * N_PASSES
    _assert_same_table(eng.table, eng2.table)
    _assert_same_params(tr, tr2)


# ---------------------------------------------------------------------------
# Restart-durable exactly-once: the dedup window outlives the server.
# ---------------------------------------------------------------------------

def _applied_unacked_push(table, dedup_handoff):
    """Push one delta whose ack the schedule drops (applied server-side,
    client left retrying), kill the server in that window, restart it on
    the same port — with or without the dedup-window handoff — and let
    the retry land.  Returns (value before, value after)."""
    srv = PSServer(table)
    port = srv.addr[1]
    restarted = []
    try:
        client = PSClient(srv.addr, retries=None, retry_sleep=0.4,
                          backoff_cap=0.8, deadline=30)
        rows = client.pull_sparse(KEYS, create=True)
        base = np.asarray(rows["show"]).copy()
        faults.install(faults.FaultPlan(seed=3)
                       .drop("send", role="server", at=(0,)))
        d = {f: np.zeros_like(v) for f, v in rows.items()}
        d["show"] = np.ones(len(KEYS), np.float32)
        done = threading.Event()

        def push():
            client.push_sparse_delta(KEYS, d)
            done.set()

        t = threading.Thread(target=push, daemon=True)
        t.start()
        deadline = time.monotonic() + 10
        while stat_get("ps.fault.send.drop") < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        assert stat_get("ps.fault.send.drop") >= 1   # applied, ack lost
        state = srv.dedup_state() if dedup_handoff else None
        srv.kill()               # dies with the retry still in flight
        faults.uninstall()
        restarted.append(PSServer(table, port=port, dedup_state=state))
        assert done.wait(timeout=30)
        t.join(timeout=5)
        got = np.asarray(client.pull_sparse(KEYS)["show"])
        return base, got
    finally:
        faults.uninstall()
        for s in restarted:
            s.shutdown()
        srv.shutdown()


def test_dedup_handoff_restart_applies_exactly_once():
    table = ShardedHostTable(_table_cfg(), seed=0)
    base, got = _applied_unacked_push(table, dedup_handoff=True)
    np.testing.assert_array_equal(got, base + 1.0)   # exactly once
    assert stat_get("ps.server.dedup_hit") >= 1
    assert stat_get("ps.server.dedup_restore_entries") >= 1
    assert any(e.get("source") == "handoff"
               for e in flight.events(kind="dedup_restore"))


def test_dedup_restart_without_handoff_double_applies():
    """Sensitivity control: the SAME schedule with the window dropped on
    restart double-applies — restart-durable exactly-once rests on the
    persisted window, not on timing."""
    table = ShardedHostTable(_table_cfg(), seed=0)
    base, got = _applied_unacked_push(table, dedup_handoff=False)
    np.testing.assert_array_equal(got, base + 2.0)   # the double apply


def test_dedup_window_persists_through_checkpoint_save_load(tmp_path):
    """DEDUP.bin rides the sparse dump: a save verb persists the DONE
    entries next to the rows they describe; a load restores both from
    the SAME dump."""
    table = ShardedHostTable(_table_cfg(), seed=0)
    srv = PSServer(table)
    path = str(tmp_path / "sparse")
    try:
        client = PSClient(srv.addr, deadline=30)
        rows = client.pull_sparse(KEYS, create=True)
        d = {f: np.zeros_like(v) for f, v in rows.items()}
        d["show"] = np.ones(len(KEYS), np.float32)
        client.push_sparse_delta(KEYS, d)    # leaves a DONE dedup entry
        client.save(path, mode="all")
        assert os.path.exists(os.path.join(path, "DEDUP.bin"))
    finally:
        srv.shutdown()

    table2 = ShardedHostTable(_table_cfg(), seed=0)
    srv2 = PSServer(table2)
    try:
        client2 = PSClient(srv2.addr, deadline=30)
        client2.load(path)
        assert stat_get("ps.server.dedup_restore_entries") >= 1
        assert any(e.get("source") == "checkpoint"
                   for e in flight.events(kind="dedup_restore"))
        got = np.asarray(client2.pull_sparse(KEYS)["show"])
        np.testing.assert_array_equal(
            got, np.asarray(rows["show"]) + 1.0)
    finally:
        srv2.shutdown()


# ---------------------------------------------------------------------------
# Generation chain mechanics: retain-K GC and the resume roundtrip.
# ---------------------------------------------------------------------------

class _StubTrainer:
    """A numpy pytree stands in for dense params — flax serialization
    round-trips it exactly like the real trainer state."""

    def __init__(self):
        self.params = {"w": np.zeros(3, np.float32)}
        self.opt_state = {"m": np.zeros((2, 2), np.float32)}


def _mini_pass(eng, p):
    keys = np.unique(np.random.default_rng(p).integers(
        1, 300, size=80).astype(np.uint64))
    eng.begin_feed_pass()
    eng.add_keys(keys)
    eng.end_feed_pass()
    eng.begin_pass()
    eng.ws["show"] = eng.ws["show"] + float(p + 1)
    eng.end_pass()


def test_retain_k_gc_keeps_heads_and_chains(tmp_path):
    """keep=2, base_every=3 over base + 6 pass saves: gens 0(B) 1(D) 2(D)
    3(B) 4(D) 5(D) 6(B).  The two newest heads are 5 and 6; their chains
    reference {3,4,5} ∪ {6} — everything else must be reclaimed."""
    eng = BoxPSEngine(_table_cfg(), seed=0)
    eng.set_date("20260801")
    tr = _StubTrainer()
    ck = TrainCheckpoint(str(tmp_path / "ckpt"), keep=2, base_every=3)
    ck.save(eng, tr)                          # gen 0, base
    for p in range(6):
        _mini_pass(eng, p)
        ck.save_pass(eng, tr)                 # gens 1..6
    assert ck._manifest() == 6
    on_disk = sorted(int(n[4:]) for n in os.listdir(ck.root)
                     if n.startswith("gen-") and not n.endswith(".tmp"))
    assert on_disk == [3, 4, 5, 6]
    assert stat_get("ckpt.gc_removed") >= 1
    assert flight.events(kind="ckpt_gc")

    # roundtrip: a fresh world restored from the head chain matches
    eng2 = BoxPSEngine(_table_cfg(), seed=0)
    tr2 = _StubTrainer()
    tr2.params["w"] += 7.0                    # must be overwritten
    state = ck.resume(eng2, tr2)
    assert state["generation"] == 6
    assert eng2.day_id == "20260801"
    _assert_same_table(eng.table, eng2.table)
    np.testing.assert_array_equal(tr2.params["w"], tr.params["w"])


# ---------------------------------------------------------------------------
# Supervisor auto-restart: same port, dedup handoff / checkpoint reload.
# ---------------------------------------------------------------------------

def test_supervisor_restarts_dead_server_same_port():
    table = ShardedHostTable(_table_cfg(), seed=0)
    sup = PSServerSupervisor(table, poll_s=0.01)
    try:
        client = PSClient(sup.addr, retries=None, retry_sleep=0.05,
                          backoff_cap=0.2, deadline=30)
        rows = client.pull_sparse(KEYS, create=True)
        faults.install(faults.FaultPlan(seed=5)
                       .kill_server(cmd="pull_sparse", at=(0,)))
        got = client.pull_sparse(KEYS)     # dies mid-verb; the retry
        faults.uninstall()                 # lands on the restart
        np.testing.assert_array_equal(np.asarray(got["show"]),
                                      np.asarray(rows["show"]))
        assert sup.restarts >= 1
        assert stat_get("ps.supervisor.restarts") >= 1
        assert sup.server.addr[1] == sup.port          # same port
        assert any(e.get("role") == "ps_server"
                   for e in flight.events(kind="resume_ok"))
    finally:
        faults.uninstall()
        sup.stop()


def test_supervisor_ckpt_reload_restart(tmp_path):
    """reload_from_ckpt: the restarted instance distrusts the in-process
    table and reloads rows (+ dedup window) from the last committed
    generation — the cross-process restart semantics."""
    eng = BoxPSEngine(_table_cfg(), seed=0)
    eng.set_date("20260801")
    _mini_pass(eng, 0)
    ck = TrainCheckpoint(str(tmp_path / "ckpt"))
    ck.save(eng, _StubTrainer())

    table2 = ShardedHostTable(_table_cfg(), seed=0)
    sup = PSServerSupervisor(table2, poll_s=0.01,
                             ckpt_root=str(tmp_path / "ckpt"),
                             reload_from_ckpt=True)
    try:
        client = PSClient(sup.addr, retries=None, retry_sleep=0.05,
                          backoff_cap=0.2, deadline=30)
        faults.install(faults.FaultPlan(seed=5)
                       .kill_server(cmd="pull_sparse", at=(0,)))
        keys, _ = _table_state(eng.table)
        client.pull_sparse(keys)           # death → reload → retry served
        faults.uninstall()
        assert sup.restarts >= 1
        _assert_same_table(eng.table, table2)
    finally:
        faults.uninstall()
        sup.stop()


# ---------------------------------------------------------------------------
# The acceptance soak: kill-anywhere across 2 days x 3 passes.
# ---------------------------------------------------------------------------

def _soak_files(tmp_path):
    out = {}
    for d in range(2):
        out[d] = []
        for p in range(3):
            path = str(tmp_path / f"d{d}p{p}.txt")
            _write_slot_file(path, np.random.default_rng(100 * d + p), 48)
            out[d].append([path])
    return out


@pytest.mark.slow
def test_kill_anywhere_soak_bit_identical(tmp_path):
    """2 days x 3 passes of real training driven through a supervised PS
    server, with seeded kills spread across BOTH tiers and BOTH days:
    trainer deaths at end-of-pass write-back, mid-checkpoint and in the
    MANIFEST window, a server death mid push_sparse_delta (supervisor
    restart + dedup handoff), and one applied-unacked ack drop.  Final
    table AND dense params must be bit-identical to the fault-free run,
    including the day-boundary decay between the days."""
    day_files = _soak_files(tmp_path)
    dates = ["20260801", "20260802"]

    def run(chaos):
        # BOTH runs train through a PS server + delta-mode adapter so the
        # comparison isolates the chaos machinery, not the (float-exact
        # but differently-ordered) local-vs-remote arithmetic paths
        table = ShardedHostTable(_table_cfg(), seed=0)
        sup = PSServerSupervisor(table, poll_s=0.01, max_restarts=16)
        client = PSClient(sup.addr, retries=None, retry_sleep=0.05,
                          backoff_cap=0.3, deadline=60)
        eng, ds, tr = _fresh(
            table=RemoteTableAdapter(client, delta_mode=True))
        ck = None
        if chaos:
            ck = TrainCheckpoint(str(tmp_path / "ckpt"))
            faults.install(
                faults.FaultPlan(seed=17)
                .drop("send", role="server", at=(2,))   # forces a dedup hit
                .kill_server(cmd="push_sparse_delta", at=(5,))
                .kill_at("end_pass", at=(1,))           # day-0 write-back
                .kill_at("ckpt_commit", at=(3,))
                .kill_at("ckpt_sparse", at=(6,)))       # lands in day 1
        metrics = []
        try:
            for d, date in enumerate(dates):
                metrics.extend(fleet.train_passes(
                    tr, ds, day_files[d], date=date, prefetch=(d == 1),
                    checkpoint=ck, resume=8 if chaos else None))
        finally:
            faults.uninstall()
            sup.stop()
        return table, tr, metrics

    table_want, tr_want, m_want = run(chaos=False)
    table_got, tr_got, m_got = run(chaos=True)

    _assert_same_table(table_want, table_got)
    _assert_same_params(tr_want, tr_got)
    np.testing.assert_array_equal(
        [m["loss"] for m in m_want],
        [m["loss"] for m in m_got if m is not None][:len(m_want)])
    assert stat_get("ps.fleet.auto_resume") >= 1     # trainer tier fired
    assert stat_get("ps.fault.lifecycle.kill") >= 1
    assert stat_get("ps.supervisor.restarts") >= 1   # server tier fired
    assert stat_get("ps.server.dedup_hit") >= 1      # zero double apply
