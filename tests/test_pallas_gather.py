import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddlebox_tpu.ops.pallas_gather import gather_pool, ROW_BLOCK


def golden(table, idx, lengths):
    R, L = idx.shape
    out = np.zeros((R, table.shape[1]), table.dtype)
    for r in range(R):
        for l in range(int(lengths[r])):
            out[r] += table[idx[r, l]]
    return out


@pytest.mark.parametrize("L", [1, 3])
def test_gather_pool_interpret(L):
    rng = np.random.default_rng(0)
    N, D = 512, 8
    R = ROW_BLOCK * 2
    table = rng.normal(0, 1, (N, D)).astype(np.float32)
    table[0] = 0.0
    idx = rng.integers(0, N, (R, L)).astype(np.int32)
    lengths = rng.integers(0, L + 1, (R,)).astype(np.int32)
    got = gather_pool(jnp.asarray(table), jnp.asarray(idx),
                      jnp.asarray(lengths), interpret=True)
    np.testing.assert_allclose(np.asarray(got), golden(table, idx, lengths),
                               rtol=1e-5, atol=1e-6)
