"""Filesystem abstraction (≙ framework/io/fs.{h,cc} local/hdfs dispatch +
the AFS plumbing of box_wrapper.h:721-743).  A shell-command FS stands in
for hadoop — verified end-to-end through table save/load and dataset
reads over a fake scheme."""

import os

import numpy as np
import pytest

from paddlebox_tpu.config import EmbeddingTableConfig, SparseSGDConfig
from paddlebox_tpu.io import fs as pfs
from paddlebox_tpu.ps.host_table import ShardedHostTable


@pytest.fixture()
def fake_remote(tmp_path):
    """A ShellFS whose 'remote' is a local staging dir driven purely
    through shell commands — exactly the hadoop pattern, no hadoop."""
    root = tmp_path / "remote"
    root.mkdir()

    def strip(p):
        return str(root / p.replace("fake://", "").lstrip("/"))

    class FakeShell(pfs.ShellFS):
        def _run(self, tmpl, path, **kw):
            import subprocess
            local = strip(path)
            cmd = tmpl.format(path=f"'{local}'")
            return subprocess.Popen(cmd, shell=True, **kw)

    fs = FakeShell(
        cat_cmd="cat {path}",
        put_cmd="mkdir -p $(dirname {path}) && cat > {path}",
        ls_cmd="ls -d {path}/* 2>/dev/null",
        mkdir_cmd="mkdir -p {path}",
        exists_cmd="test -e {path}",
        remove_cmd="rm -rf {path}")
    pfs.register_fs("fake", fs)
    yield root
    pfs._REGISTRY.pop("fake", None)


def test_roundtrip_bytes(fake_remote):
    pfs.get_fs("fake://x").write_bytes("fake://dir/a.bin", b"hello\x00world")
    assert pfs.exists("fake://dir/a.bin")
    assert not pfs.exists("fake://dir/missing")
    assert pfs.get_fs("fake://x").read_bytes("fake://dir/a.bin") == \
        b"hello\x00world"


def test_table_save_load_over_remote_scheme(fake_remote):
    cfg = EmbeddingTableConfig(embedding_dim=4, shard_num=2,
                               sgd=SparseSGDConfig(mf_create_thresholds=0.0))
    src = ShardedHostTable(cfg)
    keys = np.arange(1, 40, dtype=np.uint64)
    rows = src.bulk_pull(keys)
    rows["show"] = rows["show"] + 3.0
    rows["unseen_days"] = np.zeros((len(keys),), np.float32)
    src.bulk_write(keys, rows)
    saved = src.save("fake://models/day1", mode="all")
    assert saved == len(keys)

    dst = ShardedHostTable(cfg)
    assert dst.load("fake://models/day1") == len(keys)
    out = dst.bulk_pull(keys)
    np.testing.assert_allclose(out["show"], rows["show"])


def test_dataset_reads_remote_scheme(fake_remote):
    from paddlebox_tpu.config import DataFeedConfig, SlotConfig
    from paddlebox_tpu.data.data_feed import DataFeed

    lines = ["1 1 1 7 2 0.5 0.5", "1 0 2 8 9 2 0.1 0.2"]
    pfs.get_fs("fake://x").write_bytes(
        "fake://data/pass-0.txt", ("\n".join(lines) + "\n").encode())
    cfg = DataFeedConfig(slots=(
        SlotConfig("label", dtype="float", is_dense=True, dim=1),
        SlotConfig("s0", slot_id=100, capacity=2),
        SlotConfig("dense0", dtype="float", is_dense=True, dim=2),
    ))
    feed = DataFeed(cfg)
    blocks = list(feed.read_file("fake://data/pass-0.txt"))
    assert sum(b.n for b in blocks) == 2
    vals, offs = blocks[0].uint64_slots["s0"]
    assert vals.tolist() == [7, 8, 9]


def test_unknown_scheme_raises():
    with pytest.raises(ValueError, match="no filesystem registered"):
        pfs.get_fs("s3://bucket/x")
