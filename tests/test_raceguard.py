"""PB9xx guarded-by inference + data-race detection (pboxlint
raceguard.py) and its runtime witness (lockdep.guards): positive and
negative snippets per check, the benign-publication model, the guard_map
export, the S4 deliberate-race integration (static PB901 + runtime
race_suspect, no hang), and the tier-1 cross-validation contract —
every runtime-observed (site, held-locks) pair from a real PS round-trip
+ prefetched pass must be contained in the static guarded-by map.
"""

import json
import os
import textwrap
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu import flags
from paddlebox_tpu.tools.pboxlint import raceguard
from paddlebox_tpu.tools.pboxlint.core import Module, lint_source
from paddlebox_tpu.utils import doctor, flight, lockdep, workpool

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def codes9(src, path="snippet.py"):
    """PB9xx codes only — dogfoods the --select machinery."""
    return [f.code for f in lint_source(textwrap.dedent(src), path,
                                        select=["PB9xx"])]


def analysis(*files):
    return raceguard.analyze(
        [Module(path, textwrap.dedent(src)) for path, src in files])


# -- PB901: unguarded write on a guarded field -------------------------------

def test_pb901_unguarded_write():
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def hit(self):
            with self._lock:
                self._n += 1

        def hit2(self):
            with self._lock:
                self._n += 1

        def race(self):
            self._n += 1
    """
    assert codes9(src) == ["PB901"]


def test_pb901_constructor_writes_do_not_count():
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0          # pre-publication: neither infers nor violates

        def hit(self):
            with self._lock:
                self._n += 1
    """
    assert codes9(src) == []


def test_pb901_init_only_private_helper_exempt():
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._build()

        def _build(self):
            self._n = 0          # reachable only from __init__

        def hit(self):
            with self._lock:
                self._n += 1

        def hit2(self):
            with self._lock:
                self._n += 1
    """
    assert codes9(src) == []


def test_pb901_atomic_flag_publish_negative():
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._stop = False

        def locked1(self):
            with self._lock:
                self._stop = False

        def locked2(self):
            with self._lock:
                self._stop = False

        def shutdown(self):
            self._stop = True    # single-word literal publish: GIL-atomic
    """
    assert codes9(src) == []


def test_pb901_annotation_honored():
    """An explicit guarded-by wins over inference (no majority needed)
    and disarms the atomic-flag exemption."""
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._ready = False  # pboxlint: guarded-by=snippet.C._lock

        def publish(self):
            self._ready = True   # annotated: even a literal store races
    """
    assert codes9(src) == ["PB901"]


def test_pb901_majority_rule_foreign_lock():
    """One incidental locked path through ANOTHER object's lock must not
    define a discipline for an otherwise main-thread class."""
    src = """
    import threading

    class Calc:
        def __init__(self):
            self._acc = 0

        def add(self):
            self._acc += 1       # standalone main-thread usage

        def add2(self):
            self._acc += 1

        def add3(self):
            self._acc += 1

    class Monitor:
        def __init__(self):
            self._lock = threading.Lock()
            self.calc = Calc()

        def fold(self):
            with self._lock:
                self.calc.add()  # entry-held flows into add via this edge
    """
    assert codes9(src) == []


def test_fresh_local_object_cannot_race():
    """Escape-analysis lite: mutations of a local constructed IN the
    function are unshared — they must not pollute guard inference even
    when they form the locked majority."""
    src = """
    import threading

    class Calc:
        def __init__(self):
            self._acc = 0

        def standalone(self):
            self._acc += 1

    class Monitor:
        def __init__(self):
            self._lock = threading.Lock()

        def windowed(self):
            with self._lock:
                calc = Calc()
                calc._acc = calc._acc + 1
                calc._acc = calc._acc + 2
                return calc
    """
    assert codes9(src) == []


def test_freeze_point_immutable_after_publish_negative():
    src = """
    import threading

    class Frozen:
        def __init__(self, rows):
            self._rows = list(rows)   # never mutated after construction

        def lookup(self, i):
            return self._rows[i]

        def size(self):
            return len(self._rows)
    """
    assert codes9(src) == []


def test_threading_local_fields_negative():
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._tls = threading.local()

        def locked(self):
            with self._lock:
                self._tls = threading.local()

        def locked2(self):
            with self._lock:
                self._tls = threading.local()

        def reset(self):
            self._tls = threading.local()   # per-thread by definition
    """
    assert codes9(src) == []


# -- PB902: multi-word invariant read outside its lock -----------------------

_PAIR_SRC = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._map = None
        self._epoch = 0

    def adopt(self, m, e):
        with self._lock:
            self._map = m
            self._epoch = e

    def route(self):
        %s
"""


def test_pb902_torn_pair_read():
    src = _PAIR_SRC % "return (self._map, self._epoch)"
    assert "PB902" in codes9(src)


def test_pb902_reader_under_the_lock_negative():
    src = _PAIR_SRC % textwrap.indent(
        "with self._lock:\n    return (self._map, self._epoch)",
        "        ").lstrip()
    assert codes9(src) == []


# -- PB903: guarded container reference escape -------------------------------

_ESCAPE_SRC = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._rows = []

    def add(self, x):
        with self._lock:
            self._rows.append(x)

    def add2(self, x):
        with self._lock:
            self._rows.append(x)

    def snapshot(self):
        with self._lock:
            return %s
"""


def test_pb903_bare_reference_escape():
    assert "PB903" in codes9(_ESCAPE_SRC % "self._rows")


def test_pb903_copy_is_not_an_escape():
    assert codes9(_ESCAPE_SRC % "list(self._rows)") == []


# -- PB904: thread-spawned path touching guarded state -----------------------

_SPAWN_SRC = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._t = threading.Thread(target=self._worker, daemon=True)

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def add2(self, x):
        with self._lock:
            self._items.append(x)

    def _worker(self):
        %s
"""


def test_pb904_spawned_container_traversal():
    src = _SPAWN_SRC % textwrap.indent(
        "for it in self._items:\n    print(it)", "        ").lstrip()
    assert "PB904" in codes9(src)


def test_pb904_lock_inside_task_negative():
    src = _SPAWN_SRC % textwrap.indent(
        "with self._lock:\n    for it in self._items:\n        print(it)",
        "        ").lstrip()
    assert codes9(src) == []


# -- interprocedural plumbing ------------------------------------------------

def test_widening_not_dropped_dynamic_call():
    """A dynamic (CHA-widened) call must PROPAGATE the caller's held
    set: bump() is only reached under the lock, so its write analyzes as
    locked — dropping the set would make it a false PB901."""
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def hit(self):
            with self._lock:
                self._n += 1

        def hit2(self):
            with self._lock:
                self._n += 1

        def drive(self, other):
            with self._lock:
                other.bump()     # untyped receiver: widened to C.bump

        def bump(self):
            self._n += 1         # entry-held = {_lock} via the meet
    """
    an = analysis(("m.py", src))
    assert not an.findings, [f.render() for f in an.findings]
    assert an.guard_map().get("m.C._n") == ["m.C._lock"]


def test_entry_meet_private_helper_called_under_lock():
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def hit(self):
            with self._lock:
                self._apply()

        def hit2(self):
            with self._lock:
                self._apply()

        def _apply(self):
            self._n += 1         # always entered with the lock held
    """
    assert codes9(src) == []


def test_guard_map_export_shape():
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._a = threading.Lock()
            self._b = threading.Lock()
            self._n = 0
            self._free = 0
            self._messy = 0

        def hit(self):
            with self._lock:
                self._n += 1

        def hit2(self):
            with self._lock:
                self._n += 1

        def loose(self):
            self._free += 1      # never locked: no guard, no entry

        def m1(self):
            with self._a:
                self._messy += 1

        def m2(self):
            with self._b:
                self._messy += 1   # disagreeing locks: inconsistent
    """
    gm = analysis(("m.py", src)).guard_map()
    assert gm.get("m.C._n") == ["m.C._lock"]
    assert "m.C._free" not in gm
    assert "m.C._messy" not in gm    # inconsistent sites never export


# -- runtime witness (lockdep.guards) ----------------------------------------

@pytest.fixture()
def guards_on():
    prev = {"lockdep": flags.get_flags("lockdep"),
            "lockdep_guards": flags.get_flags("lockdep_guards")}
    flags.set_flags({"lockdep": True, "lockdep_guards": True})
    lockdep.reset()
    yield
    flags.set_flags(prev)
    lockdep.reset()


class RacyCounter:
    """Deliberate two-thread race: locked_hit keeps the discipline,
    racy_hit breaks it.  Module-level so its runtime site name is
    stable: test_raceguard.RacyCounter._n."""

    def __init__(self):
        self._lock = lockdep.lock("test.raceguard.RacyCounter._lock")
        self._n = 0

    def locked_hit(self):
        with self._lock:
            lockdep.guards(self, "_n")
            self._n += 1

    def racy_hit(self):
        lockdep.guards(self, "_n")
        self._n += 1


_RACY_SITE = "test_raceguard.RacyCounter._n"


def test_guards_zero_cost_when_off():
    assert not lockdep.guards_enabled()
    c = RacyCounter()
    c.racy_hit()                       # a plain no-op: nothing recorded
    assert lockdep.guard_observations() == {}
    assert lockdep.guard_suspects() == []


def test_s4_deliberate_race_runtime_witness(guards_on, tmp_path):
    """The S4 integration: a two-thread racy writer under
    FLAGS_lockdep_guards yields ONE race_suspect flight event carrying
    the site and a postmortem with the suspect — without hanging (the
    witness is advisory; it never blocks or raises)."""
    lockdep.set_guard_map({_RACY_SITE: ["test.raceguard.RacyCounter._lock"]})
    c = RacyCounter()
    gate = threading.Barrier(2, timeout=10)

    def disciplined():
        gate.wait()
        for _ in range(50):
            c.locked_hit()

    def racer():
        gate.wait()
        for _ in range(50):
            c.racy_hit()

    t0 = time.monotonic()
    threads = [threading.Thread(target=disciplined, daemon=True),
               threading.Thread(target=racer, daemon=True)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)             # the watchdog bound: no hang
    assert not any(t.is_alive() for t in threads)
    assert time.monotonic() - t0 < 20

    sus = [s for s in lockdep.guard_suspects() if s["site"] == _RACY_SITE]
    assert len(sus) == 1, lockdep.guard_suspects()   # once per site
    assert sus[0]["guard"] == ["test.raceguard.RacyCounter._lock"]

    evs = [e for e in flight.events(kind="race_suspect")
           if e.get("site") == _RACY_SITE]
    assert len(evs) == 1, "exactly one race_suspect flight event per site"

    # both held-set shapes were observed (containment data is complete)
    obs = lockdep.guard_observations()[_RACY_SITE]
    assert [] in obs
    assert ["test.raceguard.RacyCounter._lock"] in obs

    path = doctor.write_postmortem(reason="race-test",
                                   directory=str(tmp_path))
    with open(path, encoding="utf-8") as f:
        bundle = json.load(f)
    guards = bundle["lockdep"]["guards"]
    assert guards["enabled"] is True
    assert any(s["site"] == _RACY_SITE for s in guards["suspects"])


def test_deliberate_race_detected_statically_too():
    """The same shape the S4 test races at runtime must be a PB901 for
    the static half — detector and witness agree on the bug class."""
    src = """
    import threading

    class RacyCounter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def locked_hit(self):
            with self._lock:
                self._n += 1

        def locked_hit2(self):
            with self._lock:
                self._n += 1

        def racy_hit(self):
            self._n += 1
    """
    assert "PB901" in codes9(src)


def test_sampling_probe_for_annotated_class(guards_on):
    class Annotated:
        def __init__(self):
            self._x = 0

    restore = lockdep.install_guard_probe(Annotated, ["_x"], every=1)
    try:
        a = Annotated()
        a._x = 1
        a._x = 2
    finally:
        restore()
    obs = lockdep.guard_observations()
    assert any(site.endswith("Annotated._x") for site in obs)
    a._x = 3                           # restored: no further recording
    n = sum(len(v) for k, v in lockdep.guard_observations().items()
            if k.endswith("Annotated._x"))
    assert n == sum(len(v) for k, v in obs.items()
                    if k.endswith("Annotated._x"))


# -- the tier-1 cross-validation contract ------------------------------------

class _StubArrays:
    num_real = 4


class _StubEngine:
    day_id = None

    def set_date(self, d):
        self.day_id = d

    def begin_feed_pass(self):
        pass

    def end_feed_pass(self, async_build=False):
        pass

    def peek_next_mapper(self):
        return None

    def begin_pass(self):
        pass

    def end_pass(self, need_save_delta=False, delta_path=""):
        pass


class _StubTrainer:
    def pack_pass_host(self, dataset, mapper=None):
        return _StubArrays()

    def finish_pass_feed(self, arrays, keep_host=False):
        return arrays


def test_cross_validation_runtime_guards_subset_of_static(guards_on):
    """Every runtime-observed (site, held-locks) pair from a real
    PSServer round-trip + a prefetched pass + a timeline fold must be
    contained in the static guarded-by map: site known → one of its
    inferred guards held.  Same fingerprint namespace, runtime ⊆ static
    over-approximation — the contract that made PB6xx trustworthy."""
    from paddlebox_tpu.config import EmbeddingTableConfig
    from paddlebox_tpu.data.prefetch import PassPrefetcher
    from paddlebox_tpu.ps.host_table import ShardedHostTable
    from paddlebox_tpu.ps.service import PSClient, PSServer
    from paddlebox_tpu.utils.timeline import TimelineRing

    static = raceguard.guard_map_paths(
        [os.path.join(REPO, "paddlebox_tpu")])
    lockdep.set_guard_map(static)

    prev_threads = flags.get_flags("ps_table_threads")
    flags.set_flags({"ps_table_threads": 1})
    try:
        # 1. real PS round-trip (host-table upsert under the shard lock)
        table = ShardedHostTable(
            EmbeddingTableConfig(embedding_dim=3, shard_num=4))
        srv = PSServer(table)
        try:
            client = PSClient(srv.addr)
            keys = np.arange(1, 40, dtype=np.uint64)
            rows = client.pull_sparse(keys, create=True)
            rows["show"][:] += 1
            client.push_sparse(keys, rows)
            client.end_day()
        finally:
            srv.shutdown()

        # 2. prefetched pass (the worker/consumer condition discipline)
        pre = PassPrefetcher(_StubEngine(), _StubTrainer())
        try:
            for i in range(2):
                pre.submit(lambda: None, tag=f"p{i}")
            for _ in range(2):
                pre.next_pass()
                pre.end_pass()
        finally:
            pre.close()

        # 3. timeline fold (ring sequence under the ring lock)
        ring = TimelineRing(cap=8)
        ring.append({"x": 1.0})
        ring.append({"x": 2.0})
    finally:
        flags.set_flags({"ps_table_threads": prev_threads})
        workpool.table_pool()           # resize the singleton back

    obs = {site: helds for site, helds in
           lockdep.guard_observations().items()
           if not site.startswith(("test.", "test_raceguard."))}
    # the soak is not allowed to be vacuous: each driven subsystem's
    # assertion point must have fired
    for want in ("ps.host_table._Shard._len",
                 "data.prefetch.PassPrefetcher._adopted_n",
                 "utils.timeline.TimelineRing._seq"):
        assert want in obs, sorted(obs)

    violations = []
    for site, helds in obs.items():
        want = static.get(site)
        assert want is not None, \
            f"runtime site {site} missing from the static guard map"
        for held in helds:
            if not set(held).intersection(want):
                violations.append((site, held, want))
    assert not violations, violations
    # and the advisory witness agrees: no production race suspects
    assert not [s for s in lockdep.guard_suspects()
                if not s["site"].startswith(("test.", "test_raceguard."))]
