"""Fault-injection harness + exactly-once PS retry protocol: backoff math,
FaultPlan determinism, rid/dedup-window semantics, drain/kill lifecycle,
the chaos proxy, and the satellite fixes (connect timeout, snapshot-
eviction warning, oversized-response error)."""

import logging
import socket
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu import flags
from paddlebox_tpu.config import EmbeddingTableConfig
from paddlebox_tpu.ps import faults, wire
from paddlebox_tpu.ps import service
from paddlebox_tpu.ps.host_table import ShardedHostTable
from paddlebox_tpu.ps.service import (PSClient, PSServer,
                                      RemoteTableAdapter, _DedupWindow)
from paddlebox_tpu.utils.backoff import Backoff
from paddlebox_tpu.utils.monitor import StatRegistry, stat_get


@pytest.fixture(autouse=True)
def _clean_faults():
    StatRegistry.instance().reset()
    flags.set_flags({"ps_fault_injection": True})
    yield
    faults.uninstall()
    flags.set_flags({"ps_fault_injection": False})


@pytest.fixture()
def server():
    table = ShardedHostTable(EmbeddingTableConfig(embedding_dim=3,
                                                  shard_num=4))
    srv = PSServer(table)
    yield srv
    srv.shutdown()


# -- backoff / deadline math -------------------------------------------------

def test_backoff_delay_grows_and_caps():
    bo = Backoff(base=0.1, cap=0.8, seed=0)
    delays = [bo.delay(a) for a in range(1, 8)]
    nominals = [min(0.8, 0.1 * 2 ** (a - 1)) for a in range(1, 8)]
    for d, n in zip(delays, nominals):
        assert 0.5 * n <= d < n          # jitter in [0.5, 1.0) * nominal
    assert nominals[-1] == 0.8           # capped


def test_backoff_deterministic_under_seed():
    a = Backoff(base=0.1, cap=2.0, seed=42)
    b = Backoff(base=0.1, cap=2.0, seed=42)
    assert [a.delay(i) for i in range(1, 6)] == \
        [b.delay(i) for i in range(1, 6)]


def test_backoff_deadline_budget():
    bo = Backoff(base=0.01, cap=0.02, deadline=0.05)
    assert bo.remaining() <= 0.05
    t0 = time.monotonic()
    attempts = 0
    while bo.sleep(attempts + 1):
        attempts += 1
        assert attempts < 100            # must terminate via the budget
    assert time.monotonic() - t0 <= 0.5  # never sleeps past the deadline
    assert bo.remaining() <= 0
    assert bo.sleep(1) is False          # spent budget refuses immediately


# -- FaultPlan ---------------------------------------------------------------

def test_fault_plan_deterministic_and_scheduled():
    def decisions(seed):
        plan = (faults.FaultPlan(seed)
                .drop("send", role="client", at=(1, 3))
                .drop("recv", role="client", prob=0.3))
        return [(plan.fire("send", "client") is not None,
                 plan.fire("recv", "client") is not None)
                for _ in range(20)]

    assert decisions(7) == decisions(7)          # same seed → same firing
    plan = faults.FaultPlan(0).drop("send", role="client", at=(1, 3))
    fired = [plan.fire("send", "client") is not None for _ in range(6)]
    assert fired == [False, True, False, True, False, False]
    assert plan.fire("send", "server") is None   # role filter
    assert plan.hits("send", "client") == 6


def test_fault_plan_cmd_filter_and_limit():
    plan = faults.FaultPlan(0).drop("dispatch", role="server",
                                    cmd="push_sparse_delta", at=(0,))
    assert plan.fire("dispatch", "server", "pull_sparse") is None
    act = plan.fire("dispatch", "server", "push_sparse_delta")
    assert act is not None and act.kind == "drop"
    assert plan.fire("dispatch", "server", "push_sparse_delta") is None


def test_install_requires_flag():
    flags.set_flags({"ps_fault_injection": False})
    with pytest.raises(RuntimeError, match="fault injection is disabled"):
        faults.install(faults.FaultPlan())
    flags.set_flags({"ps_fault_injection": True})
    faults.install(faults.FaultPlan())
    assert faults.ACTIVE is not None
    faults.uninstall()
    assert faults.ACTIVE is None


# -- dedup window ------------------------------------------------------------

def test_dedup_window_replay_and_eviction():
    win = _DedupWindow(cap=3)
    for i in range(5):
        assert win.begin(f"tok:{i}") is None
        win.commit(f"tok:{i}", {"ok": True, "i": i})
    # newest 3 replay from cache; the 2 oldest were evicted → re-execute
    assert win.begin("tok:4") == {"ok": True, "i": 4}
    assert win.begin("tok:2") == {"ok": True, "i": 2}
    assert win.begin("tok:0") is None            # evicted → admitted anew
    assert stat_get("ps.server.dedup_evict") == 2
    assert stat_get("ps.server.dedup_hit") == 2


def test_dedup_window_inflight_never_evicted_and_waits():
    win = _DedupWindow(cap=1, wait_timeout=5)
    assert win.begin("tok:0") is None            # in-flight, never evicted
    for i in range(1, 4):
        assert win.begin(f"tok:{i}") is None
        win.commit(f"tok:{i}", {"ok": True})
    got = []

    def dup():
        got.append(win.begin("tok:0"))           # blocks on the in-flight

    t = threading.Thread(target=dup, daemon=True)
    t.start()
    time.sleep(0.1)
    assert not got                               # still waiting
    win.commit("tok:0", {"ok": True, "v": 7})
    t.join(timeout=5)
    assert got == [{"ok": True, "v": 7}]


def test_dedup_window_drop_allows_reexecution():
    win = _DedupWindow(cap=4)
    assert win.begin("tok:1") is None
    win.drop("tok:1")                            # verb raised / rolled back
    assert win.begin("tok:1") is None            # re-admitted, not replayed


def test_duplicate_rid_suppressed_end_to_end(server):
    client = PSClient(server.addr)
    keys = np.array([5, 6], np.uint64)
    client.pull_sparse(keys, create=True)
    req = {"cmd": "push_sparse_delta", "keys": keys,
           "rows": {"show": np.ones(2, np.float32)}, "rows_abs": {},
           "table": None, wire.RID_FIELD: "dup-tok:1"}
    r1 = server._dispatch(dict(req))
    r2 = server._dispatch(dict(req))             # resend of the same rid
    assert r1["ok"] and r2 == r1
    assert r2[wire.RID_FIELD] == "dup-tok:1"     # response echoes the rid
    np.testing.assert_allclose(client.pull_sparse(keys)["show"], [1.0, 1.0])
    assert stat_get("ps.server.dedup_hit") == 1


# -- retry protocol over injected faults ------------------------------------

def test_client_retries_through_send_drops(server):
    faults.install(faults.FaultPlan(0).drop("send", role="client",
                                            at=(0, 1)))
    client = PSClient(server.addr, retries=5, retry_sleep=0.01)
    assert client.size() == 0                    # survives 2 dropped sends
    assert stat_get("ps.client.retry") == 2
    assert stat_get("ps.fault.send.drop") == 2


def test_delta_exactly_once_when_response_lost(server):
    """The ambiguous failure: the delta APPLIES but the response frame is
    dropped — the resend must dedup, not double-apply."""
    client = PSClient(server.addr, retries=5, retry_sleep=0.01)
    keys = np.array([1, 2, 3], np.uint64)
    rows = client.pull_sparse(keys, create=True)
    d = {f: np.zeros_like(v) for f, v in rows.items()}
    d["show"] = np.ones(3, np.float32)
    faults.install(faults.FaultPlan(0).drop(
        "send", role="server", at=(0,), cmd=None))
    client.push_sparse_delta(keys, d)
    faults.uninstall()
    np.testing.assert_allclose(client.pull_sparse(keys)["show"],
                               [1.0, 1.0, 1.0])  # once, not twice
    assert stat_get("ps.server.dedup_hit") >= 1


def test_barrier_retries_through_drops(server):
    faults.install(faults.FaultPlan(0).drop("send", role="client", at=(1,)))
    clients = [PSClient(server.addr, retries=5, retry_sleep=0.01)
               for _ in range(3)]
    done = []

    def worker(c):
        c.barrier(3, timeout=30)
        done.append(1)

    threads = [threading.Thread(target=worker, args=(c,), daemon=True)
               for c in clients]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert len(done) == 3                        # no double-registration


def test_deadline_budget_bounds_total_retry_time():
    client = PSClient(("127.0.0.1", 9), retries=None, retry_sleep=0.01,
                      backoff_cap=0.05, deadline=0.3)
    t0 = time.monotonic()
    with pytest.raises(ConnectionError):
        client.size()
    assert time.monotonic() - t0 < 5.0


# -- satellites --------------------------------------------------------------

def test_connect_honors_per_call_timeout(monkeypatch):
    """Satellite: _call used to hardcode create_connection(timeout=60),
    ignoring the per-call timeout — a short-deadline call could block a
    minute on connect."""
    seen = []

    def fake_connect(addr, timeout=None):
        seen.append(timeout)
        raise ConnectionRefusedError("nope")

    monkeypatch.setattr(service.socket, "create_connection", fake_connect)
    client = PSClient(("127.0.0.1", 9), retries=1, deadline=500.0)
    with pytest.raises(ConnectionError):
        client._call({"cmd": "size", "table": None}, timeout=0.5)
    assert seen and seen[0] <= 0.5               # not 60


def test_snapshot_eviction_warns_and_counts(server, caplog):
    """Satellite: the adapter used to evict the oldest pull snapshot
    silently; the failure then surfaced as a confusing RuntimeError at
    write-back time."""
    adapter = RemoteTableAdapter(PSClient(server.addr), delta_mode=True)
    with caplog.at_level(logging.WARNING, logger="paddlebox_tpu.ps.service"):
        for i in range(adapter._snap_cap + 1):
            adapter.bulk_pull(np.arange(10 * i + 1, 10 * i + 4,
                                        dtype=np.uint64))
    assert any("evicting the oldest snapshot" in r.getMessage()
               for r in caplog.records)
    assert stat_get("ps.adapter.snap_evict") == 1


def test_oversized_response_reports_real_reason(server, monkeypatch):
    """Satellite: an oversized RESPONSE used to kill the handler thread —
    the client saw a bare ConnectionError and re-pulled the same chunk.
    Now the server replies with the actual reason."""
    monkeypatch.setattr(wire, "MAX_FRAME", 1 << 14)
    client = PSClient(server.addr, retries=2, retry_sleep=0.01)
    with pytest.raises(RuntimeError, match="response exceeds wire cap"):
        # huge client-side frame budget → one request whose response
        # overshoots the (patched) hard wire cap
        client._call({"cmd": "pull_sparse",
                      "keys": np.arange(1, 2000, dtype=np.uint64),
                      "table": None, "create": True})


# -- lifecycle: drain / kill / health ---------------------------------------

def test_health_verb(server):
    client = PSClient(server.addr)
    h = client.health()
    assert h["ok"] and h["draining"] is False
    assert "embedding" in h["tables"]


def test_graceful_drain_finishes_inflight_verb(server):
    faults.install(faults.FaultPlan(0).delay("dispatch", 0.4, at=(0,),
                                             cmd="push_dense"))
    client = PSClient(server.addr)
    errs = []

    def slow_push():
        try:
            client.push_dense("w", np.ones(4))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=slow_push, daemon=True)
    t.start()
    time.sleep(0.1)                              # verb is now in flight
    server.shutdown(drain_timeout=5)             # drains, doesn't cut it
    t.join(timeout=5)
    assert not errs
    # drained server refuses new work
    c2 = PSClient(server.addr, retries=2, retry_sleep=0.01, deadline=1)
    with pytest.raises(ConnectionError):
        c2.size()


def test_kill_and_restart_same_port(server):
    client = PSClient(server.addr, retries=None, retry_sleep=0.02,
                      deadline=20)
    keys = np.array([9, 10], np.uint64)
    client.pull_sparse(keys, create=True)
    port = server.addr[1]
    server.kill()
    srv2 = PSServer(server.table, port=port)     # same table, same port
    try:
        assert client.size() == 2                # client reconnects+retries
    finally:
        srv2.shutdown()


# -- pass-level recovery -----------------------------------------------------

def test_end_pass_redrive_after_partial_write(server):
    """A mid-sequence write-back failure leaves some chunks applied.  The
    adapter restores the snapshot and pins the rid group, so re-driving
    end_pass resends identical rids: applied chunks dedup, the rest land
    — exactly once overall."""
    from paddlebox_tpu.ps.pass_manager import BoxPSEngine
    engine = BoxPSEngine(EmbeddingTableConfig(embedding_dim=3, shard_num=4))
    # small frame budget → several delta chunks per write-back
    client = PSClient(server.addr, retries=1, retry_sleep=0.01,
                      max_frame=1 << 12)
    engine.table = RemoteTableAdapter(client, delta_mode=True)
    engine.begin_feed_pass()
    keys = np.arange(1, 101, dtype=np.uint64)
    engine.add_keys(keys)
    engine.end_feed_pass()
    engine.begin_pass()
    engine.ws["show"] = engine.ws["show"] + 1.0
    n_chunks = len(client._chunk_counts(
        100, client._rows_bytes(engine.table._snaps[
            np.sort(keys).tobytes()])))
    assert n_chunks >= 3
    # chunk 1's dispatch drops (not applied) → chunk 0 stays applied
    faults.install(faults.FaultPlan(0).drop(
        "dispatch", role="server", cmd="push_sparse_delta", at=(1,)))
    with pytest.raises(ConnectionError):
        engine.end_pass()
    faults.uninstall()
    assert engine.ws is not None                 # engine state preserved
    engine.end_pass()                            # re-drive: exactly-once
    np.testing.assert_allclose(
        PSClient(server.addr).pull_sparse(keys)["show"], np.ones(100))
    assert stat_get("ps.server.dedup_hit") >= 1  # replayed applied chunk
    assert stat_get("ps.engine.end_pass_write_failure") == 1


# -- chaos proxy -------------------------------------------------------------

def test_chaos_proxy_faults_are_survivable(server):
    plan = (faults.FaultPlan(seed=3)
            .drop("connect", role="proxy", at=(1,))
            .drop("send", role="proxy", at=(2,))
            .truncate("recv", role="proxy", at=(4,))
            .delay("send", 0.002, role="proxy", prob=0.1))
    proxy = faults.ChaosProxy(server.addr, plan)
    try:
        client = PSClient(proxy.addr, retries=None, retry_sleep=0.01,
                          backoff_cap=0.1, deadline=30)
        keys = np.arange(1, 40, dtype=np.uint64)
        rows = client.pull_sparse(keys, create=True)
        d = {f: np.zeros_like(v) for f, v in rows.items()}
        d["show"] = np.ones(39, np.float32)
        for _ in range(4):
            client.push_sparse_delta(keys, d)
        np.testing.assert_allclose(client.pull_sparse(keys)["show"],
                                   np.full(39, 4.0))
        assert plan.hits("send", "proxy") > 0    # frames really flowed
    finally:
        proxy.shutdown()
