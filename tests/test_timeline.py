"""Telemetry timeline + SLO watchdog + cluster aggregation: ring wrap
and rate derivation vs hand-computed deltas (counter reset included),
breach → ``slo_breach`` flight-event round-trip with per-rule latching,
the /timelinez + /clusterz endpoints and ?prefix= scrape filters, the
supervisor's ClusterScraper surviving a mid-scrape worker kill, quality
monitor gauges, postmortem timeline tails, and the PB207 lint rule."""

import json
import textwrap
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddlebox_tpu import flags
from paddlebox_tpu.launch import ClusterScraper
from paddlebox_tpu.metrics import quality
from paddlebox_tpu.utils import flight, obs_server, timeline
from paddlebox_tpu.utils.monitor import (StatRegistry, stat_add, stat_get,
                                         stat_observe, stat_set,
                                         stat_snapshot)


@pytest.fixture(autouse=True)
def _clean():
    StatRegistry.instance().reset()
    quality.reset()
    fr = flight.ring()
    if fr is not None:
        fr.clear()
    yield
    timeline.stop()
    obs_server.set_clusterz_provider(None)
    quality.reset()
    fr = flight.ring()
    if fr is not None:
        fr.clear()
    flags.set_flags({"obs_timeline_interval_s": 0.0,
                     "obs_timeline_ring": 512,
                     "obs_slo_watchdog": True,
                     "obs_slo_auc_drop": 0.05})


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as r:
        return r.read().decode("utf-8")


# ---------------------------------------------------------------------------
# TimelineRing: rates, resets, wrap
# ---------------------------------------------------------------------------
def test_ring_rates_match_hand_computed_deltas_across_reset():
    ring = timeline.TimelineRing(16)
    # (mono, counter value): steady growth, then a worker restart drops
    # the counter to 4 — the interval's growth is the NEW value, never a
    # negative rate
    ring.append({"c.ops": 100.0}, mono=10.0, t=1000.0)
    ring.append({"c.ops": 110.0}, mono=12.0, t=1002.0)   # d=10 dt=2 → 5.0
    ring.append({"c.ops": 111.0}, mono=13.0, t=1003.0)   # d=1  dt=1 → 1.0
    ring.append({"c.ops": 4.0}, mono=15.0, t=1005.0)     # reset: 4/2 → 2.0
    s = ring.series("c.ops")
    assert s["points"] == [[1000.0, 100.0], [1002.0, 110.0],
                           [1003.0, 111.0], [1005.0, 4.0]]
    assert s["rates"] == [[1002.0, 5.0], [1003.0, 1.0], [1005.0, 2.0]]
    # first sample has no predecessor → no rate entry
    assert len(s["rates"]) == len(s["points"]) - 1


def test_ring_gauge_keys_carry_values_but_never_rates():
    ring = timeline.TimelineRing(8)
    snap = {"ps.client.inflight_hwm": 3.0, "x.lat_s.p99": 0.5,
            "ps.cache.hit_rate": 0.9, "quality.auc": 0.7, "c.n": 1.0}
    ring.append(dict(snap), mono=1.0)
    ring.append(dict(snap), mono=2.0)
    last = ring.samples()[-1]
    assert set(last["rates"]) == {"c.n"}       # counters only
    assert ring.series("quality.auc")["points"][-1][1] == 0.7
    assert ring.series("quality.auc")["rates"] == []


def test_ring_wrap_keeps_newest_and_rates_stay_correct():
    ring = timeline.TimelineRing(4)
    for i in range(10):
        ring.append({"c.n": float(10 * i)}, mono=float(i), t=float(i))
    assert len(ring) == 4
    s = ring.samples()
    assert [x["seq"] for x in s] == [7, 8, 9, 10]       # newest-4 kept
    assert [p[1] for p in ring.series("c.n")["points"]] == \
        [60.0, 70.0, 80.0, 90.0]
    # rate derivation uses _prev, not the ring, so wrap never skews it
    assert all(r[1] == 10.0 for r in ring.series("c.n")["rates"])
    assert ring.names() == ["c.n"]
    ring.clear()
    assert len(ring) == 0


def test_tail_is_compact_top_movers():
    ring = timeline.TimelineRing(8)
    many = {f"k.{i:02d}": float(i) for i in range(40)}
    ring.append(dict(many), mono=1.0)
    many2 = {k: v + i for i, (k, v) in enumerate(sorted(many.items()))}
    ring.append(many2, mono=2.0)
    tail = ring.tail(n=5, rate_top=3, stat_top=3)
    assert len(tail) == 2
    assert len(tail[-1]["stats"]) == 3          # top movers only
    assert len(tail[-1]["rates"]) == 3
    # the largest stats won
    assert "k.39" in tail[-1]["stats"]


# ---------------------------------------------------------------------------
# SLO watchdog: sustained-window predicates, latching, flight round-trip
# ---------------------------------------------------------------------------
def _hit_rule(min_samples=3):
    return timeline.SloRule(
        "cache_hit_collapse", "ps.cache.hit_rate", kind="gauge", op="lt",
        threshold=0.10, window_s=30.0, min_samples=min_samples,
        reason="hit rate collapsed")


def test_breach_emits_exactly_one_latched_flight_event_then_clears():
    ring = timeline.TimelineRing(64)
    wd = timeline.SloWatchdog([_hit_rule()])
    for i in range(3):                                  # healthy
        ring.append({"ps.cache.hit_rate": 0.9}, mono=100.0 + i)
    assert wd.evaluate(ring, now_mono=102.0) == []
    # collapse, far enough that healthy samples aged out of the window
    for i in range(3):
        ring.append({"ps.cache.hit_rate": 0.02}, mono=200.0 + i)
    trans = wd.evaluate(ring, now_mono=202.0)
    assert [t["rule"] for t in trans] == ["cache_hit_collapse"]
    assert trans[0]["breached"] is True
    # still breached on the next samples: LATCHED — no event storm
    for i in range(3, 8):
        ring.append({"ps.cache.hit_rate": 0.02}, mono=200.0 + i)
        assert wd.evaluate(ring, now_mono=200.0 + i) == []
    breaches = flight.events(kind="slo_breach")
    assert len(breaches) == 1
    assert breaches[0]["rule"] == "cache_hit_collapse"
    assert breaches[0]["metric"] == "ps.cache.hit_rate"
    assert stat_get("obs.slo.breach") == 1.0
    assert wd.states() == {"cache_hit_collapse": True}
    # recovery → one slo_clear, counter stays at one breach
    for i in range(3):
        ring.append({"ps.cache.hit_rate": 0.95}, mono=300.0 + i)
    trans = wd.evaluate(ring, now_mono=302.0)
    assert trans and trans[0]["breached"] is False
    assert len(flight.events(kind="slo_clear")) == 1
    assert len(flight.events(kind="slo_breach")) == 1
    assert stat_get("obs.slo.active") == 0.0


def test_one_bad_scrape_never_pages():
    """min_samples + the sustained-all-window predicate: a single bad
    sample (or a window with a healthy one mixed in) is not a breach."""
    ring = timeline.TimelineRing(64)
    wd = timeline.SloWatchdog([_hit_rule(min_samples=3)])
    ring.append({"ps.cache.hit_rate": 0.01}, mono=100.0)
    assert wd.evaluate(ring, now_mono=100.0) == []      # 1 < min_samples
    ring.append({"ps.cache.hit_rate": 0.01}, mono=101.0)
    ring.append({"ps.cache.hit_rate": 0.90}, mono=102.0)  # one healthy
    assert wd.evaluate(ring, now_mono=102.0) == []      # not sustained
    assert flight.events(kind="slo_breach") == []


def test_auc_drop_rule_via_quality_gauges():
    ring = timeline.TimelineRing(64)
    rule = timeline.SloRule("auc_drop", "quality.auc", kind="drop",
                            threshold=0.05, window_s=600.0, min_samples=2)
    wd = timeline.SloWatchdog([rule])
    ring.append({"quality.auc": 0.75}, mono=10.0)
    ring.append({"quality.auc": 0.74}, mono=20.0)
    assert wd.evaluate(ring, now_mono=20.0) == []       # within epsilon
    ring.append({"quality.auc": 0.62}, mono=30.0)       # 0.13 drop
    trans = wd.evaluate(ring, now_mono=30.0)
    assert trans and trans[0]["rule"] == "auc_drop"


def test_throughput_stall_rate_rule():
    ring = timeline.TimelineRing(64)
    rule = timeline.SloRule("stall", "trainer.step_dispatch_s.count",
                            kind="rate", op="lt", threshold=1e-9,
                            window_s=60.0, min_samples=3)
    wd = timeline.SloWatchdog([rule])
    for i in range(5):                                   # flat counter
        ring.append({"trainer.step_dispatch_s.count": 40.0},
                    mono=100.0 + i)
    trans = wd.evaluate(ring, now_mono=104.0)
    assert trans and trans[0]["rule"] == "stall"


def test_default_rules_reference_only_emitted_metrics():
    """The shipped rule set parses, and PB207 (which cross-checks every
    literal against real emission sites) holds the invariant statically;
    here just pin the metric names we promise to watch."""
    rules = {r.name: r.metric for r in timeline.default_rules()}
    assert rules == {
        "cache_hit_collapse": "ps.cache.hit_rate",
        "queue_saturation": "ps.pool.table.queue_depth_hwm",
        "throughput_stall": "trainer.step_dispatch_s.count",
        "auc_drop": "quality.auc",
        "heat_shard_imbalance": "heat.shard_imbalance",
    }


# ---------------------------------------------------------------------------
# sampler lifecycle + endpoints
# ---------------------------------------------------------------------------
def test_sampler_thread_samples_and_stops():
    import time as _time
    s = timeline.start(interval_s=0.01, cap=32)
    deadline = _time.monotonic() + 5.0
    while len(s.ring) < 3 and _time.monotonic() < deadline:
        _time.sleep(0.01)
    assert len(s.ring) >= 3
    assert s.running
    timeline.stop()
    assert timeline.sampler() is None
    assert not s.running
    assert stat_get("obs.timeline.samples") >= 3.0


def test_maybe_start_from_flags_off_by_default():
    assert timeline.maybe_start_from_flags() is None
    flags.set_flags({"obs_timeline_interval_s": 60.0})
    s = timeline.maybe_start_from_flags()
    assert s is not None and s.interval_s == 60.0
    timeline.stop()


def test_timelinez_endpoint_roundtrip():
    srv = obs_server.ObsServer(port=0)
    try:
        port = srv.addr[1]
        # sampler off → disabled index, empty series
        idx = json.loads(_get(port, "/timelinez"))
        assert idx["enabled"] is False and idx["len"] == 0
        s = timeline.start(interval_s=600.0, cap=32)    # driven by hand
        stat_add("tz.counter", 7.0)
        s.sample_once()
        stat_add("tz.counter", 3.0)
        s.sample_once()
        idx = json.loads(_get(port, "/timelinez"))
        assert idx["enabled"] is True and idx["len"] == 2
        assert "tz.counter" in idx["names"]
        assert "slo" in idx
        ser = json.loads(_get(port, "/timelinez?name=tz.counter&n=8"))
        assert [p[1] for p in ser["points"]] == [7.0, 10.0]
        assert len(ser["rates"]) == 1
    finally:
        srv.shutdown()


def test_statz_and_metrics_prefix_filter():
    stat_add("pa.x", 1.0)
    stat_add("pb.y", 2.0)
    stat_observe("pa.lat_s", 0.01)
    srv = obs_server.ObsServer(port=0)
    try:
        port = srv.addr[1]
        z = json.loads(_get(port, "/statz?prefix=pa"))
        assert z["pa.x"] == 1.0 and z["pa.lat_s.count"] == 1.0
        assert not [k for k in z if k.startswith("pb.")]
        raw = json.loads(_get(port, "/statz?raw=1&prefix=pa"))
        assert "pa.lat_s" in raw[obs_server.HIST_RAW_KEY]
        m = _get(port, "/metrics?prefix=pa")
        assert "pbox_pa_x 1.0" in m and "pbox_pb_y" not in m
        # unfiltered still serves everything
        assert json.loads(_get(port, "/statz"))["pb.y"] == 2.0
    finally:
        srv.shutdown()


def test_postmortem_bundle_embeds_timeline_tail():
    from paddlebox_tpu.utils import doctor
    s = timeline.start(interval_s=600.0, cap=32)
    stat_add("pm.ops", 5.0)
    s.sample_once()
    stat_add("pm.ops", 5.0)
    s.sample_once()
    bundle = doctor.dump_state(reason="test")
    tl = bundle["timeline"]
    assert tl["interval_s"] == 600.0
    assert isinstance(tl["slo"], dict)
    assert len(tl["tail"]) == 2
    assert tl["tail"][-1]["stats"].get("pm.ops") == 10.0


# ---------------------------------------------------------------------------
# cluster aggregation (/clusterz)
# ---------------------------------------------------------------------------
def test_cluster_scraper_merged_equals_per_worker_sums():
    """Stubbed per-worker snapshots with DISTINCT values: the merged
    timeline must carry their sum (counters) and worst (quantiles)."""
    scraper = ClusterScraper([7001, 7002, 7003], interval_s=600.0)
    snaps = {7001: {"w.ops": 10.0, "w.lat_s.p99": 0.2},
             7002: {"w.ops": 4.0, "w.lat_s.p99": 0.9},
             7003: {"w.ops": 1.0, "w.lat_s.p99": 0.1}}
    real = scraper._obs
    scraper._obs = types.SimpleNamespace(
        scrape=lambda port, **kw: dict(snaps[port]),
        merge_snapshots=real.merge_snapshots,
        set_clusterz_provider=real.set_clusterz_provider)
    assert scraper.scrape_once() == 3
    latest = scraper.ring.samples()[-1]["stats"]
    assert latest["w.ops"] == 15.0                      # summed
    assert latest["w.lat_s.p99"] == 0.9                 # worst worker
    idx = scraper.render()
    assert idx["workers"] == {"7001": True, "7002": True, "7003": True}
    assert idx["latest"]["w.ops"] == 15.0


def test_cluster_scraper_survives_mid_scrape_worker_kill():
    """Chaos: worker 2's obs server is SIGKILLed while the scrape round
    is in flight — the round folds whoever answered, marks the corpse
    dead, and the merged series carries on (with a counter 'reset'
    handled as restart-from-zero when it comes back smaller)."""
    stat_add("cl.ops", 6.0)
    srv1, srv2 = obs_server.ObsServer(port=0), obs_server.ObsServer(port=0)
    p1, p2 = srv1.addr[1], srv2.addr[1]
    try:
        scraper = ClusterScraper([p1, p2], interval_s=600.0)
        assert scraper.scrape_once() == 2
        # both workers serve the same process registry → merged = 2x
        assert scraper.ring.samples()[-1]["stats"]["cl.ops"] == 12.0
        # kill worker 2 in the MIDDLE of the next round: after worker 1
        # answered, before worker 2 is polled
        real = scraper._obs

        def killing_scrape(port, **kw):
            if port == p2:
                srv2.shutdown()
            return real.scrape(port, **kw)

        scraper._obs = types.SimpleNamespace(
            scrape=killing_scrape,
            merge_snapshots=real.merge_snapshots,
            set_clusterz_provider=real.set_clusterz_provider)
        assert scraper.scrape_once() == 1               # survived
        assert scraper.ring.samples()[-1]["stats"]["cl.ops"] == 6.0
        idx = scraper.render()
        assert idx["workers"] == {str(p1): True, str(p2): False}
        # the fold dropping a worker halves the counter: rate derivation
        # treats it as a reset, never a negative rate
        rates = scraper.ring.samples()[-1]["rates"]
        assert rates["cl.ops"] >= 0.0
    finally:
        srv1.shutdown()
        srv2.shutdown()


def test_clusterz_endpoint_provider_registration():
    stat_add("cz.n", 2.0)
    srv = obs_server.ObsServer(port=0)
    try:
        port = srv.addr[1]
        assert json.loads(_get(port, "/clusterz")) == {"enabled": False}
        scraper = ClusterScraper([srv.addr[1]], interval_s=600.0)
        obs_server.set_clusterz_provider(scraper.render)
        scraper.scrape_once()
        idx = json.loads(_get(port, "/clusterz"))
        assert idx["enabled"] is True and "cz.n" in idx["names"]
        ser = json.loads(_get(port, "/clusterz?name=cz.n&n=4"))
        assert ser["points"][-1][1] == 2.0
        obs_server.set_clusterz_provider(None)
        assert json.loads(_get(port, "/clusterz")) == {"enabled": False}
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# quality monitors
# ---------------------------------------------------------------------------
def _pass_metrics(auc, pos_shift=0):
    pos = np.zeros(50)
    neg = np.zeros(50)
    pos[30 + pos_shift: 40 + pos_shift] = 10.0
    neg[10: 20] = 10.0
    return {"auc": auc, "size": 200.0, "predicted_ctr": 0.11,
            "actual_ctr": 0.10,
            "auc_buckets": {"pos": pos.tolist(), "neg": neg.tolist()}}


def test_quality_monitor_gauges_and_day_psi():
    qm = quality.QualityMonitor(window=4)
    out = qm.observe_pass(_pass_metrics(0.70))
    assert out["quality.auc"] == 0.70
    assert out["quality.auc_drop"] == 0.0
    assert out["quality.auc_window"] == pytest.approx(1.0)  # separable
    assert out["quality.calibration_drift"] == pytest.approx(0.1)
    assert "quality.psi.prediction" not in out          # needs 2 passes
    out2 = qm.observe_pass(_pass_metrics(0.60))
    assert out2["quality.auc_drop"] == pytest.approx(0.10)
    assert out2["quality.psi.prediction"] == 0.0        # same distribution
    out3 = qm.observe_pass(_pass_metrics(0.60, pos_shift=8))
    assert out3["quality.psi.prediction"] > 0.2         # shifted
    # gauges landed in the registry for the timeline/watchdog to read
    snap = stat_snapshot("quality.")
    assert snap["quality.auc"] == 0.60
    assert snap["quality.passes"] == 3.0
    # day rollover: first day has no predecessor, second day does
    assert qm.end_day("d1") == {}
    qm.observe_pass(_pass_metrics(0.61))
    out_day = qm.end_day("d2")
    assert out_day["quality.psi.day"] >= 0.0
    # None / auc-less metrics are ignored (resume-cursor skipped passes)
    assert qm.observe_pass(None) == {}
    assert qm.observe_pass({"loss": 1.0}) == {}


def test_windowed_auc_union_not_mean():
    """A tiny pass with a terrible AUC must not drag the window the way
    a mean of per-pass AUCs would — the union statistic weights by
    instances."""
    big_sep = _pass_metrics(0.9)                        # 200 instances
    pos = np.zeros(50)
    neg = np.zeros(50)
    pos[10:12] = 1.0                                    # 4 instances,
    neg[30:32] = 1.0                                    # inverted ranks
    tiny_bad = {"pos": pos.tolist(), "neg": neg.tolist()}
    w = quality.windowed_auc([big_sep["auc_buckets"], tiny_bad])
    assert w > 0.9
    assert quality.windowed_auc([]) == -0.5             # sentinel
    # single-class union → sentinel too
    only_pos = {"pos": pos.tolist(), "neg": (pos * 0).tolist()}
    assert quality.windowed_auc([only_pos]) == -0.5


def test_psi_properties():
    assert quality.psi([1, 2, 3], [1, 2, 3]) == 0.0
    assert quality.psi([10, 0, 0], [0, 0, 10]) > 1.0    # gross shift
    assert quality.psi([], []) == 0.0                   # degenerate
    assert quality.calibration_drift(0.2, 0.0) == 0.0   # no positives


# ---------------------------------------------------------------------------
# PB207 lint rule
# ---------------------------------------------------------------------------
def test_pb207_dead_slo_rule_metric():
    from paddlebox_tpu.tools.pboxlint import lint_source

    def codes(src):
        return [f.code for f in lint_source(textwrap.dedent(src))]

    # nobody emits the watched metric → dead rule
    assert codes("""
        from paddlebox_tpu.utils.timeline import SloRule
        SloRule("r", "ps.totally.absent", threshold=1.0)
    """) == ["PB207"]
    # metric= kwarg form and module-attr import form are both resolved
    assert codes("""
        from paddlebox_tpu.utils import timeline
        timeline.SloRule("r", metric="ps.nope", threshold=1.0)
    """) == ["PB207"]
    # a literal emission site anywhere in the linted set arms the rule
    assert codes("""
        from paddlebox_tpu.utils.monitor import stat_set
        from paddlebox_tpu.utils.timeline import SloRule
        stat_set("ps.ok.value", 1.0)
        SloRule("r", "ps.ok.value", threshold=1.0)
    """) == []
    # f-string emissions match as bounded patterns
    assert codes("""
        from paddlebox_tpu.utils.monitor import stat_max
        from paddlebox_tpu.utils.timeline import SloRule
        def f(kind):
            stat_max(f"ps.pool.{kind}.queue_depth_hwm", 1.0)
        SloRule("r", "ps.pool.table.queue_depth_hwm", op="gt",
                threshold=10.0)
    """) == []
    # stat_observe contributes its derived flattened-histogram keys
    assert codes("""
        from paddlebox_tpu.utils.monitor import stat_observe
        from paddlebox_tpu.utils.timeline import SloRule
        stat_observe("tr.step_s", 0.1)
        SloRule("r", "tr.step_s.count", kind="rate", op="lt",
                threshold=0.0)
    """) == []
    # a fully dynamic emission site disarms the check (emitted set is
    # out of static reach), and non-literal metric args are skipped
    assert codes("""
        from paddlebox_tpu.utils.monitor import stat_add
        from paddlebox_tpu.utils.timeline import SloRule
        def f(name):
            stat_add(name, 1.0)
        SloRule("r", "ps.unknowable", threshold=1.0)
    """) == []
    assert codes("""
        from paddlebox_tpu.utils.timeline import SloRule
        def f(metric):
            SloRule("r", metric, threshold=1.0)
    """) == []
    # no timeline import in scope → the call never resolves to our rule
    assert codes("""
        def f(SloRule):
            SloRule("r", "ps.unknown.metric", threshold=1.0)
    """) == []
