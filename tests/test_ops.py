import numpy as np
import jax
import jax.numpy as jnp

from paddlebox_tpu.ops.seqpool_cvm import fused_seqpool_cvm
from paddlebox_tpu.ops.cvm import cvm


def ref_seqpool_cvm(emb, lengths, use_cvm=True, pad_value=0.0,
                    quant_ratio=0, need_filter=False, show_coeff=0.2,
                    clk_coeff=1.0, threshold=0.96):
    """NumPy golden model implementing the CUDA kernel semantics
    (fused_seqpool_cvm_op.cu:35-160,371-395) with scalar loops."""
    S, B, L, E = emb.shape
    out_width = E if use_cvm else E - 2
    out = np.zeros((B, S * out_width), np.float64)
    for s in range(S):
        for b in range(B):
            pooled = np.full((E,), 0.0, np.float64)
            pooled += pad_value
            for l in range(int(lengths[s, b])):
                v = emb[s, b, l].astype(np.float64)
                if need_filter and ((v[0] - v[1]) * show_coeff
                                    + v[1] * clk_coeff < threshold):
                    continue
                for e in range(E):
                    if e < 2 or quant_ratio <= 0:
                        pooled[e] += v[e]
                    else:
                        pooled[e] += np.floor(
                            v[e] * quant_ratio + 0.5) / quant_ratio
            show = np.log(pooled[0] + 1)
            click = np.log(pooled[1] + 1) - show
            if use_cvm:
                res = np.concatenate([[show, click], pooled[2:]])
            else:
                res = pooled[2:]
            out[b, s * out_width:(s + 1) * out_width] = res
    return out


def make_inputs(seed=0, S=3, B=4, L=5, E=6):
    rng = np.random.default_rng(seed)
    emb = rng.uniform(0, 2, size=(S, B, L, E)).astype(np.float32)
    lengths = rng.integers(0, L + 1, size=(S, B)).astype(np.int32)
    ins_cvm = np.stack([np.ones(B), rng.integers(0, 2, B)], 1).astype(np.float32)
    return emb, lengths, ins_cvm


def test_forward_use_cvm():
    emb, lengths, ins_cvm = make_inputs()
    got = fused_seqpool_cvm(emb, lengths, ins_cvm, True)
    want = ref_seqpool_cvm(emb, lengths, use_cvm=True)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_forward_no_cvm_strips_columns():
    emb, lengths, ins_cvm = make_inputs(1)
    got = fused_seqpool_cvm(emb, lengths, ins_cvm, False)
    want = ref_seqpool_cvm(emb, lengths, use_cvm=False)
    assert got.shape == (4, 3 * 4)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_forward_quant_and_filter():
    emb, lengths, ins_cvm = make_inputs(2)
    got = fused_seqpool_cvm(emb, lengths, ins_cvm, True, 0.0, 128, True,
                            0.2, 1.0, 0.96)
    want = ref_seqpool_cvm(emb, lengths, use_cvm=True, quant_ratio=128,
                           need_filter=True)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_backward_semantics():
    """Grad must mirror FusedSeqpoolCVMGradKernelWithCVM: embedx grads are
    dout broadcast over valid keys; show/click grad cols carry ins show/click."""
    emb, lengths, ins_cvm = make_inputs(3)
    S, B, L, E = emb.shape

    def loss(e):
        out = fused_seqpool_cvm(e, lengths, ins_cvm, True)
        return jnp.sum(out * jnp.arange(out.size).reshape(out.shape))

    g = jax.grad(loss)(jnp.asarray(emb))
    g = np.asarray(g)
    dy = np.arange(B * S * E).reshape(B, S * E).astype(np.float64)
    for s in range(S):
        for b in range(B):
            for l in range(L):
                valid = l < lengths[s, b]
                expect_sc = ins_cvm[b] if valid else [0, 0]
                np.testing.assert_allclose(g[s, b, l, :2], expect_sc,
                                           rtol=1e-6)
                expect_x = dy[b, s * E + 2:(s + 1) * E] if valid else \
                    np.zeros(E - 2)
                np.testing.assert_allclose(g[s, b, l, 2:], expect_x, rtol=1e-6)


def test_backward_under_jit():
    emb, lengths, ins_cvm = make_inputs(4)

    @jax.jit
    def f(e):
        return jax.grad(
            lambda x: jnp.sum(fused_seqpool_cvm(x, lengths, ins_cvm, True))
        )(e)

    g = f(jnp.asarray(emb))
    assert g.shape == emb.shape


def test_cvm_op():
    x = np.array([[3.0, 1.0, 0.5, -0.5]], np.float32)
    ins = np.array([[1.0, 1.0]], np.float32)
    y = cvm(jnp.asarray(x), jnp.asarray(ins), True)
    np.testing.assert_allclose(
        np.asarray(y)[0],
        [np.log(4), np.log(2) - np.log(4), 0.5, -0.5], rtol=1e-6)
    y2 = cvm(jnp.asarray(x), jnp.asarray(ins), False)
    np.testing.assert_allclose(np.asarray(y2)[0], [0.5, -0.5], rtol=1e-6)
    # grad: show/click cols carry ins_cvm, embedx passes dout through
    g = jax.grad(lambda a: jnp.sum(cvm(a, jnp.asarray(ins), True) *
                                   jnp.array([[1., 2., 3., 4.]])))(
        jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g)[0], [1.0, 1.0, 3.0, 4.0],
                               rtol=1e-6)
