import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.config import MeshConfig
from paddlebox_tpu.parallel.topology import HybridTopology
from paddlebox_tpu.parallel.tp_layers import (ColumnParallelLinear,
                                              RowParallelLinear,
                                              VocabParallelEmbedding,
                                              parallel_cross_entropy)
from paddlebox_tpu.parallel.ring_attention import (reference_attention,
                                                   ring_attention)
from paddlebox_tpu.parallel.ulysses import ulysses_attention
from paddlebox_tpu.parallel.moe import MoEConfig, MoELayer


@pytest.fixture(scope="module")
def topo():
    return HybridTopology(MeshConfig(mp=4, sp=2))


def test_column_parallel_linear(topo):
    layer = ColumnParallelLinear(16, 32, gather_output=True)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    want = layer.apply(params, x)

    f = shard_map(lambda p, x: layer.apply_sharded(p, x),
                  mesh=topo.mesh,
                  in_specs=({"w": P(None, "mp"), "b": P("mp")}, P()),
                  out_specs=P(), check_vma=False)
    got = f(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_row_parallel_linear(topo):
    layer = RowParallelLinear(32, 8)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32))
    want = layer.apply(params, x)
    f = shard_map(lambda p, x: layer.apply_sharded(p, x),
                  mesh=topo.mesh,
                  in_specs=({"w": P("mp", None), "b": P()}, P()),
                  out_specs=P(), check_vma=False)
    got = f(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_vocab_parallel_embedding(topo):
    layer = VocabParallelEmbedding(64, 8)
    params = layer.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 6)))
    want = layer.apply(params, ids)
    f = shard_map(lambda p, i: layer.apply_sharded(p, i),
                  mesh=topo.mesh,
                  in_specs=({"w": P("mp", None)}, P()),
                  out_specs=P(), check_vma=False)
    got = f(params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_parallel_cross_entropy(topo):
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(0, 2, (8, 32)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 32, (8,)))
    # golden: standard CE
    want = -jax.nn.log_softmax(logits)[jnp.arange(8), labels]
    f = shard_map(lambda lg, lb: parallel_cross_entropy(lg, lb),
                  mesh=topo.mesh,
                  in_specs=(P(None, "mp"), P()),
                  out_specs=P(), check_vma=False)
    got = f(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(topo, causal):
    rng = np.random.default_rng(0)
    B, T, H, D = 2, 16, 4, 8
    q = jnp.asarray(rng.normal(0, 1, (B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, (B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, (B, T, H, D)), jnp.float32)
    want = reference_attention(q, k, v, causal=causal)

    f = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", 2, causal=causal),
        mesh=topo.mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False)
    got = f(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=1e-4)


def test_ulysses_matches_dense(topo):
    rng = np.random.default_rng(1)
    B, T, H, D = 2, 16, 4, 8
    q = jnp.asarray(rng.normal(0, 1, (B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, (B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, (B, T, H, D)), jnp.float32)
    want = reference_attention(q, k, v, causal=True)
    f = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "sp", causal=True),
        mesh=topo.mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False)
    got = f(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("gate", ["switch", "gshard"])
def test_moe_sharded_matches_dense(gate):
    topo = HybridTopology(MeshConfig(ep=8))
    cfg = MoEConfig(d_model=16, d_hidden=32, num_experts=8,
                    capacity_factor=8.0, gate=gate)  # high cap → no drops
    layer = MoELayer(cfg)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 16))
    want, aux_want = layer.apply_dense(params, x)

    specs = {"gate": P(), "w1": P("ep"), "b1": P("ep"),
             "w2": P("ep"), "b2": P("ep")}
    f = shard_map(
        lambda p, x: layer.apply_sharded(p, x, ep=8),
        mesh=topo.mesh, in_specs=(specs, P()), out_specs=(P(), P()),
        check_vma=False)
    got, aux = f(params, x)
    # token order within capacity buckets differs between dense (cap=T*...)
    # and sharded (cap per local tokens) — but with no drops the combined
    # output must match.
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-4)


def test_moe_capacity_drops_tokens():
    cfg = MoEConfig(d_model=8, d_hidden=16, num_experts=4,
                    capacity_factor=0.25, gate="switch")
    layer = MoELayer(cfg)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 8))
    y, aux = layer.apply_dense(params, x)
    # over-capacity tokens produce zero output rows
    zero_rows = np.isclose(np.abs(np.asarray(y)).sum(-1), 0.0)
    assert zero_rows.any()
    assert float(aux) > 0
